import sys

from tools.fmtrace import main

if __name__ == "__main__":
    sys.exit(main())
