"""fmtrace — export a run's metrics JSONL stream to Perfetto.

    python -m tools.fmtrace <metrics.jsonl> [more shards...] [-o out.json]
    python -m tools.fmtrace --collectives <metrics.jsonl> <metrics>.p*
    python -m tools.fmtrace --anatomy [--json] <metrics.jsonl> <metrics>.p*

The second form skips the Perfetto export and diffs the per-rank
collective sequences a ``protocol_trace = true`` run records (exit 1
naming the first mismatching rank/position/label) — the runtime oracle
for fmlint's R014 protocol checker, and the first diagnostic for a
hung multi-host cluster.

The third form renders the cross-rank step-anatomy report
(obs/anatomy.py; README "Step anatomy"): clock-aligned phase accounts,
straggler-wait vs transport split of every matched barrier, per-worker
efficiency recomputed from the phases, and a named verdict. Needs a
``trace_spans = true`` run (all shards together); ``--json`` emits the
machine-readable report instead of the table.

Converts the obs/ telemetry stream (spans, gauges, scalars, health and
crash events) into Chrome trace-event JSON loadable in ui.perfetto.dev
(or chrome://tracing). Pass a multi-process run's chief file plus its
``.p<i>`` worker shards together (a glob works): each process becomes
its own Perfetto process track (pid = process index), and each
span-emitting thread (main loop, prefetch, fetcher, watchdog) its own
row within it — so a cluster's timeline reads as one aligned picture,
wall-clock synced across workers.

Mapping:

- ``span`` events -> complete ("X") slices: ``ts`` is the span's wall
  start, ``dur`` its measured duration, extra span fields ride in
  ``args``.
- ``metrics`` events -> counter ("C") tracks for every numeric gauge
  (examples/sec and friends), sampled at the flush cadence.
- ``scalar`` events (loss, validation AUC) -> counter tracks too.
  Their timestamp is EMISSION time (the epoch barrier that fetched
  them), not the step's wall time — the step number is in ``args``.
- ``health`` / ``crash`` / ``run_start`` / ``run_end`` -> instant
  ("i") markers, so a stall or crash is visible in place on the
  timeline.

Pure functions over parsed events (no jax import) — shared by the CLI
and tests.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from typing import Any, Dict, List, Optional, Sequence

from fast_tffm_tpu.obs.sink import read_events
from tools import expand_stream_args


def _us(t: float) -> float:
    """Seconds -> the microseconds the trace-event format speaks."""
    return t * 1e6


# Counter-track unit suffixes, checked in order against the metric
# name: Perfetto counter tracks have no unit axis, so the unit rides
# in the track name (a bytes track next to a seconds track is
# otherwise two unlabeled squiggles).
_UNIT_RULES = (
    ("_ms", "ms"),
    ("seconds", "s"),
    ("bytes", "B"),
    ("per_sec", "1/s"),
    ("examples", "examples"),
    ("fraction", "ratio"),  # mem/utilization_fraction and kin
)


def counter_track(name: str) -> str:
    """The Perfetto track name for a counter/gauge: the metric name
    plus its unit in brackets when the name declares one."""
    for frag, unit in _UNIT_RULES:
        if frag in name:
            return f"{name} [{unit}]"
    return name


class _TidMap:
    """Stable small ints per (pid, thread-name), plus the metadata
    events that name the rows in the UI. tid 0 is reserved for the
    per-process counter tracks."""

    def __init__(self):
        self._map: Dict[tuple, int] = {}
        self.meta: List[Dict[str, Any]] = []

    def tid(self, pid: int, name: Optional[str]) -> int:
        name = name or "main"
        key = (pid, name)
        t = self._map.get(key)
        if t is None:
            t = self._map[key] = len(
                [k for k in self._map if k[0] == pid]) + 1
            self.meta.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": t,
                "args": {"name": name}})
        return t


def to_trace_events(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """The traceEvents list for one run's files (chief + shards)."""
    out: List[Dict[str, Any]] = []
    tids = _TidMap()
    named_pids = set()
    # Last value per (pid -> counter track): re-emitted at run_end so
    # a short run's single-sample counters still render as a line
    # (Perfetto draws nothing for a one-point counter track).
    last_counters: Dict[int, Dict[str, float]] = {}
    # protocol_trace collective events, for cross-rank flow arrows.
    collectives: List[Dict[str, Any]] = []
    for path in paths:
        pid = 0  # until a run_start announces the real process index
        for rec in read_events(path):
            ev = rec.get("event")
            t = rec.get("t", 0.0)
            if ev == "run_start":
                meta = rec.get("meta") or {}
                pid = int(meta.get("process_index") or 0)
                if pid not in named_pids:
                    named_pids.add(pid)
                    out.append({
                        "ph": "M", "name": "process_name", "pid": pid,
                        "tid": 0,
                        "args": {"name": f"worker {pid} "
                                         f"({meta.get('kind', '?')})"}})
                out.append(_instant("run_start", t, pid))
            elif ev == "span":
                extra = {k: v for k, v in rec.items()
                         if k not in ("event", "t", "name", "ts", "dur",
                                      "tid")}
                out.append({
                    "ph": "X", "cat": "span", "name": rec.get("name", "?"),
                    "pid": pid, "tid": tids.tid(pid, rec.get("tid")),
                    "ts": _us(rec.get("ts", t)),
                    "dur": _us(rec.get("dur", 0.0)),
                    "args": extra,
                })
            elif ev == "metrics":
                for name, v in (rec.get("gauges") or {}).items():
                    if isinstance(v, (int, float)) and math.isfinite(v):
                        track = counter_track(name)
                        out.append({
                            "ph": "C", "name": track, "pid": pid,
                            "tid": 0, "ts": _us(t),
                            "args": {"value": v}})
                        last_counters.setdefault(pid, {})[track] = v
            elif ev == "scalar":
                val = rec.get("value")
                if isinstance(val, (int, float)) and math.isfinite(val):
                    # args holds ONLY the value: every args key of a
                    # "C" event is its own plotted series, so a step
                    # number here would stack a huge second series
                    # that flattens the one being shown.
                    track = counter_track(rec.get("name", "scalar"))
                    out.append({
                        "ph": "C", "name": track,
                        "pid": pid, "tid": 0, "ts": _us(t),
                        "args": {"value": val}})
                    last_counters.setdefault(pid, {})[track] = val
            elif ev == "collective":
                collectives.append({
                    "pid": pid, "t": t,
                    "seq": rec.get("seq", 0),
                    "label": str(rec.get("label", "?"))})
            elif ev == "health":
                out.append(_instant(
                    f"health: {rec.get('status', '?')}", t, pid,
                    args={k: v for k, v in rec.items()
                          if k not in ("event", "t")}))
            elif ev == "crash":
                out.append(_instant(
                    "crash: " + str(rec.get("error", "?"))[:120], t, pid,
                    args={"step": rec.get("step")}))
            elif ev == "run_end":
                # Close every counter track with its last value at the
                # run's end so short runs draw a visible line instead
                # of a single invisible point.
                for track, v in sorted(
                        (last_counters.get(pid) or {}).items()):
                    out.append({
                        "ph": "C", "name": track, "pid": pid,
                        "tid": 0, "ts": _us(t),
                        "args": {"value": v}})
                out.append(_instant("run_end", t, pid))
    out.extend(_collective_flows(collectives, tids))
    out.extend(tids.meta)
    # Stable paint order: metadata first, then by timestamp.
    out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    return out


def _collective_flows(collectives: List[Dict[str, Any]],
                      tids: "_TidMap") -> List[Dict[str, Any]]:
    """Cross-rank flow arrows between matched collective events: the
    same seq on every rank IS the same collective (the protocol-trace
    invariant fmtrace --collectives checks), so each seq becomes one
    Perfetto flow threading every rank's marker slice. The arrows make
    a lagging rank visually obvious: its slice sits to the right and
    every arrow into it slopes."""
    out: List[Dict[str, Any]] = []
    by_seq: Dict[Any, List[Dict[str, Any]]] = {}
    for c in collectives:
        by_seq.setdefault(c["seq"], []).append(c)
    for seq, group in sorted(by_seq.items(),
                             key=lambda kv: str(kv[0])):
        group.sort(key=lambda c: c["t"])
        for c in group:
            # A tiny slice per rank (flows bind to slices, not
            # instants), on a dedicated per-process row.
            tid = tids.tid(c["pid"], "collectives")
            out.append({
                "ph": "X", "cat": "collective",
                "name": c["label"], "pid": c["pid"], "tid": tid,
                "ts": _us(c["t"]), "dur": 50.0,
                "args": {"seq": seq}})
        if len(group) < 2:
            continue
        for i, c in enumerate(group):
            ph = ("s" if i == 0
                  else "f" if i == len(group) - 1 else "t")
            ev = {
                "ph": ph, "cat": "collective",
                "name": c["label"], "id": int(seq),
                "pid": c["pid"],
                "tid": tids.tid(c["pid"], "collectives"),
                "ts": _us(c["t"]) + 1.0}
            if ph == "f":
                ev["bp"] = "e"
            out.append(ev)
    return out


def _instant(name: str, t: float, pid: int,
             args: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    rec = {"ph": "i", "s": "p", "name": name, "pid": pid, "tid": 0,
           "ts": _us(t)}
    if args:
        rec["args"] = args
    return rec


def collective_sequences(paths: Sequence[str]
                         ) -> Dict[int, List[str]]:
    """Per-rank ordered collective label sequences from the
    ``collective`` events a run under ``protocol_trace = true`` (or
    ``FM_PROTOCOL_TRACE=1``) emits — process index -> labels ordered
    by the emitting rank's own sequence counter."""
    raw: Dict[int, List[tuple]] = {}
    for path in paths:
        pid = 0  # until a run_start announces the real process index
        for rec in read_events(path):
            ev = rec.get("event")
            if ev == "run_start":
                meta = rec.get("meta") or {}
                pid = int(meta.get("process_index") or 0)
            elif ev == "collective":
                raw.setdefault(pid, []).append(
                    (int(rec.get("seq", 0)),
                     str(rec.get("label", "?"))))
    return {pid: [label for _, label in sorted(entries)]
            for pid, entries in raw.items()}


def diff_collectives(seqs: Dict[int, List[str]],
                     out=None) -> int:
    """The protocol-divergence verdict fmlint R014 proves statically,
    checked against a real run: 0 when every rank posted the
    bit-identical collective sequence, 1 with the first mismatching
    (rank, position, label) pair named otherwise. The first divergent
    entry IS the deadlock diagnosis: the rank whose label differs (or
    whose stream ended early) is the one whose peers are parked."""
    out = out if out is not None else sys.stderr
    if not seqs:
        print("no collective events found — was the run traced? "
              "(protocol_trace = true, or FM_PROTOCOL_TRACE=1)",
              file=out)
        return 1
    pids = sorted(seqs)
    n = max(len(seqs[p]) for p in pids)
    for i in range(n):
        at = {p: (seqs[p][i] if i < len(seqs[p]) else None)
              for p in pids}
        if len(set(at.values())) > 1:
            print(f"collective sequences DIVERGE at position {i}:",
                  file=out)
            for p in pids:
                label = at[p] if at[p] is not None else \
                    "<end of sequence>"
                print(f"  rank {p}: {label}", file=out)
            return 1
    print(f"{len(pids)} rank(s), {n} collective(s) each — "
          "sequences identical", file=out)
    return 0


def convert(paths: Sequence[str], out_path: str) -> int:
    """Write the Perfetto JSON for ``paths``; returns the event count."""
    events = to_trace_events(paths)
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, fh)
    return len(events)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fmtrace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("files", nargs="+",
                    help="metrics JSONL file(s); pass the chief file "
                         "plus its .p<i> worker shards (globs ok)")
    ap.add_argument("-o", "--out", default=None,
                    help="output path (default: <first file>.trace.json)")
    ap.add_argument("--collectives", action="store_true",
                    help="diff the per-rank collective sequences "
                         "(protocol_trace runs) instead of exporting "
                         "a Perfetto trace; exit 1 on divergence")
    ap.add_argument("--anatomy", action="store_true",
                    help="render the cross-rank step-anatomy report "
                         "(obs/anatomy.py) from a trace_spans run's "
                         "shards instead of exporting a trace")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="with --anatomy: emit the machine-readable "
                         "report instead of the table")
    ap.add_argument("--baseline-eps", type=float, default=None,
                    help="with --anatomy: a single-process "
                         "examples/sec rate (e.g. bench.py "
                         "--multihost's 1-worker leg); unlocks "
                         "absolute per-worker efficiency = useful "
                         "compute time / wall, which also counts "
                         "stalls inside the dispatched program")
    args = ap.parse_args(argv)
    # Shared glob + fail-loudly-on-unreadable policy (tools/__init__).
    files = expand_stream_args(args.files)
    if args.anatomy:
        from fast_tffm_tpu.obs import anatomy
        rep = anatomy.report(files, baseline_eps=args.baseline_eps)
        if args.as_json:
            print(json.dumps(rep, indent=1, sort_keys=True))
        else:
            print(anatomy.render(rep))
        return 1 if "error" in rep else 0
    if args.collectives:
        return diff_collectives(collective_sequences(files))
    out_path = args.out or files[0] + ".trace.json"
    n = convert(files, out_path)
    print(f"wrote {n} trace events from {len(files)} file(s) to "
          f"{out_path}\nopen in https://ui.perfetto.dev (Open trace "
          "file)", file=sys.stderr)
    return 0
