import sys

from tools.fmstat import main

if __name__ == "__main__":
    sys.exit(main())
