"""fmstat — summarize or tail a run's metrics JSONL stream.

The read-side of the obs/ telemetry subsystem:

    python -m tools.fmstat <metrics.jsonl> [more shards...]
    python -m tools.fmstat --json <metrics.jsonl>
    python -m tools.fmstat --tail <metrics.jsonl>

Summary mode merges every given file (a multi-process run's chief file
plus its ``.p<i>`` worker shards — pass a glob) through the registry's
merge rules (counters add, histograms bucket-merge, gauges per
process) and renders the same attribution table bench.py's breakdown
teaches: examples/sec, step-time quantiles, input-wait / pause /
transfer split, dedup hit rate, padding waste, and a host-bound vs
device/transfer-bound vs pause-bound verdict. Multi-worker runs with
the heartbeat lease on additionally get a per-worker liveness table
(last heartbeat age, lockstep windows, examples; LOST flag on workers
named by a ``worker_lost`` diagnosis) and the
``DEGRADED (N workers lost)`` health verdict (README "Elastic
multi-host"). Streaming runs (``run_mode = stream``) get a STREAMING
section — watermark lag, files discovered/sealed/truncated/deleted,
publishes, last-publish age — and the health verdict reads
``STALE PUBLISH`` when the last publish age exceeds 3x the configured
interval (the serving fleet is reloading stale state). ``--json``
emits the merged summary + attribution as one JSON object for
scripting. ``--tail`` follows a live file and pretty-prints events as
they land.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from fast_tffm_tpu.obs.attribution import (attribution, health_verdict,
                                           render, summarize)
from tools import expand_stream_args


def _tail(path: str, out=sys.stdout) -> None:  # pragma: no cover - loop
    """Follow a live metrics file; one formatted line per event."""
    with open(path, "r", encoding="utf-8") as fh:
        while True:
            line = fh.readline()
            if not line:
                time.sleep(0.5)
                continue
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail mid-write; the rest follows
            out.write(_format_event(rec) + "\n")
            out.flush()


def _format_event(rec: dict) -> str:
    ev = rec.get("event", "?")
    if ev == "metrics":
        c = rec.get("counters", {})
        g = rec.get("gauges", {})
        eps = g.get("train/examples_per_sec_window") or g.get(
            "predict/examples_per_sec")
        bits = [f"step={rec.get('step')}"]
        if eps:
            bits.append(f"ex/s={eps:,.0f}")
        for key, label in (("train/examples", "examples"),
                           ("pipeline/parse_errors", "parse_errs"),
                           ("pipeline/spilled_batches", "spills")):
            if c.get(key):
                bits.append(f"{label}={c[key]:,.0f}")
        return f"[metrics] {' '.join(bits)}"
    if ev == "scalar":
        return (f"[scalar]  {rec.get('name')} step={rec.get('step')} "
                f"value={rec.get('value'):.6g}")
    if ev == "run_start":
        m = rec.get("meta", {})
        return (f"[run]     kind={m.get('kind')} backend={m.get('backend')} "
                f"devices={m.get('device_count')} config="
                f"{m.get('config_hash')} git={m.get('git_rev')}")
    return f"[{ev}] " + json.dumps(
        {k: v for k, v in rec.items() if k not in ("event",)},
        default=str)[:200]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fmstat", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("files", nargs="+",
                    help="metrics JSONL file(s); globs ok — pass a "
                         "run's worker shards together to merge them")
    ap.add_argument("--json", action="store_true",
                    help="emit merged summary + attribution as JSON")
    ap.add_argument("--tail", action="store_true",
                    help="follow the (first) file, print events live")
    args = ap.parse_args(argv)
    # Shared glob + fail-loudly-on-unreadable policy (tools/__init__).
    files = expand_stream_args(args.files)
    if args.tail:
        try:
            _tail(files[0])
        except KeyboardInterrupt:
            return 0
        return 0
    summary = summarize(files)
    if args.json:
        out = dict(summary)
        out.pop("scalars", None)
        out["attribution"] = attribution(summary)
        out["health"] = health_verdict(summary)
        print(json.dumps(out, default=str))
        return 0
    print(render(summary))
    return 0
