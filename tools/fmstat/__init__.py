"""fmstat — summarize, tail, follow, or SLO-check a metrics stream.

The read-side of the obs/ telemetry subsystem:

    python -m tools.fmstat <metrics.jsonl> [more shards...]
    python -m tools.fmstat --json <metrics.jsonl>
    python -m tools.fmstat --tail <metrics.jsonl>
    python -m tools.fmstat --follow '<metrics.jsonl>*'
    python -m tools.fmstat slo <metrics.jsonl> [shards...] [--json]
    python -m tools.fmstat capacity <cfg> [--kind serve]
        [--what-if vocabulary_size=N,dtype=f16,shards=K]

Summary mode merges every given file (a multi-process run's chief file
plus its ``.p<i>`` worker shards — pass a glob) through the registry's
merge rules (counters add, histograms bucket-merge, gauges per
process) and renders the same attribution table bench.py's breakdown
teaches: examples/sec, step-time quantiles, input-wait / pause /
transfer split, dedup hit rate, padding waste, and a host-bound vs
device/transfer-bound vs pause-bound verdict. Multi-worker runs with
the heartbeat lease on additionally get a per-worker liveness table
(last heartbeat age, lockstep windows, examples; LOST flag on workers
named by a ``worker_lost`` diagnosis) and the
``DEGRADED (N workers lost)`` health verdict (README "Elastic
multi-host"). Streaming runs (``run_mode = stream``) get a STREAMING
section — watermark lag, files discovered/sealed/truncated/deleted,
publishes, last-publish age — and the health verdict reads
``STALE PUBLISH`` when the last publish age exceeds 3x the configured
interval (the serving fleet is reloading stale state). A replica
supervisor's stream (``serve --replicas N``; README "Serving fleet")
grows a FLEET section — per-replica alive/ready/step/queue rows plus
proxy traffic, retry, and shed counters — and the health verdict
reads ``FLEET DEGRADED (k/N ready)`` while any replica is down or
warming (ranked above the staleness verdicts: a capacity gap is more
urgent than a stale pointer). ``--json``
emits the merged summary + attribution as one JSON object for
scripting. ``--tail`` follows a live file and pretty-prints events as
they land. ``--follow`` re-renders the full summary + verdict on a
poll interval as the stream grows — the "watch a live soak" mode —
re-expanding the file globs each poll so per-worker ``.p<i>`` shards
appearing mid-run join the merge. The ``slo`` subcommand evaluates
the run's declared service-level objectives (the ``slo/*`` gauges the
[SLO] config section stamps into the stream, or ``--config <file>``)
and prints a per-objective PASS/FAIL table (``--json`` for the
machine form), exiting non-zero on any FAIL — the one scriptable
"is this deployment healthy" answer (README "SLOs & quality gate").
The ``capacity`` subcommand is the planner's CLI (obs/memory.py;
README "Memory observability"): predicted per-owner resident device
bytes for a config — before the run exists — against device capacity,
with ``--what-if`` overrides for the sharding/quantization frontiers;
exits non-zero on an EXCEEDS verdict. Runs with the ledger on grow a
MEMORY section here and an ``HBM-PRESSURE`` health verdict.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from fast_tffm_tpu.obs.attribution import (attribution, health_verdict,
                                           render, summarize)
from tools import expand_stream_args


def _tail(path: str, out=sys.stdout) -> None:  # pragma: no cover - loop
    """Follow a live metrics file; one formatted line per event."""
    with open(path, "r", encoding="utf-8") as fh:
        while True:
            line = fh.readline()
            if not line:
                time.sleep(0.5)
                continue
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail mid-write; the rest follows
            out.write(_format_event(rec) + "\n")
            out.flush()


def _format_event(rec: dict) -> str:
    ev = rec.get("event", "?")
    if ev == "metrics":
        c = rec.get("counters", {})
        g = rec.get("gauges", {})
        eps = g.get("train/examples_per_sec_window") or g.get(
            "predict/examples_per_sec")
        bits = [f"step={rec.get('step')}"]
        if eps:
            bits.append(f"ex/s={eps:,.0f}")
        for key, label in (("train/examples", "examples"),
                           ("pipeline/parse_errors", "parse_errs"),
                           ("pipeline/spilled_batches", "spills")):
            if c.get(key):
                bits.append(f"{label}={c[key]:,.0f}")
        return f"[metrics] {' '.join(bits)}"
    if ev == "scalar":
        return (f"[scalar]  {rec.get('name')} step={rec.get('step')} "
                f"value={rec.get('value'):.6g}")
    if ev == "run_start":
        m = rec.get("meta", {})
        return (f"[run]     kind={m.get('kind')} backend={m.get('backend')} "
                f"devices={m.get('device_count')} config="
                f"{m.get('config_hash')} git={m.get('git_rev')}")
    return f"[{ev}] " + json.dumps(
        {k: v for k, v in rec.items() if k not in ("event",)},
        default=str)[:200]


def _expand_tolerant(patterns) -> list:
    """Glob expansion that tolerates not-yet-existing inputs — the
    --follow seam (a live run's worker shards appear over time; the
    strict expand_stream_args policy would kill the watch loop on the
    very race it exists to observe). Literal paths are kept only once
    they exist."""
    import glob as globlib
    import os
    files = []
    for p in patterns:
        hits = sorted(globlib.glob(p))
        if hits:
            files.extend(hits)
        elif os.path.exists(p):
            files.append(p)
    return files


def _follow(patterns, interval: float = 2.0, out=sys.stdout,
            iterations=None) -> int:
    """Poll-based live summary: re-expand the globs, re-merge, and
    re-render the full table + verdict every ``interval`` seconds
    until interrupted (``iterations`` bounds the loop for tests)."""
    n = 0
    while iterations is None or n < iterations:
        files = _expand_tolerant(patterns)
        if files:
            try:
                body = render(summarize(files))
            except OSError as e:
                body = f"(stream unreadable this poll: {e})"
        else:
            body = f"waiting for {' '.join(patterns)} ..."
        if out.isatty():
            out.write("\x1b[2J\x1b[H")  # clear + home: a live panel
        stamp = time.strftime("%H:%M:%S")
        out.write(f"-- fmstat --follow {stamp} "
                  f"({len(files)} file(s)) --\n{body}\n")
        out.flush()
        n += 1
        if iterations is None or n < iterations:
            time.sleep(interval)
    return 0


def main_slo(argv=None) -> int:
    """The ``fmstat slo`` subcommand: PASS/FAIL table per declared
    objective; exit 1 on any FAIL."""
    from fast_tffm_tpu.obs.slo import (SloSpec, evaluate_slos, overall,
                                       render_slo, results_json)
    ap = argparse.ArgumentParser(
        prog="fmstat slo",
        description="evaluate a run's declared SLOs over its metrics "
                    "stream (README 'SLOs & quality gate')")
    ap.add_argument("files", nargs="+",
                    help="metrics JSONL file(s); globs ok")
    ap.add_argument("--json", action="store_true",
                    help="emit the spec + per-objective results as "
                         "JSON")
    ap.add_argument("--config", default="",
                    help="read the SLO spec from this config file "
                         "instead of the stream's slo/* gauges")
    ap.add_argument("--allow-skip", action="store_true",
                    help="exit 0 even when a configured objective had "
                         "no supporting data (default: exit 2 — a "
                         "declared objective that was never measured "
                         "must not read green in a monitor)")
    args = ap.parse_args(argv)
    files = expand_stream_args(args.files)
    summary = summarize(files)
    if args.config:
        from fast_tffm_tpu.config import load_config
        spec = SloSpec.from_config(load_config(args.config))
    else:
        spec = SloSpec.from_summary(summary)
    results = evaluate_slos(spec, summary)
    if args.json:
        out = results_json(spec, results)
        out["health"] = health_verdict(summary)
        print(json.dumps(out, default=str))
    else:
        print(render_slo(spec, results))
        hv = health_verdict(summary)
        print(f"health: {hv['verdict']} — {hv['detail']}")
    if overall(results) == "FAIL":
        return 1
    # SKIP (and an EMPTY spec) are visible in the output, but at the
    # exit-code level (the scriptable surface) neither may read green:
    # an unmeasured declared objective — or a stream that carries no
    # slo/* gauges at all because the metrics file was rotated or
    # truncated — is exactly when a monitor wired to this command must
    # fire, not stay silent.
    if args.allow_skip:
        return 0
    if not results or any(r.status == "SKIP" for r in results):
        return 2
    return 0


def main_capacity(argv=None) -> int:
    """The ``fmstat capacity`` subcommand: predict resident device
    bytes per owner from a CONFIG (no stream needed — sizing happens
    before the run exists) against the device capacity, with --what-if
    overrides for the capacity frontiers (sharded tables, f16/int8
    resident tables). Exit 1 on an EXCEEDS verdict — scriptable as a
    deploy gate."""
    from fast_tffm_tpu.obs.memory import (parse_what_if, plan,
                                          render_plan)
    ap = argparse.ArgumentParser(
        prog="fmstat capacity",
        description="predict per-owner resident device bytes for a "
                    "config against device capacity (README 'Memory "
                    "observability')")
    ap.add_argument("config", help="config file to size")
    ap.add_argument("--kind", choices=("train", "serve"),
                    default="train",
                    help="which resident set to plan: the train "
                         "session's (table+optimizer+wire) or the "
                         "server's (table + old+new reload transient)")
    ap.add_argument("--what-if", default="", dest="what_if",
                    metavar="K=V[,K=V...]",
                    help="overrides: vocabulary_size, factor_num, "
                         "field_num, batch_size, "
                         "max_features_per_example, dtype "
                         "(f32|f16|bf16|int8, resident table only), "
                         "shards (per-device share under row "
                         "sharding)")
    ap.add_argument("--capacity-bytes", type=int, default=0,
                    help="assume this device capacity instead of "
                         "asking the backend (sizing for a target "
                         "chip from a dev box)")
    ap.add_argument("--json", action="store_true",
                    help="emit the plan as JSON")
    args = ap.parse_args(argv)
    from fast_tffm_tpu.config import load_config
    cfg = load_config(args.config)
    overrides = parse_what_if(args.what_if)
    p = plan(cfg, args.kind, overrides)
    if args.capacity_bytes:
        p["capacity_bytes"] = args.capacity_bytes
        p["utilization_fraction"] = (p["total_bytes"]
                                     / float(args.capacity_bytes))
        p["verdict"] = ("EXCEEDS"
                        if p["total_bytes"] > args.capacity_bytes
                        else "FITS")
    if args.json:
        print(json.dumps(p, default=str))
    else:
        print(render_plan(p))
    return 1 if p["verdict"] == "EXCEEDS" else 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "slo":
        return main_slo(argv[1:])
    if argv and argv[0] == "capacity":
        return main_capacity(argv[1:])
    ap = argparse.ArgumentParser(
        prog="fmstat", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("files", nargs="+",
                    help="metrics JSONL file(s); globs ok — pass a "
                         "run's worker shards together to merge them")
    ap.add_argument("--json", action="store_true",
                    help="emit merged summary + attribution as JSON")
    ap.add_argument("--tail", action="store_true",
                    help="follow the (first) file, print events live")
    ap.add_argument("--follow", action="store_true",
                    help="re-render the merged summary + verdict as "
                         "the stream grows (globs re-expanded each "
                         "poll, so worker shards join live)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--follow poll interval in seconds")
    args = ap.parse_args(argv)
    if args.follow:
        try:
            return _follow(args.files, interval=args.interval)
        except KeyboardInterrupt:
            return 0
    # Shared glob + fail-loudly-on-unreadable policy (tools/__init__).
    files = expand_stream_args(args.files)
    if args.tail:
        try:
            _tail(files[0])
        except KeyboardInterrupt:
            return 0
        return 0
    summary = summarize(files)
    if args.json:
        out = dict(summary)
        out.pop("scalars", None)
        out["attribution"] = attribution(summary)
        out["health"] = health_verdict(summary)
        print(json.dumps(out, default=str))
        return 0
    print(render(summary))
    return 0
