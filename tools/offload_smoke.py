#!/usr/bin/env python
"""Offload smoke: train a table that dwarfs device HBM via lookup=host.

BASELINE config #5's shape is a 10^9-row hashed FM whose table lives
outside device memory. This tool runs the same *structure* at a
configurable scale (default 10^8 rows ~= 3.6 GB table + 3.6 GB Adagrad
accumulator in host RAM, vs ~16 GB device HBM on a v5 lite chip, most of
it untouched): synthesizes hashed-id libsvm data, trains steps through
the lookup.py host backend on the real chip, and prints a JSON
accounting line proving the table stayed host-side —

    host_rss_mb   ~ table + accumulator (+ interpreter)
    device_in_use_mb  stays at the [U, D] gathered-rows scale

Usage: python tools/offload_smoke.py [--rows 100000000] [--steps 20]
The result is recorded in BASELINE.md (config #5 row).
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def synth_hashed_lines(n, seed=0):
    """Criteo-like lines with STRING feature ids (hash_feature_id path):
    39 features/example over an effectively unbounded id space."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < 0.25).astype(np.int32)
    # Zipf-ish ids: a dense head plus a huge tail, like real CTR data.
    head = rng.integers(0, 10_000, size=(n, 13))
    tail = rng.integers(0, 1 << 40, size=(n, 26))
    lines = []
    for i in range(n):
        parts = [str(labels[i])]
        parts += [f"f{j}_{head[i, j]}:1" for j in range(13)]
        parts += [f"c{j}_{tail[i, j]}:1" for j in range(26)]
        lines.append(" ".join(parts))
    return lines


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000_000)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4096)
    args = ap.parse_args()

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.lookup import HostOffloadLookup, memory_report
    from fast_tffm_tpu.models.fm import ModelSpec, batch_args, make_grad_fn
    from fast_tffm_tpu.data.pipeline import batch_iterator

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        with open(path, "w") as fh:
            fh.write("\n".join(synth_hashed_lines(args.steps * args.batch))
                     + "\n")

        cfg = FmConfig(vocabulary_size=args.rows, factor_num=8,
                       batch_size=args.batch, learning_rate=0.05,
                       hash_feature_id=True, lookup="host",
                       max_features_per_example=64, bucket_ladder=(64,),
                       train_files=(path,), shuffle=False)
        spec = ModelSpec.from_config(cfg)

        t0 = time.perf_counter()
        lk = HostOffloadLookup(cfg, seed=0)
        init_s = time.perf_counter() - t0
        after_init = memory_report()

        grad_fn = make_grad_fn(spec)
        n_steps = 0
        n_examples = 0
        loss = None
        t0 = time.perf_counter()
        for batch in batch_iterator(cfg, cfg.train_files, training=True,
                                    epochs=1):
            a = batch_args(batch)
            gathered = lk.gather(a["uniq_ids"])
            loss, _, grad = grad_fn(gathered, **a)
            lk.apply_grad(a["uniq_ids"], np.asarray(grad),
                          cfg.learning_rate)
            n_steps += 1
            n_examples += batch.num_real
        dt = time.perf_counter() - t0

        import jax
        rep = memory_report()
        table_gb = lk.rows * lk.dim * 4 / 2**30
        print(json.dumps({
            "rows": lk.rows, "row_dim": lk.dim,
            "table_gb": round(table_gb, 2),
            "state_gb": round(2 * table_gb, 2),
            "init_sec": round(init_s, 1),
            "steps": n_steps, "examples": n_examples,
            "examples_per_sec": round(n_examples / dt, 1),
            "final_loss": round(float(loss), 6),
            "host_rss_mb_after_init": after_init["host_rss_mb"],
            "host_rss_mb": rep["host_rss_mb"],
            "device_in_use_mb": rep.get("device_in_use_mb"),
            "device_limit_mb": rep.get("device_limit_mb"),
            "backend": jax.default_backend(),
        }))
        # The accounting claim: host RSS covers the 2x-table state, the
        # device holds ~nothing of it.
        dev = rep.get("device_in_use_mb")
        assert rep["host_rss_mb"] > 2 * table_gb * 1024 * 0.9, rep
        if dev is not None:
            assert dev < 1024, f"table leaked onto the device: {rep}"


if __name__ == "__main__":
    main()
