#!/usr/bin/env python
"""Offload smoke: train a table that dwarfs device HBM via lookup=host.

BASELINE config #5's shape is a 10^9-row hashed FM whose table lives
outside device memory. This tool runs the same *structure* at a
configurable scale (default 10^8 rows ~= 3.6 GB table + 3.6 GB Adagrad
accumulator, vs ~16 GB device HBM on a v5 lite chip): synthesizes
hashed-id libsvm data, trains steps through the lookup.py offload seam
on the real chip, and prints a JSON accounting line proving where the
state lived —

- ``numpy`` backend: local host RSS covers table + accumulator; the
  device only ever holds the per-batch [U, D] blocks.
- ``pinned`` backend (the device-resident fast path): the state's jax
  shardings report ``memory_kind="pinned_host"`` (accelerator-host
  memory, NOT HBM, NOT local RAM — local RSS stays flat), and the whole
  step runs in-jit with no per-step Python round-trip.

Usage: python tools/offload_smoke.py [--rows 100000000] [--steps 20]
       [--backend auto|pinned|numpy]
The result is recorded in BASELINE.md (config #5 row).
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def synth_hashed_lines(n, seed=0):
    """Criteo-like lines with STRING feature ids (hash_feature_id path):
    39 features/example over an effectively unbounded id space."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < 0.25).astype(np.int32)
    # Zipf-ish ids: a dense head plus a huge tail, like real CTR data.
    head = rng.integers(0, 10_000, size=(n, 13))
    tail = rng.integers(0, 1 << 40, size=(n, 26))
    lines = []
    for i in range(n):
        parts = [str(labels[i])]
        parts += [f"f{j}_{head[i, j]}:1" for j in range(13)]
        parts += [f"c{j}_{tail[i, j]}:1" for j in range(26)]
        lines.append(" ".join(parts))
    return lines


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=100_000_000)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--backend", choices=("auto", "pinned", "numpy"),
                    default="auto")
    args = ap.parse_args()

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.lookup import (HostOffloadLookup, PinnedHostLookup,
                                      make_offload_backend,
                                      make_offload_train_step,
                                      memory_report)
    from fast_tffm_tpu.models.fm import ModelSpec, batch_args
    from fast_tffm_tpu.data.pipeline import batch_iterator

    # The CLI's persistent compile cache: without it the first step's
    # compile (tens of seconds on a tunnelled chip) lands inside
    # whatever span contains it and the recorded rates conflate
    # compile/cache state with steady-state throughput.
    from run_tffm import _enable_compilation_cache
    _enable_compilation_cache()

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        # +1 batch: the first step is an UNTIMED warmup (pays any
        # residual compile), so the timed loop still covers args.steps.
        with open(path, "w") as fh:
            fh.write("\n".join(
                synth_hashed_lines((args.steps + 1) * args.batch)) + "\n")

        cfg = FmConfig(vocabulary_size=args.rows, factor_num=8,
                       batch_size=args.batch, learning_rate=0.05,
                       hash_feature_id=True, lookup="host",
                       max_features_per_example=64, bucket_ladder=(64,),
                       train_files=(path,), shuffle=False)
        spec = ModelSpec.from_config(cfg)

        import jax
        baseline = memory_report()  # corpus transients already freed
        t0 = time.perf_counter()
        if args.backend == "pinned":
            lk = PinnedHostLookup(cfg, seed=0)
        elif args.backend == "numpy":
            lk = HostOffloadLookup(cfg, seed=0)
        else:
            lk = make_offload_backend(cfg, seed=0)
        # The pinned init dispatches its chunked fills asynchronously;
        # without a fence the fill EXECUTION would bleed into the
        # training span (understating init, deflating examples/sec).
        jax.block_until_ready((lk.table, lk.acc))
        init_s = time.perf_counter() - t0
        after_init = memory_report()

        step = make_offload_train_step(spec, lk, cfg.learning_rate)
        n_steps = 0
        n_examples = 0
        loss = None
        warm_s = None
        t0 = time.perf_counter()
        for batch in batch_iterator(cfg, cfg.train_files, training=True,
                                    epochs=1):
            loss, _ = step(**batch_args(batch))
            if warm_s is None:  # warmup step: compile + first dispatch
                jax.block_until_ready(loss)
                warm_s = time.perf_counter() - t0
                n_steps = 0
                n_examples = 0
                t0 = time.perf_counter()
                continue
            n_steps += 1
            n_examples += batch.num_real
        jax.block_until_ready(loss)
        dt = time.perf_counter() - t0

        rep = memory_report()
        table_gb = lk.rows * lk.dim * 4 / 2**30
        table_mb = table_gb * 1024
        pinned = isinstance(lk, PinnedHostLookup)
        mode = getattr(lk, "mode", "numpy")
        out = {
            "backend": type(lk).__name__,
            "mode": mode,
            "rows": lk.rows, "row_dim": lk.dim,
            "table_gb": round(table_gb, 2),
            "state_gb": round(2 * table_gb, 2),
            "init_sec": round(init_s, 1),
            "warmup_sec": round(warm_s or 0.0, 1),
            "steps": n_steps, "examples": n_examples,
            "examples_per_sec": round(n_examples / dt, 1),
            "final_loss": round(float(loss), 6),
            "host_rss_mb_baseline": baseline["host_rss_mb"],
            "host_rss_mb_after_init": after_init["host_rss_mb"],
            "host_rss_mb": rep["host_rss_mb"],
            "device_in_use_mb": rep["device_in_use_mb"],
            "device_limit_mb": rep["device_limit_mb"],
            "platform": jax.default_backend(),
        }
        if pinned:
            out["table_memory_kind"] = lk.table.sharding.memory_kind
            out["acc_memory_kind"] = lk.acc.sharding.memory_kind
        print(json.dumps(out))

        # The accounting claims, per backend. host_rss_mb is CURRENT
        # RSS and the bounds are BASELINE-RELATIVE, so the checks stay
        # meaningful at small --rows and don't bill freed transients.
        grew = rep["host_rss_mb"] - baseline["host_rss_mb"]
        if pinned and mode == "pinned":
            # State in accelerator-host memory: the shardings say so,
            # and LOCAL RAM must not have grown by anything near one
            # table copy.
            assert out["table_memory_kind"] == "pinned_host", out
            assert out["acc_memory_kind"] == "pinned_host", out
            assert grew < max(0.25 * table_mb, 512), \
                f"state appears to live in LOCAL RAM: +{grew} MB {rep}"
            # Peak-relative too: a regression that STAGES the full
            # table through local RAM during init and frees it would
            # pass the current-RSS bound; the chunked on-device init
            # exists precisely so no such copy ever materializes.
            peak_grew = (rep["host_peak_rss_mb"]
                         - baseline["host_peak_rss_mb"])
            assert peak_grew < max(0.5 * table_mb, 1024), \
                f"a transient table-sized copy crossed LOCAL RAM: " \
                f"+{peak_grew} MB peak {rep}"
        else:
            # numpy backend — and the pinned class in 'plain' mode
            # (CPU fallback), where device memory IS host RAM: local
            # RSS must have grown by ~the 2x-table state.
            assert grew > 2 * table_mb * 0.9, (grew, rep)
        dev = rep["device_in_use_mb"]
        if dev is not None:  # None = runtime reports no stats: UNMEASURED
            assert dev < 1024, f"table leaked onto the device: {rep}"


if __name__ == "__main__":
    main()
