#!/usr/bin/env python
"""Interleaved Pallas-vs-XLA A/B probe — regenerates the kernel matrix.

``kernel = auto`` follows the measured (L, dedup) regime matrix in
``ops/kernel_choice.py`` (recorded in BASELINE.md). That matrix is ONE
chip's measurement; on different hardware (or after a compiler upgrade)
re-run this tool and, if the regime boundary moved, either update the
matrix or pin ``kernel = pallas|xla`` per job.

Each cell times the FULL jitted train step (gather + scorer + grad +
sparse Adagrad — the same executable training runs, not a bare scorer)
device-only on a resident batch, INTERLEAVING the two kernels inside
each trial: ambient throughput on a shared/tunnelled chip swings
1.4-4x minute-to-minute, so only same-window ratios mean anything
(BASELINE.md "Ambient windows"). The per-cell verdict is the median of
per-trial ratios, with every sample printed.

Usage: python tools/kernel_probe.py [--k 8] [--B 8192]
       [--L 48,64] [--dedup device,host] [--steps 100] [--trials 5]
Prints one JSON object: per-cell rates, ratios, winner, and whether
auto (the shipped matrix) agrees with the measurement.
"""

import argparse
import json
import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def time_kernel(step, make_state, args, steps):
    """One timed burst of the donated-step loop; returns examples/sec.
    ``make_state`` builds FRESH table/acc each burst — the step donates
    its state buffers, so a shared pair would be deleted after the
    first burst."""
    import jax
    B = args["labels"].shape[0]
    t, a = make_state()
    for _ in range(3):  # warm (compile is cached from the prior burst)
        t, a, _, _ = step(t, a, **args)
    jax.block_until_ready((t, a))
    t0 = time.perf_counter()
    for _ in range(steps):
        t, a, _, _ = step(t, a, **args)
    jax.block_until_ready((t, a))
    return steps * B / (time.perf_counter() - t0)


def probe_cell(L, dedup, k, B, steps, trials):
    """Median-of-trials interleaved A/B for one (L, dedup) cell."""
    import dataclasses

    import jax
    from bench import synth_lines
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.pipeline import batch_iterator
    from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                         init_accumulator, init_table,
                                         make_train_step)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "probe.txt")
        with open(path, "w") as fh:
            fh.write("\n".join(synth_lines(B, 1 << 20)) + "\n")
        cfg = FmConfig(vocabulary_size=1 << 20, factor_num=k,
                       batch_size=B, max_features_per_example=L,
                       bucket_ladder=(L,), train_files=(path,),
                       dedup=dedup, shuffle=False)
        spec = ModelSpec.from_config(cfg)
        raw = spec.dedup == "device"
        batch = next(batch_iterator(cfg, cfg.train_files, training=True,
                                    raw_ids=raw))
    args = {k_: (jax.device_put(v) if v is not None else None)
            for k_, v in batch_args(batch).items()}

    def make_state():
        return init_table(cfg, 0), init_accumulator(cfg)

    steps_by = {kern: make_train_step(
        dataclasses.replace(spec, kernel=kern))
        for kern in ("pallas", "xla")}
    samples = {"pallas": [], "xla": []}
    for _ in range(trials):
        for kern in ("pallas", "xla"):  # interleaved: same window
            samples[kern].append(
                time_kernel(steps_by[kern], make_state, args, steps))
    med = {kern: statistics.median(v) for kern, v in samples.items()}
    # Verdict = median of PER-TRIAL ratios: each trial's pallas/xla
    # pair ran back-to-back in one ambient window, so its ratio is
    # comparable even when absolute rates swing 1.4-4x between trials;
    # a ratio of medians would mix windows.
    ratios = [p / x for p, x in zip(samples["pallas"], samples["xla"])]
    med_ratio = statistics.median(ratios)
    from fast_tffm_tpu.ops.kernel_choice import auto_kernel
    winner = "pallas" if med_ratio >= 1.0 else "xla"
    return {"L": L, "dedup": spec.dedup, "k": k, "B": B,
            "pallas": round(med["pallas"], 1),
            "xla": round(med["xla"], 1),
            "pallas_trials": [round(v, 1) for v in samples["pallas"]],
            "xla_trials": [round(v, 1) for v in samples["xla"]],
            "trial_ratios": [round(r, 3) for r in ratios],
            "ratio_pallas_over_xla": round(med_ratio, 3),
            "winner": winner,
            "auto_picks": auto_kernel(spec.dedup, L),
            "auto_agrees": auto_kernel(spec.dedup, L) == winner}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--B", type=int, default=8192)
    ap.add_argument("--L", default="48,64")
    ap.add_argument("--dedup", default="device,host")
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--trials", type=int, default=5)
    args = ap.parse_args()
    import jax
    cells = [probe_cell(L, dd, args.k, args.B, args.steps, args.trials)
             for L in (int(x) for x in args.L.split(","))
             for dd in args.dedup.split(",")]
    print(json.dumps({"backend": jax.default_backend(),
                      "cells": cells,
                      "all_auto_agree": all(c["auto_agrees"]
                                            for c in cells)}))


if __name__ == "__main__":
    main()
