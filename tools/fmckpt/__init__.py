"""fmckpt — inspect, verify, and garbage-collect a model's checkpoint
directory (README "Checkpoint integrity & fallback").

    python -m tools.fmckpt ls <model_file | dir.ckpt>
    python -m tools.fmckpt verify <path> [--mode size|full] [--step N]
    python -m tools.fmckpt publish <path> <step> [--mode size|full]
                                   [--canary]
    python -m tools.fmckpt gc <path> [--dry-run]

The offline view of the invariants ``fast_tffm_tpu/checkpoint.py``
enforces at run time:

- ``ls``      one row per committed step — file count, bytes, the
              manifest's epoch/vocab echo (epoch-override sidecars
              applied, exactly as restore would) — plus every
              quarantined ``corrupt-*`` dir and orphaned sidecar.
- ``verify``  run the manifest integrity check over every step (or one
              ``--step``): per-file sizes, plus a full crc32 re-hash
              under ``--mode full`` (the default here — an offline
              audit can afford to read the bytes; the in-run default
              is the cheap ``size`` pass). Steps predating manifests
              report UNVERIFIABLE, not FAIL. Exit 1 on any failure.
              Read-only: unlike restore, the tool never quarantines —
              the operator decides.
- ``publish`` verify a committed step, then atomically repoint the
              ``published`` pointer file at it — the manual operator
              path onto the same verify-then-repoint sequence the
              stream trainer's publish loop runs, and the signal a
              serving process's hot-reload poll watches (README
              "Serving"). A step that is missing or fails verification
              leaves the pointer untouched (exit 1): the pointer must
              only ever name verified bytes. ``ls`` shows the result
              as the PUBLISHED mark. ``--canary`` repoints the
              ``published-canary`` pointer instead — the step a
              fleet's canary replica scores (README "Serving fleet");
              promote it by re-running publish without the flag,
              roll back by publishing the previous step.
- ``gc``      reclaim space: delete quarantined ``corrupt-*`` dirs and
              orphaned ``manifest-*``/``epoch_override-*`` sidecars
              whose step no longer exists. This is the ONE sanctioned
              deletion path for quarantined state (run code only ever
              renames — fmlint R005 enforces it); ``--dry-run`` lists
              without deleting. Committed step dirs are never touched.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from fast_tffm_tpu.checkpoint import (QUARANTINE_PREFIX, list_step_dirs,
                                      read_canary, read_epoch_override,
                                      read_manifest, read_published,
                                      sidecar_step, verify_step_dir,
                                      vocab_sidecar_path, watermark_path)


def resolve_ckpt_dir(path: str) -> str:
    """Accept a ``model_file`` prefix (the config value) or the
    ``.ckpt`` directory itself."""
    p = os.path.abspath(path)
    if os.path.isdir(p) and p.endswith(".ckpt"):
        return p
    if os.path.isdir(p + ".ckpt"):
        return p + ".ckpt"
    raise FileNotFoundError(
        f"no checkpoint directory at {p} or {p}.ckpt "
        "(pass the config's model_file, or the .ckpt dir itself)")


def _walk_size(d: str) -> Dict[str, int]:
    files = 0
    size = 0
    for root, _dirs, names in os.walk(d):
        for name in names:
            files += 1
            try:
                size += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return {"files": files, "bytes": size}


def scan(directory: str) -> Dict[str, object]:
    """Everything ``ls``/``gc`` need in one pass: committed steps (with
    manifest echo + sidecar-corrected epoch), quarantined dirs, and
    orphaned sidecars whose step no longer exists."""
    steps: List[Dict[str, object]] = []
    for s in list_step_dirs(directory):
        info = _walk_size(os.path.join(directory, str(s)))
        man = None
        try:
            man = read_manifest(directory, s)
        except ValueError:
            pass  # garbled manifest: reported by verify, listed here
        epoch = man.get("epoch") if man else None
        override = read_epoch_override(directory, s)
        steps.append({
            "step": s, "files": info["files"], "bytes": info["bytes"],
            "manifest": man is not None,
            "epoch": override if override is not None else epoch,
            "vocab": man.get("vocab") if man else None,
            # Stream runs leave a watermark sidecar per step; ls flags
            # it so an operator can see which steps can resume the
            # stream position. Existence only — parsing every sidecar
            # just for a flag would make a plain ls read (and warn on)
            # payloads it doesn't need.
            "watermark": os.path.exists(watermark_path(directory, s)),
            # Admit-mode runs leave a vocab admission sidecar per step
            # (slot map + sketch); ls flags which steps carry one —
            # existence only, like the watermark (verify owns the crc).
            "vocab_sidecar": os.path.exists(
                vocab_sidecar_path(directory, s)),
        })
    quarantined: List[Dict[str, object]] = []
    orphans: List[str] = []
    kept = {s["step"] for s in steps}
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        names = []
    for name in names:
        full = os.path.join(directory, name)
        if name.startswith(QUARANTINE_PREFIX) and os.path.isdir(full):
            quarantined.append({"name": name, **_walk_size(full)})
            continue
        # checkpoint.py's SIDECAR_RE, via the shared helper: the scan
        # must agree with the run-time orphan pruning on what a
        # sidecar is (includes a killed writer's manifest .tmp litter).
        s = sidecar_step(name)
        if s is not None and s not in kept:
            orphans.append(name)
    return {"directory": directory, "steps": steps,
            "quarantined": quarantined, "orphans": orphans,
            # Stream-mode publish pointer (README "Streaming / online
            # learning"): the step a scorer should be serving.
            "published": read_published(directory),
            # Canary pointer (README "Serving fleet"): the step the
            # fleet's canary replica scores; None when no canary
            # publish happened (the canary replica then follows
            # ``published``, via checkpoint.read_pointer's fallback).
            "canary": read_canary(directory)}


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return (f"{n:.1f} {unit}" if unit != "B" else f"{n} B")
        n /= 1024.0
    return f"{n} B"


def cmd_ls(directory: str, as_json: bool = False, out=None) -> int:
    import sys
    out = out or sys.stdout
    state = scan(directory)
    if as_json:
        out.write(json.dumps(state) + "\n")
        return 0
    out.write(f"checkpoint dir: {directory}\n")
    if not state["steps"]:
        out.write("  no committed steps\n")
    for s in state["steps"]:
        man = "manifest" if s["manifest"] else "NO MANIFEST (legacy)"
        epoch = "?" if s["epoch"] is None else s["epoch"]
        vocab = "?" if s["vocab"] is None else s["vocab"]
        marks = ""
        if s.get("watermark"):
            marks += " +watermark"
        if s.get("vocab_sidecar"):
            marks += " +VOCAB"
        if state.get("published") == s["step"]:
            marks += "  PUBLISHED"
        if state.get("canary") == s["step"]:
            marks += "  CANARY"
        out.write(f"  step {s['step']:<10} {s['files']:>4} files "
                  f"{_fmt_bytes(s['bytes']):>10}  epoch={epoch} "
                  f"vocab={vocab}  {man}{marks}\n")
    if (state.get("published") is not None
            and state["published"] not in {s["step"]
                                           for s in state["steps"]}):
        out.write(f"  published -> step {state['published']} "
                  "(MISSING: the pointed-at step is gone — GC'd or "
                  "quarantined since the publish)\n")
    if (state.get("canary") is not None
            and state["canary"] not in {s["step"]
                                        for s in state["steps"]}):
        out.write(f"  published-canary -> step {state['canary']} "
                  "(MISSING: the pointed-at step is gone — the canary "
                  "replica falls back to the published step)\n")
    for q in state["quarantined"]:
        out.write(f"  {q['name']:<15} {q['files']:>4} files "
                  f"{_fmt_bytes(q['bytes']):>10}  QUARANTINED "
                  "(reclaim with: fmckpt gc)\n")
    for o in state["orphans"]:
        out.write(f"  {o}  ORPHANED sidecar (its step is gone)\n")
    return 0


def _verify_vocab_sidecar(directory: str, step: int):
    """(note, failed) for a step's vocab admission sidecar: absent ->
    ("", False); readable with a matching embedded crc32 -> a "+vocab
    crc OK" note; unreadable gzip/json or a crc mismatch -> a FAIL
    reason (an admit-mode resume/serve load would otherwise fall back
    to fresh admission state — the operator should know the sidecar is
    torn BEFORE pointing a scorer at the step). The decision itself is
    checkpoint.load_vocab_sidecar — the ONE reader restore shares."""
    from fast_tffm_tpu.checkpoint import load_vocab_sidecar
    payload, reason = load_vocab_sidecar(directory, step)
    if reason is not None:
        return reason, True
    if payload is None:
        return "", False  # absent (every fixed-mode step)
    return ", +vocab crc OK", False


def cmd_verify(directory: str, mode: str = "full",
               step: Optional[int] = None, out=None) -> int:
    import sys
    out = out or sys.stdout
    committed = list_step_dirs(directory)
    if step is not None:
        if step not in committed:
            # A typo'd or already-quarantined step must not read as
            # "UNVERIFIABLE, restore accepts it" — restore would fail.
            out.write(f"step {step}: MISSING — not a committed step "
                      f"(committed: {committed or 'none'})\n")
            return 1
        steps = [step]
    else:
        steps = committed
    if not steps:
        out.write(f"{directory}: no committed steps to verify\n")
        return 0
    failures = 0
    for s in steps:
        try:
            man = read_manifest(directory, s)
        except ValueError:
            man = "garbled"
        vocab_note, vocab_fail = _verify_vocab_sidecar(directory, s)
        if man is None:
            out.write(f"step {s}: UNVERIFIABLE (predates manifests; "
                      "restore accepts it as-is)\n")
            if vocab_fail:
                failures += 1
                out.write(f"step {s}: FAIL — {vocab_note}\n")
            continue
        reason = verify_step_dir(directory, s, mode)
        if reason is None and vocab_fail:
            reason = vocab_note
        if reason is None:
            n = len(man["files"]) if isinstance(man, dict) else "?"
            out.write(f"step {s}: OK ({mode} check, {n} files"
                      f"{vocab_note})\n")
        else:
            failures += 1
            out.write(f"step {s}: FAIL — {reason}\n")
    if failures:
        out.write(f"fmckpt: {failures} step(s) failed verification; "
                  "restore would quarantine and fall back\n")
    return 1 if failures else 0


def cmd_publish(directory: str, step: int, mode: str = "size",
                canary: bool = False, out=None) -> int:
    """Verify-then-repoint (the operator half of the publish
    contract): the pointer moves ONLY when the step exists and passes
    the manifest check at ``mode`` — the same gate
    ``CheckpointState.publish_step`` applies, via the same shared
    atomic-rename write, so a serving process's concurrent reload poll
    can never read a torn or unverified value. ``canary=True`` moves
    the ``published-canary`` pointer instead (the fleet's canary
    replica; README "Serving fleet") — the verification gate is
    identical, a canary must never score unverified bytes either."""
    import sys
    out = out or sys.stdout
    committed = list_step_dirs(directory)
    if step not in committed:
        out.write(f"step {step}: MISSING — not a committed step "
                  f"(committed: {committed or 'none'}); pointer "
                  "untouched\n")
        return 1
    reason = verify_step_dir(directory, step, mode)
    if reason is None:
        # A torn vocab sidecar fails the publish too: every admit-mode
        # reload of the step would raise (the fleet serves stale
        # forever) — same gate cmd_verify applies.
        vocab_note, vocab_fail = _verify_vocab_sidecar(directory, step)
        if vocab_fail:
            reason = vocab_note
    if reason is not None:
        out.write(f"step {step}: FAIL — {reason}; pointer untouched\n")
        return 1
    from fast_tffm_tpu.checkpoint import write_canary, write_published
    if canary:
        prev = read_canary(directory)
        path = write_canary(directory, step)
        label = "published-canary"
    else:
        prev = read_published(directory)
        path = write_published(directory, step)
        label = "published"
    frm = f"step {prev} -> " if prev is not None else ""
    out.write(f"{label} {frm}step {step} ({mode}-verified) -> "
              f"{path}\n")
    return 0


def cmd_gc(directory: str, dry_run: bool = False, out=None) -> int:
    import shutil
    import sys
    out = out or sys.stdout
    state = scan(directory)
    reclaimed = 0
    for q in state["quarantined"]:
        full = os.path.join(directory, q["name"])
        if dry_run:
            out.write(f"would delete {full} ({_fmt_bytes(q['bytes'])})\n")
        else:
            # fmlint: disable=R005 -- fmckpt gc IS the sanctioned
            # operator deletion path for quarantined checkpoint dirs
            shutil.rmtree(full, ignore_errors=True)
            out.write(f"deleted {full} ({_fmt_bytes(q['bytes'])})\n")
        reclaimed += int(q["bytes"])
    for o in state["orphans"]:
        full = os.path.join(directory, o)
        if dry_run:
            out.write(f"would delete orphaned sidecar {full}\n")
        else:
            try:
                # fmlint: disable=R005 -- orphaned sidecars whose step
                # is gone; fmckpt gc is the sanctioned cleanup path
                os.remove(full)
            except OSError:
                pass
            out.write(f"deleted orphaned sidecar {full}\n")
    verb = "would reclaim" if dry_run else "reclaimed"
    out.write(f"fmckpt gc: {verb} {_fmt_bytes(reclaimed)} across "
              f"{len(state['quarantined'])} quarantined dir(s), "
              f"{len(state['orphans'])} orphaned sidecar(s)\n")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys
    ap = argparse.ArgumentParser(
        prog="fmckpt", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_ls = sub.add_parser("ls", help="list steps / quarantine / orphans")
    p_ls.add_argument("path")
    p_ls.add_argument("--json", action="store_true")
    p_v = sub.add_parser("verify", help="manifest integrity check")
    p_v.add_argument("path")
    p_v.add_argument("--mode", choices=("size", "full"), default="full")
    p_v.add_argument("--step", type=int, default=None)
    p_pub = sub.add_parser(
        "publish", help="verify a step, then atomically repoint the "
                        "published pointer at it")
    p_pub.add_argument("path")
    p_pub.add_argument("step", type=int)
    p_pub.add_argument("--mode", choices=("size", "full"),
                       default="size")
    p_pub.add_argument("--canary", action="store_true",
                       help="repoint the published-canary pointer (the "
                            "fleet's canary replica) instead of "
                            "published")
    p_gc = sub.add_parser("gc", help="delete quarantined dirs + orphans")
    p_gc.add_argument("path")
    p_gc.add_argument("--dry-run", action="store_true")
    args = ap.parse_args(argv)
    try:
        directory = resolve_ckpt_dir(args.path)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if args.cmd == "ls":
        return cmd_ls(directory, as_json=args.json)
    if args.cmd == "verify":
        return cmd_verify(directory, mode=args.mode, step=args.step)
    if args.cmd == "publish":
        return cmd_publish(directory, args.step, mode=args.mode,
                           canary=args.canary)
    return cmd_gc(directory, dry_run=args.dry_run)
