import sys

from tools.fmckpt import main

if __name__ == "__main__":
    sys.exit(main())
