"""Operational tooling: benches, probes, and the fmstat/fmlint/fmtrace
CLIs.

A package (not loose scripts) so `python -m tools.fmstat` /
`python -m tools.fmlint` / `python -m tools.fmtrace` work from the
repo root — the standalone scripts (criteo_bench.py, kernel_probe.py,
offload_smoke.py) still run directly as before.
"""

from typing import List, Sequence


def expand_stream_args(paths: Sequence[str]) -> List[str]:
    """Glob-expand metrics-file CLI args and fail loudly on unreadable
    inputs — the ONE argument policy for the stream-reading CLIs
    (fmstat, fmtrace), so their glob sorting and missing-file behavior
    can't drift apart. read_events itself tolerates only torn final
    lines; a typo'd path must error, not summarize zero events."""
    import glob as globlib

    from fast_tffm_tpu.obs.sink import read_events
    out: List[str] = []
    for p in paths:
        hits = sorted(globlib.glob(p))
        out.extend(hits if hits else [p])
    for f in out:
        next(iter(read_events(f)), None)
    return out
