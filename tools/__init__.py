"""Operational tooling: benches, probes, and the fmstat/fmlint CLIs.

A package (not loose scripts) so `python -m tools.fmstat` /
`python -m tools.fmlint` work from the repo root — the standalone
scripts (criteo_bench.py, kernel_probe.py, offload_smoke.py) still run
directly as before.
"""
