"""BASELINE config #1 measurement: train->predict->AUC on the 1M-row
Criteo-Kaggle-like sample (data/synth.py), on whatever device is present
(the real TPU chip under the driver).

Runs the real CLI end to end, measures wall-clock training throughput
and score-file test AUC, trains the independent NumPy SGD-FM oracle on
the same data, and prints one JSON blob to record in BASELINE.md.

Usage: python tools/criteo_bench.py [n_train] [n_test]
       [--seed 17] [--k 8] [--lr 0.05]

``--seed`` regenerates the dataset from a different generative draw and
``--k/--lr`` move the model to a different operating point — both with
the oracle re-trained at MATCHED settings, so parity can be pinned at
more than the single (seed, hyperparameter) pair it was first recorded
at (round-4 review: one matching pair could be a coincidence).
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _write_cli_cfg(path, tmp, train, test, *, vocab, k, lr, epochs,
                   lam, batch_size, mfpe, name, general_extra=""):
    """The ONE CLI config template both parity legs (FM and FFM) fill
    in — a schema change edits one string, not per-leg copies."""
    with open(path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = {vocab}
factor_num = {k}
{general_extra}
model_file = {tmp}/model/{name}
log_file = {tmp}/log/{name}.log

[Train]
train_files = {train}
epoch_num = {epochs}
batch_size = {batch_size}
learning_rate = {lr}
factor_lambda = {lam}
bias_lambda = {lam}
init_value_range = 0.01
loss_type = logistic
max_features_per_example = {mfpe}
bucket_ladder = {mfpe}
shuffle = False

[Predict]
predict_files = {test}
score_path = {tmp}/score
""")


def main(n_train: int = 1_000_000, n_test: int = 100_000,
         seed: int = 17, k: int = None, lr: float = 0.05,
         model: str = "fm", order: int = 2) -> None:
    if order not in (2, 3):
        # fail BEFORE the multi-minute framework leg: the oracle only
        # implements orders 2 and 3
        raise SystemExit(f"--order must be 2 or 3, got {order}")
    if model == "ffm":
        if order != 2:
            raise SystemExit("--model ffm supports order 2 only "
                             "(field-aware FM is pairwise by "
                             "definition); drop --order")
        return main_ffm(n_train, n_test, seed=seed,
                        k=(4 if k is None else k), lr=lr)
    k = 8 if k is None else k
    import run_tffm
    from fast_tffm_tpu.data import synth
    from fast_tffm_tpu.metrics import exact_auc

    vocab = 1 << 22
    epochs, lam = 2, 1e-6
    with tempfile.TemporaryDirectory() as tmp:
        train = os.path.join(tmp, "train.txt")
        test = os.path.join(tmp, "test.txt")
        t0 = time.time()
        meta = synth.write_dataset(train, test, n_train, n_test, seed=seed)
        gen_sec = time.time() - t0

        cfg_path = os.path.join(tmp, "ck.cfg")
        extra = "hash_feature_id = True"
        if order != 2:
            extra += f"\norder = {order}"
        _write_cli_cfg(cfg_path, tmp, train, test, vocab=vocab, k=k,
                       lr=lr, epochs=epochs, lam=lam, batch_size=8192,
                       mfpe=48, name="ck", general_extra=extra)
        t0 = time.time()
        if run_tffm.main(["train", cfg_path]) != 0:
            raise SystemExit("train failed; not recording metrics")
        train_sec = time.time() - t0
        t0 = time.time()
        if run_tffm.main(["predict", cfg_path]) != 0:
            raise SystemExit("predict failed; not recording metrics")
        predict_sec = time.time() - t0

        scores = np.loadtxt(os.path.join(tmp, "score", "test.txt.score"))
        labels = np.loadtxt(test, usecols=0)
        fw_auc = exact_auc(scores, labels)

        # Independent oracle: SAME data, SAME batch size/hyperparameters
        # (a mismatched batch size changes the step count and therefore
        # Adagrad progress — the first run of this tool showed exactly
        # that confound). Minutes of numpy time, once per round.
        t0 = time.time()
        tr = synth.parse_file_blocks(train, vocab, 8192)
        te = synth.parse_file_blocks(test, vocab, 8192)
        oracle_auc = exact_auc(
            synth.numpy_fm_train_predict(tr, te, vocab, k=k, lr=lr,
                                         epochs=epochs, factor_lambda=lam,
                                         bias_lambda=lam, order=order),
            labels)
        oracle_sec = time.time() - t0

    print(json.dumps({
        "config": ("baseline#1 criteo-kaggle-like" if order == 2
                   else "baseline#4 order-3 criteo-kaggle-like"),
        "seed": seed, "k": k, "lr": lr, "order": order,
        "n_train": n_train, "n_test": n_test, "epochs": epochs,
        "gen_sec": round(gen_sec, 1),
        "train_sec": round(train_sec, 1),
        "train_examples_per_sec": round(n_train * epochs / train_sec, 1),
        "predict_sec": round(predict_sec, 1),
        "test_auc": round(fw_auc, 4),
        "oracle_auc": round(oracle_auc, 4),
        "oracle_sec": round(oracle_sec, 1),
        "bayes_auc": round(meta["bayes_auc"], 4),
        "positive_rate": round(meta["positive_rate_test"], 4),
    }))


def main_ffm(n_train: int, n_test: int, seed: int = 17, k: int = 4,
             lr: float = 0.05) -> None:
    """BASELINE config #3's AUC-parity leg: Avazu-like field-aware data
    with a KNOWN field-aware generative model, the real CLI FFM
    train→predict, and the independent NumPy FFM-SGD oracle at matched
    hyperparameters (synth.numpy_ffm_train_predict — hand-derived
    field-aware gradients, no shared model code)."""
    import run_tffm
    from fast_tffm_tpu.data import synth
    from fast_tffm_tpu.metrics import exact_auc

    F = len(synth.FFM_FIELDS)
    vocab = synth.ffm_vocab_size()
    B, epochs, lam = 4096, 2, 1e-6
    with tempfile.TemporaryDirectory() as tmp:
        train = os.path.join(tmp, "train.txt")
        test = os.path.join(tmp, "test.txt")
        t0 = time.time()
        meta = synth.write_ffm_dataset(train, test, n_train, n_test,
                                       seed=seed)
        gen_sec = time.time() - t0

        cfg_path = os.path.join(tmp, "ck_ffm.cfg")
        _write_cli_cfg(cfg_path, tmp, train, test, vocab=vocab, k=k,
                       lr=lr, epochs=epochs, lam=lam, batch_size=B,
                       mfpe=F, name="ckffm",
                       general_extra=("model_type = ffm\n"
                                      f"field_num = {F}"))
        t0 = time.time()
        if run_tffm.main(["train", cfg_path]) != 0:
            raise SystemExit("ffm train failed; not recording metrics")
        train_sec = time.time() - t0
        t0 = time.time()
        if run_tffm.main(["predict", cfg_path]) != 0:
            raise SystemExit("ffm predict failed; not recording metrics")
        predict_sec = time.time() - t0

        scores = np.loadtxt(os.path.join(tmp, "score", "test.txt.score"))
        labels = np.loadtxt(test, usecols=0)
        fw_auc = exact_auc(scores, labels)

        t0 = time.time()
        tr = synth.parse_ffm_file(train, B)
        te = synth.parse_ffm_file(test, B)
        oracle_auc = exact_auc(
            synth.numpy_ffm_train_predict(tr, te, vocab, k=k, lr=lr,
                                          epochs=epochs,
                                          factor_lambda=lam,
                                          bias_lambda=lam),
            labels)
        oracle_sec = time.time() - t0

    print(json.dumps({
        "config": "baseline#3 avazu-like ffm",
        "seed": seed, "k": k, "lr": lr, "field_num": F,
        "n_train": n_train, "n_test": n_test, "epochs": epochs,
        "gen_sec": round(gen_sec, 1),
        "train_sec": round(train_sec, 1),
        "train_examples_per_sec": round(n_train * epochs / train_sec, 1),
        "predict_sec": round(predict_sec, 1),
        "test_auc": round(fw_auc, 4),
        "oracle_auc": round(oracle_auc, 4),
        "oracle_sec": round(oracle_sec, 1),
        "bayes_auc": round(meta["bayes_auc"], 4),
        "positive_rate": round(meta["positive_rate_test"], 4),
    }))


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("n_train", type=int, nargs="?", default=1_000_000)
    ap.add_argument("n_test", type=int, nargs="?", default=100_000)
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--k", type=int, default=None,
                    help="latent dim (default: 8 for fm, 4 for ffm)")
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--model", choices=("fm", "ffm"), default="fm")
    ap.add_argument("--order", type=int, choices=(2, 3), default=2,
                    help="FM interaction order (fm model only)")
    a = ap.parse_args()
    main(a.n_train, a.n_test, seed=a.seed, k=a.k, lr=a.lr,
         model=a.model, order=a.order)
