"""fmchaos — end-to-end fault-injection soak scenarios for the data
plane (README "Fault tolerance").

    python -m tools.fmchaos               # run every scenario
    python -m tools.fmchaos skip preempt-resume
    python -m tools.fmchaos --list
    make chaos                            # the CI target (CPU)

Each scenario builds a tiny synthetic corpus, runs a REAL training job
through ``fast_tffm_tpu.train.train`` under one injected fault
(``fast_tffm_tpu/testing/faults.py`` — all deterministic/seeded), and
asserts the documented recovery behavior:

- ``skip``            0.5% corrupt lines + ``bad_line_policy = skip``
                      → trains to completion; the skip count equals
                      the injected corruption exactly.
- ``quarantine``      same corpus, 2 epochs → quarantine sidecar holds
                      each bad line ONCE (file/lineno/raw), while the
                      skip counter counts both epochs' passes.
- ``max-bad``         10% corruption trips the ``max_bad_fraction``
                      breaker → the run aborts naming the worst file.
- ``flaky-open``      the first 2 opens of the train file raise EIO →
                      the retry/backoff layer absorbs them; retry
                      counters land in the metrics stream.
- ``flaky-open-parallel`` the same transient-open fault soaked under
                      ``host_threads = 4`` (the parallel data plane):
                      retries absorb identically, the run's metrics
                      prove the worker pool actually ran, AND a
                      10%-corrupt quarantine run through the parallel
                      plane trips the ``max_bad_fraction`` breaker
                      exactly once naming the worst file — with no
                      ``fm-build`` worker threads leaked after the
                      abort.
- ``serve-soak``      the online serving subsystem under concurrency
                      and a hot reload: 4 client threads fire
                      variable-size requests at a live ScorerServer
                      while `fmckpt publish` repoints the pointer →
                      responses land on BOTH steps, every one
                      bit-identical to batch predict against the step
                      that scored it, fmstat's SERVING section shows
                      the p50/p99 latency histograms with served ==
                      published at close, and no fm-serve thread
                      survives close().
- ``kill-replica-midburst`` the serving FLEET under fire (README
                      "Serving fleet"): 3 supervised replica processes
                      behind the failover proxy take a 4-thread
                      request burst while one replica is SIGKILLed
                      mid-flush → ZERO client-visible failures (the
                      proxy retries on a different ready replica),
                      every response bit-identical to batch predict
                      per its step tag, a mid-incident fmstat
                      snapshot reads FLEET DEGRADED (2/3 ready), the
                      supervisor respawns the victim under backoff
                      back to OK, and client p99 holds the [SLO]
                      bound.
- ``staggered-reload`` a fleet-wide hot reload under load: `fmckpt
                      publish` repoints the pointer while clients
                      fire through the proxy → the supervisor
                      staggers the reload so a high-rate sampler on
                      the proxy's /healthz NEVER sees ready == 0,
                      responses land on both steps, and none is torn
                      (byte parity against batch predict per step).
- ``preempt-resume``  SIGTERM mid-epoch → the run saves and exits
                      cleanly, ``fmstat`` reports PREEMPTED (not
                      CRASHED); a restart resumes the interrupted
                      epoch schedule and finishes OK.
- ``stream-soak``     run_mode = stream against a LIVE writer
                      injecting torn writes, plus flaky opens and one
                      mid-stream SIGTERM+resume → every sealed line is
                      consumed exactly once (final table BIT-IDENTICAL
                      to a clean single-pass control run over the same
                      sealed corpus) and >= 2 ``published`` pointer
                      flips land on manifest-verified steps.
- ``slo-soak``        the FULL closed loop under SLOs (README "SLOs &
                      quality gate"): a live writer feeds the stream,
                      a gated trainer (``publish_min_auc``) publishes
                      on interval, and a ScorerServer serves a
                      concurrent request load against the moving
                      pointer; a label-flipped poison burst must be
                      caught by the publish gate (pointer pinned to
                      the last good step, ``health: gate_held``,
                      fmstat GATE-HELD, serving uninterrupted), clean
                      data heals the loop, and at the end every
                      declared SLO passes: publish staleness, serve
                      p99, exactly-once consumption, min AUC, and
                      per-step bit-parity of every response against
                      an offline predict control snapshot.
- ``stream-truncate`` an in-progress (unsealed) stream file SHRINKS
                      under the reader → the (inode, size) regression
                      is quarantined through the BadLineTracker, the
                      run survives and finishes the successor shard,
                      breaker accounting exact.
- ``vocab-churn``     unbounded-vocabulary admission under stream
                      churn (``vocab_mode = admit``): a heavy-tailed
                      hashed-id stream (distinct ids >= 10x the
                      physical table) through a mid-run SIGTERM and a
                      checkpoint walk-back → admission state
                      round-trips bit-exactly, the slot map stays
                      bounded at vocabulary_size rows, cold-gone hot
                      ids are EVICTED at barriers, and the published
                      step serves evicted ids from the shared cold
                      row (bit-identical to a never-seen id), never
                      their stale embeddings.
- ``truncate-latest`` the newest checkpoint step is torn (truncated
                      array file) → with ``ckpt_verify = size`` the
                      restart quarantines it (``corrupt-<step>``,
                      never deleted), resumes from the previous step
                      with the correct epoch, emits
                      ``health: ckpt_fallback``, ``fmstat`` reports
                      ``OK (ckpt fallback x1)`` — and trains to the
                      SAME final table as a clean resume from that
                      step.
- ``kill-async-save`` SIGKILL a real training child mid-async-save
                      burst → the restart restores a committed step
                      cleanly (verified restore; orbax's atomic commit
                      plus the manifest check hide/catch any torn
                      state) and completes OK.
- ``kill-worker-midwindow`` SIGKILL one of 2 lockstep workers mid-run.
                      With ``elastic = shrink`` the survivor raises
                      the worker_lost diagnosis naming the dead
                      process within the collective deadline, reforms
                      a 1-worker cluster, restores the last verified
                      checkpoint, re-shards the input so every shard
                      of the recovered pass is consumed exactly once
                      (pinned by final step arithmetic), finishes the
                      schedule, and ``fmstat`` reports
                      ``DEGRADED (1 worker lost)``. With
                      ``elastic = off`` the survivor fails FAST with
                      the same named diagnosis instead of hanging.
- ``hang-worker``     SIGSTOP one of 2 lockstep workers: the deadline
                      guard expires, the diagnosis names the stopped
                      process (it stopped heartbeating without dying),
                      and the survivor exits with WorkerLostError —
                      never an indefinite hang.
- ``kill-then-grow``  the full elastic heal (``elastic = grow``): a
                      2-worker stream job loses worker 1 to SIGKILL,
                      the survivor shrinks and keeps training, a
                      ``run_tffm.py train <cfg> --join`` replacement
                      is admitted at the next publish settle, and the
                      run finishes at FULL membership — exactly-once
                      consumption summed across the dead worker's and
                      the joiner's metrics shards, final table
                      BIT-IDENTICAL to an uninterrupted 2-worker
                      control, fmstat RECOVERED (gen 2, 2 workers),
                      lease dir swept to current-generation files.
- ``grow-joiner-dies`` a joiner SIGKILLed mid-rendezvous (announced,
                      not yet committed) never wedges the incumbents:
                      the settle window expires, the dead joiner's
                      stale lease drops it, the reform commits
                      without it, and training finishes cleanly.
- ``predict-flaky``   the cross-file streaming scorer under faults:
                      flaky opens on the first predict file plus one
                      corrupt file mid-sweep with ``bad_line_policy =
                      quarantine`` → the sweep completes, every OTHER
                      file's scores are BIT-IDENTICAL to a fault-free
                      sweep, the corrupt file's score file stays
                      line-aligned (bad lines score as zero-feature
                      examples), the quarantine sidecar names each
                      injected line, and no writer/fetcher/build
                      threads leak.

The scenario functions are plain callables (workdir in, asserts
inside) so tests/test_chaos.py runs the same soaks under tier-1; the
CLI adds CPU forcing and PASS/FAIL reporting.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, List

import numpy as np


def _write_corpus(path: str, n: int, seed: int,
                  vocab: int = 200, informative: int = 6) -> None:
    """Separable synthetic libsvm corpus (the e2e smoke shape): label-1
    examples prefer ids [0, informative), label-0 prefer the next
    block; a few noise features with float values exercise value
    parsing."""
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        y = int(rng.integers(0, 2))
        base = 0 if y else informative
        feats = {int(base + rng.integers(0, informative)): 1.0,
                 int(base + rng.integers(0, informative)): 1.0}
        for _ in range(3):
            feats[int(rng.integers(2 * informative, vocab))] = round(
                float(rng.uniform(0.5, 1.5)), 3)
        toks = " ".join(f"{i}:{v}" for i, v in sorted(feats.items()))
        lines.append(f"{y} {toks}")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")


def _cfg(workdir: str, train_file: str, **overrides):
    from fast_tffm_tpu.config import FmConfig
    base = dict(
        vocabulary_size=200, factor_num=4, batch_size=32, epoch_num=1,
        learning_rate=0.1, shuffle=True, seed=0, log_steps=0,
        train_files=(train_file,),
        model_file=os.path.join(workdir, "model", "fm"),
        log_file=os.path.join(workdir, "chaos.log"),
        metrics_file=os.path.join(workdir, "metrics.jsonl"),
        metrics_flush_steps=5, io_backoff_seconds=0.01)
    base.update(overrides)
    return FmConfig(**base)


def _summary(cfg):
    from fast_tffm_tpu.obs.attribution import summarize
    return summarize([cfg.metrics_file])


def _counters(cfg) -> dict:
    return _summary(cfg).get("counters", {})


def _verdict(cfg) -> str:
    from fast_tffm_tpu.obs.attribution import health_verdict
    return health_verdict(_summary(cfg))["verdict"]


# --- scenarios -----------------------------------------------------------


def scenario_skip(workdir: str, seed: int = 0) -> str:
    """0.5% corrupt lines, policy=skip: completes; counts pin exactly."""
    from fast_tffm_tpu.testing.faults import corrupt_corpus
    from fast_tffm_tpu.train import train
    clean = os.path.join(workdir, "clean.txt")
    dirty = os.path.join(workdir, "train_skip.txt")
    _write_corpus(clean, 400, seed)
    bad = corrupt_corpus(clean, dirty, fraction=0.005, seed=seed)
    cfg = _cfg(workdir, dirty, bad_line_policy="skip")
    train(cfg)
    c = _counters(cfg)
    assert c.get("pipeline/bad_lines") == len(bad), (
        f"skip count {c.get('pipeline/bad_lines')} != injected "
        f"{len(bad)}")
    assert c.get("train/examples") == 400 - len(bad), (
        f"trained examples {c.get('train/examples')} != "
        f"{400 - len(bad)}")
    assert _verdict(cfg) == "OK", _verdict(cfg)
    return (f"skipped {len(bad)}/400 injected bad lines, trained "
            f"{int(c['train/examples'])} examples, verdict OK")


def scenario_quarantine(workdir: str, seed: int = 0) -> str:
    """Quarantine sidecar holds each injected bad line once (dedup
    across 2 epochs) with exact file/lineno/raw provenance."""
    from fast_tffm_tpu.testing.faults import corrupt_corpus
    from fast_tffm_tpu.train import train
    clean = os.path.join(workdir, "clean.txt")
    dirty = os.path.join(workdir, "train_quar.txt")
    _write_corpus(clean, 400, seed)
    bad = corrupt_corpus(clean, dirty, fraction=0.005, seed=seed)
    cfg = _cfg(workdir, dirty, bad_line_policy="quarantine",
               epoch_num=2)
    train(cfg)
    qpath = cfg.metrics_file + ".quarantine"
    with open(qpath) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    dirty_lines = open(dirty).read().splitlines()
    assert sorted(r["lineno"] for r in recs) == [i + 1 for i in bad], (
        f"quarantined linenos {sorted(r['lineno'] for r in recs)} != "
        f"injected {[i + 1 for i in bad]}")
    for r in recs:
        assert r["file"] == dirty
        assert r["raw"] == dirty_lines[r["lineno"] - 1]
        assert r["error"]
    c = _counters(cfg)
    assert c.get("pipeline/bad_lines") == 2 * len(bad)  # both epochs
    return (f"quarantined {len(recs)} line(s) once across 2 epochs "
            f"({int(c['pipeline/bad_lines'])} skips counted) to "
            f"{os.path.basename(qpath)}")


def scenario_max_bad(workdir: str, seed: int = 0) -> str:
    """10% corruption trips the breaker; the error names the file."""
    from fast_tffm_tpu.data.badlines import BadInputError
    from fast_tffm_tpu.testing.faults import corrupt_corpus
    from fast_tffm_tpu.train import train
    clean = os.path.join(workdir, "clean.txt")
    dirty = os.path.join(workdir, "train_rotten.txt")
    _write_corpus(clean, 400, seed)
    corrupt_corpus(clean, dirty, fraction=0.10, seed=seed)
    cfg = _cfg(workdir, dirty, bad_line_policy="skip")
    try:
        train(cfg)
    except BadInputError as e:
        assert dirty in str(e), f"breaker error must name the file: {e}"
        assert "max_bad_fraction" in str(e)
        return f"breaker tripped naming {os.path.basename(dirty)}"
    raise AssertionError("max_bad_fraction breaker never tripped on a "
                         "10%-corrupt corpus")


def scenario_flaky_open(workdir: str, seed: int = 0) -> str:
    """2 transient open failures on the train file: the run completes
    and the retries are visible in the metrics stream."""
    from fast_tffm_tpu.testing.faults import flaky_open
    from fast_tffm_tpu.train import train
    data = os.path.join(workdir, "train_flaky.txt")
    _write_corpus(data, 400, seed)
    cfg = _cfg(workdir, data, io_retries=3)
    with flaky_open(2, match="train_flaky.txt") as state:
        train(cfg)
    assert state["failures"] == 2, state
    c = _counters(cfg)
    assert c.get("io/retries", 0) >= 2, c.get("io/retries")
    assert _verdict(cfg) == "OK", _verdict(cfg)
    return (f"absorbed {state['failures']} injected open failures "
            f"({int(c['io/retries'])} retries in the metrics stream)")


def scenario_flaky_open_parallel(workdir: str, seed: int = 0) -> str:
    """The parallel host data plane under faults (host_threads=4):
    IO retry/backoff and the max_bad_fraction breaker must behave
    exactly as they do serially — retries absorbed, breaker trips
    ONCE naming the worst file — and an aborted run must not leak
    build-worker threads."""
    import threading
    from fast_tffm_tpu.data.badlines import BadInputError
    from fast_tffm_tpu.testing.faults import corrupt_corpus, flaky_open
    from fast_tffm_tpu.train import train

    def leaked_workers():
        return [t.name for t in threading.enumerate()
                if t.name.startswith("fm-build") and t.is_alive()]

    # Part 1: transient opens on the train file, absorbed by the
    # retry layer while the 4-worker plane is driving the reads.
    data = os.path.join(workdir, "train_flaky_par.txt")
    _write_corpus(data, 2000, seed)
    cfg = _cfg(workdir, data, io_retries=3, host_threads=4)
    with flaky_open(2, match="train_flaky_par.txt") as state:
        train(cfg)
    assert state["failures"] == 2, state
    c = _counters(cfg)
    assert c.get("io/retries", 0) >= 2, c.get("io/retries")
    # The pool really ran: per-worker build seconds only exist when
    # groups were built on fm-build threads.
    assert c.get("pipeline/worker_build_seconds", 0) > 0, c
    assert _verdict(cfg) == "OK", _verdict(cfg)
    assert not leaked_workers(), leaked_workers()

    # Part 2: the breaker through the PARALLEL quarantine plane — own
    # metrics file so the counters aren't folded into part 1's run.
    subdir = os.path.join(workdir, "breaker")
    os.makedirs(subdir, exist_ok=True)
    clean = os.path.join(subdir, "clean.txt")
    dirty = os.path.join(subdir, "train_rotten_par.txt")
    _write_corpus(clean, 2000, seed)
    corrupt_corpus(clean, dirty, fraction=0.10, seed=seed)
    cfg2 = _cfg(subdir, dirty, bad_line_policy="quarantine",
                host_threads=4)
    try:
        train(cfg2)
    except BadInputError as e:
        assert dirty in str(e), (
            f"breaker error must name the worst file: {e}")
        assert "max_bad_fraction" in str(e)
        assert str(e).count("aborting:") == 1, str(e)
    else:
        raise AssertionError("max_bad_fraction breaker never tripped "
                             "under the parallel plane")
    assert not leaked_workers(), leaked_workers()
    return ("parallel plane absorbed 2 injected open failures "
            f"({int(c['io/retries'])} retries), breaker tripped once "
            "naming the corrupt file, no fm-build threads leaked")


def scenario_preempt_resume(workdir: str, seed: int = 0) -> str:
    """SIGTERM mid-epoch: clean save-and-exit, fmstat says PREEMPTED;
    a restart resumes the interrupted schedule and finishes OK."""
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.testing.faults import preempt_after_steps
    from fast_tffm_tpu.train import (checkpoint_template,
                                     resume_start_epoch, train)
    data = os.path.join(workdir, "train_preempt.txt")
    _write_corpus(data, 400, seed)
    cfg = _cfg(workdir, data, epoch_num=3)
    # 400/32 -> 13 steps per epoch; step 16 is mid-epoch 1.
    with preempt_after_steps(16) as state:
        train(cfg)
    assert state["fired"], "SIGTERM injector never fired"
    assert _verdict(cfg) == "PREEMPTED", _verdict(cfg)
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    epoch = int(restored["epoch"])
    assert 0 < epoch < cfg.epoch_num, (
        f"preemption checkpoint records {epoch} completed epochs; "
        f"expected mid-schedule (0 < e < {cfg.epoch_num})")
    assert resume_start_epoch(epoch, cfg.epoch_num) == epoch
    # Restart without the fault: resumes and completes the schedule.
    train(cfg)
    log = open(cfg.log_file).read()
    assert "resuming interrupted epoch schedule" in log
    assert _verdict(cfg) == "OK", _verdict(cfg)  # latest run segment
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(restored["epoch"]) == cfg.epoch_num
    return (f"preempted at step {state['steps']} (epoch {epoch} "
            f"recorded), PREEMPTED verdict, resumed to "
            f"{cfg.epoch_num}/{cfg.epoch_num} epochs")


def scenario_truncate_latest(workdir: str, seed: int = 0) -> str:
    """Torn newest checkpoint (the acceptance scenario): with
    ``ckpt_verify = size`` the restart quarantines the truncated step,
    resumes from the previous step with the correct epoch, reports the
    fallback in fmstat — and trains to the SAME final table as a
    control twin that cleanly resumed from that previous step (the
    old by-hand remedy), so the healed run lost nothing but the torn
    step."""
    import shutil
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          QUARANTINE_PREFIX,
                                          list_step_dirs, manifest_path)
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    from fast_tffm_tpu.train import checkpoint_template, train
    workdir = os.path.abspath(workdir)
    data = os.path.join(workdir, "train_trunc.txt")
    _write_corpus(data, 400, seed)
    # Run 1: 400/32 -> 13 steps; periodic saves at 5 and 10, final 13.
    cfg = _cfg(workdir, data, save_steps=5)
    train(cfg)
    ckpt_dir = cfg.model_file + ".ckpt"
    steps = list_step_dirs(ckpt_dir)
    assert steps[-2:] == [10, 13], steps
    # Control twin BEFORE the fault: same run-1 state, newest step
    # removed CLEANLY (the manual remedy this PR automates), so its
    # resume starts from the same step the fallback should pick.
    control = os.path.join(workdir, "control")
    os.makedirs(control, exist_ok=True)
    shutil.copytree(os.path.join(workdir, "model"),
                    os.path.join(control, "model"))
    control_cfg = _cfg(control, data, epoch_num=2)
    control_ckpt_dir = control_cfg.model_file + ".ckpt"
    # fmlint: disable=R005 -- chaos control twin simulates the old
    # BY-HAND remedy (operator deletes the bad step) outside any run
    shutil.rmtree(os.path.join(control_ckpt_dir, "13"))
    for sidecar in (manifest_path(control_ckpt_dir, 13),
                    os.path.join(control_ckpt_dir, "epoch_override-13")):
        if os.path.exists(sidecar):
            # fmlint: disable=R005 -- part of the same simulated
            # by-hand cleanup in the control twin
            os.remove(sidecar)
    # The fault: tear the newest step's largest array file.
    victim = truncate_checkpoint(cfg.model_file, seed=seed)
    assert victim and f"{os.sep}13{os.sep}" in victim, victim
    # Run 2: restart onto the torn state; must self-heal.
    cfg2 = _cfg(workdir, data, epoch_num=2)
    table_fb = np.asarray(train(cfg2))
    log = open(cfg2.log_file).read()
    assert "restored checkpoint at step 10" in log, (
        "fallback run did not resume from the previous intact step")
    quarantined = [n for n in os.listdir(ckpt_dir)
                   if n.startswith(QUARANTINE_PREFIX)]
    assert quarantined == [f"{QUARANTINE_PREFIX}13"], quarantined
    assert 13 not in list_step_dirs(ckpt_dir)
    victim_rel = os.path.relpath(victim, os.path.join(ckpt_dir, "13"))
    assert os.path.exists(os.path.join(ckpt_dir, quarantined[0],
                                       victim_rel)), (
        "quarantine must preserve (not delete) the torn bytes")
    c = _counters(cfg2)
    assert c.get("checkpoint/fallbacks") == 1, c
    assert c.get("checkpoint/quarantined_steps") == 1, c
    assert c.get("checkpoint/saves", 0) >= 4, c
    v = _verdict(cfg2)
    assert v.startswith("OK (ckpt fallback x1"), v
    # Control twin: clean resume from step 10 over the same corpus.
    table_ctl = np.asarray(train(control_cfg))
    assert np.array_equal(table_fb, table_ctl), (
        "fallback resume diverged from a clean resume off the same "
        "step: max |delta| = "
        f"{np.abs(table_fb - table_ctl).max()}")
    # Both twins completed the 2-epoch schedule from step 10.
    ckpt = CheckpointState(cfg2.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg2))
    ckpt.close()
    assert int(restored["step"]) == 10 + 2 * 13
    assert int(restored["epoch"]) == 2
    return (f"quarantined torn step 13 -> {quarantined[0]}, resumed "
            f"from step 10, verdict {v!r}, final table identical to "
            "the clean-resume control")


def scenario_kill_async_save(workdir: str, seed: int = 0) -> str:
    """SIGKILL a real training child while async saves are in flight
    (save_steps=1, ~22 MB state widens the write window): the restart's
    VERIFIED restore must come up cleanly on a committed step — orbax's
    atomic commit hides torn step dirs, the manifest check catches
    anything that slipped through — and complete its schedule."""
    import signal
    import subprocess
    import sys
    import time as _time
    from fast_tffm_tpu.checkpoint import list_step_dirs
    workdir = os.path.abspath(workdir)
    data = os.path.join(workdir, "train_kill.txt")
    _write_corpus(data, 2000, seed)
    model = os.path.join(workdir, "model", "fm")
    cfg_path = os.path.join(workdir, "kill.cfg")
    with open(cfg_path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = 300000
factor_num = 8
model_file = {model}

[Train]
train_files = {data}
epoch_num = 50
batch_size = 32
learning_rate = 0.1
shuffle = False
save_steps = 1
log_steps = 0
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    proc = subprocess.Popen(
        [sys.executable, "run_tffm.py", "train", cfg_path],
        cwd=repo, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    ckpt_dir = model + ".ckpt"
    try:
        # Kill once a second step commits: the NEXT async write is then
        # likely mid-flight. Generous deadline — the child pays
        # interpreter + jax + jit startup on a possibly loaded host.
        deadline = _time.time() + 300
        while _time.time() < deadline:
            if len(list_step_dirs(ckpt_dir)) >= 2:
                break
            _time.sleep(0.02)
        else:
            raise AssertionError(
                "child never committed 2 checkpoint steps")
        killed_at = max(list_step_dirs(ckpt_dir))
        proc.send_signal(signal.SIGKILL)
    finally:
        if proc.poll() is None:  # assertion path: don't leak the child
            proc.kill()
        proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGKILL
    # Restart in-process with verified restore: must come up on a
    # committed step and finish one epoch.
    cfg = _cfg(workdir, data, vocabulary_size=300000, factor_num=8,
               shuffle=False)
    from fast_tffm_tpu.train import train
    train(cfg)
    final_steps = list_step_dirs(ckpt_dir)
    assert final_steps and final_steps[-1] > killed_at, (
        killed_at, final_steps)
    v = _verdict(cfg)
    assert v.startswith("OK"), v
    return (f"SIGKILLed child at committed step {killed_at}; restart "
            f"restored cleanly and finished at step {final_steps[-1]} "
            f"(verdict {v!r})")


def scenario_predict_flaky(workdir: str, seed: int = 0) -> str:
    """ISSUE 10: the cross-file streaming scorer under faults. One
    continuous sweep means one file's damage could in principle smear
    into its neighbors' batches — this pins that it doesn't: flaky
    opens + a quarantined-corrupt file mid-sweep leave every other
    file's scores bit-identical and line-aligned, and the sweep's
    writer/fetcher/build threads all exit."""
    import threading
    from fast_tffm_tpu.predict import predict
    from fast_tffm_tpu.testing.faults import corrupt_corpus, flaky_open
    from fast_tffm_tpu.train import train

    data = os.path.join(workdir, "train.txt")
    _write_corpus(data, 400, seed)
    cfg = _cfg(workdir, data)
    train(cfg)

    preds = []
    for i in range(3):
        p = os.path.join(workdir, f"pred{i}.txt")
        _write_corpus(p, 120, seed + 10 + i)
        preds.append(p)
    dirty_mid = os.path.join(workdir, "pred1_rotten.txt")
    bad = corrupt_corpus(preds[1], dirty_mid, fraction=0.05, seed=seed)
    assert bad, "corruption injection produced no bad lines"

    # Fault-free reference sweep over the same outer files.
    ref_cfg = dataclasses.replace(
        cfg, predict_files=tuple(preds),
        score_path=os.path.join(workdir, "score_ref"),
        metrics_file=os.path.join(workdir, "ref_metrics.jsonl"))
    predict(ref_cfg)

    # Faulted sweep: transient opens on file 0, the corrupt file in
    # the middle, quarantine policy, parallel host plane.
    flt_cfg = dataclasses.replace(
        cfg, predict_files=(preds[0], dirty_mid, preds[2]),
        score_path=os.path.join(workdir, "score_flaky"),
        metrics_file=os.path.join(workdir, "flaky_metrics.jsonl"),
        bad_line_policy="quarantine", io_retries=3, host_threads=4)
    with flaky_open(2, match="pred0.txt") as state:
        predict(flt_cfg)
    assert state["failures"] == 2, state

    def _score_text(cfg_, name):
        with open(os.path.join(cfg_.score_path, name + ".score")) as fh:
            return fh.read()

    # The files beside the damage: bit-identical to the clean sweep.
    for name in ("pred0.txt", "pred2.txt"):
        assert _score_text(flt_cfg, name) == _score_text(ref_cfg, name), (
            f"{name} scores diverged beside a corrupt neighbor")
    # The corrupt file itself: still one score per input line.
    n_scores = len(_score_text(flt_cfg,
                               "pred1_rotten.txt").splitlines())
    with open(dirty_mid) as fh:
        n_lines = sum(1 for _ in fh)
    assert n_scores == n_lines, (n_scores, n_lines)
    # Quarantine sidecar names each injected line of the corrupt file.
    with open(flt_cfg.metrics_file + ".quarantine") as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    assert sorted(r["lineno"] for r in recs) == [i + 1 for i in bad], (
        f"quarantined {sorted(r['lineno'] for r in recs)} != injected "
        f"{[i + 1 for i in bad]}")
    assert all(r["file"] == dirty_mid for r in recs)
    c = _counters(flt_cfg)
    assert c.get("io/retries", 0) >= 2, c.get("io/retries")
    leaked = [t.name for t in threading.enumerate()
              if t.is_alive() and (t.name.startswith("fm-build")
                                   or t.name in ("fm-score-writer",
                                                 "fetcher"))]
    assert not leaked, leaked
    return (f"streaming sweep absorbed {state['failures']} flaky opens "
            f"+ quarantined {len(recs)} corrupt line(s) mid-sweep; "
            "neighbor scores bit-identical, alignment kept, no thread "
            "leaks")


def scenario_serve_soak(workdir: str, seed: int = 0) -> str:
    """ISSUE 11 acceptance: a long-lived scorer process serving
    CONCURRENT requests across at least one hot reload. Every response
    must be bit-identical to batch predict against the checkpoint step
    that scored it (responses are step-tagged), the reload is driven
    through the real pointer-watch loop by the `fmckpt publish`
    operator path, fmstat's SERVING section shows the p50/p99 latency
    histograms with no STALE MODEL, and no server/reload thread
    survives close()."""
    import dataclasses as dc
    import threading
    import time as _time
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          list_step_dirs)
    from fast_tffm_tpu.metrics import sigmoid
    from fast_tffm_tpu.predict import load_table, predict_scores
    from fast_tffm_tpu.serve import ScoreClient, ScorerServer
    from fast_tffm_tpu.train import train
    from tools.fmckpt import cmd_publish

    data = os.path.join(workdir, "train.txt")
    _write_corpus(data, 400, seed)
    cfg = _cfg(workdir, data, epoch_num=2, save_steps=5,
               bucket_ladder=(8, 16), max_features_per_example=16,
               serve_max_batch=8, serve_max_wait_ms=2.0,
               serve_poll_seconds=0.02,
               metrics_file=os.path.join(workdir,
                                         "serve_metrics.jsonl"))
    train(dc.replace(cfg, metrics_file=""))
    ckpt = CheckpointState(cfg.model_file)
    steps = list_step_dirs(ckpt.directory)
    ckpt.close()
    assert len(steps) >= 2, f"need >= 2 retained steps, got {steps}"
    s_old, s_new = steps[0], steps[-1]
    # First publish through the operator CLI — the same path the
    # mid-soak repoint uses, so both flips exercise fmckpt publish.
    assert cmd_publish(cfg.model_file + ".ckpt", s_old) == 0

    server = ScorerServer(cfg)
    client = ScoreClient(server)
    req_lines = _corpus_lines(60, seed + 99)
    results = []   # (request lines, scores, step) — appended under lock
    res_lock = threading.Lock()
    errors = []
    stop_firing = threading.Event()

    def fire(worker: int) -> None:
        rng = np.random.default_rng(seed + worker)
        while not stop_firing.is_set():
            k = int(rng.integers(1, 6))
            lo = int(rng.integers(0, len(req_lines) - k))
            lines = req_lines[lo:lo + k]
            try:
                res = client.score(lines, timeout=30)
            except Exception as e:  # noqa: BLE001 - assert at the end
                errors.append(e)
                return
            with res_lock:
                results.append((lines, res.scores, res.step))

    threads = [threading.Thread(target=fire, args=(i,),
                                name=f"soak-client-{i}")
               for i in range(4)]
    for t in threads:
        t.start()
    # Let requests land on the OLD step, flip the pointer through the
    # operator CLI mid-fire, then keep firing until requests are
    # provably landing on the NEW step.
    deadline = _time.monotonic() + 30
    while not any(r[2] == s_old for r in list(results)):
        assert _time.monotonic() < deadline, "no old-step responses"
        _time.sleep(0.01)
    assert cmd_publish(cfg.model_file + ".ckpt", s_new) == 0
    while not any(r[2] == s_new for r in list(results)):
        assert _time.monotonic() < deadline, (
            f"hot reload to step {s_new} never served a request "
            f"(errors: {errors[:1]})")
        _time.sleep(0.01)
    stop_firing.set()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    server.close()

    leaked = [t.name for t in threading.enumerate()
              if t.is_alive() and t.name.startswith("fm-serve")]
    assert not leaked, f"leaked server threads: {leaked}"

    by_step = {}
    for _lines, _scores, step in results:
        by_step.setdefault(step, []).append((_lines, _scores))
    assert set(by_step) == {s_old, s_new}, (
        f"responses span steps {sorted(by_step)}, wanted "
        f"{[s_old, s_new]}")
    # Bit-identical parity per step: batch predict over the SAME lines
    # against the same published checkpoint must reproduce every
    # response byte for byte (the step tag says which table scored it).
    pcfg = dc.replace(cfg, metrics_file="")
    for step, pairs in sorted(by_step.items()):
        table = load_table(pcfg, step=step)
        req_path = os.path.join(workdir, f"requests_{step}.txt")
        flat, sizes = [], []
        for lines, _scores in pairs:
            flat.extend(lines)
            sizes.append(len(lines))
        with open(req_path, "w") as fh:
            fh.write("\n".join(flat) + "\n")
        want = sigmoid(predict_scores(pcfg, table, [req_path]))
        pos = 0
        for (lines, scores), n in zip(pairs, sizes):
            ref = want[pos:pos + n]
            pos += n
            assert np.array_equal(ref, scores), (
                f"step {step}: served scores diverged from batch "
                f"predict on the same checkpoint ({scores[:3]} vs "
                f"{ref[:3]})")
    # fmstat SERVING section: latency histograms visible, reload
    # counted, and the final flush shows served == published (no
    # STALE MODEL).
    from fast_tffm_tpu.obs.attribution import attribution, render
    summ = _summary(cfg)
    att = attribution(summ)
    assert att["serve_requests"] == len(results), (
        att["serve_requests"], len(results))
    assert att["serve_latency_p50_ms"] is not None
    assert att["serve_latency_p99_ms"] is not None
    assert att["serve_reloads"] >= 1
    assert att["serve_served_step"] == s_new
    text = render(summ)
    assert "SERVING" in text and "request latency p50 / p99" in text
    assert _verdict(cfg) == "OK", _verdict(cfg)
    n_old, n_new = len(by_step[s_old]), len(by_step[s_new])
    return (f"{len(results)} concurrent requests ({n_old} on step "
            f"{s_old}, {n_new} on step {s_new} after the hot reload) "
            f"all bit-identical to batch predict; p50="
            f"{att['serve_latency_p50_ms']:.1f}ms p99="
            f"{att['serve_latency_p99_ms']:.1f}ms, no thread leaks")


# --- serving-fleet scenarios ---------------------------------------------


def _free_port_block(n: int) -> int:
    """Base of n consecutive bindable loopback ports — the fleet
    contract puts replica i on ``serve_port + i``, so the scenario
    needs a whole block, not n scattered ports."""
    import socket
    for _ in range(64):
        socks = []
        try:
            s0 = socket.socket()
            s0.bind(("127.0.0.1", 0))
            base = s0.getsockname()[1]
            socks.append(s0)
            if base + n >= 65535:
                continue
            ok = True
            for i in range(1, n):
                s = socket.socket()
                socks.append(s)
                try:
                    s.bind(("127.0.0.1", base + i))
                except OSError:
                    ok = False
                    break
            if ok:
                return base
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no block of consecutive free loopback ports")


def _fleet_cfg_file(workdir: str, data: str, replicas: int,
                    base_port: int, **serve) -> str:
    """The ONE config file both the in-process FleetSupervisor and its
    replica child processes load (children see per-replica FM_* env
    deltas on top — port, metrics shard, external reload mode)."""
    knobs = {
        "serve_port": base_port,
        "serve_replicas": replicas,
        "serve_proxy_port": 0,
        "serve_max_batch": 8,
        "serve_max_wait_ms": 2.0,
        "serve_poll_seconds": 0.05,
        "serve_health_poll_seconds": 0.1,
        "serve_restart_backoff_seconds": 0.2,
        "serve_retry_budget": 2,
    }
    knobs.update(serve)
    block = "\n".join(f"{k} = {v}" for k, v in knobs.items())
    path = os.path.join(workdir, "fleet.cfg")
    with open(path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = 200
factor_num = 4
model_file = {os.path.join(workdir, 'model', 'fm')}
log_file = {os.path.join(workdir, 'fleet.log')}

[Train]
train_files = {data}
batch_size = 32
learning_rate = 0.1
epoch_num = 2
save_steps = 5
shuffle = true
seed = 0
log_steps = 0
bucket_ladder = 8
max_features_per_example = 8
metrics_file = {os.path.join(workdir, 'fleet_metrics.jsonl')}
metrics_flush_steps = 5
io_backoff_seconds = 0.01

[SLO]
slo_p99_ms = 10000

[Serve]
{block}
""")
    return path


def _replica_log_tails(cfg, tail: int = 2000) -> str:
    out = []
    for i in range(cfg.serve_replicas):
        p = f"{cfg.model_file}.replica{i}.log"
        try:
            with open(p) as fh:
                out.append(f"--- replica {i} ---\n{fh.read()[-tail:]}")
        except OSError:
            out.append(f"--- replica {i}: no log at {p} ---")
    return "\n".join(out)


def _fire_proxy(port: int, req_lines, seed: int, stop_firing,
                results, res_lock, failures):
    """One proxy client: variable-size bursts of libsvm lines POSTed
    through the fleet front door, collecting (lines, response text,
    step, latency ms) — or the failure, which the scenarios assert
    NEVER happens."""
    import http.client as _http
    import time as _time
    rng = np.random.default_rng(seed)
    while not stop_firing.is_set():
        k = int(rng.integers(1, 6))
        lo = int(rng.integers(0, len(req_lines) - k))
        lines = req_lines[lo:lo + k]
        body = ("\n".join(lines) + "\n").encode("utf-8")
        t0 = _time.monotonic()
        try:
            conn = _http.HTTPConnection("127.0.0.1", port, timeout=60)
            try:
                conn.request("POST", "/score", body=body,
                             headers={"Content-Type": "text/plain"})
                resp = conn.getresponse()
                out = resp.read().decode("utf-8")
                status = resp.status
                step = resp.getheader("X-FM-Step")
            finally:
                conn.close()
        except Exception as e:  # noqa: BLE001 - asserted empty later
            failures.append(repr(e))
            continue
        lat_ms = (_time.monotonic() - t0) * 1000.0
        if status != 200 or step is None:
            failures.append(f"HTTP {status}: {out[:200]}")
            continue
        with res_lock:
            results.append((lines, out, int(step), lat_ms))


def _assert_fleet_parity(cfg, workdir: str, results) -> dict:
    """Per-step byte parity: every proxied response's text must equal
    the ``%.6f`` rendering of batch predict over the same lines
    against the step that scored it (the X-FM-Step tag). Torn or
    truncated responses fail here by construction. Returns the
    responses grouped by step."""
    import dataclasses as dc
    from fast_tffm_tpu.metrics import sigmoid
    from fast_tffm_tpu.predict import load_table, predict_scores
    pcfg = dc.replace(cfg, metrics_file="")
    by_step = {}
    for lines, text, step, _lat in results:
        by_step.setdefault(step, []).append((lines, text))
    for step, pairs in sorted(by_step.items()):
        table = load_table(pcfg, step=step)
        req_path = os.path.join(workdir, f"fleet_requests_{step}.txt")
        flat = [ln for lines, _text in pairs for ln in lines]
        with open(req_path, "w") as fh:
            fh.write("\n".join(flat) + "\n")
        want = sigmoid(predict_scores(pcfg, table, [req_path]))
        pos = 0
        for lines, text in pairs:
            n = len(lines)
            ref = "".join(f"{v:.6f}\n" for v in want[pos:pos + n])
            pos += n
            assert text == ref, (
                f"step {step}: proxied response diverged from batch "
                f"predict on the same checkpoint ({text[:40]!r} vs "
                f"{ref[:40]!r})")
    return by_step


def scenario_kill_replica_midburst(workdir: str, seed: int = 0) -> str:
    """ISSUE 19 acceptance (tentpole): a 3-replica serving fleet
    behind the failover proxy survives SIGKILL of one replica in the
    middle of a concurrent request burst. Zero client-visible
    failures (the proxy fails refused/reset forwards over to a
    different ready replica), every response byte-identical to batch
    predict against the step that scored it, a MID-INCIDENT fmstat
    snapshot reads FLEET DEGRADED (2/3 ready) (the supervisor's eager
    flush on the ready edge), the dead replica auto-restarts under
    backoff back to 3/3 with the post-drain verdict OK, and the
    client-observed p99 honors the [SLO] bound."""
    import signal as _signal
    import threading
    import time as _time
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          list_step_dirs)
    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.obs.attribution import (health_verdict, render,
                                               summarize)
    from fast_tffm_tpu.serve.fleet import FleetSupervisor
    from fast_tffm_tpu.train import train
    from tools.fmckpt import cmd_publish
    import dataclasses as dc

    workdir = os.path.abspath(workdir)
    data = os.path.join(workdir, "train.txt")
    _write_corpus(data, 400, seed)
    cfg_path = _fleet_cfg_file(workdir, data, replicas=3,
                               base_port=_free_port_block(3))
    cfg = load_config(cfg_path)
    train(dc.replace(cfg, metrics_file=""))
    ckpt = CheckpointState(cfg.model_file)
    steps = list_step_dirs(ckpt.directory)
    ckpt.close()
    s_pub = steps[-1]
    assert cmd_publish(cfg.model_file + ".ckpt", s_pub) == 0

    sup = FleetSupervisor(cfg, cfg_path).start()
    req_lines = _corpus_lines(60, seed + 99)
    results, res_lock, failures = [], threading.Lock(), []
    stop_firing = threading.Event()
    clients = []
    try:
        assert sup.wait_ready(3, timeout=300), (
            "fleet never reached 3 ready replicas:\n"
            + _replica_log_tails(cfg))
        clients = [threading.Thread(
            target=_fire_proxy,
            args=(sup.proxy_port, req_lines, seed + i, stop_firing,
                  results, res_lock, failures),
            name=f"burst-client-{i}") for i in range(4)]
        for t in clients:
            t.start()
        deadline = _time.monotonic() + 60
        while len(results) < 10:
            assert _time.monotonic() < deadline, (
                f"burst never started (failures: {failures[:3]})")
            _time.sleep(0.01)

        # The incident: SIGKILL one replica mid-burst.
        victim = sup.replicas[1]
        old_pid = victim.pid()
        os.kill(old_pid, _signal.SIGKILL)
        # Mid-incident observability: the supervisor flushes eagerly
        # on the ready-count edge, so fmstat over the live stream must
        # show the degradation window NOW, not after the fact.
        deadline = _time.monotonic() + 60
        while True:
            v = health_verdict(summarize([cfg.metrics_file]))["verdict"]
            if v.startswith("FLEET DEGRADED"):
                break
            assert _time.monotonic() < deadline, (
                f"no FLEET DEGRADED snapshot mid-incident (verdict "
                f"stayed {v!r})")
            _time.sleep(0.05)
        mid_verdict = v
        # Self-healing: the supervisor respawns the victim (capped
        # backoff) and the fleet returns to full strength.
        assert sup.wait_ready(3, timeout=300), (
            "killed replica never came back ready:\n"
            + _replica_log_tails(cfg))
        assert victim.pid() != old_pid, "victim was never respawned"
        # Keep the burst going on the healed fleet before stopping.
        n_mark = len(results)
        deadline = _time.monotonic() + 60
        while len(results) < n_mark + 10:
            assert _time.monotonic() < deadline, (
                f"no responses after recovery (failures: "
                f"{failures[:3]})")
            _time.sleep(0.01)
        stop_firing.set()
        for t in clients:
            t.join()
    finally:
        stop_firing.set()
        for t in clients:
            t.join(timeout=30)
        sup.stop()

    assert not failures, (
        f"{len(failures)} client-visible failure(s) — the proxy must "
        f"absorb the kill: {failures[:3]}")
    by_step = _assert_fleet_parity(cfg, workdir, results)
    assert set(by_step) == {s_pub}, (
        f"responses span steps {sorted(by_step)}, wanted [{s_pub}]")
    lat = sorted(r[3] for r in results)
    p99 = float(np.percentile(lat, 99))
    assert p99 <= cfg.slo_p99_ms, (
        f"client p99 {p99:.1f}ms blew the [SLO] slo_p99_ms = "
        f"{cfg.slo_p99_ms} bound")
    summ = summarize([cfg.metrics_file])
    c = summ.get("counters", {})
    assert c.get("fleet/deaths", 0) >= 1, c
    assert c.get("fleet/restarts", 0) >= 1, c
    assert c.get("proxy/requests") == len(results), (
        c.get("proxy/requests"), len(results))
    v_end = health_verdict(summ)["verdict"]
    assert v_end == "OK", v_end
    text = render(summ)
    assert "FLEET (serve --replicas)" in text and "r2:" in text, text
    leaked = [t.name for t in threading.enumerate()
              if t.is_alive() and (t.name.startswith("fm-fleet")
                                   or t.name.startswith("fm-proxy"))]
    assert not leaked, f"leaked fleet threads: {leaked}"
    retries = int(c.get("proxy/retries", 0)
                  + c.get("proxy/transport_errors", 0))
    return (f"{len(results)} proxied requests, 0 failures across a "
            f"SIGKILL of replica 1 (pid {old_pid}) mid-burst "
            f"({retries} failover retries/transport errors absorbed); "
            f"mid-incident fmstat read '{mid_verdict}', the replica "
            f"respawned and the final verdict is OK; all responses "
            f"bit-identical to batch predict on step {s_pub}; "
            f"p99 {p99:.1f}ms within the {cfg.slo_p99_ms}ms SLO")


def scenario_staggered_reload(workdir: str, seed: int = 0) -> str:
    """ISSUE 19 acceptance: a fleet-wide hot reload under load never
    has a zero-ready instant. `fmckpt publish` repoints the pointer
    while clients fire through the proxy; the supervisor staggers the
    reload (each replica waits for another ready replica before
    taking the token); a high-rate sampler on the proxy's aggregated
    /healthz must never observe ready == 0; responses land on BOTH
    steps and every one is byte-identical to batch predict against
    its step — none torn."""
    import json as _json
    import http.client as _http
    import threading
    import time as _time
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          list_step_dirs)
    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.obs.attribution import health_verdict, summarize
    from fast_tffm_tpu.serve.fleet import FleetSupervisor
    from fast_tffm_tpu.train import train
    from tools.fmckpt import cmd_publish
    import dataclasses as dc

    workdir = os.path.abspath(workdir)
    data = os.path.join(workdir, "train.txt")
    _write_corpus(data, 400, seed)
    cfg_path = _fleet_cfg_file(workdir, data, replicas=2,
                               base_port=_free_port_block(2))
    cfg = load_config(cfg_path)
    train(dc.replace(cfg, metrics_file=""))
    ckpt = CheckpointState(cfg.model_file)
    steps = list_step_dirs(ckpt.directory)
    ckpt.close()
    assert len(steps) >= 2, f"need >= 2 retained steps, got {steps}"
    s_old, s_new = steps[0], steps[-1]
    assert cmd_publish(cfg.model_file + ".ckpt", s_old) == 0

    sup = FleetSupervisor(cfg, cfg_path).start()
    req_lines = _corpus_lines(60, seed + 99)
    results, res_lock, failures = [], threading.Lock(), []
    stop_firing = threading.Event()
    stop_sampling = threading.Event()
    ready_samples = []
    clients = []
    sampler = None

    def sample_healthz():
        while not stop_sampling.is_set():
            try:
                conn = _http.HTTPConnection("127.0.0.1",
                                            sup.proxy_port, timeout=5)
                try:
                    conn.request("GET", "/healthz")
                    resp = conn.getresponse()
                    payload = _json.loads(resp.read())
                    ready_samples.append(
                        (int(payload["ready"]), resp.status))
                finally:
                    conn.close()
            except OSError:
                pass  # proxy briefly unreachable = not a zero-ready
            _time.sleep(0.005)

    try:
        assert sup.wait_ready(2, timeout=300), (
            "fleet never reached 2 ready replicas:\n"
            + _replica_log_tails(cfg))
        sampler = threading.Thread(target=sample_healthz,
                                   name="stagger-healthz-sampler")
        sampler.start()
        clients = [threading.Thread(
            target=_fire_proxy,
            args=(sup.proxy_port, req_lines, seed + i, stop_firing,
                  results, res_lock, failures),
            name=f"stagger-client-{i}") for i in range(3)]
        for t in clients:
            t.start()
        deadline = _time.monotonic() + 60
        while len(results) < 5:
            assert _time.monotonic() < deadline, (
                f"no responses before the publish (failures: "
                f"{failures[:3]})")
            _time.sleep(0.01)

        # The reload, through the operator path, under load.
        assert cmd_publish(cfg.model_file + ".ckpt", s_new) == 0
        deadline = _time.monotonic() + 180
        while True:
            rows = [r.probe() for r in sup.replicas]
            if all(h and h.get("served_step") == s_new
                   and h.get("ready") for h in rows):
                break
            assert _time.monotonic() < deadline, (
                f"staggered reload to step {s_new} never completed "
                f"(rows: {rows})\n" + _replica_log_tails(cfg))
            _time.sleep(0.05)
        # A few responses must land on the NEW step before we stop.
        deadline = _time.monotonic() + 60
        while not any(r[2] == s_new for r in list(results)):
            assert _time.monotonic() < deadline, (
                "no responses on the reloaded step")
            _time.sleep(0.01)
        stop_firing.set()
        for t in clients:
            t.join()
        stop_sampling.set()
        sampler.join()
        # Let the supervisor's CACHED health view (the source of the
        # fleet/ready gauge) observe full strength again before the
        # drain, so the final flush carries the healed fleet, not the
        # mid-reload edge.
        assert sup.wait_ready(2, timeout=60), (
            "fleet health view never recovered to 2 ready after the "
            "reload:\n" + _replica_log_tails(cfg))
    finally:
        stop_firing.set()
        stop_sampling.set()
        for t in clients:
            t.join(timeout=30)
        if sampler is not None:
            sampler.join(timeout=10)
        sup.stop()

    assert not failures, (
        f"{len(failures)} client-visible failure(s) during the "
        f"staggered reload: {failures[:3]}")
    assert ready_samples, "healthz sampler never sampled"
    min_ready = min(s[0] for s in ready_samples)
    assert min_ready >= 1, (
        f"zero-ready window observed during the staggered reload "
        f"({len(ready_samples)} samples)")
    assert all(s[1] == 200 for s in ready_samples), (
        "proxy /healthz went 503 during the reload")
    by_step = _assert_fleet_parity(cfg, workdir, results)
    assert set(by_step) == {s_old, s_new}, (
        f"responses span steps {sorted(by_step)}, wanted "
        f"{[s_old, s_new]}")
    summ = summarize([cfg.metrics_file])
    c = summ.get("counters", {})
    assert c.get("fleet/reloads", 0) >= 2, c
    assert c.get("fleet/reload_failures", 0) == 0, c
    v_end = health_verdict(summ)["verdict"]
    assert v_end == "OK", v_end
    n_old = len(by_step[s_old])
    n_new = len(by_step[s_new])
    return (f"staggered reload {s_old} -> {s_new} under load: "
            f"{len(results)} responses ({n_old} on the old step, "
            f"{n_new} on the new), 0 failures, min ready across "
            f"{len(ready_samples)} healthz samples = {min_ready} "
            f"(never zero), {int(c['fleet/reloads'])} replica "
            f"reloads, all responses bit-identical to batch predict")


# --- streaming run-mode scenarios ----------------------------------------


def _corpus_lines(n: int, seed: int) -> list:
    """The synthetic corpus as a line list (the stream writer appends
    them progressively instead of writing a file at once)."""
    import tempfile
    with tempfile.NamedTemporaryFile("r", suffix=".txt",
                                     delete=False) as fh:
        tmp = fh.name
    try:
        _write_corpus(tmp, n, seed)
        with open(tmp) as fh:
            return fh.read().splitlines()
    finally:
        os.remove(tmp)


def _append_shard_torn(path: str, lines: list, pause: float) -> None:
    """Append one shard the hostile way: several flushes, each ending
    with a TORN half-line that the next write completes — the reader
    must hold the torn tail back or it trains garbage — then the
    ``.done`` seal marker."""
    import time as _time
    thirds = max(1, len(lines) // 3)
    pos = 0
    with open(path, "a") as fh:
        while pos < len(lines):
            seg = lines[pos:pos + thirds]
            pos += len(seg)
            blob = "\n".join(seg) + "\n"
            if pos < len(lines):
                nxt = lines[pos]
                cut = max(1, len(nxt) // 2)
                fh.write(blob + nxt[:cut])   # torn write: half a line
                fh.flush()
                _time.sleep(pause)
                fh.write(nxt[cut:] + "\n")   # completed next flush
                fh.flush()
                pos += 1
            else:
                fh.write(blob)
                fh.flush()
            _time.sleep(pause)
    open(path + ".done", "w").close()


def _stream_cfg(workdir: str, stream_dir: str, **overrides):
    base = dict(run_mode="stream", stream_dir=stream_dir,
                stream_poll_seconds=0.05, seal_policy="done",
                shuffle=False, epoch_num=1)
    base.update(overrides)
    return _cfg(workdir, "", train_files=(), **base)


def scenario_stream_soak(workdir: str, seed: int = 0) -> str:
    """The streaming acceptance soak: a writer thread appends 6 shards
    WITH injected torn writes while the trainer streams them; a
    SIGTERM lands mid-stream and the restart resumes from the
    checkpointed watermark; the tail of the corpus is consumed under
    injected flaky opens. The run must finish having consumed every
    sealed line exactly once — pinned the strong way: the final table
    is BIT-IDENTICAL to a clean single-pass control run over the same
    sealed corpus — and at least 2 ``published`` pointer flips must
    land on manifest-verified steps."""
    import threading
    from fast_tffm_tpu.checkpoint import read_published
    from fast_tffm_tpu.testing.faults import (flaky_open,
                                              preempt_after_steps)
    from fast_tffm_tpu.train import train
    from tools.fmckpt import cmd_verify
    workdir = os.path.abspath(workdir)
    sd = os.path.join(workdir, "stream")
    os.makedirs(sd, exist_ok=True)
    n_shards, lines_per = 6, 400
    shard_lines = [_corpus_lines(lines_per, seed * 100 + i)
                   for i in range(n_shards)]

    def writer():
        for i in range(n_shards):
            _append_shard_torn(os.path.join(sd, f"part-{i:03d}.txt"),
                               shard_lines[i], pause=0.03)
        open(os.path.join(sd, "STOP"), "w").close()

    cfg = _stream_cfg(workdir, sd, publish_interval_seconds=0.25,
                      io_retries=3)
    w = threading.Thread(target=writer, name="stream-writer",
                         daemon=True)
    w.start()
    # Run 1: stream against the LIVE writer (torn writes in flight);
    # SIGTERM after 8 steps — mid-stream by construction (8 * 32 = 256
    # of 2400 lines).
    with preempt_after_steps(8) as st:
        train(cfg)
    assert st["fired"], "SIGTERM injector never fired"
    assert _verdict(cfg) == "PREEMPTED", _verdict(cfg)
    w.join(timeout=120)
    assert not w.is_alive(), "stream writer never finished"
    # Run 2: resume from the watermark; the first opens of a
    # not-yet-consumed shard fail transiently (EIO) — the retry layer
    # must absorb them.
    with flaky_open(2, match="part-003.txt") as fstate:
        table_stream = np.asarray(train(cfg))
    assert fstate["failures"] == 2, fstate
    # Exactly-once: total stepped examples across both run segments
    # equals the corpus exactly (no line lost at the preemption cut,
    # none double-trained on resume) ...
    c = _counters(cfg)
    total = n_shards * lines_per
    assert c.get("train/examples") == total, (
        c.get("train/examples"), total)
    assert c.get("io/retries", 0) >= 2, c.get("io/retries")
    # >= rather than ==: files the first segment discovered AFTER its
    # last adopted watermark are legitimately re-discovered (and
    # re-sealed) by the resumed segment's tracker, so the folded
    # counters can exceed the shard count — the exactness claims live
    # in train/examples and the bit-identity check.
    assert c.get("stream/files_discovered", 0) >= n_shards, c
    assert c.get("stream/files_sealed", 0) >= n_shards, c
    # ... and the strong form: bit-identical to a clean single-pass
    # control run over the same sealed corpus.
    ctl_dir = os.path.join(workdir, "ctl")
    os.makedirs(ctl_dir, exist_ok=True)
    ctl = _cfg(ctl_dir, "", shuffle=False, epoch_num=1,
               train_files=(os.path.join(sd, "part-*.txt"),))
    table_ctl = np.asarray(train(ctl))
    assert np.array_equal(table_stream, table_ctl), (
        "stream run diverged from the clean single-pass control: "
        f"max |delta| = {np.abs(table_stream - table_ctl).max()}")
    # Publishing: >= 2 pointer flips across the two segments, and the
    # final published pointer names a step fmckpt verify passes FULL.
    publishes = int(c.get("stream/publishes", 0))
    assert publishes >= 2, c
    assert not c.get("stream/publish_failures"), c
    ckpt_dir = cfg.model_file + ".ckpt"
    pub = read_published(ckpt_dir)
    assert pub is not None
    assert cmd_verify(ckpt_dir, mode="full", step=pub) == 0, (
        f"published step {pub} failed full verification")
    return (f"consumed {total} sealed lines exactly once across "
            f"SIGTERM+resume (torn writes held back, 2 flaky opens "
            f"absorbed), table bit-identical to the control, "
            f"{publishes} verified publishes (pointer at step {pub})")


def scenario_stream_truncate(workdir: str, seed: int = 0) -> str:
    """An in-progress (unsealed) stream file SHRINKS under the reader:
    the (inode, size) regression is detected, the file is sealed at
    the consumed position and the event is quarantined through the
    BadLineTracker — the run survives, finishes the rest of the
    stream, and the breaker accounting is exact (1 bad record, no
    trip)."""
    import json as _json
    from fast_tffm_tpu.testing.faults import preempt_after_steps
    from fast_tffm_tpu.train import train
    workdir = os.path.abspath(workdir)
    sd = os.path.join(workdir, "stream")
    os.makedirs(sd, exist_ok=True)
    growing = os.path.join(sd, "part-000.txt")
    lines = _corpus_lines(100, seed)
    with open(growing, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    # Run 1: tail the growing (UNSEALED — no .done) file; preempt after
    # 3 steps = 96 lines consumed, watermark mid-file.
    cfg = _stream_cfg(workdir, sd, bad_line_policy="quarantine",
                      save_steps=0)
    with preempt_after_steps(3) as st:
        train(cfg)
    assert st["fired"]
    # The fault: the in-progress file shrinks BELOW the consumed
    # position (a rewriting producer), a sealed successor shard
    # arrives, and the stream ends.
    with open(growing, "r+") as fh:
        fh.truncate(len("\n".join(lines[:50])) + 1)
    _write_corpus(os.path.join(sd, "part-001.txt"), 320,
                  seed + 1)
    open(os.path.join(sd, "part-001.txt.done"), "w").close()
    open(os.path.join(sd, "STOP"), "w").close()
    # Run 2: must detect the regression, quarantine it, and survive.
    train(cfg)
    c = _counters(cfg)
    assert c.get("stream/truncated_files") == 1, c
    assert c.get("pipeline/bad_lines") == 1, c
    # 96 lines before the cut + the whole successor shard, never the
    # vanished tail: exactly-once accounting around the damage.
    assert c.get("train/examples") == 96 + 320, c
    assert _verdict(cfg) == "OK", _verdict(cfg)
    qpath = cfg.metrics_file + ".quarantine"
    with open(qpath) as fh:
        recs = [_json.loads(ln) for ln in fh if ln.strip()]
    assert len(recs) == 1 and recs[0]["file"] == growing, recs
    assert "truncated" in recs[0]["error"], recs
    log = open(cfg.log_file).read()
    assert "truncated mid-stream" in log
    return ("in-progress file shrank 100 -> 50 lines at consumed line "
            "96: sealed at the watermark, 1 quarantine record, no "
            "breaker trip, run finished the successor shard (416 "
            "examples exactly once)")


def scenario_vocab_churn(workdir: str, seed: int = 0) -> str:
    """Unbounded-vocabulary admission under stream churn (README
    "Unbounded vocabulary"): a streaming run over a heavy-tailed
    hashed-id distribution — an early hot "era A" that goes cold, a
    later "era B", and a long unique tail far exceeding
    ``vocabulary_size`` — takes a mid-run SIGTERM, then resumes
    through a checkpoint WALK-BACK (the newest step is torn, so
    restore quarantines it and loads the older step's vocab sidecar).
    Asserts: admission state round-trips the preemption bit-exactly
    (payload -> load -> payload identity, and the resumed run logs the
    walked-back step's own live-row count), the slot map never exceeds
    the physical table (every row in [1, vocabulary_size), live <=
    vocabulary_size - 1) while the distinct-id count is >= 10x it,
    era-A rows are EVICTED once their decayed frequency falls below
    the floor, and the final published step serves an evicted id from
    the shared cold row — bit-identical to a never-seen id's score,
    NOT its stale embedding."""
    from fast_tffm_tpu.checkpoint import (QUARANTINE_PREFIX,
                                          list_step_dirs,
                                          read_published,
                                          read_vocab_sidecar)
    from fast_tffm_tpu.data.hashing import murmur64
    from fast_tffm_tpu.testing.faults import (preempt_after_steps,
                                              truncate_checkpoint)
    from fast_tffm_tpu.train import train
    from fast_tffm_tpu.vocab.sketch import HASH_SPACE
    from fast_tffm_tpu.vocab.table import VocabRuntime, payload_crc_ok
    import base64
    workdir = os.path.abspath(workdir)
    sd = os.path.join(workdir, "stream")
    os.makedirs(sd, exist_ok=True)
    V = 16  # physical table rows (1 cold + 15 live)
    rng = np.random.default_rng(seed)
    era_a = [f"hotA{i}" for i in range(4)]
    era_b = [f"hotB{i}" for i in range(4)]
    distinct = set()

    def write_shard(i, hot):
        lines = []
        for k in range(400):
            y = k % 2
            h = hot[(k % 2) * 2 + (k % 4) // 2]
            tail = f"u{int(rng.integers(0, 20000))}"
            distinct.update((h, tail))
            lines.append(f"{y} {h}:1 {tail}:0.5")
        path = os.path.join(sd, f"part-{i:03d}.txt")
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        open(path + ".done", "w").close()

    # LIVE writer with arrival gaps: publish barriers fire on the
    # driver's idle ticks inside each gap, so admission/eviction
    # decisions land deterministically BETWEEN eras regardless of how
    # fast the machine steps a sealed shard.
    import threading
    import time as _time

    def writer():
        write_shard(0, era_a)       # era A: hot, then never again
        _time.sleep(0.5)
        write_shard(1, era_b)       # era B takes over
        _time.sleep(0.5)
        write_shard(2, era_b)
        open(os.path.join(sd, "STOP"), "w").close()

    w = threading.Thread(target=writer, name="vocab-churn-writer",
                         daemon=True)
    cfg = _stream_cfg(workdir, sd, hash_feature_id=True,
                      vocabulary_size=V, save_steps=5,
                      publish_interval_seconds=0.15,
                      vocab_mode="admit", vocab_admit_threshold=2.0,
                      vocab_decay=0.25, vocab_sketch_mb=0.25)
    ckpt_dir = cfg.model_file + ".ckpt"

    def slot_keys(payload):
        return set(np.frombuffer(
            base64.b64decode(payload["state"]["slot_keys"]),
            np.int64).tolist())

    def slot_rows(payload):
        return np.frombuffer(
            base64.b64decode(payload["state"]["slot_rows"]), np.int32)

    # Run 1: SIGTERM after 20 steps — mid-era-B (shard 0 is 13
    # batches, so the era-A admission barrier has run inside the
    # first arrival gap), leaving shard 2 for the resumed run.
    w.start()
    with preempt_after_steps(20) as st:
        train(cfg)
    assert st["fired"], "SIGTERM injector never fired"
    assert _verdict(cfg) == "PREEMPTED", _verdict(cfg)
    w.join(timeout=120)
    assert not w.is_alive(), "stream writer never finished"
    assert len(distinct) >= 10 * V, len(distinct)
    steps = list_step_dirs(ckpt_dir)
    assert len(steps) >= 2, steps
    newest = steps[-1]
    payload = read_vocab_sidecar(ckpt_dir, newest)
    assert payload is not None and payload_crc_ok(payload)
    # Era A was admitted at SOME barrier before the preemption — pinned
    # via the cumulative counter, NOT membership in the newest sidecar:
    # barriers ride the wall-clock publish cadence, so on a fast machine
    # several fire inside the first arrival gap and era A can be
    # admitted AND already decayed out again by the step-20 save (that
    # early eviction is correct behavior, not a miss).
    c1 = _counters(cfg)
    assert c1.get("vocab/admitted_rows", 0) >= len(era_a), (
        f"expected >= {len(era_a)} admissions before the preemption, "
        f"got {c1.get('vocab/admitted_rows', 0)}")
    # Bit-exact round trip of the admission state through the sidecar
    # machinery: payload -> runtime.load -> state_payload identity.
    rt = VocabRuntime.from_config(cfg)
    rt.load(cfg, payload)
    assert rt.state_payload() == payload, (
        "vocab admission payload does not round-trip bit-exactly")
    # The walk-back fault: tear the newest step's largest array file —
    # the resume must quarantine it and load the OLDER step's sidecar.
    victim = truncate_checkpoint(cfg.model_file, seed=seed)
    assert victim and f"{os.sep}{newest}{os.sep}" in victim, victim
    older = steps[-2]
    older_payload = read_vocab_sidecar(ckpt_dir, older)
    assert older_payload is not None
    older_live = len(slot_keys(older_payload))
    # Run 2: resume through the walk-back, consume the rest of the
    # stream (era B + tail), evicting era A as its estimate decays.
    train(cfg)
    log = open(cfg.log_file).read()
    assert f"restored checkpoint at step {older}" in log, (
        "resume did not walk back to the older step")
    assert (f"restored vocab admission state at step {older}: "
            f"{older_live} live rows") in log, (
        "resume did not load the walked-back step's OWN vocab sidecar")
    assert any(n.startswith(QUARANTINE_PREFIX)
               for n in os.listdir(ckpt_dir))
    c = _counters(cfg)
    assert c.get("checkpoint/fallbacks", 0) >= 1, c
    # Final published state: bounded table, era A evicted.
    pub = read_published(ckpt_dir)
    assert pub is not None
    final_payload = read_vocab_sidecar(ckpt_dir, pub)
    assert final_payload is not None and payload_crc_ok(final_payload)
    rows = slot_rows(final_payload)
    assert len(rows) <= V - 1, len(rows)
    assert rows.size == 0 or (rows.min() >= 1 and rows.max() < V), rows
    assert c.get("vocab/evicted_rows", 0) >= 1, c
    final_keys = slot_keys(final_payload)
    evicted_a = [s for s in era_a
                 if murmur64(s.encode()) % HASH_SPACE not in final_keys]
    assert evicted_a, (
        "era-A ids all survived to the published step; eviction never "
        "reclaimed their rows")
    # Cold-row semantics at the published step: an EVICTED id scores
    # bit-identically to a never-seen id (both route to the shared
    # cold row) — never through its stale pre-eviction embedding.
    import dataclasses
    from fast_tffm_tpu.predict import load_table, predict_scores
    from fast_tffm_tpu.vocab.table import VocabMap
    pcfg = dataclasses.replace(cfg, run_mode="epochs", stream_dir="",
                               train_files=())
    table = load_table(pcfg, step=pub)
    vmap = VocabMap.from_payload(pcfg, final_payload)
    probe = os.path.join(workdir, "probe.txt")
    with open(probe, "w") as fh:
        fh.write(f"0 {evicted_a[0]}:1\n0 never_seen_xyzzy:1\n")
    s = predict_scores(pcfg, table, (probe,), vocab=vmap)
    assert s.shape == (2,)
    assert s[0] == s[1], (
        f"evicted id scored {s[0]} but the cold row scores {s[1]}: "
        "the published step is serving a stale embedding")
    return (f"{len(distinct)} distinct hashed ids (>= 10x the {V}-row "
            f"table) streamed through SIGTERM+resume and a walk-back "
            f"to step {older}; admission state round-tripped "
            f"bit-exactly, {int(c.get('vocab/evicted_rows', 0))} rows "
            f"evicted, published step {pub} serves evicted era-A ids "
            "from the cold row")


def scenario_slo_soak(workdir: str, seed: int = 0) -> str:
    """ISSUE 13 acceptance: the FULL closed loop under SLOs. A live
    writer feeds the stream, a gated trainer (``publish_min_auc``)
    publishes on interval, and a ScorerServer serves a concurrent
    request load against the moving pointer. Mid-soak a POISONED burst
    (label-flipped shard) arrives: the per-publish quality sweep must
    catch the regression — the ``published`` pointer never advances to
    a held step, ``health: gate_held`` fires, fmstat's verdict reads
    GATE-HELD — while serving continues uninterrupted on the last
    good step. Clean data then heals the model, publishes resume, and
    at the end EVERY SLO must hold: publish staleness bound, serve
    p99 bound, exactly-once stream consumption, minimum quality AUC,
    and per-step response parity — every served score bit-identical
    to offline predict against a control snapshot of the step that
    scored it (snapshots taken at pointer-observation time, so
    retention GC can't erase the evidence)."""
    import dataclasses as dc
    import shutil
    import subprocess
    import sys
    import threading
    import time as _time
    from fast_tffm_tpu.checkpoint import read_published
    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.metrics import sigmoid
    from fast_tffm_tpu.obs.attribution import render
    from fast_tffm_tpu.obs.slo import SloSpec, evaluate_slos, overall
    from fast_tffm_tpu.predict import load_table, predict_scores
    from fast_tffm_tpu.serve import ScoreClient, ScorerServer
    from tools.fmstat import main as fmstat_main

    workdir = os.path.abspath(workdir)
    sd = os.path.join(workdir, "stream")
    os.makedirs(sd, exist_ok=True)
    val = os.path.join(workdir, "val.txt")
    _write_corpus(val, 240, seed + 1)

    shard_i = [0]
    total = [0]

    def write_shard(lines) -> None:
        path = os.path.join(sd, f"part-{shard_i[0]:03d}.txt")
        shard_i[0] += 1
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        open(path + ".done", "w").close()
        total[0] += len(lines)

    def flip(line: str) -> str:
        y, rest = line.split(" ", 1)
        return f"{1 - int(y)} {rest}"

    write_shard(_corpus_lines(400, seed))
    write_shard(_corpus_lines(400, seed + 2))

    # The trainer runs as a REAL process driving run_tffm.py (the
    # production entry point): the harness orchestrates purely through
    # the filesystem — the stream dir, the published pointer, and the
    # metrics JSONL — exactly like an operator's deployment.
    MIN_AUC = 0.7
    cfg_path = os.path.join(workdir, "slo_soak.cfg")
    with open(cfg_path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = 200
factor_num = 4
model_file = {os.path.join(workdir, 'model', 'fm')}
log_file = {os.path.join(workdir, 'trainer.log')}

[Train]
run_mode = stream
stream_dir = {sd}
stream_poll_seconds = 0.05
seal_policy = done
shuffle = false
epoch_num = 1
batch_size = 32
learning_rate = 0.1
log_steps = 0
metrics_file = {os.path.join(workdir, 'metrics.jsonl')}
metrics_flush_steps = 2
io_backoff_seconds = 0.01
publish_interval_seconds = 0.2
publish_min_auc = {MIN_AUC}
validation_files = {val}

[SLO]
slo_publish_staleness_seconds = 60
slo_p99_ms = 10000
slo_min_auc = {MIN_AUC}
slo_max_bad_fraction = 0.001
""")
    cfg = load_config(cfg_path)
    ckpt_dir = cfg.model_file + ".ckpt"
    serve_metrics = os.path.join(workdir, "serve_metrics.jsonl")

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    trainer_out_path = os.path.join(workdir, "trainer.out")
    trainer_out = open(trainer_out_path, "w")
    trainer = subprocess.Popen(
        [sys.executable, "run_tffm.py", "train", cfg_path],
        cwd=repo, env=env, stdout=trainer_out,
        stderr=subprocess.STDOUT)

    def _trainer_tail() -> str:
        try:
            with open(trainer_out_path) as fh:
                return fh.read()[-3000:]
        except OSError:
            return "<no trainer output>"

    def wait_for(fn, what, deadline_s: float = 180.0):
        deadline = _time.monotonic() + deadline_s
        while True:
            v = fn()
            if v not in (None, False) and v != []:
                return v
            assert trainer.poll() is None, (
                f"trainer exited (rc {trainer.returncode}) before "
                f"{what}:\n{_trainer_tail()}")
            assert _time.monotonic() < deadline, (
                f"timed out waiting for {what}")
            _time.sleep(0.02)

    # Best-effort teardown on ANY exit: a wait_for timeout or a
    # failed assertion must not leak a live training subprocess
    # (polling the stream forever) or server/client threads into
    # the rest of the suite.
    server = None
    clients = []
    poller = None
    stop_firing = threading.Event()
    stop_polling = threading.Event()
    try:
        # The first publish only lands once the gate passes — an untrained
        # model's validation AUC holds publish_min_auc, so a pointer here
        # already proves the gate's first-publish (min-AUC-only) path ran.
        wait_for(lambda: read_published(ckpt_dir) is not None,
                 "first gate-passing publish")

        # Pointer trajectory + per-step offline CONTROL snapshots: each
        # newly observed published step dir (and its manifest) is copied
        # out at observation time, so the end-of-run parity check can
        # score against steps max_to_keep retention GC'd long before the
        # soak ended.
        ctl_prefix = os.path.join(workdir, "control", "fm")
        ctl_dir = ctl_prefix + ".ckpt"
        os.makedirs(ctl_dir, exist_ok=True)
        # pub_seen records every pointer value OBSERVED (the held-step
        # and response-subset assertions key on observation, not on
        # snapshot success); ctl_ok records the steps whose control
        # snapshot actually landed — a copytree can lose a race with
        # retention GC, in which case that step's parity is checked
        # only if the server also never scored it.
        pub_seen = set()
        ctl_ok = set()

        def snapshot(step) -> bool:
            src = os.path.join(ckpt_dir, str(step))
            dst = os.path.join(ctl_dir, str(step))
            if os.path.isdir(dst):
                return True
            if not os.path.isdir(src):
                return False
            try:
                shutil.copytree(src, dst)
                man = os.path.join(ckpt_dir, f"manifest-{step}.json")
                if os.path.isfile(man):
                    shutil.copy(man, os.path.join(
                        ctl_dir, f"manifest-{step}.json"))
                return True
            except OSError:
                # racing retention GC mid-copy: drop the partial snapshot
                # and let the next poll retry (the pointer only names live
                # steps, so a re-observation re-snapshots it)
                shutil.rmtree(dst, ignore_errors=True)
                return False

        def poll_pointer():
            while not stop_polling.is_set():
                s = read_published(ckpt_dir)
                if s is not None:
                    pub_seen.add(s)
                    # Retry failed/pending snapshots while their step
                    # dirs are still live (GC may yet win — that only
                    # weakens parity for a step nothing served).
                    for p in pub_seen - ctl_ok:
                        if snapshot(p):
                            ctl_ok.add(p)
                _time.sleep(0.005)

        poller = threading.Thread(target=poll_pointer,
                                  name="slo-pointer-poll", daemon=True)
        poller.start()
        wait_for(lambda: bool(ctl_ok), "pointer snapshot")

        # The serving plane, live against the moving pointer.
        scfg = dc.replace(cfg, metrics_file=serve_metrics,
                          serve_poll_seconds=0.02, serve_max_batch=8,
                          serve_max_wait_ms=2.0)
        server = ScorerServer(scfg)
        client = ScoreClient(server)
        req_lines = _corpus_lines(60, seed + 99)
        results, res_lock, errors = [], threading.Lock(), []

        def fire(worker: int) -> None:
            rng = np.random.default_rng(seed + worker)
            while not stop_firing.is_set():
                k = int(rng.integers(1, 6))
                lo = int(rng.integers(0, len(req_lines) - k))
                lines = req_lines[lo:lo + k]
                try:
                    res = client.score(lines, timeout=30)
                except Exception as e:  # noqa: BLE001 - assert at the end
                    errors.append(e)
                    return
                with res_lock:
                    results.append((lines, res.scores, res.step))

        clients = [threading.Thread(target=fire, args=(i,),
                                    name=f"slo-client-{i}")
                   for i in range(3)]
        for t in clients:
            t.start()
        wait_for(lambda: len(results) >= 5, "first served responses")

        # The poisoned burst: the same feature distribution with every
        # label flipped — training through it inverts the model, and the
        # next publish tick's validation sweep must catch it.
        write_shard([flip(ln) for ln in _corpus_lines(1600, seed + 3)])

        def gate_events():
            return [h for h in _summary(cfg).get("health_events", [])
                    if h.get("status") == "gate_held"]

        held = wait_for(gate_events, "gate_held health event")
        held_steps = {int(h["step"]) for h in held}
        pub_at_hold = read_published(ckpt_dir)
        n_before_recovery = len(results)

        # Recovery: clean shards until a NEW step publishes past the hold
        # — the closed loop healing itself.
        write_shard(_corpus_lines(800, seed + 4))
        write_shard(_corpus_lines(800, seed + 5))
        wait_for(lambda: read_published(ckpt_dir) not in (None,
                                                          pub_at_hold),
                 "post-recovery publish")
        open(os.path.join(sd, "STOP"), "w").close()
        try:
            rc = trainer.wait(timeout=180)
        except subprocess.TimeoutExpired:
            trainer.kill()
            raise AssertionError(
                f"trainer never drained the stream:\n{_trainer_tail()}")
        finally:
            trainer_out.close()
        assert rc == 0, f"trainer failed (rc {rc}):\n{_trainer_tail()}"
        final_pub = read_published(ckpt_dir)
        assert final_pub is not None
        pub_seen.add(final_pub)
        if snapshot(final_pub):  # post-join: the final step is live
            ctl_ok.add(final_pub)
        # Let the server observe the exit publish so responses cover the
        # final step too, then stop traffic.
        deadline = _time.monotonic() + 30
        while (server.served_step != final_pub
               and _time.monotonic() < deadline):
            _time.sleep(0.01)
        assert server.served_step == final_pub, (
            f"server never reloaded the final published step {final_pub} "
            f"(serving {server.served_step})")
        _time.sleep(0.1)  # a few requests on the final step
        stop_firing.set()
        for t in clients:
            t.join()
        assert not errors, errors[:3]
        server.close()
        stop_polling.set()
        poller.join(timeout=5)

        # --- the five SLO assertions -------------------------------------
        c = _counters(cfg)
        # (1) exactly-once consumption: every written line (good AND
        # poisoned) trained exactly once.
        assert c.get("train/examples") == total[0], (
            c.get("train/examples"), total[0])
        # (2) the gate caught the burst: >= 1 hold, the held steps never
        # published and never served, and serving CONTINUED through the
        # hold (responses kept landing before the recovery publish).
        assert held_steps, "no gate_held step recorded"
        assert int(c.get("quality/gate_held", 0)) >= 1, c
        assert not held_steps & pub_seen, (
            f"held step(s) {held_steps & pub_seen} reached the pointer")
        resp_steps = {r[2] for r in results}
        assert not held_steps & resp_steps, (
            f"held step(s) {held_steps & resp_steps} served traffic")
        assert resp_steps <= pub_seen, (
            f"responses tagged unpublished steps: {resp_steps - pub_seen}")
        assert len(results) > n_before_recovery, (
            "serving stalled during the gate hold")
        assert len(pub_seen) >= 2, pub_seen
        # (3) per-step score parity with the offline predict control: every
        # response bit-identical against its step's snapshot.
        pcfg = dc.replace(cfg, metrics_file="", model_file=ctl_prefix,
                          run_mode="epochs", stream_dir="",
                          publish_interval_seconds=0.0,
                          publish_min_auc=0.0, validation_files=())
        by_step = {}
        for lines, scores, step in results:
            by_step.setdefault(step, []).append((lines, scores))
        # Every SERVED step must have its control snapshot: the server
        # loads a step strictly after publishing it, and the retry
        # loop re-snapshots while the dir is live, so only a step
        # nothing ever served may legitimately lose the GC race.
        assert set(by_step) <= ctl_ok, (
            f"served step(s) {set(by_step) - ctl_ok} have no control "
            f"snapshot (observed {sorted(pub_seen)}, "
            f"snapshotted {sorted(ctl_ok)})")
        assert final_pub in by_step, (
            f"no responses landed on the final step {final_pub}")
        for step, pairs in sorted(by_step.items()):
            table = load_table(pcfg, step=step)
            req_path = os.path.join(workdir, f"requests_{step}.txt")
            flat, sizes = [], []
            for lines, _scores in pairs:
                flat.extend(lines)
                sizes.append(len(lines))
            with open(req_path, "w") as fh:
                fh.write("\n".join(flat) + "\n")
            want = sigmoid(predict_scores(pcfg, table, [req_path]))
            pos = 0
            for (lines, scores), n in zip(pairs, sizes):
                ref = want[pos:pos + n]
                pos += n
                assert np.array_equal(ref, scores), (
                    f"step {step}: served scores diverged from the "
                    f"offline predict control ({scores[:3]} vs {ref[:3]})")
        # (4) + (5) the declared SLOs all PASS from the JSONL alone —
        # publish staleness, serve p99, min AUC (recovered past the
        # poison), bad fraction — via the library AND the fmstat slo CLI.
        from fast_tffm_tpu.obs.attribution import summarize
        summary = summarize([cfg.metrics_file, serve_metrics])
        spec = SloSpec.from_summary(summary)
        slo_rows = evaluate_slos(spec, summary)
        assert len(slo_rows) == 4, slo_rows
        assert overall(slo_rows) == "PASS", [
            (r.objective, r.status, r.measured) for r in slo_rows]
        assert fmstat_main(["slo", cfg.metrics_file, serve_metrics,
                            "--json"]) == 0
        # fmstat renders the verdict + QUALITY section.
        v = _verdict(cfg)
        assert v.startswith("GATE-HELD"), v
        text = render(_summary(cfg))
        assert "QUALITY (per-publish eval + gate)" in text, text
        auc_final = summary["gauges"].get("quality/auc")
        return (f"{total[0]} streamed lines trained exactly once; gate "
                f"held {len(held)}x at step(s) {sorted(held_steps)} on the "
                f"poisoned burst (pointer pinned, serving continued), "
                f"{len(pub_seen)} publishes landed, {len(results)} "
                f"concurrent responses across {len(by_step)} step(s) all "
                f"bit-identical to the offline control, final AUC "
                f"{auc_final:.3f}, all 4 SLOs PASS")
    finally:
        stop_firing.set()
        stop_polling.set()
        for t in clients:
            t.join(timeout=10)
        if poller is not None:
            poller.join(timeout=5)
        if server is not None:
            server.close()  # idempotent: a no-op on the orderly path
        if trainer.poll() is None:
            trainer.kill()
            trainer.wait(timeout=30)
        try:
            trainer_out.close()
        except OSError:
            pass


# --- multi-worker compute-plane scenarios --------------------------------


def _free_port() -> int:
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_cluster_cfg(workdir: str, data: str, model: str,
                       metrics: str, epoch_num: int, elastic: str,
                       collective_timeout: float = 30.0,
                       save_steps: int = 0) -> str:
    """A 2-worker localhost cluster config with the compute-plane
    knobs the scenarios exercise: sub-second heartbeats so a dead
    worker goes visibly stale fast, and a small collective deadline so
    a hang is diagnosed in test time, not operator time."""
    coord = _free_port()
    cfg_path = os.path.join(workdir, f"cluster_{elastic}.cfg")
    with open(cfg_path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = 200
factor_num = 4
model_file = {model}

[Train]
train_files = {data}
epoch_num = {epoch_num}
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 0
save_steps = {save_steps}
metrics_file = {metrics}
metrics_flush_steps = 2

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
cluster_connect_timeout_seconds = 120
collective_timeout_seconds = {collective_timeout}
heartbeat_seconds = 0.4
elastic = {elastic}
""")
    return cfg_path


def _spawn_workers(workdir: str, cfg_path: str, n: int = 2):
    """Launch n real worker processes (run_tffm.py train ... dist_train
    worker i), stdout+stderr into worker<i>.out files."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = []
    for i in range(n):
        out = open(os.path.join(workdir, f"worker{i}.out"), "w")
        procs.append((subprocess.Popen(
            [sys.executable, "run_tffm.py", "train", cfg_path,
             "dist_train", "worker", str(i)],
            cwd=repo, env=env, stdout=out, stderr=subprocess.STDOUT),
            out))
    return procs


def _worker_out(workdir: str, i: int) -> str:
    with open(os.path.join(workdir, f"worker{i}.out")) as fh:
        return fh.read()


def _metrics_step(metrics_path: str) -> int:
    """Latest flushed train/steps counter in a (possibly mid-write)
    metrics stream — the milestone the scenarios key fault delivery
    on: steps flushing means every worker is past bring-up and
    stepping in lockstep."""
    best = 0
    try:
        with open(metrics_path, encoding="utf-8") as fh:
            for line in fh:
                if '"metrics"' not in line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail mid-write
                best = max(best, int((rec.get("counters") or {})
                                     .get("train/steps", 0)))
    except OSError:
        pass
    return best


def _reap(procs, sig=None) -> None:
    """Never leak a worker, assertions included. ``sig`` is delivered
    first to still-running workers (the hang scenario SIGCONTs its
    frozen worker so the SIGKILL can land)."""
    for p, out in procs:
        if p.poll() is None:
            if sig is not None:
                try:
                    p.send_signal(sig)
                except OSError:
                    pass
            try:
                p.kill()
            except OSError:
                pass
        try:
            p.wait(timeout=30)
        finally:
            out.close()


def scenario_kill_worker_midwindow(workdir: str, seed: int = 0) -> str:
    """SIGKILL one of 2 lockstep workers mid-run: with elastic=shrink
    the survivor diagnoses, reforms, restores the last verified
    checkpoint, and finishes the WHOLE schedule with every input shard
    of the recovered pass consumed exactly once; with elastic=off the
    survivor fails fast with the same named diagnosis."""
    import re
    import signal
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.testing.faults import committed_steps, wait_until
    from fast_tffm_tpu.train import checkpoint_template
    workdir = os.path.abspath(workdir)
    data = os.path.join(workdir, "train_elastic.txt")
    n_lines, batch = 4864, 32         # 152 exact steps per single pass
    steps_per_pass = n_lines // batch
    _write_corpus(data, n_lines, seed)

    # Phase A (elastic=shrink): a fresh 2-worker job with periodic
    # saves; SIGKILL worker 1 in the window between two saves — after
    # a committed step exists (the recovery's restore point) and well
    # clear of the next save's orbax commit barrier.
    model = os.path.join(workdir, "model", "fm")
    metrics = os.path.join(workdir, "metrics.jsonl")
    epochs, save_steps = 4, 60
    cfg_path = _write_cluster_cfg(workdir, data, model, metrics,
                                  epoch_num=epochs, elastic="shrink",
                                  save_steps=save_steps)
    procs = _spawn_workers(workdir, cfg_path)
    try:
        def mid_save_window() -> bool:
            committed = committed_steps(model)
            if not committed:
                return False
            s = _metrics_step(metrics)
            return (s >= committed[-1] + 3
                    and s % save_steps < save_steps - 15)

        wait_until(mid_save_window, timeout=240, interval=0.02,
                   message="2-worker job stepping past a committed "
                           "save, clear of the next")
        procs[1][0].send_signal(signal.SIGKILL)
        wait_until(lambda: procs[0][0].poll() is not None, timeout=300,
                   message="survivor finishing after the kill")
    finally:
        _reap(procs)
    out0 = _worker_out(workdir, 0)
    assert procs[0][0].returncode == 0, (
        f"survivor failed:\n{out0[-3000:]}")
    assert "worker lost" in out0 and "process 1" in out0, out0[-3000:]
    assert "elastic reform generation 1" in out0, out0[-3000:]
    assert "elastic recovery complete" in out0, out0[-3000:]
    assert "training done" in out0, out0[-3000:]
    # Exactly-once recovered pass: the survivor restored the last
    # verified checkpoint (step s0, epoch e0) and re-ran epochs
    # e0..epochs-1 ALONE, so each recovered epoch is one full
    # 152-step pass over every byte of the corpus — the dead worker's
    # shards redistributed by construction. Any dropped or
    # double-consumed shard changes the final step count.
    restores = re.findall(r"restored checkpoint at step (\d+)", out0)
    assert restores, "recovered session never restored a checkpoint"
    s0 = int(restores[-1])
    resumes = re.findall(
        r"resuming interrupted epoch schedule at epoch (\d+)/", out0)
    e0 = int(resumes[-1]) if resumes else 0
    cfg = FmConfig(vocabulary_size=200, factor_num=4, batch_size=batch,
                   epoch_num=epochs, train_files=(data,),
                   model_file=model)
    ckpt = CheckpointState(model)
    final = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    want_step = s0 + (epochs - e0) * steps_per_pass
    assert int(final["step"]) == want_step, (int(final["step"]),
                                             want_step, s0, e0)
    assert int(final["epoch"]) == epochs, int(final["epoch"])
    # fmstat over the chief stream + the dead worker's shard: the
    # worker_lost diagnosis and the elastic recovery land in ONE run
    # segment, and the verdict is DEGRADED (ranked below PREEMPTED).
    from fast_tffm_tpu.obs.attribution import health_verdict, summarize
    shards = [metrics] + ([metrics + ".p1"]
                          if os.path.exists(metrics + ".p1") else [])
    summary = summarize(shards)
    statuses = [h.get("status") for h in summary["health_events"]]
    assert "worker_lost" in statuses, statuses
    assert "elastic_recovered" in statuses, statuses
    v = health_verdict(summary)["verdict"]
    assert v == "DEGRADED (1 worker lost)", v

    # Phase B: same kill, elastic=off — fail FAST with the named
    # diagnosis (bounded by the collective deadline), never a hang.
    offdir = os.path.join(workdir, "off")
    os.makedirs(offdir, exist_ok=True)
    off_metrics = os.path.join(offdir, "metrics.jsonl")
    off_cfg = _write_cluster_cfg(
        offdir, data, os.path.join(offdir, "model", "fm"), off_metrics,
        epoch_num=20, elastic="off", collective_timeout=20.0)
    procs = _spawn_workers(offdir, off_cfg)
    try:
        wait_until(lambda: _metrics_step(off_metrics) >= 4, timeout=240,
                   message="elastic=off job stepping")
        procs[1][0].send_signal(signal.SIGKILL)
        # Fail-fast bound: deadline + staleness grace + teardown slack.
        wait_until(lambda: procs[0][0].poll() is not None, timeout=120,
                   message="elastic=off survivor failing fast")
    finally:
        _reap(procs)
    out0 = _worker_out(offdir, 0)
    assert procs[0][0].returncode != 0, "elastic=off must fail fast"
    assert "WorkerLostError" in out0 and "process 1" in out0, (
        out0[-3000:])
    return (f"shrink: survivor recovered to step {want_step}/"
            f"epoch {epochs} with verdict {v!r}; off: survivor failed "
            "fast naming process 1")


def scenario_hang_worker(workdir: str, seed: int = 0) -> str:
    """SIGSTOP one of 2 lockstep workers: the deadline guard expires
    and the survivor exits with a WorkerLostError naming the stopped
    process (its heartbeats went quiet without the process dying) —
    never an indefinite hang."""
    import signal
    from fast_tffm_tpu.testing.faults import wait_until
    workdir = os.path.abspath(workdir)
    data = os.path.join(workdir, "train_hang.txt")
    _write_corpus(data, 1216, seed)
    metrics = os.path.join(workdir, "metrics.jsonl")
    cfg_path = _write_cluster_cfg(
        workdir, data, os.path.join(workdir, "model", "fm"), metrics,
        epoch_num=20, elastic="off", collective_timeout=8.0)
    procs = _spawn_workers(workdir, cfg_path)
    try:
        wait_until(lambda: _metrics_step(metrics) >= 4, timeout=240,
                   message="2-worker job stepping")
        procs[1][0].send_signal(signal.SIGSTOP)
        # Never an indefinite hang: the guard's 8s deadline + the
        # staleness grace bound the diagnosis; 120s covers teardown.
        wait_until(lambda: procs[0][0].poll() is not None, timeout=120,
                   message="survivor diagnosing the stopped worker")
    finally:
        _reap(procs, sig=signal.SIGCONT)
    out0 = _worker_out(workdir, 0)
    assert procs[0][0].returncode != 0, (
        "survivor must fail fast, not complete, when a peer is "
        "stopped mid-schedule")
    assert "WorkerLostError" in out0, out0[-3000:]
    assert "process 1" in out0, out0[-3000:]
    from fast_tffm_tpu.obs.attribution import summarize
    summary = summarize([metrics])
    lost = [h for h in summary["health_events"]
            if h.get("status") == "worker_lost"]
    assert lost, summary["health_events"]
    named = {p.get("process_index")
             for h in lost for p in h.get("lost", [])}
    assert 1 in named, named
    return ("survivor diagnosed the SIGSTOPped worker 1 within the "
            "collective deadline and exited with WorkerLostError")


# --- elastic GROW scenarios ----------------------------------------------


def _write_grow_cfg(workdir: str, stream_dir: str, model: str,
                    metrics: str, join_settle: float = 2.5) -> str:
    """A 2-worker localhost STREAM cluster with elastic = grow: fast
    heartbeats/publishes so rendezvous runs in test time, an explicit
    uniq_bucket (no probe — bucket choice must not depend on which
    shards exist when a session starts), and per-step metrics flushes
    so a SIGKILLed worker's final counters are already durable (the
    exactly-once accounting below sums the dead worker's shard)."""
    coord = _free_port()
    cfg_path = os.path.join(workdir, "grow.cfg")
    with open(cfg_path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = 200
factor_num = 4
model_file = {model}

[Train]
epoch_num = 1
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 0
save_steps = 0
metrics_file = {metrics}
metrics_flush_steps = 1
run_mode = stream
stream_dir = {stream_dir}
stream_poll_seconds = 0.05
seal_policy = done
publish_interval_seconds = 0.3
max_features_per_example = 16
uniq_bucket = 256

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
cluster_connect_timeout_seconds = 120
collective_timeout_seconds = 30
heartbeat_seconds = 0.4
elastic = grow
join_settle_seconds = {join_settle}
""")
    return cfg_path


def _stage_shard(stream_dir: str, index: int, lines: list) -> None:
    """Publish one COMPLETE sealed shard atomically: written as a
    dotfile (discovery skips hidden names), renamed into place in one
    operation, sealed immediately. The bit-parity contract of the grow
    scenarios depends on this — a shard must never be discovered
    half-written, or batch grouping (and the final table's bits) would
    depend on writer/reader timing instead of only on the corpus."""
    name = f"part-{index:03d}.txt"
    tmp = os.path.join(stream_dir, "." + name)
    with open(tmp, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    os.replace(tmp, os.path.join(stream_dir, name))
    open(os.path.join(stream_dir, name + ".done"), "w").close()


def _spawn_joiner(workdir: str, cfg_path: str):
    """Launch the replacement worker: run_tffm.py train <cfg> --join."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    out = open(os.path.join(workdir, "joiner.out"), "w")
    return (subprocess.Popen(
        [sys.executable, "run_tffm.py", "train", cfg_path, "--join"],
        cwd=repo, env=env, stdout=out, stderr=subprocess.STDOUT), out)


class _SignalDeath(Exception):
    """A spawned worker died on SIGSEGV/SIGABRT/SIGBUS — the KNOWN
    upstream jaxlib restore-then-step crash class
    (tests/test_multiprocess._rerun_on_worker_signal carries the same
    bounded guard for the slow suite; the silent-corruption variant is
    fixed by checkpoint._restore_host_staged, the process-death
    variant still fires intermittently). Distinct from an assertion
    or a nonzero exit, which must NEVER retry."""

    def __init__(self, sig: int, what: str):
        super().__init__(f"worker died on signal {sig} {what}")
        self.sig = sig


_RERUN_SIGNALS = (11, 6, 7)  # SIGSEGV / SIGABRT / SIGBUS


def _raise_if_signal_death(p, what: str) -> None:
    rc = p.returncode
    if rc is not None and rc < 0 and -rc in _RERUN_SIGNALS:
        raise _SignalDeath(-rc, what)


def _retry_known_jaxlib_flake(body, workdir: str, name: str,
                              attempts: int = 2):
    """Bounded rerun for the known upstream crash above: ONLY a
    _SignalDeath reruns, each attempt in a FRESH subdir so leftover
    checkpoints/leases can't contaminate the retry; assertion failures
    and nonzero worker exits propagate on the first attempt — a real
    regression must never hide behind the retry."""
    import sys
    for attempt in range(attempts + 1):
        sub = os.path.join(workdir,
                           name if attempt == 0
                           else f"{name}_retry{attempt}")
        os.makedirs(sub, exist_ok=True)
        try:
            return body(sub)
        except _SignalDeath as e:
            if attempt >= attempts:
                raise
            print(f"fmchaos: {name}: worker died on signal {e.sig} "
                  f"(known jaxlib restore-then-step flake); rerun "
                  f"{attempt + 1}/{attempts}", file=sys.stderr)


def _wait_published(ckpt_dir: str, step: int, timeout: float = 240,
                    procs=()) -> None:
    """Block until the published pointer reaches ``step`` — the
    consumption gate between staged shards. Fails EARLY if a process
    whose exit we are not expecting dies (a crashed chief would
    otherwise burn the whole timeout looking at a frozen pointer); a
    SIGNAL death raises _SignalDeath so the bounded flake guard can
    rerun it."""
    from fast_tffm_tpu.checkpoint import read_published
    from fast_tffm_tpu.testing.faults import wait_until

    def due() -> bool:
        for p, _out in procs:
            if p.poll() is not None:
                _raise_if_signal_death(
                    p, f"while waiting for published step {step}")
                raise AssertionError(
                    f"worker exited rc={p.returncode} while waiting "
                    f"for published step {step}")
        return (read_published(ckpt_dir) or -1) >= step
    wait_until(due, timeout=timeout, interval=0.05,
               message=f"published pointer reaching step {step}")


def scenario_kill_then_grow(workdir: str, seed: int = 0) -> str:
    """ISSUE 14 acceptance: a 2-worker stream job loses worker 1 to
    SIGKILL mid-window, the survivor shrinks and keeps training, a
    freshly launched ``--join`` replacement is admitted at the next
    publish settle, and the run finishes at FULL membership — with
    exactly-once consumption (train/examples == every line written,
    summed across the chief's stream, the dead worker's shard, and the
    joiner's shard) and the final table BIT-IDENTICAL to an
    uninterrupted 2-worker control run over the same phase-gated
    corpus. fmstat renders RECOVERED, not DEGRADED: the cluster
    healed."""
    import signal
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.testing.faults import wait_until
    from fast_tffm_tpu.train import checkpoint_template
    workdir = os.path.abspath(workdir)
    lines_per, batch = 416, 32      # 13 EXACT steps per shard: batch
    steps_per = lines_per // batch  # grouping never spans shards, so
    # membership changes between shards cannot move batch boundaries
    shard_lines = [_corpus_lines(lines_per, seed * 10 + i)
                   for i in range(4)]

    def run_cluster(subdir: str, heal: bool) -> dict:
        """One phase-gated stream job over the 4 shards; with ``heal``
        the kill-then-grow sequence runs between shards 1 and 2 (ledger
        owners alternate 0,1,0,1 — shard 3 is consumed by the
        REPLACEMENT, proving the re-balance)."""
        os.makedirs(subdir, exist_ok=True)
        sd = os.path.join(subdir, "stream")
        os.makedirs(sd, exist_ok=True)
        model = os.path.join(subdir, "model", "fm")
        metrics = os.path.join(subdir, "metrics.jsonl")
        cfg_path = _write_grow_cfg(subdir, sd, model, metrics)
        ckpt_dir = model + ".ckpt"
        procs = _spawn_workers(subdir, cfg_path)
        joiner = None
        try:
            for i in (0, 1):
                _stage_shard(sd, i, shard_lines[i])
                _wait_published(ckpt_dir, steps_per * (i + 1),
                                procs=procs)
            if heal:
                # Mid-window kill: worker 1 sits in the lockstep
                # flags window (the stream idles between phases).
                procs[1][0].send_signal(signal.SIGKILL)
                wait_until(lambda: "elastic recovery complete"
                           in _worker_out(subdir, 0),
                           timeout=120, message="survivor shrinking")
                joiner = _spawn_joiner(subdir, cfg_path)
                wait_until(lambda: "input shards re-balanced"
                           in _worker_out(subdir, 0),
                           timeout=120, message="joiner admitted at "
                           "the publish settle")
            for i in (2, 3):
                _stage_shard(sd, i, shard_lines[i])
                _wait_published(
                    ckpt_dir, steps_per * (i + 1),
                    procs=[procs[0]] + ([joiner] if joiner else
                                        [procs[1]]))
            open(os.path.join(sd, "STOP"), "w").close()
            wait_until(lambda: procs[0][0].poll() is not None,
                       timeout=240, message="chief finishing")
            _raise_if_signal_death(procs[0][0], "at chief exit")
            if joiner is not None:
                wait_until(lambda: joiner[0].poll() is not None,
                           timeout=120, message="joiner finishing")
                _raise_if_signal_death(joiner[0], "at joiner exit")
        finally:
            _reap(procs)
            if joiner is not None:
                _reap([joiner])
        return {"cfg_path": cfg_path, "model": model,
                "metrics": metrics, "subdir": subdir,
                "joiner_rc": joiner[0].returncode if joiner else None,
                "chief_rc": procs[0][0].returncode}

    total = 4 * lines_per
    el = _retry_known_jaxlib_flake(
        lambda sub: run_cluster(sub, heal=True), workdir, "elastic")
    out0 = _worker_out(el["subdir"], 0)
    assert el["chief_rc"] == 0, f"chief failed:\n{out0[-3000:]}"
    assert el["joiner_rc"] == 0, (
        "joiner failed:\n"
        + open(os.path.join(el["subdir"], "joiner.out")).read()[-3000:])
    assert "worker lost" in out0 and "process 1" in out0, out0[-3000:]
    assert "elastic reform generation 1" in out0, out0[-3000:]
    assert "elastic grow generation 2" in out0, out0[-3000:]
    assert "training done" in out0, out0[-3000:]
    # Exactly-once across the membership changes: chief stream + the
    # DEAD worker's shard + the joiner's shard (two run segments in
    # the same .p1 file — the sink appends) sum to every line written.
    from fast_tffm_tpu.obs.attribution import health_verdict, summarize
    shards = [el["metrics"], el["metrics"] + ".p1"]
    assert os.path.exists(shards[1]), "worker-1 metrics shard missing"
    summary = summarize(shards)
    got = summary["counters"].get("train/examples")
    assert got == total, (got, total)
    statuses = [h.get("status") for h in summary["health_events"]]
    assert "worker_lost" in statuses, statuses
    kinds = [(h.get("kind"), h.get("status")) for h in
             summary["health_events"]
             if h.get("status") == "elastic_recovered"]
    assert ("shrink", "elastic_recovered") in kinds, kinds
    assert ("grow", "elastic_recovered") in kinds, kinds
    v = health_verdict(summary)["verdict"]
    assert v == "RECOVERED (gen 2, 2 workers)", v
    # Rendezvous litter: after 2 reforms only current-generation files
    # (and the live membership's leases) remain in the lease dir.
    hb_dir = os.path.abspath(el["model"]) + ".hb"
    litter = sorted(n for n in os.listdir(hb_dir)
                    if n.startswith(("reform-", "grow-", "commit-",
                                     "join-")))
    assert all(("-2-" in n or n.endswith("2.json")) for n in litter
               if n.startswith(("reform-", "grow-", "commit-"))), litter
    assert not [n for n in litter if n.startswith("join-")], litter
    # The control twin: an UNINTERRUPTED 2-worker run over the same
    # phase-gated corpus. Bit-identical final state pins that the
    # shrink+grow detour replayed nothing and skipped nothing.
    ct = _retry_known_jaxlib_flake(
        lambda sub: run_cluster(sub, heal=False), workdir, "control")
    assert ct["chief_rc"] == 0, _worker_out(ct["subdir"], 0)[-3000:]

    def final_state(run):
        cfg = load_config(run["cfg_path"])
        ckpt = CheckpointState(run["model"])
        restored = ckpt.restore(template=checkpoint_template(cfg))
        ckpt.close()
        return restored
    fe, fc = final_state(el), final_state(ct)
    assert int(fe["step"]) == int(fc["step"]) == 4 * steps_per, (
        int(fe["step"]), int(fc["step"]))
    for k in ("table", "acc"):
        a, b = np.asarray(fe[k]), np.asarray(fc[k])
        assert np.array_equal(a, b), (
            f"healed run's final {k} diverged from the uninterrupted "
            f"control: max |delta| = {np.abs(a - b).max()}")
    return (f"{total} lines consumed exactly once across SIGKILL -> "
            f"shrink (gen 1) -> --join grow (gen 2): final table "
            f"bit-identical to the uninterrupted 2-worker control at "
            f"step {int(fe['step'])}, verdict {v!r}, lease dir swept "
            "to current-generation files")


def scenario_grow_joiner_dies(workdir: str, seed: int = 0) -> str:
    """ISSUE 14 acceptance: a joiner SIGKILLed MID-RENDEZVOUS (after
    its announce, before the commit) never wedges the incumbents — the
    settle window expires, the dead joiner's lease is visibly stale,
    the reform COMMITS without it, and training continues to a clean
    finish. The stale ticket is never re-planned, and fmstat stays
    DEGRADED (the cluster never healed)."""
    import signal
    from fast_tffm_tpu.testing.faults import wait_until
    workdir = os.path.abspath(workdir)
    lines_per, batch = 416, 32
    steps_per = lines_per // batch

    def attempt(sub: str):
        sd = os.path.join(sub, "stream")
        os.makedirs(sd, exist_ok=True)
        model = os.path.join(sub, "model", "fm")
        metrics = os.path.join(sub, "metrics.jsonl")
        cfg_path = _write_grow_cfg(sub, sd, model, metrics,
                                   join_settle=2.5)
        ckpt_dir = model + ".ckpt"
        hb_dir = os.path.abspath(model) + ".hb"
        procs = _spawn_workers(sub, cfg_path)
        joiner = None
        try:
            _stage_shard(sd, 0, _corpus_lines(lines_per, seed))
            _wait_published(ckpt_dir, steps_per, procs=procs)
            procs[1][0].send_signal(signal.SIGKILL)
            wait_until(lambda: "elastic recovery complete"
                       in _worker_out(sub, 0),
                       timeout=120, message="survivor shrinking")
            joiner = _spawn_joiner(sub, cfg_path)

            def announced() -> bool:
                try:
                    return any(n.startswith("reform-2-")
                               and not n.startswith("reform-2-0")
                               for n in os.listdir(hb_dir))
                except OSError:
                    return False
            wait_until(announced, timeout=120, interval=0.005,
                       message="joiner announcing generation 2")
            # MID-RENDEZVOUS: announced, not yet committed (the settle
            # window always runs its full course — that is the
            # designed death-detection window). Kill it here.
            joiner[0].send_signal(signal.SIGKILL)
            wait_until(lambda: "never rendezvoused inside the settle "
                       "window" in _worker_out(sub, 0),
                       timeout=120, message="incumbent dropping the "
                       "dead joiner at the settle window")
            wait_until(lambda: "elastic recovery complete"
                       in _worker_out(sub, 0).split(
                           "never rendezvoused")[-1],
                       timeout=120, message="reform completing "
                       "without the dead joiner")
            # Training continues: the next shard is consumed and the
            # run finishes cleanly — the incumbents were never wedged.
            _stage_shard(sd, 1, _corpus_lines(lines_per, seed + 1))
            _wait_published(ckpt_dir, 2 * steps_per, procs=[procs[0]])
            open(os.path.join(sd, "STOP"), "w").close()
            wait_until(lambda: procs[0][0].poll() is not None,
                       timeout=240, message="survivor finishing")
            _raise_if_signal_death(procs[0][0], "at survivor exit")
        finally:
            _reap(procs)
            if joiner is not None:
                _reap([joiner])
        return sub, metrics, procs[0][0].returncode

    sub, metrics, rc0 = _retry_known_jaxlib_flake(attempt, workdir,
                                                  "run")
    out0 = _worker_out(sub, 0)
    assert rc0 == 0, out0[-3000:]
    assert "elastic grow generation 2: members [0]" in out0, (
        out0[-3000:])
    assert "training done" in out0, out0[-3000:]
    from fast_tffm_tpu.obs.attribution import health_verdict, summarize
    shards = [metrics] + ([metrics + ".p1"]
                          if os.path.exists(metrics + ".p1") else [])
    summary = summarize(shards)
    got = summary["counters"].get("train/examples")
    assert got == 2 * lines_per, (got, 2 * lines_per)
    grows = [h for h in summary["health_events"]
             if h.get("status") == "elastic_recovered"
             and h.get("kind") == "grow"]
    assert grows and grows[-1].get("members") == [0], grows
    v = health_verdict(summary)["verdict"]
    assert v == "DEGRADED (1 worker lost)", v
    return (f"joiner SIGKILLed mid-rendezvous: settle window dropped "
            f"it, reform committed [0] alone, survivor consumed all "
            f"{2 * lines_per} lines and finished (verdict {v!r}) — "
            "never wedged")


def scenario_oom_pressure(workdir: str, seed: int = 0) -> str:
    """Capacity wall under an injected HBM size (obs/memory.py): an
    oversized config is REFUSED by the pre-flight with the planner's
    per-owner breakdown (and the exact what-if invocation); a
    borderline config trains to completion while emitting
    ``health: hbm_pressure`` exactly once per episode, and fmstat
    renders the HBM-PRESSURE verdict."""
    from fast_tffm_tpu.obs.memory import (FAKE_CAPACITY_ENV, LEDGER,
                                          plan, table_bytes)
    from fast_tffm_tpu.train import train
    corpus = os.path.join(workdir, "train_oom.txt")
    _write_corpus(corpus, 400, seed)
    prev = os.environ.get(FAKE_CAPACITY_ENV)
    LEDGER.reset()
    try:
        # Leg 1: predicted resident bytes (a ~2 MB table) vs a 64 KB
        # injected capacity — refused at startup, never dispatched.
        big = _cfg(workdir, corpus, vocabulary_size=100000,
                   metrics_file=os.path.join(workdir,
                                             "metrics_big.jsonl"))
        os.environ[FAKE_CAPACITY_ENV] = str(64 * 1024)
        refused = False
        try:
            train(big)
        except ValueError as e:
            refused = True
            msg = str(e)
            assert "fmstat capacity" in msg, (
                f"pre-flight refusal must name the planner CLI: {msg}")
            assert "predicted device total" in msg, (
                f"pre-flight refusal must carry the breakdown: {msg}")
        assert refused, ("oversized config started under a 64 KB "
                         "injected capacity — pre-flight did not fire")
        LEDGER.reset()
        # Leg 2: borderline. The table+accumulator resident set is
        # ~60% of the injected capacity — above the 0.5 pressure
        # threshold at every flush (ONE episode, never re-armed), but
        # the full predicted set still FITS, so pre-flight lets it
        # run.
        cfg = _cfg(workdir, corpus, vocabulary_size=20000,
                   factor_num=8, mem_pressure_fraction=0.5)
        resident = 2 * table_bytes(cfg)
        cap = int(resident / 0.6)
        assert plan(cfg, "train")["total_bytes"] <= cap, (
            "scenario shape drifted: the borderline config no longer "
            "fits its own injected capacity")
        os.environ[FAKE_CAPACITY_ENV] = str(cap)
        train(cfg)
        h = [e for e in (_summary(cfg).get("health_events") or [])
             if e.get("status") == "hbm_pressure"]
        assert len(h) == 1, (
            f"expected exactly 1 hbm_pressure episode event, got "
            f"{len(h)}")
        assert h[0].get("owners"), "pressure event lost its owner map"
        v = _verdict(cfg)
        assert v.startswith("HBM-PRESSURE"), v
    finally:
        if prev is None:
            os.environ.pop(FAKE_CAPACITY_ENV, None)
        else:
            os.environ[FAKE_CAPACITY_ENV] = prev
        LEDGER.reset()
    return ("pre-flight refused the oversized config with the planner "
            "breakdown; the borderline run trained under pressure with "
            "exactly one hbm_pressure episode and fmstat reads "
            "HBM-PRESSURE")


SCENARIOS: Dict[str, Callable[..., str]] = {
    "skip": scenario_skip,
    "quarantine": scenario_quarantine,
    "max-bad": scenario_max_bad,
    "flaky-open": scenario_flaky_open,
    "flaky-open-parallel": scenario_flaky_open_parallel,
    "predict-flaky": scenario_predict_flaky,
    "serve-soak": scenario_serve_soak,
    "kill-replica-midburst": scenario_kill_replica_midburst,
    "staggered-reload": scenario_staggered_reload,
    "preempt-resume": scenario_preempt_resume,
    "stream-soak": scenario_stream_soak,
    "slo-soak": scenario_slo_soak,
    "stream-truncate": scenario_stream_truncate,
    "vocab-churn": scenario_vocab_churn,
    "truncate-latest": scenario_truncate_latest,
    "kill-async-save": scenario_kill_async_save,
    "kill-worker-midwindow": scenario_kill_worker_midwindow,
    "hang-worker": scenario_hang_worker,
    "kill-then-grow": scenario_kill_then_grow,
    "grow-joiner-dies": scenario_grow_joiner_dies,
    "oom-pressure": scenario_oom_pressure,
}


def main(argv: List[str] = None) -> int:
    import argparse
    import sys
    import tempfile
    ap = argparse.ArgumentParser(
        prog="fmchaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("scenarios", nargs="*",
                    help="scenario names (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--workdir", default=None,
                    help="keep artifacts here instead of a tempdir")
    args = ap.parse_args(argv)
    if args.list:
        for name in SCENARIOS:
            print(name)
        return 0
    # The chaos soaks run on CPU by contract (`make chaos` in CI): the
    # fault paths under test are host-side, and the scenarios must run
    # on machines with no accelerator.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    names = args.scenarios or list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"fmchaos: unknown scenario(s) {unknown}; "
              f"known: {list(SCENARIOS)}", file=sys.stderr)
        return 2
    failures = 0
    for name in names:
        if args.workdir:
            wd = os.path.join(args.workdir, name.replace("-", "_"))
            os.makedirs(wd, exist_ok=True)
            ctx = None
        else:
            ctx = tempfile.TemporaryDirectory(prefix=f"fmchaos_{name}_")
            wd = ctx.name
        try:
            detail = SCENARIOS[name](wd, seed=args.seed)
            print(f"PASS {name}: {detail}")
        except Exception as e:  # noqa: BLE001 - report, don't die
            failures += 1
            print(f"FAIL {name}: {type(e).__name__}: {e}",
                  file=sys.stderr)
        finally:
            if ctx is not None:
                ctx.cleanup()
    print(f"fmchaos: {len(names) - failures}/{len(names)} scenarios "
          "passed")
    return 1 if failures else 0
