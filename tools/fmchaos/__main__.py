import sys

from tools.fmchaos import main

if __name__ == "__main__":
    sys.exit(main())
