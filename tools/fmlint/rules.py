"""fmlint rules — the hot-loop device-fetch/print invariants.

Scope: HOT_MODULES below — the modules whose loops dispatch (or feed)
the jitted step stream. Everything else may fetch scalars freely; the
bench and tools print by design.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from tools.fmlint.core import Finding

# The hot-loop surface (ISSUE 2 satellite): the train/predict drivers,
# the batch pipeline, and the whole telemetry layer (obs/ must never
# cause the stalls it exists to measure).
HOT_MODULE_SUFFIXES = (
    "fast_tffm_tpu/train.py",
    "fast_tffm_tpu/predict.py",
    "fast_tffm_tpu/data/pipeline.py",
)
HOT_PACKAGE_FRAGMENTS = ("fast_tffm_tpu/obs/",)


def is_hot_module(path: str) -> bool:
    p = path.replace("\\", "/")
    return (p.endswith(HOT_MODULE_SUFFIXES)
            or any(frag in p for frag in HOT_PACKAGE_FRAGMENTS))


def _loops(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            yield node


def r001_scalar_fetch(path: str, tree: ast.AST) -> List[Finding]:
    """float(x)/int(x) inside any loop body, and .item() anywhere, in
    hot modules: each is a synchronous per-scalar device->host fetch
    when x is a device array — one such fetch in the hot stream stalls
    the async dispatch pipeline for seconds over a tunnelled link
    (measured 528k -> 50k examples/sec). Host-value exceptions carry a
    justified pragma; bulk paths go through utils/fetch.bulk_fetch."""
    if not is_hot_module(path):
        return []
    found: List[Finding] = []
    in_loop: set = set()
    for loop in _loops(tree):
        for node in ast.walk(loop):
            in_loop.add(id(node))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Name) and f.id in ("float", "int")
                and len(node.args) == 1
                and not isinstance(node.args[0], ast.Constant)
                and id(node) in in_loop):
            found.append(Finding(
                "R001", path, node.lineno,
                f"{f.id}() in a hot-loop body is a per-scalar device "
                "fetch if its argument is a device array; buffer and "
                "bulk_fetch at a barrier, or justify with a pragma"))
        if (isinstance(f, ast.Attribute) and f.attr == "item"
                and not node.args):
            found.append(Finding(
                "R001", path, node.lineno,
                ".item() is a per-scalar device fetch on device "
                "arrays; buffer and bulk_fetch at a barrier, or "
                "justify with a pragma"))
    return found


def r002_bare_print(path: str, tree: ast.AST) -> List[Finding]:
    """print() in hot modules: blocks the dispatch loop on stdout and
    bypasses the logging/telemetry sinks (get_logger / obs)."""
    if not is_hot_module(path):
        return []
    found: List[Finding] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            found.append(Finding(
                "R002", path, node.lineno,
                "bare print() in a hot-loop module; use "
                "utils.logging.get_logger or the obs/ sink"))
    return found


def r003_raw_perf_counter(path: str, tree: ast.AST) -> List[Finding]:
    """time.perf_counter() inside a loop body in hot modules: the
    hand-rolled version of span timing. obs/trace.span() is a no-op
    when no run traces (one module-global read), emits into the same
    JSONL stream fmtrace replays, and can't be forgotten half-paired.
    Raw timing that feeds an always-on aggregate (a telemetry counter/
    histogram) is legitimate — justify it with a pragma."""
    if not is_hot_module(path):
        return []
    in_loop: set = set()
    for loop in _loops(tree):
        for node in ast.walk(loop):
            in_loop.add(id(node))
    found: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or id(node) not in in_loop:
            continue
        f = node.func
        named = (isinstance(f, ast.Attribute) and f.attr == "perf_counter"
                 ) or (isinstance(f, ast.Name) and f.id == "perf_counter")
        if named:
            found.append(Finding(
                "R003", path, node.lineno,
                "raw perf_counter() in a hot-loop body; use the "
                "no-op-when-inactive obs.trace.span() for timeline "
                "timing, or justify an aggregate-feeding timer with "
                "a pragma"))
    return found


def _is_broad_handler(node: ast.ExceptHandler) -> bool:
    """Bare ``except:``, ``except Exception:``/``BaseException:``, or a
    tuple containing either."""
    t = node.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in ("Exception", "BaseException") for n in names)


def r004_swallowed_exception(path: str, tree: ast.AST) -> List[Finding]:
    """Broad swallow-and-continue in hot modules: a bare/``Exception``
    handler whose body is only ``pass``/``continue`` turns an
    unexpected failure — a wedged filesystem, a poisoned batch, a
    telemetry bug — into silence exactly where the fault-tolerance
    layer needs a counter, a health event, or a loud abort
    (data/badlines.py, utils/retry.py give it both). Narrow handlers
    (``except ParseError:``, ``except FileNotFoundError:``) are fine:
    they document the one expected failure they absorb. Deliberate
    broad swallows (a watchdog that must outlive its own bugs) carry
    a justified pragma."""
    if not is_hot_module(path):
        return []
    found: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        body_swallows = all(isinstance(s, (ast.Pass, ast.Continue))
                            for s in node.body)
        if body_swallows and _is_broad_handler(node):
            found.append(Finding(
                "R004", path, node.lineno,
                "broad except swallows and continues; narrow the "
                "exception type, count/emit the failure (obs/, "
                "data/badlines), or justify with a pragma"))
    return found


def r005_ckpt_delete(path: str, tree: ast.AST) -> List[Finding]:
    """``os.remove``/``os.unlink``/``shutil.rmtree`` aimed at
    checkpoint state OUTSIDE checkpoint.py: quarantine-not-delete is
    the state-plane invariant (a bad step dir is renamed
    ``corrupt-<step>`` so the bytes survive for forensics/recovery;
    only ``fmckpt gc`` — an explicit operator action — reclaims them).
    Heuristic: the deleted path's source expression mentions a
    checkpoint (``ckpt``) or a step dir. Applies to every linted
    module, not just hot ones — a cold cleanup path deleting a
    checkpoint is exactly as fatal. Deliberate deletions carry a
    justified pragma, as with R001–R004."""
    p = path.replace("\\", "/")
    if p.endswith("checkpoint.py"):
        return []
    found: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("remove",
                                                       "unlink",
                                                       "rmtree"):
            name = f.attr
        elif isinstance(f, ast.Name) and f.id == "rmtree":
            name = f.id
        else:
            continue
        try:
            arg_src = ast.unparse(node.args[0])
        except Exception:  # noqa: BLE001 - unparsable arg: skip
            continue
        low = arg_src.lower()
        if "ckpt" in low or "step_dir" in low:
            found.append(Finding(
                "R005", path, node.lineno,
                f"{name}() on a checkpoint path outside checkpoint.py "
                "breaks the quarantine-not-delete invariant; rename to "
                "corrupt-<step> (CheckpointState.quarantine_step) or "
                "justify with a pragma"))
    return found


# R006 scope: the modules whose blocking host collectives can park a
# whole cluster — the drivers, the lockstep protocol, the restore
# broadcasts, and (post the wire/stream PRs) the data plane's own
# agreement primitives: data/stream.py OWNS broadcast_blob /
# allgather_blob, and wire.py is the packed-transfer layer those
# payloads ride. parallel/liveness.py is the guard's own
# implementation (it receives collectives as arguments, never names
# them bare).
R006_MODULE_SUFFIXES = (
    "fast_tffm_tpu/train.py",
    "fast_tffm_tpu/predict.py",
    "fast_tffm_tpu/checkpoint.py",
    "fast_tffm_tpu/data/stream.py",
    "fast_tffm_tpu/wire.py",
)
R006_PACKAGE_FRAGMENTS = ("fast_tffm_tpu/parallel/",)
R006_COLLECTIVES = ("process_allgather", "broadcast_one_to_all",
                    "sync_global_devices")


def r006_unguarded_collective(path: str, tree: ast.AST) -> List[Finding]:
    """A bare blocking host collective (``process_allgather``,
    ``broadcast_one_to_all``, ``sync_global_devices``) CALLED outside
    ``guarded_collective()`` in the cluster-critical modules: one dead
    or wedged peer parks every caller of such a collective forever —
    the hang-forever failure mode the deadline guards exist to remove
    (parallel/liveness.py). Pass the collective INTO
    ``guarded_collective(multihost_utils.process_allgather, ...)`` —
    referencing the function is fine, calling it bare is the finding.
    Deliberate unguarded calls carry a justified pragma."""
    p = path.replace("\\", "/")
    in_scope = (p.endswith(R006_MODULE_SUFFIXES)
                or any(frag in p for frag in R006_PACKAGE_FRAGMENTS))
    if not in_scope or p.endswith("parallel/liveness.py"):
        return []
    found: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = None
        if isinstance(f, ast.Attribute) and f.attr in R006_COLLECTIVES:
            name = f.attr
        elif isinstance(f, ast.Name) and f.id in R006_COLLECTIVES:
            name = f.id
        if name is None:
            continue
        found.append(Finding(
            "R006", path, node.lineno,
            f"bare {name}() blocks forever on a dead peer; run it "
            "under parallel.liveness.guarded_collective(fn, ...) so a "
            "lost worker raises a named WorkerLostError, or justify "
            "with a pragma"))
    return found


# R011 scope: every linted module EXCEPT the two that ARE the
# embedding-storage seam — lookup.py (the backend gather/apply/reset
# surface) and the vocab/ package (the slot map itself).
R011_EXEMPT_SUFFIXES = ("fast_tffm_tpu/lookup.py",)
R011_EXEMPT_FRAGMENTS = ("fast_tffm_tpu/vocab/",)


def r011_raw_table_index(path: str, tree: ast.AST) -> List[Finding]:
    """Direct integer indexing into the embedding table (``table[ids]``
    or ``x.table[ids]``) outside lookup.py/vocab/: with ``vocab_mode =
    admit`` every id must route through the slot-indirection seam
    (vocab.VocabMap.remap / a lookup backend's gather) — a raw gather
    on unmapped ids is how eviction bugs are born: it reads rows the
    slot map may have reassigned or reset. Plain slices
    (``table[:n]``, checkpoint layout trims) are fine — they address
    LAYOUT, not ids. The jitted math that runs BELOW the seam (the
    batch reaching it is already physical-space) carries the usual
    justified pragma."""
    p = path.replace("\\", "/")
    if (p.endswith(R011_EXEMPT_SUFFIXES)
            or any(frag in p for frag in R011_EXEMPT_FRAGMENTS)):
        return []
    found: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Subscript):
            continue
        v = node.value
        named_table = ((isinstance(v, ast.Name) and v.id == "table")
                       or (isinstance(v, ast.Attribute)
                           and v.attr == "table"))
        if not named_table:
            continue
        def _layout(e) -> bool:
            # Slices and fixed rows address LAYOUT, not id routing.
            # Negative constants (table[-1], the dead tail row) parse
            # as UnaryOp(USub, Constant), not Constant.
            return (isinstance(e, (ast.Slice, ast.Constant))
                    or (isinstance(e, ast.UnaryOp)
                        and isinstance(e.op, ast.USub)
                        and isinstance(e.operand, ast.Constant)))

        sl = node.slice
        if _layout(sl):
            continue
        if isinstance(sl, ast.Tuple) and all(_layout(e)
                                             for e in sl.elts):
            continue
        found.append(Finding(
            "R011", path, node.lineno,
            "direct indexing into the embedding table bypasses the "
            "slot-indirection seam (vocab_mode = admit remaps ids to "
            "physical rows); gather through lookup.py / remap through "
            "vocab.VocabMap, or justify with a pragma"))
    return found


# R013 scope: the device-bound dispatch surfaces — train, predict, the
# scoring core, and the serving process. Every batch crossing the
# host->device wall there must route through the ONE wire-format
# encoder (fast_tffm_tpu/wire.py WireEncoder): an ad-hoc
# jax.device_put of raw [B, L] rectangles bypasses the packed format,
# the double-buffered dispatch, AND the h2d byte accounting at once.
# wire.py itself (the encoder's own put) is out of scope by
# construction; bench.py measures raw transfer deliberately and is
# not in scope either.
R013_MODULE_SUFFIXES = (
    "fast_tffm_tpu/train.py",
    "fast_tffm_tpu/predict.py",
    "fast_tffm_tpu/scoring.py",
)
R013_PACKAGE_FRAGMENTS = ("fast_tffm_tpu/serve/",)


def r013_adhoc_device_put(path: str, tree: ast.AST) -> List[Finding]:
    """Ad-hoc ``jax.device_put`` (or a bare imported ``device_put``)
    in a device-bound dispatch module: batch arrays must cross the
    wall through the wire encoder (``WireEncoder.device_put`` after
    ``encode_train``/``encode_score``) so the packed format, the
    depth-2 double buffer, and the ``train/h2d_bytes`` accounting all
    see the same arrays. Non-batch payloads (a warmup probe scalar)
    carry the usual justified pragma."""
    p = path.replace("\\", "/")
    if not (p.endswith(R013_MODULE_SUFFIXES)
            or any(frag in p for frag in R013_PACKAGE_FRAGMENTS)):
        return []
    found: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        adhoc = ((isinstance(f, ast.Name) and f.id == "device_put")
                 or (isinstance(f, ast.Attribute)
                     and f.attr == "device_put"
                     and isinstance(f.value, ast.Name)
                     and f.value.id in ("jax", "jnp")))
        if not adhoc:
            continue
        found.append(Finding(
            "R013", path, node.lineno,
            "ad-hoc device_put in a dispatch module bypasses the wire-"
            "format layer (packed encoding, double buffering, h2d byte "
            "accounting); route batches through wire.WireEncoder "
            "(encode_train/encode_score + .device_put), or justify "
            "with a pragma"))
    return found


# R018 scope: everywhere except the one memory seam. The runtime's
# memory introspection (memory_stats / live_arrays) must route through
# fast_tffm_tpu/obs/memory.device_memory_stats so the unmeasured-is-
# None policy, the CPU-backend opt-out, and the FM_FAKE_HBM_BYTES test
# injection hold at EVERY consumer — a direct call site sees real
# stats where a test injected fake ones, and branches a capacity
# decision the chaos suite cannot reach. The seam module itself is out
# of scope by construction (same shape as R013's one-encoder rule).
R018_SEAM_SUFFIX = "fast_tffm_tpu/obs/memory.py"
R018_CALLS = ("memory_stats", "live_arrays")


def r018_adhoc_memory_stats(path: str, tree: ast.AST) -> List[Finding]:
    """Direct ``memory_stats()`` / ``live_arrays()`` outside the
    obs/memory seam: capacity reads must share one policy (None when
    unmeasured, CPU opt-out, fake-capacity injection). Justified
    pragma for genuinely raw probes."""
    p = path.replace("\\", "/")
    if p.endswith(R018_SEAM_SUFFIX):
        return []
    found: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        adhoc = ((isinstance(f, ast.Name) and f.id in R018_CALLS)
                 or (isinstance(f, ast.Attribute)
                     and f.attr in R018_CALLS))
        if not adhoc:
            continue
        found.append(Finding(
            "R018", path, node.lineno,
            "direct device-memory introspection bypasses the one "
            "memory seam (obs/memory.device_memory_stats): the "
            "unmeasured-is-None policy, the CPU-backend opt-out, and "
            "the FM_FAKE_HBM_BYTES injection only hold through the "
            "seam; route through it, or justify with a pragma"))
    return found


RULES = (r001_scalar_fetch, r002_bare_print, r003_raw_perf_counter,
         r004_swallowed_exception, r005_ckpt_delete,
         r006_unguarded_collective, r011_raw_table_index,
         r013_adhoc_device_put, r018_adhoc_memory_stats)
