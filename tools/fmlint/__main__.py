import sys

from tools.fmlint.core import main

if __name__ == "__main__":
    sys.exit(main())
