"""fmlint — AST-based static checks for this repo's performance
invariants.

The invariants live in prose (README "Device-link sync pathology",
BASELINE.md's measured one-fetch-collapses-dispatch pathology); this
package makes the hot-loop subset machine-checked and wires it into
the tier-1 test run (tests/test_fmlint.py):

R001  per-scalar device fetch in a hot-loop module: ``float(x)`` /
      ``int(x)`` inside a loop body, or any ``.item()`` call — one
      synchronous scalar materialization in the hot stream costs
      seconds over a tunnelled device link (measured 528k -> 50k
      examples/sec).
R002  bare ``print(`` in a hot-loop module: stdout writes block the
      dispatch loop and bypass the logging/telemetry sinks.

Hot-loop modules: train.py, predict.py, data/pipeline.py, and all of
obs/ (the telemetry layer must never cause the stalls it measures).

Deliberate exceptions carry a justified pragma:

    x = float(probe)  # fmlint: disable=R001 -- pre-loop link probe

A whole-line pragma comment suppresses the entire next statement; a
pragma without a ``--`` justification is itself reported (R000).

Run: ``python -m tools.fmlint`` (repo default paths) or pass files.
"""

from tools.fmlint.core import Finding, main, run_paths

__all__ = ["Finding", "main", "run_paths"]
