"""fmlint — static checks for this repo's performance and
cluster-correctness invariants.

The invariants live in prose (README "Device-link sync pathology",
the PR 3-5 robustness postmortems); this package makes them
machine-checked and wires them into the tier-1 test run
(tests/test_fmlint.py). Two layers:

Per-file rules (stdlib-``ast``, tools/fmlint/rules.py):

R001  per-scalar device fetch in a hot-loop module (``float``/``int``
      in a loop body, any ``.item()``) — one synchronous scalar
      materialization in the hot stream costs seconds over a
      tunnelled device link (measured 528k -> 50k examples/sec).
R002  bare ``print(`` in a hot-loop module.
R003  raw ``perf_counter()`` pairs in hot loops (use obs.trace.span).
R004  broad swallow-and-continue handlers in hot modules.
R005  checkpoint deletion outside checkpoint.py (quarantine, never
      delete).
R006  bare blocking collective outside ``guarded_collective()``.
R999  file fails to parse (fails the gate for the whole surface).

Whole-program rules (tools/fmlint/project.py builds one parsed,
import-resolved, call-graph-summarized model of the full lint
surface; tools/fmlint/xrules.py consumes it):

R007  a collective reachable (transitively) on only one arm of a
      rank-conditioned branch — the multi-host deadlock.
R008  shared state written from a provably thread-reachable function
      without holding a lock.
R009  config/knob drift: knobs missing from sample.cfg/README,
      unknown sample.cfg keys, inconsistent ``FM_*`` env fallbacks,
      stale ``cfg.<attr>`` reads.
R010  raw ``open()`` on pipeline/checkpoint hot paths with no
      utils/retry wrapper and no explicit OSError contract.

Deliberate exceptions carry a justified pragma:

    x = float(probe)  # fmlint: disable=R001 -- pre-loop link probe

A whole-line pragma comment suppresses the entire next statement; a
pragma without a ``--`` justification is itself reported (R000).
``tools/fmlint/baseline.txt`` holds the committed baseline for
gradual adoption (``--update-baseline`` / ``--baseline``); ``--json``
emits machine-readable findings.

Run: ``python -m tools.fmlint`` (whole repo surface: fast_tffm_tpu/,
tools/, run_tffm.py, bench.py) or pass files/dirs.
"""

from tools.fmlint.core import Finding, main, run_file, run_paths

__all__ = ["Finding", "main", "run_file", "run_paths"]
