"""fmlint core: findings, suppression pragmas, runner, CLI.

Rules are stdlib-``ast`` analyses (tools/fmlint/rules.py) run per
file; findings then filter through the suppression pragmas:

    x = float(loss)   # fmlint: disable=R001 -- probed link, live mode
    # fmlint: disable=R001 -- host allgather result, not a device array
    spilled = int(tot[:, 0].sum())
    # fmlint: disable-file=R002 -- CLI module, print IS the output

``disable=`` on a code line suppresses matching findings on that line;
as a whole-line comment it suppresses the entire NEXT statement
(multi-line calls included). ``disable-file=`` suppresses the rule for
the whole file. The text after ``--`` is the REQUIRED justification —
a pragma without one is itself a finding (R000).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_PRAGMA = re.compile(
    r"#\s*fmlint:\s*(disable|disable-file)=([A-Z0-9,]+)"
    r"(?:\s*--\s*(.*))?")


@dataclasses.dataclass
class Suppressions:
    # rule -> set of suppressed line numbers (resolved statement spans)
    lines: Dict[str, Set[int]]
    file_rules: Set[str]
    bad_pragmas: List[Finding]  # R000: pragma without justification

    def allows(self, f: Finding) -> bool:
        if f.rule in self.file_rules:
            return True
        return f.line in self.lines.get(f.rule, ())


def _statement_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    """(lineno, end_lineno) for every statement, sorted by start."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt):
            spans.append((node.lineno, node.end_lineno or node.lineno))
    return sorted(spans)


def parse_suppressions(path: str, source: str,
                       tree: ast.AST) -> Suppressions:
    lines: Dict[str, Set[int]] = {}
    file_rules: Set[str] = set()
    bad: List[Finding] = []
    spans = _statement_spans(tree)

    def next_stmt_span(after_line: int) -> Tuple[int, int]:
        for lo, hi in spans:
            if lo > after_line:
                return lo, hi
        return after_line + 1, after_line + 1

    for i, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA.search(text)
        if not m:
            continue
        kind, rules_s, why = m.groups()
        rules = [r for r in rules_s.split(",") if r]
        if not (why or "").strip():
            bad.append(Finding(
                "R000", path, i,
                "suppression pragma without a `-- justification`"))
            continue
        if kind == "disable-file":
            file_rules.update(rules)
            continue
        whole_line = text.lstrip().startswith("#")
        if whole_line:
            lo, hi = next_stmt_span(i)
            covered = range(lo, hi + 1)
        else:
            covered = (i,)
        for r in rules:
            lines.setdefault(r, set()).update(covered)
    return Suppressions(lines=lines, file_rules=file_rules,
                       bad_pragmas=bad)


# --- parse cache -----------------------------------------------------------
#
# Parsing + suppression-scanning ~80 modules dominates a no-finding
# sweep's cost. Each file's (source, tree, suppressions) triple is
# pickled under .fmlint_cache/ keyed by (mtime_ns, size): an unchanged
# file is unpickled instead of re-parsed. Bump _CACHE_VERSION when the
# cached shape changes (pragma grammar, Suppressions layout). A cache
# that can't be read or written is ignored — caching is an
# optimization, never a correctness dependency.

_CACHE_VERSION = 1


def _cache_key(path: str) -> Optional[tuple]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (_CACHE_VERSION, sys.version_info[:2], st.st_mtime_ns,
            st.st_size)


def _cache_file(cache_dir: str, path: str) -> str:
    import hashlib
    return os.path.join(
        cache_dir, hashlib.sha1(path.encode("utf-8")).hexdigest()
        + ".pkl")


def _cache_get(cache_dir: str, path: str):
    import pickle
    key = _cache_key(path)
    if key is None:
        return None
    try:
        with open(_cache_file(cache_dir, path), "rb") as fh:
            entry = pickle.load(fh)
        if entry.get("key") == key:
            return entry["value"]
    except Exception:
        pass
    return None


def _cache_put(cache_dir: str, path: str, value) -> None:
    import pickle
    key = _cache_key(path)
    if key is None:
        return
    try:
        os.makedirs(cache_dir, exist_ok=True)
        target = _cache_file(cache_dir, path)
        tmp = target + f".tmp{os.getpid()}"
        with open(tmp, "wb") as fh:
            pickle.dump({"key": key, "value": value}, fh,
                        protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, target)  # atomic: no torn cache entries
    except Exception:
        pass


def default_cache_dir() -> str:
    return os.path.join(repo_root(), ".fmlint_cache")


def _parse_one(path: str, source: Optional[str] = None,
               cache_dir: Optional[str] = None):
    """(source, tree, suppressions) for one file, or a one-element
    R999 finding list when it doesn't parse. ``source`` (the overlay
    seam) bypasses the cache entirely."""
    if source is None and cache_dir is not None:
        hit = _cache_get(cache_dir, path)
        if hit is not None:
            return hit
    from_disk = source is None
    if source is None:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return source, None, [Finding("R999", path, e.lineno or 0,
                                      f"syntax error: {e.msg}")]
    result = source, tree, parse_suppressions(path, source, tree)
    if from_disk and cache_dir is not None:
        _cache_put(cache_dir, path, result)
    return result


def run_file(path: str) -> List[Finding]:
    """Per-file rules only (R000-R006 + R999). The whole-program pass
    (R007-R017; tools/fmlint/xrules.py) needs the full surface — use
    ``run_paths``."""
    from tools.fmlint.rules import RULES
    source, tree, supp = _parse_one(path)
    if tree is None:
        return supp  # the R999 finding list
    found: List[Finding] = list(supp.bad_pragmas)
    for rule_fn in RULES:
        found.extend(f for f in rule_fn(path, tree)
                     if not supp.allows(f))
    return sorted(found, key=lambda f: (f.path, f.line, f.rule))


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand dirs to their .py files. A path that doesn't exist or
    isn't lintable raises — a typo'd lint target must fail the gate,
    not exit 0 having linted zero files. Fully deterministic: both the
    directory descent order and the per-directory file order are
    sorted, so finding order — and therefore baseline diffs — is
    stable across filesystems."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                # In-place: os.walk descends in THIS order.
                _dirs[:] = sorted(d for d in _dirs
                                  if d != "__pycache__")
                out.extend(os.path.join(root, n) for n in sorted(names)
                           if n.endswith(".py"))
        elif os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(
                f"fmlint: {p!r} is not a directory or .py file")
    return out


def run_paths(paths: Sequence[str],
              overlay: Optional[Dict[str, str]] = None,
              baseline: Optional[str] = None,
              cache_dir: Optional[str] = None,
              profile: Optional[Dict[str, float]] = None,
              partial: bool = False) -> List[Finding]:
    """The whole-program pass: every file parsed ONCE, per-file rules
    (R000-R006) plus the cross-file rules (R007-R017) over one shared
    project model (tools/fmlint/project.py). ``overlay`` maps absolute
    paths to replacement source (the mutant-testing seam);
    ``baseline`` filters findings recorded in a committed baseline
    file (gradual adoption — see load_baseline); ``cache_dir`` reuses
    pickled parses for unchanged files (the CLI passes
    .fmlint_cache/); ``profile``, when a dict, receives per-stage and
    per-rule wall seconds; ``partial`` marks a subset surface
    (--changed): rules whose contract is "X appears NOWHERE on the
    surface" (the R009/R012 stale/drift directions) are skipped —
    absence over a subset proves nothing, and the full sweep remains
    the gate."""
    import time as _time
    from tools.fmlint.rules import RULES
    from tools.fmlint.project import load_project
    from tools.fmlint.xrules import PROGRAM_RULES

    def clocked(name: str, fn, *a):
        t0 = _time.perf_counter()
        out = fn(*a)
        if profile is not None:
            profile[name] = profile.get(name, 0.0) \
                + _time.perf_counter() - t0
        return out

    overlay = {os.path.abspath(k): v for k, v in (overlay or {}).items()}
    found: List[Finding] = []
    entries = []                      # (abspath, source, tree)
    supp_by_path: Dict[str, Suppressions] = {}
    for f in collect_files(paths):
        ap = os.path.abspath(f)
        source, tree, supp = clocked(
            "parse", _parse_one, ap, overlay.get(ap), cache_dir)
        if tree is None:
            found.extend(supp)        # R999: excluded from the project
            continue
        entries.append((ap, source, tree))
        supp_by_path[ap] = supp
        found.extend(supp.bad_pragmas)
        for rule_fn in RULES:
            found.extend(x for x in clocked(rule_fn.__name__,
                                            rule_fn, ap, tree)
                         if not supp.allows(x))
    proj = clocked("load_project", load_project, entries)
    for rule_fn in PROGRAM_RULES:
        if partial and getattr(rule_fn, "needs_full_surface", False):
            continue
        for x in clocked(rule_fn.__name__, rule_fn, proj):
            supp = supp_by_path.get(os.path.abspath(x.path))
            # Non-python findings (sample.cfg drift) carry no pragma
            # surface; the baseline below is their suppression path.
            if supp is None or not supp.allows(x):
                found.append(x)
    if baseline:
        found = apply_baseline(found, baseline, proj.root)
    return sorted(found, key=lambda f: (f.path, f.line, f.rule))


# --- incremental mode (--changed) ------------------------------------------

def _git_dirty_files(root: str) -> List[str]:
    """Absolute paths of git-dirty (modified/added/renamed/untracked)
    .py files under ``root``; [] when git is unavailable."""
    import subprocess
    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=30, check=True
        ).stdout
    except Exception:
        return []
    dirty: List[str] = []
    for line in out.splitlines():
        if len(line) < 4:
            continue
        rel = line[3:]
        if " -> " in rel:             # rename: lint the new name
            rel = rel.split(" -> ", 1)[1]
        rel = rel.strip().strip('"')
        if rel.endswith(".py"):
            dirty.append(os.path.join(root, rel))
    return dirty


def _imported_names(tree: ast.AST, modname: str) -> Set[str]:
    """Dotted module names this tree imports (absolute form),
    relative imports resolved against ``modname``."""
    out: Set[str] = set()
    pkg_parts = modname.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            out.update(alias.name for alias in node.names)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                base = pkg_parts[:len(pkg_parts) - node.level]
                stem = ".".join(base + ([node.module]
                                        if node.module else []))
            else:
                stem = node.module or ""
            if stem:
                out.add(stem)
                # `from pkg import name` may bind the submodule
                out.update(f"{stem}.{alias.name}"
                           for alias in node.names)
    return out


def changed_closure(paths: Sequence[str],
                    cache_dir: Optional[str] = None) -> List[str]:
    """The git-dirty .py files of the surface plus their reverse-
    import closure (everything that imports them, transitively) — the
    files whose findings an edit can change. Program rules then run
    over this subset only: the fast inner-loop check; the full sweep
    remains the gate."""
    from tools.fmlint.project import package_root
    files = [os.path.abspath(f) for f in collect_files(paths)]
    if not files:
        return []
    root = package_root(os.path.commonpath(
        [os.path.dirname(f) for f in files]))
    dirty = {f for f in _git_dirty_files(repo_root()) if f in set(files)}
    if not dirty:
        return []

    def modname(ap: str) -> str:
        rel = os.path.relpath(ap, root)
        return rel[:-3].replace(os.sep, ".")

    by_mod = {modname(f): f for f in files}
    importers: Dict[str, Set[str]] = {}   # file -> files importing it
    for f in files:
        parsed = _parse_one(f, cache_dir=cache_dir)
        tree = parsed[1]
        if tree is None:
            continue
        for name in _imported_names(tree, modname(f)):
            target = by_mod.get(name)
            if target is not None and target != f:
                importers.setdefault(target, set()).add(f)
    closure = set(dirty)
    frontier = list(dirty)
    while frontier:
        for dep in importers.get(frontier.pop(), ()):
            if dep not in closure:
                closure.add(dep)
                frontier.append(dep)
    return sorted(closure)


# --- committed baseline ----------------------------------------------------
#
# Gradual adoption: a repo turning a new rule on records its existing
# findings once (``--update-baseline``) and commits the file; the gate
# then fails only on NEW findings. Entries are line-number-free
# (``relpath|rule|message``) so unrelated edits shifting a file don't
# churn the baseline; each entry absorbs at most as many findings as
# its multiplicity.

def load_baseline(path: str) -> List[str]:
    keys: List[str] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                keys.append(line)
    return keys


def baseline_key(f: Finding, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(f.path), root)
    return f"{rel.replace(os.sep, '/')}|{f.rule}|{f.message}"


def apply_baseline(findings: List[Finding], path: str,
                   root: str) -> List[Finding]:
    from collections import Counter
    budget = Counter(load_baseline(path))
    out: List[Finding] = []
    for f in findings:
        k = baseline_key(f, root)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out


def write_baseline(findings: List[Finding], path: str,
                   root: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# fmlint baseline — one `relpath|rule|message` per "
                 "accepted pre-existing finding.\n"
                 "# Regenerate with: python -m tools.fmlint "
                 "--update-baseline\n")
        for f in findings:
            fh.write(baseline_key(f, root) + "\n")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def project_root_for(paths: Sequence[str]) -> str:
    """The root baseline keys are computed against — the same
    common-directory derivation the project loader uses, so a baseline
    written by ``--update-baseline`` matches what ``run_paths``
    applies."""
    from tools.fmlint.project import package_root
    dirs = [os.path.dirname(os.path.abspath(f))
            for f in collect_files(paths)]
    return package_root(os.path.commonpath(dirs)) if dirs \
        else os.getcwd()


def default_paths() -> List[str]:
    """The repo's lint surface when run with no arguments: the package,
    the tools, and the CLI entry points (each rule scopes itself to the
    modules it governs; the whole surface gets the R999 parse gate and
    the cross-file rules)."""
    here = repo_root()
    return [os.path.join(here, "fast_tffm_tpu"),
            os.path.join(here, "tools"),
            os.path.join(here, "run_tffm.py"),
            os.path.join(here, "bench.py")]


def default_baseline_path() -> Optional[str]:
    p = os.path.join(repo_root(), "tools", "fmlint", "baseline.txt")
    return p if os.path.isfile(p) else None


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = update = changed = do_profile = False
    json_out = protocol = None
    baseline = default_baseline_path()
    cache_dir: Optional[str] = default_cache_dir()
    paths: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--json":
            as_json = True
        elif a == "--update-baseline":
            update = True
        elif a == "--no-baseline":
            baseline = None
        elif a == "--no-cache":
            cache_dir = None
        elif a == "--changed":
            changed = True
        elif a == "--profile":
            do_profile = True
        elif a in ("--baseline", "--json-out", "--protocol"):
            flag = a
            i += 1
            if i >= len(args):
                print(f"fmlint: {flag} needs a value", file=sys.stderr)
                return 2
            if flag == "--baseline":
                baseline = args[i]
            elif flag == "--json-out":
                json_out = args[i]
            else:
                protocol = args[i]
        else:
            paths.append(a)
        i += 1
    if protocol is not None:
        # Dump the protocol automaton for one driver entry point
        # (qualified name, e.g. fast_tffm_tpu.train._train_session).
        from tools.fmlint.project import (load_project,
                                          protocol_automaton)
        entries = []
        for f in collect_files(paths or default_paths()):
            ap = os.path.abspath(f)
            source, tree, _supp = _parse_one(ap, cache_dir=cache_dir)
            if tree is not None:
                entries.append((ap, source, tree))
        proj = load_project(entries)
        if protocol not in proj.functions:
            close = sorted(q for q in proj.functions
                           if q.endswith("." + protocol)
                           or protocol in q)[:8]
            print(f"fmlint: unknown function {protocol!r}"
                  + (f"; close matches: {', '.join(close)}"
                     if close else ""), file=sys.stderr)
            return 2
        for line in protocol_automaton(proj, protocol):
            print(line)
        return 0
    lint_paths = paths or default_paths()
    if changed:
        lint_paths = changed_closure(lint_paths, cache_dir=cache_dir)
        if not lint_paths:
            print("fmlint: no git-dirty files on the lint surface",
                  file=sys.stderr)
            return 0
        print(f"fmlint: --changed linting {len(lint_paths)} file(s) "
              "(catalog-drift rules deferred to the full sweep)",
              file=sys.stderr)
    prof: Optional[Dict[str, float]] = {} if do_profile else None
    try:
        findings = run_paths(lint_paths,
                             baseline=None if update else baseline,
                             cache_dir=cache_dir, profile=prof,
                             partial=changed)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if prof is not None:
        total = sum(prof.values())
        print("fmlint: per-stage/per-rule wall time:", file=sys.stderr)
        for name, secs in sorted(prof.items(), key=lambda kv: -kv[1]):
            print(f"  {secs * 1000:8.1f} ms  {name}", file=sys.stderr)
        print(f"  {total * 1000:8.1f} ms  total", file=sys.stderr)
    if update:
        target = baseline or os.path.join(repo_root(), "tools",
                                          "fmlint", "baseline.txt")
        write_baseline(findings, target,
                       project_root_for(paths or default_paths()))
        print(f"fmlint: wrote {len(findings)} baseline entr"
              f"{'y' if len(findings) == 1 else 'ies'} to {target}",
              file=sys.stderr)
        return 0
    if as_json or json_out is not None:
        import json
        payload = json.dumps({
            "findings": [dataclasses.asdict(f) for f in findings],
            "count": len(findings)}, indent=2)
        if json_out is not None:
            # CI artifact: machine-readable findings alongside the
            # human rendering (make lint publishes this).
            with open(json_out, "w", encoding="utf-8") as fh:
                fh.write(payload + "\n")
        if as_json:
            print(payload)
    if not as_json:
        for f in findings:
            print(f.render())
    if findings:
        print(f"fmlint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0
