"""fmlint core: findings, suppression pragmas, runner, CLI.

Rules are stdlib-``ast`` analyses (tools/fmlint/rules.py) run per
file; findings then filter through the suppression pragmas:

    x = float(loss)   # fmlint: disable=R001 -- probed link, live mode
    # fmlint: disable=R001 -- host allgather result, not a device array
    spilled = int(tot[:, 0].sum())
    # fmlint: disable-file=R002 -- CLI module, print IS the output

``disable=`` on a code line suppresses matching findings on that line;
as a whole-line comment it suppresses the entire NEXT statement
(multi-line calls included). ``disable-file=`` suppresses the rule for
the whole file. The text after ``--`` is the REQUIRED justification —
a pragma without one is itself a finding (R000).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_PRAGMA = re.compile(
    r"#\s*fmlint:\s*(disable|disable-file)=([A-Z0-9,]+)"
    r"(?:\s*--\s*(.*))?")


@dataclasses.dataclass
class Suppressions:
    # rule -> set of suppressed line numbers (resolved statement spans)
    lines: Dict[str, Set[int]]
    file_rules: Set[str]
    bad_pragmas: List[Finding]  # R000: pragma without justification

    def allows(self, f: Finding) -> bool:
        if f.rule in self.file_rules:
            return True
        return f.line in self.lines.get(f.rule, ())


def _statement_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    """(lineno, end_lineno) for every statement, sorted by start."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt):
            spans.append((node.lineno, node.end_lineno or node.lineno))
    return sorted(spans)


def parse_suppressions(path: str, source: str,
                       tree: ast.AST) -> Suppressions:
    lines: Dict[str, Set[int]] = {}
    file_rules: Set[str] = set()
    bad: List[Finding] = []
    spans = _statement_spans(tree)

    def next_stmt_span(after_line: int) -> Tuple[int, int]:
        for lo, hi in spans:
            if lo > after_line:
                return lo, hi
        return after_line + 1, after_line + 1

    for i, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA.search(text)
        if not m:
            continue
        kind, rules_s, why = m.groups()
        rules = [r for r in rules_s.split(",") if r]
        if not (why or "").strip():
            bad.append(Finding(
                "R000", path, i,
                "suppression pragma without a `-- justification`"))
            continue
        if kind == "disable-file":
            file_rules.update(rules)
            continue
        whole_line = text.lstrip().startswith("#")
        if whole_line:
            lo, hi = next_stmt_span(i)
            covered = range(lo, hi + 1)
        else:
            covered = (i,)
        for r in rules:
            lines.setdefault(r, set()).update(covered)
    return Suppressions(lines=lines, file_rules=file_rules,
                       bad_pragmas=bad)


def run_file(path: str) -> List[Finding]:
    from tools.fmlint.rules import RULES
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("R999", path, e.lineno or 0,
                        f"syntax error: {e.msg}")]
    supp = parse_suppressions(path, source, tree)
    found: List[Finding] = list(supp.bad_pragmas)
    for rule_fn in RULES:
        found.extend(f for f in rule_fn(path, tree)
                     if not supp.allows(f))
    return sorted(found, key=lambda f: (f.path, f.line, f.rule))


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand dirs to their .py files. A path that doesn't exist or
    isn't lintable raises — a typo'd lint target must fail the gate,
    not exit 0 having linted zero files."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                out.extend(os.path.join(root, n) for n in sorted(names)
                           if n.endswith(".py"))
        elif os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(
                f"fmlint: {p!r} is not a directory or .py file")
    return out


def run_paths(paths: Sequence[str]) -> List[Finding]:
    found: List[Finding] = []
    for f in collect_files(paths):
        found.extend(run_file(f))
    return found


def default_paths() -> List[str]:
    """The repo's lint surface when run with no arguments: the whole
    package (each rule scopes itself to the modules it governs)."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return [os.path.join(here, "fast_tffm_tpu")]


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    try:
        findings = run_paths(args or default_paths())
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    for f in findings:
        print(f.render())
    if findings:
        print(f"fmlint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0
