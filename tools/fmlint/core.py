"""fmlint core: findings, suppression pragmas, runner, CLI.

Rules are stdlib-``ast`` analyses (tools/fmlint/rules.py) run per
file; findings then filter through the suppression pragmas:

    x = float(loss)   # fmlint: disable=R001 -- probed link, live mode
    # fmlint: disable=R001 -- host allgather result, not a device array
    spilled = int(tot[:, 0].sum())
    # fmlint: disable-file=R002 -- CLI module, print IS the output

``disable=`` on a code line suppresses matching findings on that line;
as a whole-line comment it suppresses the entire NEXT statement
(multi-line calls included). ``disable-file=`` suppresses the rule for
the whole file. The text after ``--`` is the REQUIRED justification —
a pragma without one is itself a finding (R000).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule} {self.message}"


_PRAGMA = re.compile(
    r"#\s*fmlint:\s*(disable|disable-file)=([A-Z0-9,]+)"
    r"(?:\s*--\s*(.*))?")


@dataclasses.dataclass
class Suppressions:
    # rule -> set of suppressed line numbers (resolved statement spans)
    lines: Dict[str, Set[int]]
    file_rules: Set[str]
    bad_pragmas: List[Finding]  # R000: pragma without justification

    def allows(self, f: Finding) -> bool:
        if f.rule in self.file_rules:
            return True
        return f.line in self.lines.get(f.rule, ())


def _statement_spans(tree: ast.AST) -> List[Tuple[int, int]]:
    """(lineno, end_lineno) for every statement, sorted by start."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt):
            spans.append((node.lineno, node.end_lineno or node.lineno))
    return sorted(spans)


def parse_suppressions(path: str, source: str,
                       tree: ast.AST) -> Suppressions:
    lines: Dict[str, Set[int]] = {}
    file_rules: Set[str] = set()
    bad: List[Finding] = []
    spans = _statement_spans(tree)

    def next_stmt_span(after_line: int) -> Tuple[int, int]:
        for lo, hi in spans:
            if lo > after_line:
                return lo, hi
        return after_line + 1, after_line + 1

    for i, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA.search(text)
        if not m:
            continue
        kind, rules_s, why = m.groups()
        rules = [r for r in rules_s.split(",") if r]
        if not (why or "").strip():
            bad.append(Finding(
                "R000", path, i,
                "suppression pragma without a `-- justification`"))
            continue
        if kind == "disable-file":
            file_rules.update(rules)
            continue
        whole_line = text.lstrip().startswith("#")
        if whole_line:
            lo, hi = next_stmt_span(i)
            covered = range(lo, hi + 1)
        else:
            covered = (i,)
        for r in rules:
            lines.setdefault(r, set()).update(covered)
    return Suppressions(lines=lines, file_rules=file_rules,
                       bad_pragmas=bad)


def _parse_one(path: str, source: Optional[str] = None):
    """(source, tree, suppressions) for one file, or a one-element
    R999 finding list when it doesn't parse."""
    if source is None:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return source, None, [Finding("R999", path, e.lineno or 0,
                                      f"syntax error: {e.msg}")]
    return source, tree, parse_suppressions(path, source, tree)


def run_file(path: str) -> List[Finding]:
    """Per-file rules only (R000-R006 + R999). The whole-program pass
    (R007-R010; tools/fmlint/xrules.py) needs the full surface — use
    ``run_paths``."""
    from tools.fmlint.rules import RULES
    source, tree, supp = _parse_one(path)
    if tree is None:
        return supp  # the R999 finding list
    found: List[Finding] = list(supp.bad_pragmas)
    for rule_fn in RULES:
        found.extend(f for f in rule_fn(path, tree)
                     if not supp.allows(f))
    return sorted(found, key=lambda f: (f.path, f.line, f.rule))


def collect_files(paths: Sequence[str]) -> List[str]:
    """Expand dirs to their .py files. A path that doesn't exist or
    isn't lintable raises — a typo'd lint target must fail the gate,
    not exit 0 having linted zero files. Fully deterministic: both the
    directory descent order and the per-directory file order are
    sorted, so finding order — and therefore baseline diffs — is
    stable across filesystems."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                # In-place: os.walk descends in THIS order.
                _dirs[:] = sorted(d for d in _dirs
                                  if d != "__pycache__")
                out.extend(os.path.join(root, n) for n in sorted(names)
                           if n.endswith(".py"))
        elif os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        else:
            raise FileNotFoundError(
                f"fmlint: {p!r} is not a directory or .py file")
    return out


def run_paths(paths: Sequence[str],
              overlay: Optional[Dict[str, str]] = None,
              baseline: Optional[str] = None) -> List[Finding]:
    """The whole-program pass: every file parsed ONCE, per-file rules
    (R000-R006) plus the cross-file rules (R007-R010) over one shared
    project model (tools/fmlint/project.py). ``overlay`` maps absolute
    paths to replacement source (the mutant-testing seam);
    ``baseline`` filters findings recorded in a committed baseline
    file (gradual adoption — see load_baseline)."""
    from tools.fmlint.rules import RULES
    from tools.fmlint.project import load_project
    from tools.fmlint.xrules import PROGRAM_RULES
    overlay = {os.path.abspath(k): v for k, v in (overlay or {}).items()}
    found: List[Finding] = []
    entries = []                      # (abspath, source, tree)
    supp_by_path: Dict[str, Suppressions] = {}
    for f in collect_files(paths):
        ap = os.path.abspath(f)
        source, tree, supp = _parse_one(ap, overlay.get(ap))
        if tree is None:
            found.extend(supp)        # R999: excluded from the project
            continue
        entries.append((ap, source, tree))
        supp_by_path[ap] = supp
        found.extend(supp.bad_pragmas)
        for rule_fn in RULES:
            found.extend(x for x in rule_fn(ap, tree)
                         if not supp.allows(x))
    proj = load_project(entries)
    for rule_fn in PROGRAM_RULES:
        for x in rule_fn(proj):
            supp = supp_by_path.get(os.path.abspath(x.path))
            # Non-python findings (sample.cfg drift) carry no pragma
            # surface; the baseline below is their suppression path.
            if supp is None or not supp.allows(x):
                found.append(x)
    if baseline:
        found = apply_baseline(found, baseline, proj.root)
    return sorted(found, key=lambda f: (f.path, f.line, f.rule))


# --- committed baseline ----------------------------------------------------
#
# Gradual adoption: a repo turning a new rule on records its existing
# findings once (``--update-baseline``) and commits the file; the gate
# then fails only on NEW findings. Entries are line-number-free
# (``relpath|rule|message``) so unrelated edits shifting a file don't
# churn the baseline; each entry absorbs at most as many findings as
# its multiplicity.

def load_baseline(path: str) -> List[str]:
    keys: List[str] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                keys.append(line)
    return keys


def baseline_key(f: Finding, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(f.path), root)
    return f"{rel.replace(os.sep, '/')}|{f.rule}|{f.message}"


def apply_baseline(findings: List[Finding], path: str,
                   root: str) -> List[Finding]:
    from collections import Counter
    budget = Counter(load_baseline(path))
    out: List[Finding] = []
    for f in findings:
        k = baseline_key(f, root)
        if budget.get(k, 0) > 0:
            budget[k] -= 1
        else:
            out.append(f)
    return out


def write_baseline(findings: List[Finding], path: str,
                   root: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# fmlint baseline — one `relpath|rule|message` per "
                 "accepted pre-existing finding.\n"
                 "# Regenerate with: python -m tools.fmlint "
                 "--update-baseline\n")
        for f in findings:
            fh.write(baseline_key(f, root) + "\n")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def project_root_for(paths: Sequence[str]) -> str:
    """The root baseline keys are computed against — the same
    common-directory derivation the project loader uses, so a baseline
    written by ``--update-baseline`` matches what ``run_paths``
    applies."""
    from tools.fmlint.project import package_root
    dirs = [os.path.dirname(os.path.abspath(f))
            for f in collect_files(paths)]
    return package_root(os.path.commonpath(dirs)) if dirs \
        else os.getcwd()


def default_paths() -> List[str]:
    """The repo's lint surface when run with no arguments: the package,
    the tools, and the CLI entry points (each rule scopes itself to the
    modules it governs; the whole surface gets the R999 parse gate and
    the cross-file rules)."""
    here = repo_root()
    return [os.path.join(here, "fast_tffm_tpu"),
            os.path.join(here, "tools"),
            os.path.join(here, "run_tffm.py"),
            os.path.join(here, "bench.py")]


def default_baseline_path() -> Optional[str]:
    p = os.path.join(repo_root(), "tools", "fmlint", "baseline.txt")
    return p if os.path.isfile(p) else None


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = update = False
    baseline = default_baseline_path()
    paths: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--json":
            as_json = True
        elif a == "--update-baseline":
            update = True
        elif a == "--no-baseline":
            baseline = None
        elif a == "--baseline":
            i += 1
            if i >= len(args):
                print("fmlint: --baseline needs a path",
                      file=sys.stderr)
                return 2
            baseline = args[i]
        else:
            paths.append(a)
        i += 1
    try:
        findings = run_paths(paths or default_paths(),
                             baseline=None if update else baseline)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if update:
        target = baseline or os.path.join(repo_root(), "tools",
                                          "fmlint", "baseline.txt")
        write_baseline(findings, target,
                       project_root_for(paths or default_paths()))
        print(f"fmlint: wrote {len(findings)} baseline entr"
              f"{'y' if len(findings) == 1 else 'ies'} to {target}",
              file=sys.stderr)
        return 0
    if as_json:
        import json
        print(json.dumps({
            "findings": [dataclasses.asdict(f) for f in findings],
            "count": len(findings)}, indent=2))
    else:
        for f in findings:
            print(f.render())
    if findings:
        print(f"fmlint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0
