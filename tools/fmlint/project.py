"""fmlint whole-program layer: the project loader the cross-file rules
(tools/fmlint/xrules.py, R007-R012 and the R014-R017 protocol/lock
model checker) consume.

Every module on the lint surface is parsed ONCE into a ``Project``:

- an import table per module (``import a.b as c`` / ``from a import b``
  in any scope — function-level imports, which this codebase uses
  heavily to defer jax, are treated module-wide);
- a function index over plain functions, methods, and nested defs
  (``pkg.mod.Class.method``, ``pkg.mod.outer.worker``);
- a call graph restricted to what static resolution can PROVE:
  bare names through local/nested/module scope and imports,
  ``self.method()`` within the enclosing class, and
  ``imported_module.func()`` chains. Attribute calls on arbitrary
  objects stay unresolved — the summaries underclaim rather than
  guess, so rule findings are evidence, not speculation;
- fixpoint summaries over that graph:

  * ``may_collectives[qualname]`` — which blocking collectives
    (``process_allgather``, ``broadcast_one_to_all``,
    ``sync_global_devices``, ``guarded_collective``) a call to this
    function may transitively execute (R007's reachability);
  * ``thread_funcs`` — functions that can run on a spawned thread:
    every resolved ``threading.Thread(target=...)`` entry point plus
    its transitive callees (R008's "proves can run on a thread");
  * per-function shared-state writes (``self.attr`` assignment /
    augassign / subscript store, known in-place mutator calls, and
    mutations of module-level globals) with a held-a-lock bit
    (R008's evidence);
  * project-wide ``FM_*`` environment reads and ``cfg.<knob>``
    attribute reads (R009's env/knob consistency).

Loading accepts a source ``overlay`` keyed by absolute path, so tests
can analyze the REAL repo with one file's source swapped for a mutant
(the R007 seeded-deadlock acceptance test) without touching disk.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

# The blocking host collectives (and their one sanctioned wrapper) —
# the same surface R006 polices per call site. ``guarded_collective``
# counts: it EXECUTES the collective it wraps, so a rank-gated guarded
# call deadlocks exactly like a bare one.
COLLECTIVE_NAMES = ("process_allgather", "broadcast_one_to_all",
                    "sync_global_devices", "guarded_collective")

# Blocking device fetches: a D2H transfer (or a wait for one) parks the
# calling thread until the producing program completes — on a dead
# cluster that is an indefinite block, and under a lock (R017) it
# wedges every other thread contending for the lock behind device
# latency.
FETCH_NAMES = ("block_until_ready", "bulk_fetch", "device_get")

# In-place mutator methods: a call to one of these on a shared object
# is a write even though no assignment appears.
_MUTATORS = frozenset({
    "add", "append", "appendleft", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update",
})


@dataclasses.dataclass
class SharedWrite:
    """One write to shared state observed in a function body."""
    line: int
    target: str        # human-readable, e.g. "self._stalled_at"
    locked: bool       # lexically inside a `with <...lock...>:` block


@dataclasses.dataclass
class LockAcquire:
    """One ``with <lock>:`` acquisition, with the locks already held
    lexically at that point (outermost first) — the raw edges of the
    R016 lock-order graph."""
    line: int
    lock: str                  # normalized identity, e.g.
    #                            "pkg.serve.server.ScorerServer._lock"
    held: Tuple[str, ...]      # locks held when this one is taken


@dataclasses.dataclass
class LockedCall:
    """One call made while holding at least one lock (R016's
    interprocedural edges; R017's held-across-blocking-op evidence)."""
    line: int
    locks: Tuple[str, ...]     # held locks, outermost first
    basename: Optional[str]    # the called name ("device_get", ...)
    callee: Optional[str]      # resolved qualname, if provable


@dataclasses.dataclass
class FunctionInfo:
    qualname: str
    module: "ModuleInfo"
    node: ast.AST
    cls: Optional[str] = None       # enclosing class name, if a method
    parent: Optional[str] = None    # enclosing function qualname
    nested: Dict[str, str] = dataclasses.field(default_factory=dict)
    calls: Set[str] = dataclasses.field(default_factory=set)
    direct_collectives: Set[str] = dataclasses.field(default_factory=set)
    # (line, kind) per direct collective call site, in source order —
    # R015 anchors findings here; the protocol extraction orders them.
    collective_sites: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list)
    direct_fetches: Set[str] = dataclasses.field(default_factory=set)
    thread_targets: Set[str] = dataclasses.field(default_factory=set)
    shared_writes: List[SharedWrite] = dataclasses.field(
        default_factory=list)
    lock_acquires: List[LockAcquire] = dataclasses.field(
        default_factory=list)
    locked_calls: List[LockedCall] = dataclasses.field(
        default_factory=list)

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]


@dataclasses.dataclass
class EnvRead:
    path: str
    line: int
    var: str


@dataclasses.dataclass
class KnobRead:
    path: str
    line: int
    obj: str   # the receiver name ("cfg")
    attr: str  # the knob attribute read


@dataclasses.dataclass
class ModuleInfo:
    path: str
    modname: str
    tree: ast.Module
    source: str
    is_package: bool = False      # an __init__.py (modname IS the pkg)
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    toplevel: Dict[str, str] = dataclasses.field(default_factory=dict)
    globals: Set[str] = dataclasses.field(default_factory=set)


class Project:
    """The parsed, resolved, summarized lint surface."""

    def __init__(self, root: str):
        self.root = root
        self.modules: Dict[str, ModuleInfo] = {}       # modname -> info
        self.by_path: Dict[str, ModuleInfo] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.may_collectives: Dict[str, Set[str]] = {}
        self.may_locks: Dict[str, Set[str]] = {}
        self.may_fetch: Set[str] = set()
        self.thread_funcs: Set[str] = set()
        self.env_reads: List[EnvRead] = []
        self.knob_reads: List[KnobRead] = []

    # -- convenience for rules ------------------------------------------
    def module_at(self, suffix: str) -> Optional[ModuleInfo]:
        """The one module whose normalized path ends with ``suffix``."""
        suffix = suffix.replace("\\", "/")
        for m in self.by_path.values():
            if m.path.replace("\\", "/").endswith(suffix):
                return m
        return None

    def collectives_of(self, qualname: str) -> Set[str]:
        return self.may_collectives.get(qualname, set())


def package_root(directory: str) -> str:
    """Walk up out of package directories (ones holding __init__.py):
    module names must match what import statements say, so the root is
    the first NON-package ancestor — linting ``repo/pkg/sub`` alone
    must still name its modules ``pkg.sub.x``."""
    d = os.path.abspath(directory)
    while os.path.isfile(os.path.join(d, "__init__.py")):
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return d


def _modname(path: str, root: str) -> str:
    rel = os.path.relpath(os.path.abspath(path), root)
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = [p for p in rel.replace("\\", "/").split("/") if p != "."]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def load_project(entries: Sequence[Tuple[str, str, ast.Module]],
                 root: Optional[str] = None) -> Project:
    """Build a Project from pre-parsed ``(path, source, tree)`` entries
    (tools/fmlint/core.py parses each file exactly once and shares the
    trees between the per-file rules and this loader)."""
    paths = [os.path.abspath(p) for p, _, _ in entries]
    if root is None:
        dirs = [os.path.dirname(p) for p in paths] or [os.getcwd()]
        root = package_root(os.path.commonpath(dirs))
    proj = Project(root)
    for path, source, tree in entries:
        mod = ModuleInfo(path=os.path.abspath(path),
                         modname=_modname(path, root),
                         tree=tree, source=source,
                         is_package=os.path.basename(path)
                         == "__init__.py")
        _collect_imports(mod)
        _collect_toplevel(mod)
        proj.modules[mod.modname] = mod
        proj.by_path[mod.path] = mod
    for mod in proj.modules.values():
        _index_functions(proj, mod)
    for fn in proj.functions.values():
        _analyze_function(proj, fn)
    _fixpoint_collectives(proj)
    _fixpoint_threads(proj)
    _fixpoint_locks(proj)
    _fixpoint_fetch(proj)
    return proj


def parse_files(paths: Sequence[str],
                overlay: Optional[Dict[str, str]] = None
                ) -> List[Tuple[str, str, ast.Module]]:
    """Parse files into loader entries, skipping unparsable ones (the
    caller reports those as R999). ``overlay`` maps absolute paths to
    replacement source — the mutant-testing seam."""
    overlay = {os.path.abspath(k): v for k, v in (overlay or {}).items()}
    out: List[Tuple[str, str, ast.Module]] = []
    for p in paths:
        ap = os.path.abspath(p)
        if ap in overlay:
            source = overlay[ap]
        else:
            with open(ap, "r", encoding="utf-8") as fh:
                source = fh.read()
        try:
            out.append((ap, source, ast.parse(source, filename=ap)))
        except SyntaxError:
            continue
    return out


# --- per-module collection -------------------------------------------------

def _collect_imports(mod: ModuleInfo) -> None:
    """Alias -> dotted-target table. Imports ANYWHERE in the module
    (this repo defers heavy imports into function bodies) are treated
    as module-wide: for call RESOLUTION that over-approximates scope
    harmlessly — a name only resolves if something imported it."""
    pkg = mod.modname.rsplit(".", 1)[0] if "." in mod.modname else ""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                mod.imports[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
                if a.asname is None and "." in a.name:
                    # `import a.b.c` binds `a`, but the full dotted
                    # path is resolvable too.
                    mod.imports[a.name] = a.name
        elif isinstance(node, ast.ImportFrom):
            base = node.module or ""
            if node.level:
                up = mod.modname.split(".") if mod.modname else []
                # level=1 strips the module's own name, each extra
                # level strips one more package — but an __init__.py's
                # modname IS its package (no own-name segment to
                # strip), so it drops one level fewer.
                drop = node.level - (1 if mod.is_package else 0)
                if drop > 0:
                    up = up[:len(up) - drop]
                base = ".".join(up + ([node.module]
                                      if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                mod.imports[a.asname or a.name] = (
                    f"{base}.{a.name}" if base else a.name)


def _collect_toplevel(mod: ModuleInfo) -> None:
    for node in mod.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        mod.globals.add(n.id)


def _iter_scope_children(node):
    """Direct defs of a scope, INCLUDING ones nested inside compound
    statements (a thread-target closure defined under ``if`` — the
    Watchdog/HeartbeatLease start() pattern — is still this scope's)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop(0)
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
            yield child
        else:
            stack.extend(ast.iter_child_nodes(child))


def _index_functions(proj: Project, mod: ModuleInfo) -> None:
    def visit(node, prefix: str, cls: Optional[str],
              parent: Optional[FunctionInfo]):
        for child in _iter_scope_children(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}"
                fn = FunctionInfo(qualname=q, module=mod, node=child,
                                  cls=cls,
                                  parent=parent.qualname if parent
                                  else None)
                proj.functions[q] = fn
                if parent is not None:
                    parent.nested[child.name] = q
                elif cls is None:
                    mod.toplevel[child.name] = q
                # Nested defs keep the enclosing class context: a
                # thread-target closure inside a method closes over
                # `self`, and its `self.x()` calls must resolve.
                visit(child, q, cls, fn)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}.{child.name}", child.name,
                      parent)

    visit(mod.tree, mod.modname, None, None)


# --- per-function analysis -------------------------------------------------

def _dotted(expr) -> Optional[List[str]]:
    """["a", "b", "c"] for a pure a.b.c chain, else None."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return parts[::-1]
    return None


def resolve_call(proj: Project, fn: FunctionInfo,
                 func_expr) -> Optional[str]:
    """Qualname of the function a call expression provably targets, or
    None. See the module docstring for what 'provably' covers."""
    mod = fn.module
    if isinstance(func_expr, ast.Name):
        name = func_expr.id
        cur: Optional[FunctionInfo] = fn
        while cur is not None:      # nested defs / closures, innermost out
            if name in cur.nested:
                return cur.nested[name]
            cur = proj.functions.get(cur.parent) if cur.parent else None
        if name in mod.toplevel:
            return mod.toplevel[name]
        tgt = mod.imports.get(name)
        if tgt is not None and tgt in proj.functions:
            return tgt
        return None
    parts = _dotted(func_expr)
    if not parts or len(parts) < 2:
        return None
    if parts[0] in ("self", "cls") and fn.cls is not None and len(
            parts) == 2:
        return f"{mod.modname}.{fn.cls}.{parts[1]}"
    # imported_module.func (or pkg.sub.func through an import alias)
    for split in range(len(parts) - 1, 0, -1):
        alias = ".".join(parts[:split])
        tgt = mod.imports.get(alias)
        if tgt is None:
            continue
        cand = ".".join([tgt] + parts[split:])
        if cand in proj.functions:
            return cand
    cand = ".".join(parts)
    return cand if cand in proj.functions else None


def _call_basename(func_expr) -> Optional[str]:
    if isinstance(func_expr, ast.Name):
        return func_expr.id
    if isinstance(func_expr, ast.Attribute):
        return func_expr.attr
    return None


def _is_lock_expr(expr) -> bool:
    """``with self._lock:`` / ``with LOCK:`` — any name in the context
    manager chain containing 'lock' (case-insensitive) counts as
    holding the owning lock."""
    for n in ast.walk(expr):
        name = None
        if isinstance(n, ast.Name):
            name = n.id
        elif isinstance(n, ast.Attribute):
            name = n.attr
        if name is not None and "lock" in name.lower():
            return True
    return False


def lock_identity(fn: FunctionInfo, expr) -> Optional[str]:
    """Normalized identity of the lock a ``with`` item holds, for the
    R016 lock graph: ``self._lock`` in a method of C in module m is
    ``m.C._lock`` (every instance shares the ordering discipline, so
    instances collapse into their class), a module-global ``_lock`` is
    ``m._lock``, and an imported module's lock resolves through the
    import table. Returns None when no lock-ish name is present."""
    mod = fn.module
    parts = _dotted(expr)
    if parts is None:
        # Subscripted / computed manager (`with self._locks[i]:`):
        # anchor on the first lock-ish name found.
        for n in ast.walk(expr):
            name = None
            if isinstance(n, ast.Name):
                name = n.id
            elif isinstance(n, ast.Attribute):
                name = n.attr
            if name is not None and "lock" in name.lower():
                return f"{mod.modname}.{name}"
        return None
    if parts[0] in ("self", "cls"):
        owner = fn.cls if fn.cls is not None else fn.name
        return ".".join([mod.modname, owner] + parts[1:])
    tgt = mod.imports.get(parts[0])
    if tgt is not None and len(parts) > 1:
        return ".".join([tgt] + parts[1:])
    return ".".join([mod.modname] + parts)


def _analyze_function(proj: Project, fn: FunctionInfo) -> None:
    """One pass over the function's OWN statements (nested defs are
    their own FunctionInfo) collecting calls, collective seeds, thread
    targets, shared writes, lock scopes, and env/knob reads."""
    own_nested = {proj.functions[q].node for q in fn.nested.values()}

    def walk(node, held: Tuple[str, ...]):
        for child in ast.iter_child_nodes(node):
            if child not in own_nested:
                handle(child, held)

    def handle(child, held: Tuple[str, ...]):
        if isinstance(child, ast.With):
            inner = held
            for item in child.items:
                walk(item, held)
                if _is_lock_expr(item.context_expr):
                    lid = lock_identity(fn, item.context_expr)
                    if lid is not None:
                        fn.lock_acquires.append(LockAcquire(
                            line=child.lineno, lock=lid, held=inner))
                        inner = inner + (lid,)
            for s in child.body:
                # Through handle(), not walk(): a With nested directly
                # in this body must get its own held-locks branch.
                handle(s, inner)
            return
        _visit(child, held)
        walk(child, held)

    def record_write(node, target: str, held: Tuple[str, ...]):
        fn.shared_writes.append(SharedWrite(
            line=node.lineno, target=target, locked=bool(held)))

    declared_global: Set[str] = set()
    for n in ast.walk(fn.node):
        if isinstance(n, ast.Global):
            declared_global.update(n.names)

    def _visit(child, held: Tuple[str, ...]):
        if isinstance(child, ast.Call):
            callee = resolve_call(proj, fn, child.func)
            if callee is not None:
                fn.calls.add(callee)
            base = _call_basename(child.func)
            if base in COLLECTIVE_NAMES:
                fn.direct_collectives.add(base)
                fn.collective_sites.append((child.lineno, base))
            if base in FETCH_NAMES:
                fn.direct_fetches.add(base)
            if held and (base is not None or callee is not None):
                fn.locked_calls.append(LockedCall(
                    line=child.lineno, locks=held, basename=base,
                    callee=callee))
            if base == "Thread":
                for kw in child.keywords:
                    if kw.arg == "target":
                        tgt = resolve_call(proj, fn, kw.value)
                        if tgt is not None:
                            fn.thread_targets.add(tgt)
            # in-place mutators on self attrs / module globals
            if (isinstance(child.func, ast.Attribute)
                    and child.func.attr in _MUTATORS):
                parts = _dotted(child.func.value)
                if parts and parts[0] == "self" and len(parts) >= 2:
                    record_write(child, ".".join(parts), held)
                elif (parts and len(parts) == 1
                      and parts[0] in fn.module.globals):
                    record_write(child, parts[0], held)
            _scan_env_read(proj, fn, child)
        elif isinstance(child, (ast.Assign, ast.AugAssign)):
            targets = (child.targets if isinstance(child, ast.Assign)
                       else [child.target])
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Attribute):
                        # Store ctx only: `buf[self.idx] = 1` READS
                        # self.idx, and in `self.a.b = 1` only the
                        # outermost attribute is the write.
                        if not isinstance(n.ctx, ast.Store):
                            continue
                        parts = _dotted(n)
                        if parts and parts[0] == "self":
                            record_write(child, ".".join(parts),
                                         held)
                    elif (isinstance(n, ast.Name)
                          and isinstance(getattr(n, "ctx", None),
                                         ast.Store)
                          and n.id in declared_global):
                        record_write(child, n.id, held)
            # subscript store on a module global: G[k] = v
            for t in targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in fn.module.globals
                        and t.value.id not in declared_global):
                    record_write(child, t.value.id, held)
        elif isinstance(child, ast.Attribute):
            _scan_knob_read(proj, fn, child)

    walk(fn.node, ())


def _scan_env_read(proj: Project, fn: FunctionInfo,
                   call: ast.Call) -> None:
    """os.environ.get("FM_X") / os.getenv("FM_X") reads."""
    parts = _dotted(call.func)
    if not parts:
        return
    is_env_get = (parts[-2:] == ["environ", "get"]
                  or parts[-1] == "getenv")
    if not is_env_get or not call.args:
        return
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if arg.value.startswith("FM_"):
            proj.env_reads.append(EnvRead(
                path=fn.module.path, line=call.lineno, var=arg.value))


def _scan_knob_read(proj: Project, fn: FunctionInfo,
                    node: ast.Attribute) -> None:
    """``cfg.<attr>`` attribute reads (receiver conventionally named
    cfg/config) — R009 checks them against the FmConfig surface."""
    if (isinstance(node.value, ast.Name)
            and node.value.id in ("cfg", "config")
            and isinstance(node.ctx, ast.Load)):
        proj.knob_reads.append(KnobRead(
            path=fn.module.path, line=node.lineno,
            obj=node.value.id, attr=node.attr))


# --- fixpoints -------------------------------------------------------------

def _fixpoint_collectives(proj: Project) -> None:
    may = {q: set(f.direct_collectives)
           for q, f in proj.functions.items()}
    changed = True
    while changed:
        changed = False
        for q, f in proj.functions.items():
            for callee in f.calls:
                extra = may.get(callee)
                if extra and not extra <= may[q]:
                    may[q] |= extra
                    changed = True
    proj.may_collectives = may


def _fixpoint_threads(proj: Project) -> None:
    on_thread: Set[str] = set()
    for f in proj.functions.values():
        on_thread |= f.thread_targets
    changed = True
    while changed:
        changed = False
        for q in list(on_thread):
            f = proj.functions.get(q)
            if f is None:
                continue
            for callee in f.calls:
                if callee in proj.functions and callee not in on_thread:
                    on_thread.add(callee)
                    changed = True
    proj.thread_funcs = on_thread


def _fixpoint_locks(proj: Project) -> None:
    """``may_locks[q]`` — locks a call to ``q`` may transitively
    acquire (the R016 interprocedural edge source)."""
    may = {q: {a.lock for a in f.lock_acquires}
           for q, f in proj.functions.items()}
    changed = True
    while changed:
        changed = False
        for q, f in proj.functions.items():
            for callee in f.calls:
                extra = may.get(callee)
                if extra and not extra <= may[q]:
                    may[q] |= extra
                    changed = True
    proj.may_locks = may


def _fixpoint_fetch(proj: Project) -> None:
    """Functions that may (transitively) execute a blocking device
    fetch (FETCH_NAMES) — R017's held-across-fetch reachability."""
    fetch = {q for q, f in proj.functions.items() if f.direct_fetches}
    changed = True
    while changed:
        changed = False
        for q, f in proj.functions.items():
            if q in fetch:
                continue
            if any(c in fetch for c in f.calls):
                fetch.add(q)
                changed = True
    proj.may_fetch = fetch


# --- protocol extraction ---------------------------------------------------
#
# The collective-protocol model (R014, `python -m tools.fmlint
# --protocol`): each function's body is read as an ordered sequence of
# collective operations. A direct call site becomes a concrete op
# token — the collective kind plus its static ``label=`` where one is
# written (`guarded_collective[lockstep/window_fill]`) — and a resolved
# call into a function that may itself execute collectives becomes an
# opaque sub-protocol token (`ckpt._broadcast_int()`): its INTERNAL
# order is that function's own protocol, checked where it is defined.
# Rank-invariance of a whole driver entry point then decomposes into a
# per-branch-point obligation: at every conditional either both arms
# carry the same op sequence, or the condition is rank-uniform
# (broadcast-produced / process_count / constant) — which is exactly
# what R014 discharges branch by branch.

def _static_label(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "label" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def op_token(proj: Project, fn: FunctionInfo,
             call: ast.Call) -> Optional[str]:
    """The protocol-op token for one call node, or None if the call
    provably executes no collective."""
    base = _call_basename(call.func)
    if base in COLLECTIVE_NAMES:
        label = _static_label(call)
        return f"{base}[{label}]" if label else base
    callee = resolve_call(proj, fn, call.func)
    if callee is not None and proj.collectives_of(callee):
        return f"{callee}()"
    return None


def collective_ops(proj: Project, fn: FunctionInfo,
                   stmts: Sequence[ast.stmt]) -> List[str]:
    """Ordered op tokens for a statement list (position-sorted, nested
    defs excluded: defining a closure executes nothing)."""
    found: List[Tuple[int, int, str]] = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Call):
                tok = op_token(proj, fn, child)
                if tok is not None:
                    found.append((child.lineno, child.col_offset, tok))
            visit(child)

    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, ast.Call):  # bare-expression guard
            tok = op_token(proj, fn, stmt)
            if tok is not None:
                found.append((stmt.lineno, stmt.col_offset, tok))
        visit(stmt)
    return [t for _, _, t in sorted(found)]


def protocol_automaton(proj: Project, qualname: str,
                       depth: int = 2) -> List[str]:
    """Human-readable protocol automaton for one entry point: the
    ordered collective ops with branch/loop/try structure, sub-protocol
    calls inlined ``depth`` levels deep. The ``--protocol`` CLI view —
    what a reviewer used to reconstruct by hand for every PR touching
    the multi-process layer."""
    fn = proj.functions.get(qualname)
    if fn is None:
        return [f"<unknown function {qualname}>"]
    lines: List[str] = [f"protocol of {qualname}:"]
    seen: Set[str] = {qualname}

    def emit(ctx: FunctionInfo, stmts: Sequence[ast.stmt],
             indent: int, d: int) -> None:
        pad = "  " * indent
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            own_ops = collective_ops(proj, ctx, [stmt])
            if not own_ops:
                continue
            if isinstance(stmt, ast.If):
                lines.append(f"{pad}if <line {stmt.lineno}>:")
                emit(ctx, stmt.body, indent + 1, d)
                if stmt.orelse:
                    lines.append(f"{pad}else:")
                    emit(ctx, stmt.orelse, indent + 1, d)
            elif isinstance(stmt, (ast.While, ast.For)):
                kind = ("while" if isinstance(stmt, ast.While)
                        else "for")
                lines.append(f"{pad}{kind} <line {stmt.lineno}>:")
                emit(ctx, stmt.body, indent + 1, d)
                if stmt.orelse:
                    lines.append(f"{pad}else:")
                    emit(ctx, stmt.orelse, indent + 1, d)
            elif isinstance(stmt, ast.Try):
                lines.append(f"{pad}try:")
                emit(ctx, stmt.body, indent + 1, d)
                for h in stmt.handlers:
                    lines.append(f"{pad}except <line {h.lineno}>:")
                    emit(ctx, h.body, indent + 1, d)
                if stmt.orelse:
                    lines.append(f"{pad}else:")
                    emit(ctx, stmt.orelse, indent + 1, d)
                if stmt.finalbody:
                    lines.append(f"{pad}finally:")
                    emit(ctx, stmt.finalbody, indent + 1, d)
            elif isinstance(stmt, ast.With):
                emit(ctx, stmt.body, indent, d)
            else:
                for tok in own_ops:
                    inlined = False
                    if tok.endswith("()") and d > 0:
                        callee = tok[:-2]
                        sub = proj.functions.get(callee)
                        if sub is not None and callee not in seen:
                            seen.add(callee)
                            lines.append(f"{pad}{tok} -> inlined:")
                            emit(sub, sub.node.body, indent + 1, d - 1)
                            inlined = True
                    if not inlined:
                        lines.append(f"{pad}{tok}")
    emit(fn, fn.node.body, 1, depth)
    return lines
