"""fmlint whole-program rules (R007-R012) over tools/fmlint/project.py.

These are the bug classes PRs 3-5's reviews kept catching by hand —
whole-program properties no per-file syntactic rule can see:

R007  divergent collective: a call that may (transitively) execute a
      blocking collective is reachable under one arm of a branch
      conditioned on process rank, with no matching collective on the
      other arm — the multi-host deadlock (peers never post the
      matching call; the exact bug PR 4's review caught in the restore
      walk-back).
R008  unsynchronized shared mutation: an instance attribute or module
      global written from a function the thread summary proves can run
      on a spawned thread, without holding a lock — the data race that
      multiplies as the perf roadmap adds threads.
R009  config/knob drift: every knob in config.py's section tables must
      appear in sample.cfg AND the README; FM_* env fallbacks must map
      to a real knob name; unknown keys in sample.cfg and unknown
      ``cfg.<attr>`` reads are findings — the doc/schema rot the
      [Cluster]/[Train] knob additions kept reintroducing.
R010  unwrapped hot-path IO: a raw ``open()`` in the pipeline/
      checkpoint hot modules that neither goes through utils/retry
      (``open_with_retry`` / ``retry_io`` / ``@retrying``) nor sits
      under an explicit OSError-family handler — IO with no failure
      contract on exactly the paths transient NFS errors hit.
R012  health-catalog drift: every ``health: <kind>`` event emitted
      anywhere must appear in obs/attribution.HEALTH_KINDS (the fmstat
      verdict/notes mapping) AND in the README's health-event catalog;
      a catalog entry nothing emits is stale — the drift gate that
      keeps "fmstat explains every event the system can write" true
      as subsystems grow (the R009 pattern applied to the health
      stream).

Each rule returns standard Findings, so the pragma grammar and the
baseline mechanism apply unchanged. Precision policy: the engine's
summaries UNDERCLAIM (tools/fmlint/project.py docstring) — a finding
here is evidence, and the sweep fixing or pragma-justifying every one
is part of the rule's contract.
"""

from __future__ import annotations

import ast
import configparser
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.fmlint.core import Finding
from tools.fmlint.project import (COLLECTIVE_NAMES, FunctionInfo,
                                  Project, resolve_call)

# --- shared helpers --------------------------------------------------------

_BLOCK_FIELDS = ("body", "orelse", "finalbody")


def _own_expr_nodes(stmt) -> Iterable[ast.AST]:
    """Every AST node belonging to ``stmt`` itself — headers and inline
    expressions — excluding nested statement blocks (those are walked
    as statements in their own right)."""
    for field, value in ast.iter_fields(stmt):
        if field in _BLOCK_FIELDS or field == "handlers":
            continue
        vals = value if isinstance(value, list) else [value]
        for v in vals:
            if isinstance(v, ast.AST):
                yield from ast.walk(v)


def _walk_skip_defs(node) -> Iterable[ast.AST]:
    """Walk ``node`` without descending into nested function/class
    bodies: defining a function executes nothing."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


# --- R007: divergent collective -------------------------------------------

_RANK_TOKENS = frozenset({"process_index", "process_id", "rank",
                          "shard_index"})


def _is_sanitizing(proj: Project, fn: FunctionInfo, expr) -> bool:
    """A value produced BY a collective is rank-uniform by
    construction — ``cand = self._broadcast_int(cand)`` is the
    agreement primitive, not a divergence source."""
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        base = None
        if isinstance(n.func, ast.Name):
            base = n.func.id
        elif isinstance(n.func, ast.Attribute):
            base = n.func.attr
        if base in COLLECTIVE_NAMES:
            return True
        callee = resolve_call(proj, fn, n.func)
        if callee is not None and proj.collectives_of(callee):
            return True
    return False


def _taint_assigns(fn: FunctionInfo
                   ) -> List[Tuple[int, ast.AST, ast.AST]]:
    """(lineno, target, value) for every simple assignment in source
    order. Tuple assignments pair elementwise so ``p, P =
    jax.process_index(), jax.process_count()`` can taint only ``p``."""
    out: List[Tuple[int, ast.AST, ast.AST]] = []
    for n in _walk_skip_defs(fn.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t, v = n.targets[0], n.value
            if (isinstance(t, ast.Tuple) and isinstance(v, ast.Tuple)
                    and len(t.elts) == len(v.elts)):
                out.extend((n.lineno, te, ve)
                           for te, ve in zip(t.elts, v.elts))
            else:
                out.append((n.lineno, t, v))
    return sorted(out, key=lambda x: x[0])


def _tainted_at(proj: Project, fn: FunctionInfo,
                assigns: Sequence[Tuple[int, ast.AST, ast.AST]],
                line: int) -> Set[str]:
    """Replay assignments in source order up to ``line``: a value
    mentioning a rank token (or an already-tainted name) taints its
    target — ``proc0 = jax.process_index() == 0`` — and a value routed
    through a collective KILLS the taint (the broadcast result is the
    agreed, rank-uniform value). Linear source order stands in for
    control flow; good enough for the assign-then-branch shapes this
    rule polices."""
    tainted: Set[str] = set()
    for lineno, t, v in assigns:
        if lineno >= line:
            break
        if not isinstance(t, ast.Name):
            continue
        if _is_sanitizing(proj, fn, v):
            tainted.discard(t.id)
        elif _mentions_rank(v, tainted):
            tainted.add(t.id)
    return tainted


def _mentions_rank(expr, tainted: Set[str] = frozenset()) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and (n.id in _RANK_TOKENS
                                        or n.id in tainted):
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_TOKENS:
            return True
    return False


def _arm_collectives(proj: Project, fn: FunctionInfo,
                     stmts: Sequence[ast.stmt]) -> Set[str]:
    """Collective kinds that MAY execute somewhere in ``stmts``:
    direct calls plus anything the call graph proves a callee may
    reach."""
    kinds: Set[str] = set()
    for stmt in stmts:
        for n in _walk_skip_defs(stmt):
            if not isinstance(n, ast.Call):
                continue
            base = None
            if isinstance(n.func, ast.Name):
                base = n.func.id
            elif isinstance(n.func, ast.Attribute):
                base = n.func.attr
            if base in COLLECTIVE_NAMES:
                kinds.add(base)
            callee = resolve_call(proj, fn, n.func)
            if callee is not None:
                kinds |= proj.collectives_of(callee)
    return kinds


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def r007_divergent_collective(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for fn in proj.functions.values():
        assigns = _taint_assigns(fn)
        for block in _statement_blocks(fn.node):
            for i, stmt in enumerate(block):
                if not isinstance(stmt, ast.If):
                    continue
                tainted = _tainted_at(proj, fn, assigns, stmt.lineno)
                if not _mentions_rank(stmt.test, tainted):
                    continue
                arm_t: List[ast.stmt] = list(stmt.body)
                arm_f: List[ast.stmt] = list(stmt.orelse)
                tail = list(block[i + 1:])
                # An arm that returns/raises diverts the OTHER arm
                # into the block's tail: `if rank != 0: return` then a
                # collective below is rank-divergent too.
                if _terminates(arm_t) and not _terminates(arm_f):
                    arm_f = arm_f + tail
                elif _terminates(arm_f) and not _terminates(arm_t):
                    arm_t = arm_t + tail
                kt = _arm_collectives(proj, fn, arm_t)
                kf = _arm_collectives(proj, fn, arm_f)
                diff = sorted((kt - kf) | (kf - kt))
                if not diff:
                    continue
                found.append(Finding(
                    "R007", fn.module.path, stmt.lineno,
                    f"collective(s) {', '.join(diff)} reachable on only "
                    "one arm of a rank-conditioned branch "
                    f"(in {fn.qualname.rsplit('.', 1)[-1]}): processes "
                    "on the other arm never post the matching call and "
                    "the cluster deadlocks; hoist the collective out "
                    "of the branch, give the other arm its matching "
                    "call, or justify with a pragma"))
    return found


def _statement_blocks(func_node) -> Iterable[List[ast.stmt]]:
    """Every statement list in the function body — the function's own
    blocks only, not nested defs'."""
    out: List[List[ast.stmt]] = []

    def visit_block(stmts: List[ast.stmt]):
        out.append(stmts)
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for field in _BLOCK_FIELDS:
                sub = getattr(s, field, None)
                if sub:
                    visit_block(sub)
            for h in getattr(s, "handlers", []) or []:
                visit_block(h.body)

    visit_block(list(func_node.body))
    return out


# --- R008: unsynchronized shared mutation ----------------------------------

def r008_unsynchronized_shared_mutation(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for q in sorted(proj.thread_funcs):
        fn = proj.functions.get(q)
        if fn is None or fn.name == "__init__":
            continue
        for w in fn.shared_writes:
            if w.locked:
                continue
            found.append(Finding(
                "R008", fn.module.path, w.line,
                f"'{w.target}' is mutated in {fn.name}(), which the "
                "thread summary shows can run on a spawned thread, "
                "without holding a lock; serialize on the owning lock "
                "(`with self._lock:`), or justify a single-writer / "
                "GIL-atomic design with a pragma"))
    return found


# --- R009: config/knob drift ----------------------------------------------

_SECTION_BY_DICT = {"_GENERAL_KEYS": "General", "_TRAIN_KEYS": "Train",
                    "_SLO_KEYS": "SLO", "_VOCAB_KEYS": "Vocab",
                    "_PREDICT_KEYS": "Predict", "_SERVE_KEYS": "Serve",
                    "_CLUSTER_KEYS": "Cluster"}


def _config_schema(mod) -> Tuple[Dict[str, Dict[str, int]], Set[str]]:
    """From config.py's AST: per-section {knob: definition line} from
    the ``_*_KEYS`` tables, and the full FmConfig attribute surface
    (fields + properties/methods) for the cfg.<attr> read check."""
    sections: Dict[str, Dict[str, int]] = {}
    surface: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            section = _SECTION_BY_DICT.get(node.targets[0].id)
            if section and isinstance(node.value, ast.Dict):
                keys = sections.setdefault(section, {})
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        keys[k.value] = k.lineno
        elif isinstance(node, ast.ClassDef) and node.name == "FmConfig":
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    surface.add(item.target.id)
                elif isinstance(item, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    surface.add(item.name)
    return sections, surface


def _word_in(text: str, word: str) -> bool:
    return re.search(rf"\b{re.escape(word)}\b", text) is not None


def _cfg_key_line(cfg_text: str, section: str, key: str) -> int:
    """Line of an assigned (non-comment) key in an INI file, for
    anchoring unknown-key findings."""
    in_section = False
    for i, line in enumerate(cfg_text.splitlines(), start=1):
        s = line.strip()
        if s.startswith("["):
            in_section = s == f"[{section}]"
        elif in_section and re.match(
                rf"{re.escape(key)}\s*[=:]", s):
            return i
    return 0


def r009_config_drift(proj: Project) -> List[Finding]:
    cfg_mod = proj.module_at("fast_tffm_tpu/config.py")
    if cfg_mod is None:
        return []
    root = os.path.dirname(os.path.dirname(cfg_mod.path))
    sample_path = os.path.join(root, "sample.cfg")
    readme_path = os.path.join(root, "README.md")
    sections, surface = _config_schema(cfg_mod)
    knobs = {k for keys in sections.values() for k in keys}
    found: List[Finding] = []

    sample_text = readme_text = None
    if os.path.isfile(sample_path):
        with open(sample_path, "r", encoding="utf-8") as fh:
            sample_text = fh.read()
    if os.path.isfile(readme_path):
        with open(readme_path, "r", encoding="utf-8") as fh:
            readme_text = fh.read()

    # 1. every knob documented in sample.cfg and the README
    for section, keys in sorted(sections.items()):
        for knob, line in sorted(keys.items()):
            if sample_text is not None and not _word_in(sample_text,
                                                        knob):
                found.append(Finding(
                    "R009", cfg_mod.path, line,
                    f"[{section}] knob '{knob}' is not documented in "
                    "sample.cfg; add it (a value or a commented "
                    "default) so the quick-start config can't drift "
                    "from the schema"))
            if readme_text is not None and not _word_in(readme_text,
                                                        knob):
                found.append(Finding(
                    "R009", cfg_mod.path, line,
                    f"[{section}] knob '{knob}' is not documented in "
                    "the README; add it to the config-reference table"))

    # 2. unknown keys actually set in sample.cfg
    if sample_text is not None:
        cp = configparser.ConfigParser(
            inline_comment_prefixes=(";", "#"))
        try:
            cp.read_string(sample_text)
        except configparser.Error:
            cp = None
        if cp is not None:
            for section in cp.sections():
                known = sections.get(section)
                if known is None:
                    continue
                for key in cp.options(section):
                    if key not in known:
                        found.append(Finding(
                            "R009", sample_path,
                            _cfg_key_line(sample_text, section, key),
                            f"sample.cfg sets unknown [{section}] key "
                            f"'{key}' — config.py would reject it at "
                            "load time; fix the key or add it to the "
                            "schema"))

    # 3. FM_* env fallbacks must map to a real knob name
    for read in proj.env_reads:
        expect = read.var[len("FM_"):].lower()
        if expect not in knobs:
            found.append(Finding(
                "R009", read.path, read.line,
                f"env fallback '{read.var}' does not map to any config "
                f"knob ('{expect}' is not in config.py's section "
                "tables); FM_<KNOB> must stay consistent with its knob "
                "name"))

    # 4. cfg.<attr> reads against the FmConfig surface (package
    # modules only — `cfg` is FmConfig by convention there)
    pkg_prefix = os.path.dirname(cfg_mod.path) + os.sep
    extra_ok = {os.path.join(root, "run_tffm.py"),
                os.path.join(root, "bench.py")}
    for read in proj.knob_reads:
        if read.obj != "cfg" or read.attr.startswith("_"):
            continue
        if not (read.path.startswith(pkg_prefix)
                or read.path in extra_ok):
            continue
        if surface and read.attr not in surface:
            found.append(Finding(
                "R009", read.path, read.line,
                f"cfg.{read.attr} is not a knob, property, or method "
                "of FmConfig — a renamed/removed knob left a stale "
                "reader (frozen dataclass: this raises at runtime)"))
    return found


# --- R010: unwrapped hot-path IO ------------------------------------------

R010_MODULE_SUFFIXES = ("fast_tffm_tpu/data/pipeline.py",
                        "fast_tffm_tpu/checkpoint.py")

# A handler for any of these has an explicit contract for the failing
# open — the checkpoint sidecars' degrade-to-a-verdict pattern.
_OSERROR_FAMILY = frozenset({"OSError", "IOError", "EnvironmentError",
                             "FileNotFoundError", "PermissionError",
                             "Exception", "BaseException"})
_RETRY_NAMES = frozenset({"open_with_retry", "retry_io"})


def _handles_oserror(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for n in ast.walk(t):
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in _OSERROR_FAMILY for n in names)


def _stmt_mentions_retry(stmt) -> bool:
    for n in _own_expr_nodes(stmt):
        if isinstance(n, ast.Name) and n.id in _RETRY_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RETRY_NAMES:
            return True
    return False


def _decorated_retrying(node) -> bool:
    for dec in getattr(node, "decorator_list", []):
        for n in ast.walk(dec):
            if isinstance(n, ast.Name) and n.id == "retrying":
                return True
            if isinstance(n, ast.Attribute) and n.attr == "retrying":
                return True
    return False


def r010_unwrapped_io(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for mod in proj.by_path.values():
        p = mod.path.replace("\\", "/")
        if not p.endswith(R010_MODULE_SUFFIXES):
            continue

        def walk_stmts(stmts, protected: bool, retried: bool):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    walk_stmts(stmt.body, protected,
                               retried or _decorated_retrying(stmt))
                    continue
                if isinstance(stmt, ast.ClassDef):
                    walk_stmts(stmt.body, protected, retried)
                    continue
                exempt = (protected or retried
                          or _stmt_mentions_retry(stmt))
                for n in _own_expr_nodes(stmt):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Name)
                            and n.func.id == "open"
                            and not exempt):
                        found.append(Finding(
                            "R010", mod.path, n.lineno,
                            "raw open() on a pipeline/checkpoint hot "
                            "path bypasses utils/retry — a transient "
                            "NFS/object-store error kills the run; "
                            "use open_with_retry/retry_io, handle "
                            "OSError explicitly, or justify with a "
                            "pragma"))
                if isinstance(stmt, ast.Try):
                    prot = protected or any(_handles_oserror(h)
                                            for h in stmt.handlers)
                    walk_stmts(stmt.body, prot, retried)
                    for h in stmt.handlers:
                        walk_stmts(h.body, protected, retried)
                    walk_stmts(stmt.orelse, protected, retried)
                    walk_stmts(stmt.finalbody, protected, retried)
                    continue
                for field in _BLOCK_FIELDS:
                    sub = getattr(stmt, field, None)
                    if sub:
                        walk_stmts(sub, protected, retried)

        walk_stmts(mod.tree.body, False, False)
    return found


# --- R012: health-event catalog drift --------------------------------------

_ATTRIBUTION_SUFFIX = "fast_tffm_tpu/obs/attribution.py"
_HEALTH_SET_NAME = "HEALTH_KINDS"


def _function_scopes(tree) -> Iterable[ast.AST]:
    """Every def (and the module itself) as one scope: the emit call
    and its status-dict always share a function in this codebase
    (inline literal, or a ``fields = {...}`` built beside the call)."""
    yield tree
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


def _scope_own_nodes(scope) -> Iterable[ast.AST]:
    """Walk one scope's own statements, not nested defs' (a nested
    def is its own scope in _function_scopes — walking it here too
    would double-report every site)."""
    body = scope.body if hasattr(scope, "body") else []
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from _walk_skip_defs(stmt)


def _health_emit_payloads(scope) -> Iterable[ast.Dict]:
    """The dict literals actually PASSED to an ``emit("health", ...)``
    call in this scope: an inline ``emit("health", {...})`` argument,
    or the scope-local ``fields = {...}`` a name argument resolves to.
    Anchoring on the argument (not every dict in the scope) keeps an
    unrelated ``{"status": "ok"}`` stats payload in the same function
    from being misread as a health kind."""
    assigns: Dict[str, List[ast.Dict]] = {}
    emits: List[ast.Call] = []
    for n in _scope_own_nodes(scope):
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Dict)):
            assigns.setdefault(n.targets[0].id, []).append(n.value)
        if not (isinstance(n, ast.Call) and n.args
                and isinstance(n.args[0], ast.Constant)
                and n.args[0].value == "health"):
            continue
        base = None
        if isinstance(n.func, ast.Name):
            base = n.func.id
        elif isinstance(n.func, ast.Attribute):
            base = n.func.attr
        if base == "emit":
            emits.append(n)
    for call in emits:
        if len(call.args) < 2:
            continue
        payload = call.args[1]
        if isinstance(payload, ast.Dict):
            yield payload
        elif isinstance(payload, ast.Name):
            yield from assigns.get(payload.id, [])


def _emitted_health_kinds(proj) -> List[Tuple[str, str, int]]:
    """(kind, path, line) for every ``"status": "<kind>"`` literal in
    a dict a health-event emit actually ships."""
    out: List[Tuple[str, str, int]] = []
    for mod in proj.by_path.values():
        for scope in _function_scopes(mod.tree):
            for d in _health_emit_payloads(scope):
                for k, v in zip(d.keys, d.values):
                    if (isinstance(k, ast.Constant)
                            and k.value == "status"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        out.append((v.value, mod.path, v.lineno))
    return out


def _catalog_kinds(att_mod) -> Dict[str, int]:
    """HEALTH_KINDS frozenset contents {kind: line} from
    attribution.py's AST."""
    for node in att_mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == _HEALTH_SET_NAME
                and isinstance(node.value, ast.Call)
                and node.value.args
                and isinstance(node.value.args[0], ast.Set)):
            return {e.value: e.lineno
                    for e in node.value.args[0].elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return {}


def r012_health_catalog(proj: Project) -> List[Finding]:
    att_mod = next((m for m in proj.by_path.values()
                    if m.path.replace("\\", "/").endswith(
                        _ATTRIBUTION_SUFFIX)), None)
    if att_mod is None:
        return []
    catalog = _catalog_kinds(att_mod)
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(att_mod.path)))
    readme_path = os.path.join(root, "README.md")
    readme_text = None
    if os.path.isfile(readme_path):
        with open(readme_path, "r", encoding="utf-8") as fh:
            readme_text = fh.read()
    emitted = _emitted_health_kinds(proj)
    found: List[Finding] = []
    readme_flagged: Set[str] = set()
    for kind, path, line in emitted:
        if kind not in catalog:
            found.append(Finding(
                "R012", path, line,
                f"health kind '{kind}' is emitted here but missing "
                "from obs/attribution.HEALTH_KINDS — fmstat has no "
                "verdict/notes mapping for it; map it (and add the "
                "README catalog row) or justify with a pragma"))
        if (readme_text is not None and kind not in readme_flagged
                and not _word_in(readme_text, kind)):
            # One finding per KIND (at its first emit site), not one
            # per site: the missing artifact is the catalog row.
            readme_flagged.add(kind)
            found.append(Finding(
                "R012", path, line,
                f"health kind '{kind}' has no README health-event "
                "catalog row; document what emits it, what fmstat "
                "shows, and the first diagnostic"))
    emitted_kinds = {k for k, _, _ in emitted}
    for kind, line in sorted(catalog.items()):
        if kind not in emitted_kinds:
            found.append(Finding(
                "R012", att_mod.path, line,
                f"HEALTH_KINDS entry '{kind}' is emitted nowhere in "
                "the linted surface — a stale catalog entry (event "
                "removed?); drop it or justify with a pragma"))
    return found


PROGRAM_RULES = (r007_divergent_collective,
                 r008_unsynchronized_shared_mutation,
                 r009_config_drift,
                 r010_unwrapped_io,
                 r012_health_catalog)
