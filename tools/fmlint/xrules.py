"""fmlint whole-program rules (R007-R012, R014-R017) over
tools/fmlint/project.py.

These are the bug classes PRs 3-5's reviews kept catching by hand —
whole-program properties no per-file syntactic rule can see:

R007  divergent collective: a call that may (transitively) execute a
      blocking collective is reachable under one arm of a branch
      conditioned on process rank, with no matching collective on the
      other arm — the multi-host deadlock (peers never post the
      matching call; the exact bug PR 4's review caught in the restore
      walk-back).
R008  unsynchronized shared mutation: an instance attribute or module
      global written from a function the thread summary proves can run
      on a spawned thread, without holding a lock — the data race that
      multiplies as the perf roadmap adds threads.
R009  config/knob drift: every knob in config.py's section tables must
      appear in sample.cfg AND the README; FM_* env fallbacks must map
      to a real knob name; unknown keys in sample.cfg and unknown
      ``cfg.<attr>`` reads are findings — the doc/schema rot the
      [Cluster]/[Train] knob additions kept reintroducing.
R010  unwrapped hot-path IO: a raw ``open()`` in the pipeline/
      checkpoint hot modules that neither goes through utils/retry
      (``open_with_retry`` / ``retry_io`` / ``@retrying``) nor sits
      under an explicit OSError-family handler — IO with no failure
      contract on exactly the paths transient NFS errors hit.
R012  health-catalog drift: every ``health: <kind>`` event emitted
      anywhere must appear in obs/attribution.HEALTH_KINDS (the fmstat
      verdict/notes mapping) AND in the README's health-event catalog;
      a catalog entry nothing emits is stale — the drift gate that
      keeps "fmstat explains every event the system can write" true
      as subsystems grow (the R009 pattern applied to the health
      stream).
R014  protocol divergence (the model checker): the ordered collective
      sequence a function executes must be rank-invariant — a branch/
      loop/try arm conditioned on a LOCAL (per-process) value whose
      arms post different collective sequences is the walk-back
      deadlock class PR 4's review caught by hand; values routed
      through a collective are agreed and sanitize the condition.
R015  thread-reachable collective: a blocking collective reachable
      from a ``Thread(target=...)`` entry point — collective order
      across ranks is only defined for the driver loop.
R016  lock-order cycle: the ``with <lock>`` nesting graph (direct and
      through resolved calls) must stay acyclic, or two threads
      deadlock on the inverted pair.
R017  lock across blocking op: a collective or device fetch executing
      while a lock is held — one stalled peer turns the lock into a
      cluster-wide stall.

Each rule returns standard Findings, so the pragma grammar and the
baseline mechanism apply unchanged. Precision policy: the engine's
summaries UNDERCLAIM (tools/fmlint/project.py docstring) — a finding
here is evidence, and the sweep fixing or pragma-justifying every one
is part of the rule's contract.
"""

from __future__ import annotations

import ast
import weakref
import configparser
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.fmlint.core import Finding
from tools.fmlint.project import (COLLECTIVE_NAMES, FETCH_NAMES,
                                  FunctionInfo, Project, _dotted,
                                  collective_ops, resolve_call)

# --- shared helpers --------------------------------------------------------

_BLOCK_FIELDS = ("body", "orelse", "finalbody")


def _own_expr_nodes(stmt) -> Iterable[ast.AST]:
    """Every AST node belonging to ``stmt`` itself — headers and inline
    expressions — excluding nested statement blocks (those are walked
    as statements in their own right)."""
    for field, value in ast.iter_fields(stmt):
        if field in _BLOCK_FIELDS or field == "handlers":
            continue
        vals = value if isinstance(value, list) else [value]
        for v in vals:
            if isinstance(v, ast.AST):
                yield from ast.walk(v)


def _walk_skip_defs(node) -> Iterable[ast.AST]:
    """Walk ``node`` without descending into nested function/class
    bodies: defining a function executes nothing."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


# --- R007: divergent collective -------------------------------------------

_RANK_TOKENS = frozenset({"process_index", "process_id", "rank",
                          "shard_index"})


def _is_sanitizing(proj: Project, fn: FunctionInfo, expr) -> bool:
    """A value produced BY a collective is rank-uniform by
    construction — ``cand = self._broadcast_int(cand)`` is the
    agreement primitive, not a divergence source."""
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        base = None
        if isinstance(n.func, ast.Name):
            base = n.func.id
        elif isinstance(n.func, ast.Attribute):
            base = n.func.attr
        if base in COLLECTIVE_NAMES:
            return True
        callee = resolve_call(proj, fn, n.func)
        if callee is not None and proj.collectives_of(callee):
            return True
    return False


def _taint_assigns(fn: FunctionInfo
                   ) -> List[Tuple[int, ast.AST, ast.AST]]:
    """(lineno, target, value) for every simple assignment in source
    order. Tuple assignments pair elementwise so ``p, P =
    jax.process_index(), jax.process_count()`` can taint only ``p``."""
    out: List[Tuple[int, ast.AST, ast.AST]] = []
    for n in _walk_skip_defs(fn.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t, v = n.targets[0], n.value
            if (isinstance(t, ast.Tuple) and isinstance(v, ast.Tuple)
                    and len(t.elts) == len(v.elts)):
                out.extend((n.lineno, te, ve)
                           for te, ve in zip(t.elts, v.elts))
            else:
                out.append((n.lineno, t, v))
    return sorted(out, key=lambda x: x[0])


def _tainted_at(proj: Project, fn: FunctionInfo,
                assigns: Sequence[Tuple[int, ast.AST, ast.AST]],
                line: int) -> Set[str]:
    """Replay assignments in source order up to ``line``: a value
    mentioning a rank token (or an already-tainted name) taints its
    target — ``proc0 = jax.process_index() == 0`` — and a value routed
    through a collective KILLS the taint (the broadcast result is the
    agreed, rank-uniform value). Linear source order stands in for
    control flow; good enough for the assign-then-branch shapes this
    rule polices."""
    tainted: Set[str] = set()
    for lineno, t, v in assigns:
        if lineno >= line:
            break
        if not isinstance(t, ast.Name):
            continue
        if _is_sanitizing(proj, fn, v):
            tainted.discard(t.id)
        elif _mentions_rank(v, tainted):
            tainted.add(t.id)
    return tainted


def _mentions_rank(expr, tainted: Set[str] = frozenset()) -> bool:
    for n in ast.walk(expr):
        if isinstance(n, ast.Name) and (n.id in _RANK_TOKENS
                                        or n.id in tainted):
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_TOKENS:
            return True
    return False


def _arm_collectives(proj: Project, fn: FunctionInfo,
                     stmts: Sequence[ast.stmt]) -> Set[str]:
    """Collective kinds that MAY execute somewhere in ``stmts``:
    direct calls plus anything the call graph proves a callee may
    reach."""
    kinds: Set[str] = set()
    for stmt in stmts:
        for n in _walk_skip_defs(stmt):
            if not isinstance(n, ast.Call):
                continue
            base = None
            if isinstance(n.func, ast.Name):
                base = n.func.id
            elif isinstance(n.func, ast.Attribute):
                base = n.func.attr
            if base in COLLECTIVE_NAMES:
                kinds.add(base)
            callee = resolve_call(proj, fn, n.func)
            if callee is not None:
                kinds |= proj.collectives_of(callee)
    return kinds


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def r007_divergent_collective(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for fn in proj.functions.values():
        assigns = _taint_assigns(fn)
        for block in _statement_blocks(fn.node):
            for i, stmt in enumerate(block):
                if not isinstance(stmt, ast.If):
                    continue
                tainted = _tainted_at(proj, fn, assigns, stmt.lineno)
                if not _mentions_rank(stmt.test, tainted):
                    continue
                arm_t: List[ast.stmt] = list(stmt.body)
                arm_f: List[ast.stmt] = list(stmt.orelse)
                tail = list(block[i + 1:])
                # An arm that returns/raises diverts the OTHER arm
                # into the block's tail: `if rank != 0: return` then a
                # collective below is rank-divergent too.
                if _terminates(arm_t) and not _terminates(arm_f):
                    arm_f = arm_f + tail
                elif _terminates(arm_f) and not _terminates(arm_t):
                    arm_t = arm_t + tail
                kt = _arm_collectives(proj, fn, arm_t)
                kf = _arm_collectives(proj, fn, arm_f)
                diff = sorted((kt - kf) | (kf - kt))
                if not diff:
                    continue
                found.append(Finding(
                    "R007", fn.module.path, stmt.lineno,
                    f"collective(s) {', '.join(diff)} reachable on only "
                    "one arm of a rank-conditioned branch "
                    f"(in {fn.qualname.rsplit('.', 1)[-1]}): processes "
                    "on the other arm never post the matching call and "
                    "the cluster deadlocks; hoist the collective out "
                    "of the branch, give the other arm its matching "
                    "call, or justify with a pragma"))
    return found


def _statement_blocks(func_node) -> Iterable[List[ast.stmt]]:
    """Every statement list in the function body — the function's own
    blocks only, not nested defs'."""
    out: List[List[ast.stmt]] = []

    def visit_block(stmts: List[ast.stmt]):
        out.append(stmts)
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for field in _BLOCK_FIELDS:
                sub = getattr(s, field, None)
                if sub:
                    visit_block(sub)
            for h in getattr(s, "handlers", []) or []:
                visit_block(h.body)

    visit_block(list(func_node.body))
    return out


# --- R008: unsynchronized shared mutation ----------------------------------

def r008_unsynchronized_shared_mutation(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for q in sorted(proj.thread_funcs):
        fn = proj.functions.get(q)
        if fn is None or fn.name == "__init__":
            continue
        for w in fn.shared_writes:
            if w.locked:
                continue
            found.append(Finding(
                "R008", fn.module.path, w.line,
                f"'{w.target}' is mutated in {fn.name}(), which the "
                "thread summary shows can run on a spawned thread, "
                "without holding a lock; serialize on the owning lock "
                "(`with self._lock:`), or justify a single-writer / "
                "GIL-atomic design with a pragma"))
    return found


# --- R009: config/knob drift ----------------------------------------------

_SECTION_BY_DICT = {"_GENERAL_KEYS": "General", "_TRAIN_KEYS": "Train",
                    "_SLO_KEYS": "SLO", "_VOCAB_KEYS": "Vocab",
                    "_PREDICT_KEYS": "Predict", "_SERVE_KEYS": "Serve",
                    "_CLUSTER_KEYS": "Cluster"}


def _config_schema(mod) -> Tuple[Dict[str, Dict[str, int]], Set[str]]:
    """From config.py's AST: per-section {knob: definition line} from
    the ``_*_KEYS`` tables, and the full FmConfig attribute surface
    (fields + properties/methods) for the cfg.<attr> read check."""
    sections: Dict[str, Dict[str, int]] = {}
    surface: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            section = _SECTION_BY_DICT.get(node.targets[0].id)
            if section and isinstance(node.value, ast.Dict):
                keys = sections.setdefault(section, {})
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        keys[k.value] = k.lineno
        elif isinstance(node, ast.ClassDef) and node.name == "FmConfig":
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and isinstance(
                        item.target, ast.Name):
                    surface.add(item.target.id)
                elif isinstance(item, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    surface.add(item.name)
    return sections, surface


def _word_in(text: str, word: str) -> bool:
    return re.search(rf"\b{re.escape(word)}\b", text) is not None


def _cfg_key_line(cfg_text: str, section: str, key: str) -> int:
    """Line of an assigned (non-comment) key in an INI file, for
    anchoring unknown-key findings."""
    in_section = False
    for i, line in enumerate(cfg_text.splitlines(), start=1):
        s = line.strip()
        if s.startswith("["):
            in_section = s == f"[{section}]"
        elif in_section and re.match(
                rf"{re.escape(key)}\s*[=:]", s):
            return i
    return 0


def r009_config_drift(proj: Project) -> List[Finding]:
    cfg_mod = proj.module_at("fast_tffm_tpu/config.py")
    if cfg_mod is None:
        return []
    root = os.path.dirname(os.path.dirname(cfg_mod.path))
    sample_path = os.path.join(root, "sample.cfg")
    readme_path = os.path.join(root, "README.md")
    sections, surface = _config_schema(cfg_mod)
    knobs = {k for keys in sections.values() for k in keys}
    found: List[Finding] = []

    sample_text = readme_text = None
    if os.path.isfile(sample_path):
        with open(sample_path, "r", encoding="utf-8") as fh:
            sample_text = fh.read()
    if os.path.isfile(readme_path):
        with open(readme_path, "r", encoding="utf-8") as fh:
            readme_text = fh.read()

    # 1. every knob documented in sample.cfg and the README
    for section, keys in sorted(sections.items()):
        for knob, line in sorted(keys.items()):
            if sample_text is not None and not _word_in(sample_text,
                                                        knob):
                found.append(Finding(
                    "R009", cfg_mod.path, line,
                    f"[{section}] knob '{knob}' is not documented in "
                    "sample.cfg; add it (a value or a commented "
                    "default) so the quick-start config can't drift "
                    "from the schema"))
            if readme_text is not None and not _word_in(readme_text,
                                                        knob):
                found.append(Finding(
                    "R009", cfg_mod.path, line,
                    f"[{section}] knob '{knob}' is not documented in "
                    "the README; add it to the config-reference table"))

    # 2. unknown keys actually set in sample.cfg
    if sample_text is not None:
        cp = configparser.ConfigParser(
            inline_comment_prefixes=(";", "#"))
        try:
            cp.read_string(sample_text)
        except configparser.Error:
            cp = None
        if cp is not None:
            for section in cp.sections():
                known = sections.get(section)
                if known is None:
                    continue
                for key in cp.options(section):
                    if key not in known:
                        found.append(Finding(
                            "R009", sample_path,
                            _cfg_key_line(sample_text, section, key),
                            f"sample.cfg sets unknown [{section}] key "
                            f"'{key}' — config.py would reject it at "
                            "load time; fix the key or add it to the "
                            "schema"))

    # 3. FM_* env fallbacks must map to a real knob name
    for read in proj.env_reads:
        expect = read.var[len("FM_"):].lower()
        if expect not in knobs:
            found.append(Finding(
                "R009", read.path, read.line,
                f"env fallback '{read.var}' does not map to any config "
                f"knob ('{expect}' is not in config.py's section "
                "tables); FM_<KNOB> must stay consistent with its knob "
                "name"))

    # 4. cfg.<attr> reads against the FmConfig surface (package
    # modules only — `cfg` is FmConfig by convention there)
    pkg_prefix = os.path.dirname(cfg_mod.path) + os.sep
    extra_ok = {os.path.join(root, "run_tffm.py"),
                os.path.join(root, "bench.py")}
    for read in proj.knob_reads:
        if read.obj != "cfg" or read.attr.startswith("_"):
            continue
        if not (read.path.startswith(pkg_prefix)
                or read.path in extra_ok):
            continue
        if surface and read.attr not in surface:
            found.append(Finding(
                "R009", read.path, read.line,
                f"cfg.{read.attr} is not a knob, property, or method "
                "of FmConfig — a renamed/removed knob left a stale "
                "reader (frozen dataclass: this raises at runtime)"))
    return found


# --- R010: unwrapped hot-path IO ------------------------------------------

R010_MODULE_SUFFIXES = ("fast_tffm_tpu/data/pipeline.py",
                        "fast_tffm_tpu/checkpoint.py")

# A handler for any of these has an explicit contract for the failing
# open — the checkpoint sidecars' degrade-to-a-verdict pattern.
_OSERROR_FAMILY = frozenset({"OSError", "IOError", "EnvironmentError",
                             "FileNotFoundError", "PermissionError",
                             "Exception", "BaseException"})
_RETRY_NAMES = frozenset({"open_with_retry", "retry_io"})


def _handles_oserror(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for n in ast.walk(t):
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in _OSERROR_FAMILY for n in names)


def _stmt_mentions_retry(stmt) -> bool:
    for n in _own_expr_nodes(stmt):
        if isinstance(n, ast.Name) and n.id in _RETRY_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RETRY_NAMES:
            return True
    return False


def _decorated_retrying(node) -> bool:
    for dec in getattr(node, "decorator_list", []):
        for n in ast.walk(dec):
            if isinstance(n, ast.Name) and n.id == "retrying":
                return True
            if isinstance(n, ast.Attribute) and n.attr == "retrying":
                return True
    return False


def r010_unwrapped_io(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for mod in proj.by_path.values():
        p = mod.path.replace("\\", "/")
        if not p.endswith(R010_MODULE_SUFFIXES):
            continue

        def walk_stmts(stmts, protected: bool, retried: bool):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    walk_stmts(stmt.body, protected,
                               retried or _decorated_retrying(stmt))
                    continue
                if isinstance(stmt, ast.ClassDef):
                    walk_stmts(stmt.body, protected, retried)
                    continue
                exempt = (protected or retried
                          or _stmt_mentions_retry(stmt))
                for n in _own_expr_nodes(stmt):
                    if (isinstance(n, ast.Call)
                            and isinstance(n.func, ast.Name)
                            and n.func.id == "open"
                            and not exempt):
                        found.append(Finding(
                            "R010", mod.path, n.lineno,
                            "raw open() on a pipeline/checkpoint hot "
                            "path bypasses utils/retry — a transient "
                            "NFS/object-store error kills the run; "
                            "use open_with_retry/retry_io, handle "
                            "OSError explicitly, or justify with a "
                            "pragma"))
                if isinstance(stmt, ast.Try):
                    prot = protected or any(_handles_oserror(h)
                                            for h in stmt.handlers)
                    walk_stmts(stmt.body, prot, retried)
                    for h in stmt.handlers:
                        walk_stmts(h.body, protected, retried)
                    walk_stmts(stmt.orelse, protected, retried)
                    walk_stmts(stmt.finalbody, protected, retried)
                    continue
                for field in _BLOCK_FIELDS:
                    sub = getattr(stmt, field, None)
                    if sub:
                        walk_stmts(sub, protected, retried)

        walk_stmts(mod.tree.body, False, False)
    return found


# --- R012: health-event catalog drift --------------------------------------

_ATTRIBUTION_SUFFIX = "fast_tffm_tpu/obs/attribution.py"
_HEALTH_SET_NAME = "HEALTH_KINDS"


def _function_scopes(tree) -> Iterable[ast.AST]:
    """Every def (and the module itself) as one scope: the emit call
    and its status-dict always share a function in this codebase
    (inline literal, or a ``fields = {...}`` built beside the call)."""
    yield tree
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n


def _scope_own_nodes(scope) -> Iterable[ast.AST]:
    """Walk one scope's own statements, not nested defs' (a nested
    def is its own scope in _function_scopes — walking it here too
    would double-report every site)."""
    body = scope.body if hasattr(scope, "body") else []
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield from _walk_skip_defs(stmt)


def _health_emit_payloads(scope) -> Iterable[ast.Dict]:
    """The dict literals actually PASSED to an ``emit("health", ...)``
    call in this scope: an inline ``emit("health", {...})`` argument,
    or the scope-local ``fields = {...}`` a name argument resolves to.
    Anchoring on the argument (not every dict in the scope) keeps an
    unrelated ``{"status": "ok"}`` stats payload in the same function
    from being misread as a health kind."""
    assigns: Dict[str, List[ast.Dict]] = {}
    emits: List[ast.Call] = []
    for n in _scope_own_nodes(scope):
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Dict)):
            assigns.setdefault(n.targets[0].id, []).append(n.value)
        if not (isinstance(n, ast.Call) and n.args
                and isinstance(n.args[0], ast.Constant)
                and n.args[0].value == "health"):
            continue
        base = None
        if isinstance(n.func, ast.Name):
            base = n.func.id
        elif isinstance(n.func, ast.Attribute):
            base = n.func.attr
        if base == "emit":
            emits.append(n)
    for call in emits:
        if len(call.args) < 2:
            continue
        payload = call.args[1]
        if isinstance(payload, ast.Dict):
            yield payload
        elif isinstance(payload, ast.Name):
            yield from assigns.get(payload.id, [])


def _emitted_health_kinds(proj) -> List[Tuple[str, str, int]]:
    """(kind, path, line) for every ``"status": "<kind>"`` literal in
    a dict a health-event emit actually ships."""
    out: List[Tuple[str, str, int]] = []
    for mod in proj.by_path.values():
        for scope in _function_scopes(mod.tree):
            for d in _health_emit_payloads(scope):
                for k, v in zip(d.keys, d.values):
                    if (isinstance(k, ast.Constant)
                            and k.value == "status"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        out.append((v.value, mod.path, v.lineno))
    return out


def _catalog_kinds(att_mod) -> Dict[str, int]:
    """HEALTH_KINDS frozenset contents {kind: line} from
    attribution.py's AST."""
    for node in att_mod.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == _HEALTH_SET_NAME
                and isinstance(node.value, ast.Call)
                and node.value.args
                and isinstance(node.value.args[0], ast.Set)):
            return {e.value: e.lineno
                    for e in node.value.args[0].elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)}
    return {}


def r012_health_catalog(proj: Project) -> List[Finding]:
    att_mod = next((m for m in proj.by_path.values()
                    if m.path.replace("\\", "/").endswith(
                        _ATTRIBUTION_SUFFIX)), None)
    if att_mod is None:
        return []
    catalog = _catalog_kinds(att_mod)
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(att_mod.path)))
    readme_path = os.path.join(root, "README.md")
    readme_text = None
    if os.path.isfile(readme_path):
        with open(readme_path, "r", encoding="utf-8") as fh:
            readme_text = fh.read()
    emitted = _emitted_health_kinds(proj)
    found: List[Finding] = []
    readme_flagged: Set[str] = set()
    for kind, path, line in emitted:
        if kind not in catalog:
            found.append(Finding(
                "R012", path, line,
                f"health kind '{kind}' is emitted here but missing "
                "from obs/attribution.HEALTH_KINDS — fmstat has no "
                "verdict/notes mapping for it; map it (and add the "
                "README catalog row) or justify with a pragma"))
        if (readme_text is not None and kind not in readme_flagged
                and not _word_in(readme_text, kind)):
            # One finding per KIND (at its first emit site), not one
            # per site: the missing artifact is the catalog row.
            readme_flagged.add(kind)
            found.append(Finding(
                "R012", path, line,
                f"health kind '{kind}' has no README health-event "
                "catalog row; document what emits it, what fmstat "
                "shows, and the first diagnostic"))
    emitted_kinds = {k for k, _, _ in emitted}
    for kind, line in sorted(catalog.items()):
        if kind not in emitted_kinds:
            found.append(Finding(
                "R012", att_mod.path, line,
                f"HEALTH_KINDS entry '{kind}' is emitted nowhere in "
                "the linted surface — a stale catalog entry (event "
                "removed?); drop it or justify with a pragma"))
    return found




# --- R014: protocol sequence divergence ------------------------------------
#
# R007 proves one shape: a collective under one arm of a RANK-conditioned
# ``if``. The protocol model (tools/fmlint/project.py, collective_ops)
# generalizes the obligation to the whole sequence: at every branch
# point in a protocol module, either both paths carry the SAME ordered
# collective-op sequence, or the condition is rank-uniform (a
# broadcast/allgather product, process_count, a constant). R014
# discharges the cases R007 cannot see: branches on per-process DATA
# (the PR 4 walk-back bug class — restore success is local until
# _all_agree), loop-carried divergence (a loop whose trip count or
# escape is not uniform), and exception arms (a handler that swallows
# an error mid-protocol leaves this rank's sequence a prefix of its
# peers').

R014_MODULE_SUFFIXES = (
    "fast_tffm_tpu/train.py", "fast_tffm_tpu/predict.py",
    "fast_tffm_tpu/checkpoint.py", "fast_tffm_tpu/data/stream.py",
    "fast_tffm_tpu/wire.py")
R014_PACKAGE_FRAGMENTS = ("fast_tffm_tpu/parallel/",)
# liveness.py IS the guard implementation: its try/except around the
# wrapped collective is the escalation path, not a protocol bug.
R014_EXCLUDE_SUFFIXES = ("fast_tffm_tpu/parallel/liveness.py",)


def _in_protocol_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    if p.endswith(R014_EXCLUDE_SUFFIXES):
        return False
    return p.endswith(R014_MODULE_SUFFIXES) or any(
        frag in p for frag in R014_PACKAGE_FRAGMENTS)


def _mentions_names(expr, names: Set[str]) -> bool:
    return any(isinstance(n, ast.Name) and n.id in names
               for n in ast.walk(expr))


def _is_local_source(proj: Project, fn: FunctionInfo, expr,
                     local: Set[str] = frozenset()) -> bool:
    """A value the engine can prove is computed WITHOUT synchronizing
    AND from per-process inputs: a resolved collective-free call that
    is an instance method (``self._attempt_restore`` — instance state
    plus per-process IO) or that is fed already-local data. A plain
    function over config/constants stays neutral — the config file is
    identical on every rank by the deployment contract, so
    ``is_stream_source(cfg.train_files)`` is uniform, while unresolved
    calls stay neutral by the underclaim policy. Any collective en
    route makes the value uniform (_is_sanitizing wins before this is
    consulted)."""
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        callee = resolve_call(proj, fn, n.func)
        if callee is None or proj.collectives_of(callee):
            continue
        if isinstance(n.func, ast.Attribute):
            parts = _dotted(n.func)
            if parts and parts[0] in ("self", "cls"):
                return True
        for arg in list(n.args) + [kw.value for kw in n.keywords]:
            for a in ast.walk(arg):
                if isinstance(a, ast.Name) and (a.id in local
                                                or a.id == "self"):
                    return True
    return False


_TAINT_TIMELINES: "weakref.WeakKeyDictionary[Project, Dict[str, list]]" \
    = weakref.WeakKeyDictionary()


def _local_taint_at(proj: Project, fn: FunctionInfo,
                    line: int) -> Set[str]:
    """Names holding provably-local (per-process) values at ``line``,
    from the function's taint timeline (computed once per function:
    R014 queries every branch point, and replaying the resolve-heavy
    event scan per query dominated the whole sweep's wall time)."""
    snap: Set[str] = set()
    for lineno, names in _taint_timeline(proj, fn):
        if lineno >= line:
            break
        snap = names
    return snap


def _taint_timeline(proj: Project, fn: FunctionInfo):
    """[(lineno, local-name snapshot AFTER that line's event)] by the
    same linear source-order replay as R007's rank taint: local-source
    assignments taint (tuple unpacks taint every element — the
    ``restored, err = self._attempt_restore(...)`` shape),
    collective-routed assignments sanitize, exception captures and
    handler-body assignments are local by nature (an error outcome is
    per-process)."""
    per_fn = _TAINT_TIMELINES.setdefault(proj, {})
    cached = per_fn.get(fn.qualname)
    if cached is not None:
        return cached
    events: List[Tuple[int, Optional[ast.AST], List[str], bool]] = []
    for n in _walk_skip_defs(fn.node):
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            t = n.targets[0]
            names = [e.id for e in (t.elts if isinstance(t, ast.Tuple)
                                    else [t])
                     if isinstance(e, ast.Name)]
            if names:
                events.append((n.lineno, n.value, names, False))
    for n in _walk_skip_defs(fn.node):
        if isinstance(n, ast.Try):
            for h in n.handlers:
                if h.name:
                    events.append((h.lineno, None, [h.name], True))
                for hn in h.body:
                    for a in _walk_skip_defs(hn):
                        if isinstance(a, ast.Assign):
                            names = [e.id for t in a.targets
                                     for e in (t.elts if isinstance(
                                         t, ast.Tuple) else [t])
                                     if isinstance(e, ast.Name)]
                            if names:
                                events.append((a.lineno, a.value,
                                               names, True))
    timeline: List[Tuple[int, Set[str]]] = []
    local: Set[str] = set()
    for lineno, value, names, forced in sorted(
            events, key=lambda e: e[0]):
        if value is not None and _is_sanitizing(proj, fn, value):
            local.difference_update(names)
        elif forced or (value is not None and (
                _is_local_source(proj, fn, value, local)
                or _mentions_names(value, local))):
            local.update(names)
        timeline.append((lineno, set(local)))
    per_fn[fn.qualname] = timeline
    return timeline


def _condition_class(proj: Project, fn: FunctionInfo, test,
                     line: int) -> str:
    """'uniform' (broadcast-produced — safe to branch on), 'rank'
    (R007's domain), 'local' (per-process data), or 'neutral'
    (parameters, unresolved calls — not provably anything)."""
    if _is_sanitizing(proj, fn, test):
        return "uniform"
    if _mentions_rank(test, _tainted_at(proj, fn, _taint_assigns(fn),
                                        line)):
        return "rank"
    local = _local_taint_at(proj, fn, line)
    if (_mentions_names(test, local)
            or _is_local_source(proj, fn, test, local)):
        return "local"
    return "neutral"


def _raise_terminated(stmts: Sequence[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(stmts[-1], ast.Raise)


def _first_mismatch(a: Sequence[str], b: Sequence[str]
                    ) -> Tuple[str, str]:
    for x, y in zip(a, b):
        if x != y:
            return x, y
    return ((a[len(b)], "<nothing>") if len(a) > len(b)
            else ("<nothing>", b[len(a)]))


def _handler_escalates(stmts: Sequence[ast.stmt]) -> bool:
    """A handler whose last statement re-raises (or hard-exits) keeps
    the failure loud: the guard layer converts it to a diagnosed,
    bounded death instead of a silently shorter protocol sequence."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Raise):
        return True
    if isinstance(last, ast.Expr) and isinstance(last.value, ast.Call):
        base = None
        if isinstance(last.value.func, ast.Name):
            base = last.value.func.id
        elif isinstance(last.value.func, ast.Attribute):
            base = last.value.func.attr
        return base in ("exit", "_exit", "abort")
    return False


def _loop_escape_ifs(loop) -> Iterable[ast.If]:
    """``if`` statements anywhere in the loop's own body containing a
    break/return that escapes THIS loop (breaks inside nested loops
    belong to those loops and are checked there)."""
    def scan(stmts, innermost: bool):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.While, ast.For)):
                yield from scan(stmt.body, False)
                yield from scan(stmt.orelse, False)
                continue
            if isinstance(stmt, ast.If):
                # Break/Continue inside a NESTED loop bind to it; the
                # arm walk below rebinds across loop boundaries.
                if _arm_escapes(stmt, innermost):
                    yield stmt
                yield from scan(stmt.body, innermost)
                yield from scan(stmt.orelse, innermost)
                continue
            for field in _BLOCK_FIELDS:
                sub = getattr(stmt, field, None)
                if sub:
                    yield from scan(sub, innermost)
            for h in getattr(stmt, "handlers", []) or []:
                yield from scan(h.body, innermost)
    yield from scan(loop.body, True)


def _arm_escapes(stmt: ast.If, innermost: bool) -> bool:
    def block_escapes(stmts) -> bool:
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Return):
                return True
            if innermost and isinstance(s, (ast.Break, ast.Continue)):
                return True
            if isinstance(s, (ast.While, ast.For)):
                # returns still escape; break/continue rebind
                if any(isinstance(n, ast.Return)
                       for n in _walk_skip_defs(s)):
                    return True
                continue
            for field in _BLOCK_FIELDS:
                sub = getattr(s, field, None)
                if sub and block_escapes(sub):
                    return True
            for h in getattr(s, "handlers", []) or []:
                if block_escapes(h.body):
                    return True
        return False
    return block_escapes(stmt.body) or block_escapes(stmt.orelse)


def r014_protocol_divergence(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for fn in sorted(proj.functions.values(),
                     key=lambda f: (f.module.path, f.node.lineno)):
        if not _in_protocol_scope(fn.module.path):
            continue
        flagged: Set[int] = set()

        def flag(line: int, message: str) -> None:
            if line not in flagged:
                flagged.add(line)
                found.append(Finding("R014", fn.module.path, line,
                                     message))

        short = fn.qualname.rsplit(".", 1)[-1]
        # (a) branch divergence on per-process data
        for block in _statement_blocks(fn.node):
            for i, stmt in enumerate(block):
                if not isinstance(stmt, ast.If):
                    continue
                disp = _condition_class(proj, fn, stmt.test,
                                        stmt.lineno)
                if disp != "local":
                    continue
                # A raise-terminated arm with no collectives of its
                # own is the sanctioned die-loudly path: the raising
                # rank's death goes stale on the lease table and the
                # peers' parked collective becomes a diagnosed,
                # bounded WorkerLostError exit — divergence-by-dying
                # is how per-process failures are DESIGNED to surface
                # when no walk-back recovery exists.
                if any(_raise_terminated(arm)
                       and not collective_ops(proj, fn, arm)
                       for arm in (stmt.body, stmt.orelse)):
                    continue
                arm_t: List[ast.stmt] = list(stmt.body)
                arm_f: List[ast.stmt] = list(stmt.orelse)
                tail = list(block[i + 1:])
                if _terminates(arm_t) and not _terminates(arm_f):
                    arm_f = arm_f + tail
                elif _terminates(arm_f) and not _terminates(arm_t):
                    arm_t = arm_t + tail
                seq_t = collective_ops(proj, fn, arm_t)
                seq_f = collective_ops(proj, fn, arm_f)
                if seq_t == seq_f:
                    continue
                a, b = _first_mismatch(seq_t, seq_f)
                flag(stmt.lineno,
                     "collective protocol diverges on per-process "
                     f"data (in {short}): the branch condition is a "
                     "local value no collective agreed on, and the "
                     f"arms' collective sequences differ ({a} vs {b}) "
                     "— ranks whose data differs pair mismatched "
                     "collectives and deadlock; agree on the "
                     "condition first (the _all_agree/_broadcast_int "
                     "pattern) or justify with a pragma")
        # (b) loop-carried divergence
        for loop in _walk_skip_defs(fn.node):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            body_ops = collective_ops(proj, fn, loop.body)
            if not body_ops:
                continue
            ctrl = loop.test if isinstance(loop, ast.While) \
                else loop.iter
            disp = _condition_class(proj, fn, ctrl, loop.lineno)
            if disp in ("rank", "local"):
                flag(loop.lineno,
                     f"collective(s) {', '.join(sorted(set(body_ops)))}"
                     " execute inside a loop whose "
                     f"{'condition' if isinstance(loop, ast.While) else 'iterable'}"
                     f" is {disp} (per-process) — ranks run different "
                     f"iteration counts (in {short}) and the extra "
                     "iterations' collectives never match; drive the "
                     "loop off a broadcast/allgather-agreed bound or "
                     "justify with a pragma")
            for esc in _loop_escape_ifs(loop):
                disp = _condition_class(proj, fn, esc.test, esc.lineno)
                if disp not in ("rank", "local"):
                    continue
                # An escape whose arm-set difference R007 already
                # reports (rank case) stays R007's finding.
                if disp == "rank":
                    kt = _arm_collectives(proj, fn, esc.body)
                    kf = _arm_collectives(proj, fn, esc.orelse)
                    if kt != kf:
                        continue
                flag(esc.lineno,
                     f"a {disp} (per-process) condition escapes a "
                     f"collective-bearing loop early (in {short}): "
                     "ranks leave the loop on different iterations "
                     f"and the remaining {', '.join(sorted(set(body_ops)))}"
                     " calls go unmatched; make the escape decision "
                     "a broadcast/allgather product or justify with "
                     "a pragma")
        # (c) exception-arm divergence
        for t in _walk_skip_defs(fn.node):
            if not isinstance(t, ast.Try):
                continue
            try_ops = collective_ops(proj, fn, t.body)
            if not try_ops:
                continue
            for h in t.handlers:
                if _handler_escalates(h.body):
                    continue
                flag(h.lineno,
                     "this handler swallows a failure of a "
                     "collective-bearing try body (ops: "
                     f"{', '.join(try_ops)}) in {short}: the "
                     "excepting rank continues with a shorter "
                     "collective sequence than its peers and the "
                     "cluster deadlocks at the next sync point; "
                     "re-raise (the liveness guard converts it to a "
                     "diagnosed bounded exit) or justify with a "
                     "pragma")
    return found


# --- R015: collective reachable from a spawned thread ----------------------

def r015_threaded_collective(proj: Project) -> List[Finding]:
    """A blocking collective posted from a helper thread: the peers'
    protocol order assumes collectives post from the driver loop, the
    deadline guard's in-flight slot is process-global (a thread's
    collective shadows the driver's), and two threads posting
    concurrently interleave nondeterministically across ranks —
    ROADMAP item 2's overlap work steps exactly here."""
    found: List[Finding] = []
    for q in sorted(proj.thread_funcs):
        fn = proj.functions.get(q)
        if fn is None:
            continue
        for line, kind in sorted(fn.collective_sites):
            found.append(Finding(
                "R015", fn.module.path, line,
                f"blocking collective {kind} can execute on a spawned "
                f"thread ({fn.qualname.rsplit('.', 1)[-1]} is "
                "thread-reachable per the Thread-target summary): "
                "collective order across ranks is only defined for "
                "the driver loop — post it from the main thread, or "
                "justify a provably-serialized design with a pragma"))
    return found


# --- R016: lock-order cycles -----------------------------------------------

def _lock_edges(proj: Project) -> Dict[Tuple[str, str],
                                       Tuple[str, int, str]]:
    """Directed held->acquired edges with one witness site each:
    lexical nesting (``with a: with b:``) and interprocedural
    acquisition (a call made under ``a`` into a function that may
    acquire ``b``)."""
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    for fn in sorted(proj.functions.values(),
                     key=lambda f: (f.module.path, f.node.lineno)):
        short = fn.qualname.rsplit(".", 1)[-1]
        for acq in fn.lock_acquires:
            for h in acq.held:
                if h != acq.lock:
                    edges.setdefault((h, acq.lock), (
                        fn.module.path, acq.line,
                        f"{short}() takes {acq.lock} while holding "
                        f"{h}"))
        for lc in fn.locked_calls:
            if lc.callee is None:
                continue
            for m in sorted(proj.may_locks.get(lc.callee, ())):
                for h in lc.locks:
                    if m != h:
                        edges.setdefault((h, m), (
                            fn.module.path, lc.line,
                            f"{short}() calls "
                            f"{lc.callee.rsplit('.', 1)[-1]}() "
                            f"(which takes {m}) while holding {h}"))
    return edges


def _sccs(nodes: Set[str],
          succ: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan, iterative; returns SCCs with >= 2 nodes (sorted)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(succ.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(succ.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                if len(comp) >= 2:
                    out.append(sorted(comp))

    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)
    return out


def r016_lock_order_cycle(proj: Project) -> List[Finding]:
    edges = _lock_edges(proj)
    succ: Dict[str, Set[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        succ.setdefault(a, set()).add(b)
        nodes.add(a)
        nodes.add(b)
    found: List[Finding] = []
    for comp in _sccs(nodes, succ):
        in_cycle = sorted((a, b) for (a, b) in edges
                          if a in comp and b in comp)
        witness = [f"{edges[e][2]} [{os.path.basename(edges[e][0])}:"
                   f"{edges[e][1]}]" for e in in_cycle]
        path, line, _ = edges[in_cycle[0]]
        found.append(Finding(
            "R016", path, line,
            "lock-order cycle between "
            f"{' and '.join(comp)}: {'; '.join(witness)} — two "
            "threads taking these locks in opposite orders deadlock; "
            "pick one global order (document it at the lock "
            "definitions) or justify with a pragma"))
    return found


# --- R017: lock held across a collective / blocking fetch ------------------

def r017_lock_across_blocking(proj: Project) -> List[Finding]:
    found: List[Finding] = []
    for fn in sorted(proj.functions.values(),
                     key=lambda f: (f.module.path, f.node.lineno)):
        short = fn.qualname.rsplit(".", 1)[-1]
        seen_lines: Set[int] = set()
        for lc in fn.locked_calls:
            ops: List[str] = []
            if lc.basename in COLLECTIVE_NAMES:
                ops.append(lc.basename)
            if lc.basename in FETCH_NAMES:
                ops.append(lc.basename)
            if lc.callee is not None:
                ops.extend(sorted(proj.collectives_of(lc.callee)))
                if lc.callee in proj.may_fetch:
                    ops.append(
                        f"{lc.callee.rsplit('.', 1)[-1]}() "
                        "(reaches a device fetch)")
            if not ops or lc.line in seen_lines:
                continue
            seen_lines.add(lc.line)
            found.append(Finding(
                "R017", fn.module.path, lc.line,
                f"{' + '.join(dict.fromkeys(ops))} runs while "
                f"{short}() holds {lc.locks[-1]}: a blocked "
                "collective/fetch (dead peer, slow device) wedges "
                "every thread contending for the lock — and if the "
                "unblocking path needs it, the process deadlocks "
                "outright; move the blocking call outside the lock "
                "(snapshot under the lock, block after) or justify "
                "with a pragma"))
    return found


# Catalog-drift rules reason about ABSENCE over the whole surface
# ("this knob/kind is emitted/used nowhere") — meaningless on the
# --changed subset, where the emitting module may simply not be in
# the closure. run_paths(partial=True) skips them.
r009_config_drift.needs_full_surface = True
r012_health_catalog.needs_full_surface = True

PROGRAM_RULES = (r007_divergent_collective,
                 r008_unsynchronized_shared_mutation,
                 r009_config_drift,
                 r010_unwrapped_io,
                 r012_health_catalog,
                 r014_protocol_divergence,
                 r015_threaded_collective,
                 r016_lock_order_cycle,
                 r017_lock_across_blocking)
