#!/usr/bin/env python
"""CLI-compatible entrypoint — the reference's ``run_tffm.py`` surface
(SURVEY.md §1 L1, §3):

    python run_tffm.py train   <cfg>
    python run_tffm.py train   <cfg> dist_train <job_name> <task_index>
    python run_tffm.py train   <cfg> --join
    python run_tffm.py predict <cfg>
    python run_tffm.py predict <cfg> dist_train <job_name> <task_index>
    python run_tffm.py serve   <cfg> [--replicas N]

``dist_train`` roles map onto synchronous jax.distributed processes
instead of TF1 ps/worker async-SGD (SURVEY §7): ``worker i`` becomes DP
process i; a ``ps`` role is accepted and exits with an explanatory
message, since parameter serving is subsumed by the row-sharded table.
``predict ... dist_train`` (an extension: the reference predicts
single-process) shards the predict input across the same worker
cluster and merges ordered score files on the chief.

``serve`` (an extension; README "Serving") runs the long-lived online
scorer: it loads the ``published`` checkpoint step, micro-batches
concurrent requests behind a stdlib HTTP front end (POST /score, GET
/healthz on ``serve_port``), and hot-reloads when the pointer moves.
SIGTERM/SIGINT drain and exit cleanly. ``--replicas N`` (or
``serve_replicas``; README "Serving fleet") instead runs the replica
supervisor: N scorer children on ``serve_port + i`` behind the
failover proxy on ``serve_proxy_port``, with health-gated routing,
capped-backoff restarts, staggered hot reloads, and canary scoring.

``train --join`` (an extension; README "Elastic multi-host") launches
a REPLACEMENT worker for a running ``elastic = grow`` cluster: it
publishes a join-request lease in ``<model_file>.hb/``, waits for the
cluster to admit it at a safe barrier, and comes up as an ordinary
member — verified checkpoint restore, re-balanced input shards and
all. Its worker slot is assigned by the cluster, so no task index is
given.
"""

from __future__ import annotations

import os
import sys

from fast_tffm_tpu.config import apply_env_overrides, load_config


def _enable_compilation_cache() -> None:
    """Persistent XLA compilation cache across CLI invocations.

    First compile of the train/score programs costs tens of seconds on
    TPU; without a persistent cache every `run_tffm.py` process pays it
    again (predict right after train recompiles everything; measured
    49s -> 13s on the sample config). jax keys cache entries by
    program/compiler fingerprint, so staleness is handled; an unusable
    cache dir just disables itself.

    An explicit JAX_COMPILATION_CACHE_DIR is left entirely to jax — it
    honors the env var natively (including non-local URIs like gs://,
    which a local makedirs would mangle)."""
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return
    path = os.path.join(os.path.expanduser("~"), ".cache",
                        "fast_tffm_tpu", "jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
        import jax
        jax.config.update("jax_compilation_cache_dir", path)
        # Cache everything, including sub-second compiles: the CLI's
        # cost is dominated by many medium programs, not one giant one.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass  # cache is an optimization; never block the run on it


def _usage() -> int:
    print(__doc__, file=sys.stderr)
    return 2


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) < 2 or argv[0] not in ("train", "predict", "serve"):
        return _usage()
    mode, cfg_path = argv[0], argv[1]
    rest = argv[2:]
    _enable_compilation_cache()
    cfg = load_config(cfg_path)
    # One-off per-process overrides without editing the config file:
    # FM_METRICS_FILE (the `metrics_file` knob's values; "auto" =
    # <model_file>.metrics.jsonl — summarize with `python -m
    # tools.fmstat <file>`), FM_TRACE_SPANS / FM_WATCHDOG_STALL_SECONDS
    # for the timeline/health layer, and the serve-fleet knobs the
    # supervisor hands each replica (config.apply_env_overrides).
    cfg = apply_env_overrides(cfg)

    if mode == "serve":
        replicas = None
        if rest and rest[0] == "--replicas":
            if len(rest) != 2:
                return _usage()
            try:
                replicas = int(rest[1])
            except ValueError:
                print(f"--replicas wants an integer, got {rest[1]!r}",
                      file=sys.stderr)
                return _usage()
            if replicas < 1:
                print("--replicas must be >= 1", file=sys.stderr)
                return _usage()
            rest = []
        if rest:
            print("serve takes no dist_train role: the scorer is "
                  "single-process; a multi-replica fleet is "
                  "`serve <cfg> --replicas N` (README 'Serving "
                  "fleet')", file=sys.stderr)
            return _usage()
        n = replicas if replicas is not None else cfg.serve_replicas
        if n > 1:
            from fast_tffm_tpu.serve.fleet import run_fleet
            return run_fleet(cfg, cfg_path, replicas=n)
        from fast_tffm_tpu.serve.frontend import run_serve
        return run_serve(cfg)

    job_name = task_index = None
    join = False
    if rest == ["--join"]:
        if mode != "train":
            print("--join is a train mode: a replacement worker joins "
                  "a running elastic = grow training cluster",
                  file=sys.stderr)
            return _usage()
        join = True
        rest = []
    if rest:
        if len(rest) != 3 or rest[0] != "dist_train":
            return _usage()
        job_name = rest[1]
        try:
            task_index = int(rest[2])
        except ValueError:
            # Same treatment as every other malformed argv form: the
            # usage text, not a raw int() traceback.
            print(f"dist_train task index must be an integer, got "
                  f"{rest[2]!r}", file=sys.stderr)
            return _usage()
        if job_name == "ps":
            print("fast_tffm_tpu has no parameter servers: the table is "
                  "row-sharded across the device mesh. Launch worker "
                  "roles only.", file=sys.stderr)
            return 0
        if job_name != "worker":
            return _usage()

    if mode == "predict":
        from fast_tffm_tpu.predict import predict
        predict(cfg, job_name=job_name, task_index=task_index)
        return 0

    from fast_tffm_tpu.train import train
    train(cfg, job_name, task_index, join=join)
    return 0


def _exit(rc: int) -> "None":
    """sys.exit, EXCEPT after a run that retired a dead cluster's
    jax.distributed client (elastic recovery / WorkerLostError fail
    fast): normal interpreter teardown destroys the retired
    coordination service, whose call cancellation trips the retired
    client's fatal error handler — a SIGABRT after an otherwise clean
    exit. Every durable artifact (checkpoint, metrics stream, logs,
    exports) is already closed by the drivers' finally blocks, so
    skipping C++ teardown of dead cluster plumbing via os._exit is the
    correct last step."""
    try:
        from fast_tffm_tpu.parallel.distributed import has_retired_clients
        retired = has_retired_clients()
    except Exception:
        retired = False
    if retired:
        try:
            # A RETIRED client's teardown is skipped (dead cluster,
            # doomed handshake) — but an elastic GROW may have formed
            # a LIVE cluster since (incumbents retire the old client,
            # then rejoin with the newcomers). That healthy client's
            # coordination service must be shut down with the proper
            # handshake, or os._exit below would tear it out from
            # under the peers mid-teardown — their error poll then
            # LOG(FATAL)-aborts an otherwise clean exit on THEIR side.
            import jax
            if jax.process_count() > 1:
                jax.distributed.shutdown()
        except Exception:
            pass  # a half-formed live client must not block the exit
        import logging
        logging.shutdown()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(rc)
    sys.exit(rc)


if __name__ == "__main__":
    try:
        rc = main()
    except SystemExit as e:  # preserve explicit exit codes
        _exit(e.code if isinstance(e.code, int) else (0 if e.code is
                                                      None else 1))
    except KeyboardInterrupt:
        raise  # standard ^C semantics (exit 130), not a failure exit
    except Exception:
        import traceback
        traceback.print_exc()
        _exit(1)
    _exit(rc)
