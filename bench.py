"""Benchmark: end-to-end training throughput on the flagship FM config.

Mirrors BASELINE config #1 shapes (2nd-order FM, k=8, Criteo-Kaggle-like
data: ~39 features/example, 1M-row hash space) on whatever single device
is present (the driver runs this on one real TPU chip).

Measures the full training loop — host text parsing (C++ parser), batch
building/dedup, host->device transfer, and the jitted train step — i.e.
the same end-to-end examples/sec the reference's `sess.run` loop measures.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}

vs_baseline: BASELINE.json publishes no reference numbers ("published":
{}); the only stated target is the north star of 1e9 examples/hour on a
v5e-64 slice == 1e9/3600/64 ~= 4340 examples/sec/chip. vs_baseline is
value / 4340 — i.e. >= 1.0 means this single chip sustains its share of
the north-star rate.
"""

import json
import time

import numpy as np

NORTH_STAR_PER_CHIP = 1e9 / 3600.0 / 64.0  # examples/sec/chip


def synth_lines(n, vocab, seed=0):
    """Criteo-like libsvm lines: 39 features (13 numeric-ish ids with
    values + 26 one-hot categorical ids), ids spread over the hash space."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < 0.25).astype(np.int32)
    num_ids = rng.integers(0, 13, size=(n, 13)) * 997 % vocab
    num_vals = np.round(rng.gamma(1.0, 2.0, size=(n, 13)), 2)
    cat_ids = rng.integers(0, vocab, size=(n, 26))
    lines = []
    for i in range(n):
        parts = [str(labels[i])]
        parts += [f"{num_ids[i, j]}:{num_vals[i, j]}" for j in range(13)]
        parts += [f"{cat_ids[i, j]}:1" for j in range(26)]
        lines.append(" ".join(parts))
    return lines


def main():
    import os
    import tempfile

    import jax
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.pipeline import batch_iterator, prefetch
    from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                         init_accumulator, init_table,
                                         make_train_step)

    B = 8192
    n_warm, n_timed = 4, 40

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        lines = synth_lines((n_warm + n_timed) * B, 1 << 20)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        del lines

        cfg = FmConfig(vocabulary_size=1 << 20, factor_num=8, batch_size=B,
                       learning_rate=0.05, factor_lambda=1e-6,
                       bias_lambda=1e-6, max_features_per_example=64,
                       bucket_ladder=(64,), train_files=(path,),
                       shuffle=False)
        spec = ModelSpec.from_config(cfg)
        table = init_table(cfg, 0)
        acc = init_accumulator(cfg)
        step = make_train_step(spec)

        # Honest end-to-end: file -> C++ parse -> dedup/pad -> H2D -> jitted
        # step, with the host pipeline prefetching ahead of the device (the
        # same loop train() runs).
        it = prefetch(batch_iterator(cfg, cfg.train_files, training=True),
                      depth=4)
        t0 = None
        n = 0
        for batch in it:
            table, acc, loss, _ = step(table, acc, **batch_args(batch))
            n += 1
            if n == n_warm:  # compile + cache warm; start the clock
                jax.block_until_ready((table, acc))
                t0 = time.perf_counter()
        jax.block_until_ready((table, acc))
        dt = time.perf_counter() - t0

    eps = n_timed * B / dt
    print(json.dumps({
        "metric": "train_examples_per_sec_per_chip",
        "value": round(eps, 1),
        "unit": "examples/sec",
        "vs_baseline": round(eps / NORTH_STAR_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
