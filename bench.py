"""Benchmark: end-to-end training throughput on the flagship FM config,
with an attributable breakdown.

Mirrors BASELINE config #1 shapes (2nd-order FM, k=8, Criteo-Kaggle-like
data: ~39 features/example, 1M-row hash space) on whatever single device
is present (the driver runs this on one real TPU chip).

The headline metric is the median of ``TRIALS`` end-to-end runs of the
full training loop — host text parsing (C++ parser), batch building/
dedup, host->device transfer, and the jitted train step — i.e. the same
end-to-end examples/sec the reference's ``sess.run`` loop measures.
Because one tunnelled-TPU number proved undiagnosable when it moved
between rounds, the same JSON carries the attribution breakdown:

- ``e2e_trials``: every end-to-end trial (spread = environment noise),
- ``host_only``: pipeline-only rate (file -> C++ parse -> dedup -> padded
  batch, device never touched) — the input-bound ceiling, measured at
  the e2e-chosen ``host_threads``; ``host_only_workers`` carries the
  1/2/4-worker sweep of the parallel host data plane (also standalone:
  ``python bench.py --host-sweep`` / ``make bench-host``),
- ``device_only``: jitted-step rate on one cached resident batch (no host
  work, no transfer) — the compute-bound ceiling,
- ``h2d_only``: device_put rate for one batch's actual payload (raw-ids
  mode ships ids+vals, ~3 MB/step at L=48) — the transfer ceiling; on a
  tunnelled TPU this is the usual culprit,
- ``sharded_input_per_worker``: host-only rate of ONE of 2 byte-range
  shards (the multi-process fast path's per-worker input build),
  recorded so the "sharded input ~matches unsharded" claim is an
  artifact, not a commit message,
- ``ffm_e2e``: end-to-end rate of the field-aware model (BASELINE
  config #3 shapes: Avazu-like ~24 fields, k=4) through the same C++
  fast path — FFM's own bench line,
- ``order3_e2e``: end-to-end rate of the order-3 ANOVA-kernel FM
  (BASELINE config #4 shapes) — the higher-order capability's line,
- ``hashed_e2e``: end-to-end rate with ``hash_feature_id`` on (configs
  #2/#5 hash string ids; the headline uses plain int ids),
- ``predict_e2e``: batch-scoring rate through the real predict path
  (the reference's second workload: parse keep_empty -> score ->
  ordered scores),
- ``l64_e2e``: the DEFAULT production regime (auto ladder -> L=64 for
  Criteo-39 data; kernel auto -> Pallas there) — the headline's
  hand-tuned L=48 is the XLA cell, so this line both documents the
  default path and keeps the Pallas kernel exercised end-to-end.

Every e2e line (headline, ffm, order3, hashed, predict, k16, l64) is the median of TRIALS
runs with the per-trial values alongside: a single late-in-the-run
trial can read 8x low on a tunnelled chip (measured), and the medians
make that attributable instead of alarming.

Whichever of host_only/device_only sits near the e2e number names the
bottleneck; a regression that moves e2e but neither ceiling is noise.

Prints ONE JSON line:
  {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ..., ...}

vs_baseline: BASELINE.json publishes no reference numbers ("published":
{}); the only stated target is the north star of 1e9 examples/hour on a
v5e-64 slice == 1e9/3600/64 ~= 4340 examples/sec/chip. vs_baseline is
value / 4340 — i.e. >= 1.0 means this single chip sustains its share of
the north-star rate.
"""

import json
import os
import statistics
import time

import numpy as np

NORTH_STAR_PER_CHIP = 1e9 / 3600.0 / 64.0  # examples/sec/chip


def _parse_threads() -> int:
    """The C++ builder's NATIVE feed parse-thread count — a different
    axis from the pipeline's ``host_threads`` build workers. Earlier
    rounds reported this value AS ``host_threads`` (BENCH_r05), which
    made the artifact claim a build parallelism the pipeline didn't
    have; the JSON now carries both, correctly named."""
    from fast_tffm_tpu.data import cparser
    return cparser.auto_threads()


def _with_workers(cfg, host_threads):
    """The same bench config at an explicit data-plane worker count."""
    import dataclasses
    return dataclasses.replace(cfg, host_threads=host_threads)


# The parallel-plane sweep points: 1 (the serial pre-parallel path),
# 2, and 4 (the auto cap).
HOST_WORKER_SWEEP = (1, 2, 4)

B = 8192
N_WARM, N_TIMED = 4, 40
TRIALS = 3


def synth_lines(n, vocab, seed=0):
    """Criteo-like libsvm lines: 39 features (13 numeric-ish ids with
    values + 26 one-hot categorical ids), ids spread over the hash space."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < 0.25).astype(np.int32)
    num_ids = rng.integers(0, 13, size=(n, 13)) * 997 % vocab
    num_vals = np.round(rng.gamma(1.0, 2.0, size=(n, 13)), 2)
    cat_ids = rng.integers(0, vocab, size=(n, 26))
    lines = []
    for i in range(n):
        parts = [str(labels[i])]
        parts += [f"{num_ids[i, j]}:{num_vals[i, j]}" for j in range(13)]
        parts += [f"{cat_ids[i, j]}:1" for j in range(26)]
        lines.append(" ".join(parts))
    return lines


def make_cfg(path):
    from fast_tffm_tpu.config import FmConfig
    # L=48 covers Criteo's 39 features with the least padding that still
    # wins on this tunnel (measured 2026-07-30: 48 -> 456k median e2e vs
    # 392k at 64 — the loop is H2D-bound, so slot count is bandwidth).
    return FmConfig(vocabulary_size=1 << 20, factor_num=8, batch_size=B,
                    learning_rate=0.05, factor_lambda=1e-6,
                    bias_lambda=1e-6, max_features_per_example=48,
                    bucket_ladder=(48,), train_files=(path,),
                    shuffle=False)


def _raw_mode(cfg):
    """Whether the resolved spec ships raw ids (dedup=device on the one
    real chip) — the pipeline must build matching batches."""
    from fast_tffm_tpu.models.fm import ModelSpec
    return ModelSpec.from_config(cfg).dedup == "device"


def _wire_dispatch(cfg, step):
    """The bench's train-step dispatch, routed through the wire layer
    exactly as train() routes it (README "Wire format"): encode ->
    explicit async device_put (the depth-2 double buffer) -> padded or
    packed jitted step. One body for run_e2e and the --wire sweep so
    the measured loop cannot drift from the production dispatch."""
    import jax
    from fast_tffm_tpu.models.fm import ModelSpec, make_packed_train_step
    from fast_tffm_tpu.wire import WireEncoder, resolve_wire
    wire = resolve_wire(cfg, train=True)
    enc = WireEncoder(wire, pad_id=cfg.pad_id)
    if wire.packed:
        pstep = make_packed_train_step(ModelSpec.from_config(cfg))

        def dispatch(table, acc, batch):
            wb = enc.encode_train(batch)
            return pstep(wb.L, table, acc, **jax.device_put(wb.args))
    else:
        def dispatch(table, acc, batch):
            wb = enc.encode_train(batch)
            return step(table, acc, **jax.device_put(wb.args))
    return dispatch


def run_e2e(cfg, step, n_warm=N_WARM, vocab=None):
    """One honest end-to-end trial: file -> C++ parse -> build -> wire
    encode -> H2D -> jitted step, host pipeline prefetching ahead of
    the device (the same loop train() runs; dedup runs host- or
    device-side per the resolved spec, and the dispatch routes through
    the wire layer, like train() does). One timing protocol for every
    e2e line (FM headline and FFM). ``vocab`` (the --vocab line): the
    admission runtime, exercised exactly as train() does — remap in
    the pipeline, note_trained per stepped batch."""
    import jax
    from fast_tffm_tpu.data.pipeline import (batch_iterator,
                                             gil_bound_iteration, prefetch)
    from fast_tffm_tpu.models.fm import init_accumulator, init_table
    table = init_table(cfg, 0)
    acc = init_accumulator(cfg)
    dispatch = _wire_dispatch(cfg, step)
    it = prefetch(batch_iterator(cfg, cfg.train_files, training=True,
                                 raw_ids=_raw_mode(cfg), vocab=vocab),
                  depth=4, gil_bound=gil_bound_iteration(cfg))
    t0 = None
    n = 0
    n_real = 0  # real examples in the timed span (short final batch counts
    # its actual rows, not batch_size)
    for batch in it:
        table, acc, loss, _ = dispatch(table, acc, batch)
        if vocab is not None:
            vocab.note_trained(batch)
        n += 1
        if t0 is not None:
            n_real += batch.num_real
        if n == n_warm:  # compile + cache warm; start the clock
            jax.block_until_ready((table, acc))
            t0 = time.perf_counter()
    if t0 is None or n_real == 0:
        raise ValueError(
            f"run_e2e needs more than n_warm={n_warm} batches to time "
            f"anything; the input yielded {n}")
    jax.block_until_ready((table, acc))
    return n_real / (time.perf_counter() - t0)


def run_host_only(cfg, shard_index=0, num_shards=1, raw_ids=None):
    """Pipeline-only rate: consume every batch, never touch the device.
    Defaults to the same raw/dedup build mode the e2e loop uses;
    sharded callers pass raw_ids=False (multi-process mode requires the
    host-dedup build, so that metric must measure it)."""
    from fast_tffm_tpu.data.pipeline import batch_iterator
    if raw_ids is None:
        raw_ids = _raw_mode(cfg)
    n_ex = 0
    t0 = time.perf_counter()
    for batch in batch_iterator(cfg, cfg.train_files, training=True,
                                shard_index=shard_index,
                                num_shards=num_shards, raw_ids=raw_ids):
        n_ex += batch.num_real
    return n_ex / (time.perf_counter() - t0)


def run_device_only(cfg, step):
    """Jitted-step rate on one device-resident batch: no host pipeline,
    no transfer. The batch args are device arrays reused every call
    (table/acc are donated and threaded through)."""
    import jax
    from fast_tffm_tpu.data.pipeline import batch_iterator
    from fast_tffm_tpu.models.fm import (batch_args, init_accumulator,
                                         init_table)
    batch = next(batch_iterator(cfg, cfg.train_files, training=True,
                                raw_ids=_raw_mode(cfg)))
    args = {k: (jax.device_put(v) if v is not None else None)
            for k, v in batch_args(batch).items()}
    table = init_table(cfg, 0)
    acc = init_accumulator(cfg)
    for _ in range(N_WARM):
        table, acc, loss, _ = step(table, acc, **args)
    jax.block_until_ready((table, acc))
    t0 = time.perf_counter()
    for _ in range(N_TIMED):
        table, acc, loss, _ = step(table, acc, **args)
    jax.block_until_ready((table, acc))
    return N_TIMED * B / (time.perf_counter() - t0)


def synth_ffm_lines(n, vocab, field_num=24, seed=0):
    """Avazu-like FFM lines: one categorical feature per field."""
    rng = np.random.default_rng(seed)
    labels = (rng.random(n) < 0.17).astype(np.int32)
    ids = rng.integers(0, vocab, size=(n, field_num))
    lines = []
    for i in range(n):
        toks = [f"{f}:{ids[i, f]}" for f in range(field_num)]
        lines.append(" ".join([str(labels[i])] + toks))
    return lines


def ffm_cfg(tmp):
    from fast_tffm_tpu.config import FmConfig
    return FmConfig(vocabulary_size=1 << 18, factor_num=4, batch_size=4096,
                    model_type="ffm", field_num=24, learning_rate=0.05,
                    factor_lambda=1e-6, bias_lambda=1e-6,
                    max_features_per_example=32, bucket_ladder=(32,),
                    train_files=(os.path.join(tmp, "ffm.txt"),),
                    shuffle=False)


def run_ffm_e2e(tmp):
    """FFM end-to-end trials (config #3 shapes), same timing protocol as
    the headline (run_e2e). Returns TRIALS rates: the first full bench
    run showed a single late-in-the-run trial can read 8x low on this
    tunnel (order3 138k in-run vs 880-938k re-run in isolation), so
    every e2e line gets the headline's median-of-trials treatment —
    post-compile trials cost ~0.4 s each."""
    from fast_tffm_tpu.models.fm import ModelSpec, make_train_step
    B_ffm, n_warm, n_timed = 4096, 3, 12
    cfg = ffm_cfg(tmp)
    with open(cfg.train_files[0], "w") as fh:
        fh.write("\n".join(synth_ffm_lines((n_warm + n_timed) * B_ffm,
                                           1 << 18)) + "\n")
    step = make_train_step(ModelSpec.from_config(cfg))
    return [run_e2e(cfg, step, n_warm=n_warm) for _ in range(TRIALS)]


def order3_cfg(tmp):
    from fast_tffm_tpu.config import FmConfig
    return FmConfig(vocabulary_size=1 << 20, factor_num=8, order=3,
                    batch_size=4096, learning_rate=0.05,
                    factor_lambda=1e-6, bias_lambda=1e-6,
                    max_features_per_example=48, bucket_ladder=(48,),
                    train_files=(os.path.join(tmp, "train.txt"),),
                    shuffle=False)


def run_order3_e2e(tmp):
    """Order-3 FM end-to-end trials (config #4 shapes), same timing
    protocol and median-of-trials treatment as the headline (see
    run_ffm_e2e on why). Reuses the FM data file already in ``tmp``."""
    from fast_tffm_tpu.models.fm import ModelSpec, make_train_step
    cfg = order3_cfg(tmp)
    step = make_train_step(ModelSpec.from_config(cfg))
    return [run_e2e(cfg, step, n_warm=3) for _ in range(TRIALS)]


def run_k16(cfg16):
    """BASELINE config #2's model shape (2nd-order FM, k=16): e2e trials
    plus the device-only Pallas-vs-XLA pair — the round-3 kernel claim
    (2.9x at k=8) was never validated at this k (VERDICT r3 weak #6).
    Reuses the headline data file via ``cfg16``."""
    import dataclasses
    from fast_tffm_tpu.models.fm import ModelSpec, make_train_step
    spec = ModelSpec.from_config(cfg16)
    step = make_train_step(spec)
    e2e = [run_e2e(cfg16, step, n_warm=3) for _ in range(TRIALS)]
    dev = {}
    for kern in ("pallas", "xla"):
        kspec = dataclasses.replace(spec, kernel=kern)
        dev[kern] = run_device_only(cfg16, make_train_step(kspec))
    return e2e, dev


def run_h2d_only(cfg):
    """Transfer-only rate: device_put one batch's WIRE payload per step
    (the per-step H2D traffic the resolved wire format actually ships —
    padded rectangles by default, flat CSR under wire_format = packed),
    nothing else. Also returns the payload bytes so the --wire sweep
    can report bytes/example beside the rate."""
    import jax
    from fast_tffm_tpu.data.pipeline import batch_iterator
    from fast_tffm_tpu.wire import WireEncoder, resolve_wire
    batch = next(batch_iterator(cfg, cfg.train_files, training=True,
                                raw_ids=_raw_mode(cfg)))
    enc = WireEncoder(resolve_wire(cfg, train=True), pad_id=cfg.pad_id)
    wb = enc.encode_train(batch)
    payload = [v for v in wb.args.values() if v is not None]
    jax.block_until_ready(jax.device_put(payload))
    t0 = time.perf_counter()
    for _ in range(N_TIMED):
        jax.block_until_ready(jax.device_put(payload))
    rate = N_TIMED * B / (time.perf_counter() - t0)
    return rate, wb.wire_bytes, wb.logical_bytes


# The --wire sweep's three variants (README "Wire format"): the
# bit-identical legacy layout, the packed CSR wire, and packed with
# f16 values/weights.
WIRE_VARIANTS = (("padded-wide", "padded", "wide"),
                 ("packed-wide", "packed", "wide"),
                 ("packed-narrow", "packed", "narrow"))


def run_wire_sweep(path):
    """The wire-format trio on the headline corpus shape: ``h2d_only``
    (device_put rate of the variant's actual payload) and ``e2e`` (the
    full loop through the variant's dispatch) for padded-wide vs
    packed-wide vs packed-narrow, plus bytes/example on the wire — the
    ISSUE 15 acceptance artifact (`python bench.py --wire` /
    `make bench-wire`; pinned in the full artifact's "wire" object)."""
    import dataclasses
    from fast_tffm_tpu.models.fm import ModelSpec, make_train_step
    out = {}
    for name, wf, wd in WIRE_VARIANTS:
        cfg = dataclasses.replace(make_cfg(path), wire_format=wf,
                                  wire_dtypes=wd)
        step = make_train_step(ModelSpec.from_config(cfg))
        h2d, wire_bytes, logical_bytes = run_h2d_only(cfg)
        e2e = statistics.median(
            run_e2e(cfg, step, n_warm=3) for _ in range(TRIALS))
        out[name] = {
            "h2d_only": round(h2d, 1),
            "e2e": round(e2e, 1),
            "bytes_per_example": round(wire_bytes / B, 1),
            "logical_bytes_per_example": round(logical_bytes / B, 1),
        }
    base = out["padded-wide"]["bytes_per_example"]
    for name in out:
        bpe = out[name]["bytes_per_example"]
        out[name]["bytes_savings_x"] = (round(base / bpe, 2)
                                        if bpe else None)
    return out


def wire_sweep_main():
    """Standalone wire-format sweep (`python bench.py --wire` /
    `make bench-wire`): one JSON line with the padded-wide vs
    packed-wide vs packed-narrow trio."""
    import tempfile
    _enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        lines = synth_lines((N_WARM + N_TIMED) * B, 1 << 20)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        del lines
        res = run_wire_sweep(path)
    packed = res["packed-wide"]
    print(json.dumps({
        "metric": "wire_bytes_savings_x",
        "value": packed["bytes_savings_x"],
        "unit": "padded bytes/example over packed (wide)",
        "wire": res,
    }))


def run_memory_profile(tmp):
    """The bytes-axis bench rows (README "Memory observability";
    `python bench.py --memory` / `make bench-memory`): bytes/row of
    the resident state, the planner-vs-ledger agreement and the
    peak-vs-model ratio measured off a REAL train run's mem/* gauges,
    and the serve reload spike (the old+new transient) off a real
    hot reload — the numbers the capacity frontiers (sharded / f16
    tables) will move."""
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.obs.attribution import summarize
    from fast_tffm_tpu.obs.memory import LEDGER, plan, table_bytes
    from fast_tffm_tpu.serve import ScorerServer
    from fast_tffm_tpu.train import train
    wd = os.path.join(tmp, "memory")
    os.makedirs(wd, exist_ok=True)
    path = os.path.join(wd, "train.txt")
    with open(path, "w") as fh:
        fh.write("\n".join(synth_lines(3072, 1 << 15)) + "\n")
    LEDGER.reset()
    # max_features 64 keeps the planner's wire ceiling honest for the
    # 39-feature synth lines (cap >= real nnz, same order as the
    # padded rectangle) — the agreement row measures planner-vs-ledger
    # drift, not ceiling slack from an uncapped default.
    cfg = FmConfig(vocabulary_size=1 << 15, factor_num=8,
                   batch_size=256, epoch_num=1, train_files=(path,),
                   max_features_per_example=64,
                   model_file=os.path.join(wd, "fm"),
                   metrics_file=os.path.join(wd, "metrics.jsonl"),
                   metrics_flush_steps=4)
    train(cfg)
    g = summarize([cfg.metrics_file]).get("gauges", {})
    model = table_bytes(cfg)
    p = plan(cfg, "train")
    # The stream's LAST mem/live_bytes is post-release (0); the
    # resident set the planner predicts is the mid-run maximum.
    live = 0.0
    with open(cfg.metrics_file) as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("event") == "metrics":
                live = max(live,
                           ev.get("gauges", {}).get("mem/live_bytes",
                                                    0.0))
    peak = g.get("mem/peak_bytes") or 0.0
    out = {
        "model_bytes": model,
        "bytes_per_row": round(model / cfg.num_rows, 1),
        "ledger_live_bytes": int(live),
        "ledger_peak_bytes": int(peak),
        "plan_total_bytes": p["total_bytes"],
        # Planner prediction over the measured live ledger: the wire
        # row is a from-config ceiling, so slightly > 1.0 is expected;
        # far from 1.0 means planner and producers disagree.
        "plan_vs_ledger_x": (round(p["total_bytes"] / live, 3)
                             if live else None),
        # Peak over one dense model copy: table + optimizer state
        # (+ wire) — the "how much bigger than the .npz is the run"
        # multiplier capacity planning actually needs.
        "peak_vs_model_x": round(peak / model, 3) if model else None,
    }
    # Serve reload spike: a real server, a real hot reload — the gauge
    # carries the old+new transient the reload held until the swap.
    LEDGER.reset()
    swd = os.path.join(wd, "serve")
    os.makedirs(swd, exist_ok=True)
    scfg = FmConfig(vocabulary_size=1 << 15, factor_num=8,
                    max_features_per_example=48, bucket_ladder=(48,),
                    model_file=os.path.join(swd, "fm"),
                    serve_max_batch=64, serve_poll_seconds=60.0)
    rng = np.random.default_rng(0)
    table = rng.standard_normal(
        (scfg.ckpt_rows, scfg.row_dim)).astype(np.float32) * 0.01
    ckpt = CheckpointState(scfg.model_file)
    ckpt.save(1, table, np.full_like(table, 0.1),
              vocabulary_size=scfg.vocabulary_size, wait=True)
    ckpt.save(2, table, np.full_like(table, 0.1),
              vocabulary_size=scfg.vocabulary_size, wait=True)
    ckpt.publish_step(1)
    ckpt.close()
    del table
    server = ScorerServer(scfg, watch=False)
    try:
        if not server.reload_step(2):
            raise RuntimeError("bench --memory: hot reload of step 2 "
                               "failed")
        sg = server._reg.snapshot()["gauges"]
    finally:
        server.close()
    spike = sg.get("serve/reload_peak_bytes") or 0.0
    serve_model = table_bytes(scfg)
    out["serve_reload_spike_bytes"] = int(spike)
    out["serve_reload_spike_vs_model_x"] = (
        round(spike / serve_model, 3) if serve_model else None)
    LEDGER.reset()
    return out


def memory_main():
    """Standalone device-memory profile (`python bench.py --memory` /
    `make bench-memory`): one JSON line with the ledger/planner/reload
    rows."""
    import tempfile
    _enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        res = run_memory_profile(tmp)
    print(json.dumps({
        "metric": "mem_peak_vs_model_x",
        "value": res["peak_vs_model_x"],
        "unit": "peak ledger bytes over one dense model copy",
        "memory": res,
    }))


def _enable_compile_cache():
    """Share the CLI's persistent XLA compile cache so the isolated
    line subprocesses (and repeat bench invocations) skip recompiles.
    Compile time is already excluded from every timed span by warmup;
    the cache only shrinks bench wall-clock."""
    from run_tffm import _enable_compilation_cache
    _enable_compilation_cache()


def cfg_e2e_trials(cfg):
    """TRIALS end-to-end runs of a _line_cfg config through the shared
    timing protocol — the one body behind every cfg-generic e2e line
    (hashed, l64), so their protocols cannot drift apart."""
    from fast_tffm_tpu.models.fm import ModelSpec, make_train_step
    step = make_train_step(ModelSpec.from_config(cfg))
    return [run_e2e(cfg, step, n_warm=3) for _ in range(TRIALS)]


def run_hashed_e2e(cfg):
    """Hashed-id FM end-to-end trials: configs #2 (Criteo-1TB) and #5
    (1e9-feature iPinYou) both hash string ids, so the hashed parse +
    murmur path gets its own e2e line (the headline uses plain int ids).
    Reuses the headline data file — its int ids hash like any string.
    ``cfg`` comes from _line_cfg so the regime stamp and the measurement
    cannot diverge."""
    return cfg_e2e_trials(cfg)


def run_predict_e2e(cfg):
    """Batch-scoring throughput — the reference's second workload
    (SURVEY §3.4: file -> parse(keep_empty, line-aligned) -> score ->
    ordered scores): examples/sec over full sweeps of the headline file
    through the real predict path (the cross-file streaming scorer:
    fast_tffm_tpu.predict.predict_scores, chunked overlap fetches
    included). Sweep 0 pays the compiles and is discarded; then the
    same 1/2/4 ``host_threads`` regime search the train headline runs
    (keep_empty rides the parallel host plane since ISSUE 10) picks the
    best worker count, and TRIALS full sweeps run there. Returns
    (trial rates, best host_threads, search dict). ``cfg`` comes from
    _line_cfg (stamp/measurement unity)."""
    from fast_tffm_tpu.models.fm import init_table
    from fast_tffm_tpu.predict import predict_scores
    table = init_table(cfg, 0)

    def one_sweep(c):
        t0 = time.perf_counter()
        scores = predict_scores(c, table, c.train_files)
        return scores.shape[0] / (time.perf_counter() - t0)

    one_sweep(cfg)  # compile warmup, discarded
    search = {w: one_sweep(_with_workers(cfg, w))
              for w in HOST_WORKER_SWEEP}
    best = max(search, key=search.get)
    cfg = _with_workers(cfg, best)
    return [one_sweep(cfg) for _ in range(TRIALS)], best, search


def regime_stamp(cfg):
    """The (L, dedup, kernel) a config's hot loop actually runs —
    stamped into every bench line so a future reader of BENCH_r0N.json
    alone can tell WHICH cell of BASELINE.md's kernel/bucket matrix a
    number is (round-4 review: the bench's hand-tuned L=48 is exactly
    the cell where the Pallas/XLA winner flips, and the JSON didn't say
    so). Kernel goes through models.fm.resolved_kernel — the same
    resolution the traced step uses, so the stamp can't drift from the
    dispatch."""
    from fast_tffm_tpu.data.pipeline import effective_L_cap
    from fast_tffm_tpu.models.fm import ModelSpec, resolved_kernel
    spec = ModelSpec.from_config(cfg)
    if cfg.max_features_per_example == 0:
        # Unlimited features: the generic path extends buckets per
        # BATCH, so the widest width is data-dependent. auto's kernel
        # is only L-dependent under DEVICE dedup — for host dedup the
        # matrix resolves to xla at every width, so stamp that
        # deterministically rather than an uninformative null.
        kern = spec.kernel
        if kern == "auto":
            kern = None if spec.dedup == "device" else "xla"
        return {"L": None, "dedup": spec.dedup, "kernel": kern,
                "note": ("max_features_per_example=0: bucket width "
                         "is data-dependent"
                         + ("" if kern else "; so is auto's kernel "
                            "under device dedup"))}
    # The widest bucket a job can RUN is effective_L_cap, not the
    # ladder top: max_features_per_example past the ladder extends it
    # by DOUBLING rungs, and batches land per their own width — so
    # stamp every extended rung, not just the cap.
    rungs = [l for l in cfg.bucket_ladder]
    cap = effective_L_cap(cfg)
    while rungs[-1] < cap:
        rungs.append(rungs[-1] * 2)
    L = rungs[-1]
    stamp = {"L": L, "dedup": spec.dedup,
             "kernel": resolved_kernel(spec, L)}
    if len(rungs) > 1:
        # resolution is per bucket; with several rungs a single
        # (L, kernel) pair would claim a kernel most batches may not
        # run, so stamp every rung (bench configs today are all
        # single-rung — this keeps the stamp honest if that changes)
        stamp["kernel_per_bucket"] = {
            str(l): resolved_kernel(spec, l) for l in rungs}
    return stamp


def _line_cfg(name, train_path):
    """The config each named line measures — one factory for the line
    runners AND their regime stamps, so the stamp describes the config
    that actually ran."""
    import dataclasses
    tmp = os.path.dirname(train_path)
    if name == "ffm":
        return ffm_cfg(tmp)
    if name == "order3":
        return order3_cfg(tmp)
    if name == "hashed":
        return dataclasses.replace(make_cfg(train_path),
                                   hash_feature_id=True)
    if name == "predict":
        return make_cfg(train_path)
    if name == "k16":
        return dataclasses.replace(make_cfg(train_path), factor_num=16)
    if name == "l64":
        # The DEFAULT production regime for Criteo-39 data (auto ladder
        # lands at L=64; dedup=device on one chip -> kernel auto
        # resolves to Pallas): the headline's hand-tuned L=48 is the
        # XLA cell, so without this line the bench would never run the
        # Pallas path end-to-end (round-4 review weak #6).
        return dataclasses.replace(make_cfg(train_path),
                                   bucket_ladder=(64,))
    raise SystemExit(f"unknown bench line {name!r}")


def _run_line(name, train_path):
    """One secondary e2e line by name -> its result dict. The single
    dispatch both the subprocess entry and the in-process fallback go
    through, so they cannot drift apart."""
    tmp = os.path.dirname(train_path)
    cfg = _line_cfg(name, train_path)  # raises on unknown names
    out = {"regime": regime_stamp(cfg)}
    if name == "ffm":
        out["trials"] = run_ffm_e2e(tmp)
    elif name == "order3":
        out["trials"] = run_order3_e2e(tmp)
    elif name == "hashed":
        out["trials"] = run_hashed_e2e(cfg)
    elif name == "predict":
        trials, best, search = run_predict_e2e(cfg)
        out["trials"] = trials
        # The predict sweep's OWN data-plane regime (chosen by its
        # search — keep_empty batches are a different build shape from
        # the train headline's, so its best worker count is its own).
        out["host_threads"] = best
        out["host_threads_search"] = {str(w): round(v, 1)
                                      for w, v in search.items()}
    elif name == "l64":
        out["trials"] = cfg_e2e_trials(cfg)
    else:
        e2e, dev = run_k16(cfg)
        out.update(trials=e2e, device=dev)
    return out


def _line_main(name, train_path):
    """Subprocess entry for one isolated e2e line: prints one JSON
    object on stdout (see _isolated_line for why these run out of
    process)."""
    _enable_compile_cache()
    print(json.dumps(_run_line(name, train_path)))


# A line is ~1 min including compile (cache-cold); a child that takes
# 10x that is wedged (the tunnelled runtime stalling is exactly the
# flakiness that motivated isolation) and the parent must not hang
# silently on it.
LINE_TIMEOUT_S = 600


def _isolated_line(name, train_path):
    """Run one e2e line in a fresh process and return its JSON dict,
    with ``isolation`` recording whether isolation actually happened.

    Measured on this tunnelled chip (2026-07-30): an e2e line that
    sustains 0.9-1.2M examples/sec in a fresh process reads as low as
    118k when it runs AFTER other compiled programs in the same
    process — same-program repetition is stable (order3 x9: 830-926k),
    but mixing programs degrades every later line, and all TRIALS of a
    late line read low together, so medians alone cannot repair it.
    Local state is clean when it happens (no leaked threads,
    jax.live_arrays() empty), pointing at the remote device runtime;
    process isolation is the level that provably restores the rate.
    Failure handling never runs foreign programs before the headline:
    a subprocess that fails to spawn or crashes is marked ``isolation:
    "failed"`` and main() reruns it in-process only AFTER its own
    measurements (so the fallback's compiled programs cannot
    contaminate the headline; the rerun is then marked
    ``"in-process"`` — the caveat the number must carry). A child that
    WEDGES (timeout) is different again: the stall is the device
    runtime, so any rerun could hang the parent unbounded — that line
    stays null (``isolation: "timeout"``) and the rest of the artifact
    survives."""
    import subprocess
    import sys
    detail = ""
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--line", name,
             train_path],
            capture_output=True, text=True, timeout=LINE_TIMEOUT_S)
        if res.returncode == 0:
            try:
                out = json.loads(res.stdout.strip().splitlines()[-1])
            except (ValueError, IndexError):
                out = None
            if isinstance(out, dict):
                out["isolation"] = "subprocess"
                return out
            detail = f"unparseable stdout: {res.stdout[-200:]!r}"
        else:
            detail = (f"rc={res.returncode}, stderr tail: "
                      f"{res.stderr[-500:]}")
    except subprocess.TimeoutExpired:
        print(f"bench line {name}: subprocess wedged for "
              f"{LINE_TIMEOUT_S}s (stalled device runtime?); recording "
              f"null rather than risking a hung rerun", file=sys.stderr)
        return {"trials": None, "device": None, "isolation": "timeout"}
    print(f"bench line {name}: subprocess failed ({detail}); will rerun "
          f"in-process after the headline measurements", file=sys.stderr)
    return {"trials": None, "device": None, "isolation": "failed"}


# Serving-latency line shape: concurrent client threads x requests
# each, small variable-size requests (the online traffic shape — the
# admission queue's micro-batching is the thing under test).
SERVE_CLIENTS = 8
SERVE_REQUESTS_PER_CLIENT = 150


def run_serve_latency(tmp):
    """The serving path's bench line (README "Serving"): publish a
    checkpoint, run the REAL ScorerServer (verified load + warmed
    [B rung, L rung] ladder), fire concurrent variable-size requests
    through the in-process client, and report the request-latency
    p50/p99 the server's own histogram measured — the number the
    ``serve_p99_ms`` row pins and fmstat's SERVING section shows in
    production."""
    import threading
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.serve import ScoreClient, ScorerServer
    wd = os.path.join(tmp, "serve")
    os.makedirs(wd, exist_ok=True)
    cfg = FmConfig(vocabulary_size=1 << 20, factor_num=8,
                   max_features_per_example=48, bucket_ladder=(48,),
                   model_file=os.path.join(wd, "fm"),
                   serve_max_batch=256, serve_max_wait_ms=2.0,
                   serve_poll_seconds=60.0)
    rng = np.random.default_rng(0)
    table = rng.standard_normal(
        (cfg.ckpt_rows, cfg.row_dim)).astype(np.float32) * 0.01
    ckpt = CheckpointState(cfg.model_file)
    ckpt.save(1, table, np.full_like(table, 0.1),
              vocabulary_size=cfg.vocabulary_size, wait=True)
    ckpt.publish_step(1)
    ckpt.close()
    del table
    req_pool = synth_lines(512, 1 << 20, seed=7)
    server = ScorerServer(cfg, watch=False)
    client = ScoreClient(server)
    errors = []

    def fire(worker):
        r = np.random.default_rng(worker)
        try:
            for _ in range(SERVE_REQUESTS_PER_CLIENT):
                k = int(r.integers(1, 9))
                lo = int(r.integers(0, len(req_pool) - k))
                client.score(req_pool[lo:lo + k], timeout=120)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(SERVE_CLIENTS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    stats = server.stats()
    server.close()
    if errors:
        raise errors[0]
    return {
        "p50_ms": round(stats["latency_p50_ms"], 2),
        "p99_ms": round(stats["latency_p99_ms"], 2),
        "requests": stats["requests"],
        "requests_per_sec": round(stats["requests"] / dt, 1),
        "examples_per_sec": round(stats["examples"] / dt, 1),
        "flushes": stats["flushes"],
        "clients": SERVE_CLIENTS,
    }


# Fleet-latency line shape (ISSUE 19): the serving soak's traffic
# through the REAL front door — FleetSupervisor children behind the
# failover proxy over loopback HTTP — with a fixed request count per
# client so req/s is a client-side measurement, comparable between the
# single-replica baseline and the fleet shape.
FLEET_REPLICAS = 3
FLEET_CLIENTS = 8
FLEET_REQUESTS_PER_CLIENT = 60


def run_fleet_latency(tmp):
    """The serving fleet's bench line (README "Serving fleet"): train
    and publish once, then run the SAME fixed concurrent-client load
    against two real front doors — ONE directly-served replica child
    (what ``run_tffm.py serve`` is) and the ``FleetSupervisor`` fleet
    behind the failover proxy. ``throughput_x`` is therefore the whole
    fleet claim: fan-out gain minus the proxy hop's cost, measured
    client-side over loopback HTTP (each replica is a real child
    process paying its own admission queue)."""
    import dataclasses as dc
    import http.client
    import threading
    from fast_tffm_tpu.checkpoint import CheckpointState, list_step_dirs
    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.serve.fleet import FleetSupervisor, ReplicaProc
    from fast_tffm_tpu.train import train
    from tools.fmchaos import (_corpus_lines, _fleet_cfg_file,
                               _free_port_block, _write_corpus)
    from tools.fmckpt import cmd_publish

    wd = os.path.join(tmp, "fleet")
    os.makedirs(wd, exist_ok=True)
    data = os.path.join(wd, "train.txt")
    _write_corpus(data, 400, 0)
    # Train + publish ONCE; both front doors serve this step.
    cfg_path = _fleet_cfg_file(
        wd, data, replicas=FLEET_REPLICAS,
        base_port=_free_port_block(FLEET_REPLICAS + 1),
        serve_max_batch=64)
    cfg = load_config(cfg_path)
    train(dc.replace(cfg, metrics_file=""))
    ckpt = CheckpointState(cfg.model_file)
    step = list_step_dirs(ckpt.directory)[-1]
    ckpt.close()
    if cmd_publish(cfg.model_file + ".ckpt", step) != 0:
        raise RuntimeError(f"publish of step {step} failed")
    req_pool = _corpus_lines(60, seed=99)

    def soak(port, replicas):
        lat, failures = [], []
        lock = threading.Lock()

        def fire(worker):
            rng = np.random.default_rng(worker)
            try:
                for _ in range(FLEET_REQUESTS_PER_CLIENT):
                    k = int(rng.integers(1, 6))
                    lo = int(rng.integers(0, len(req_pool) - k))
                    body = ("\n".join(req_pool[lo:lo + k])
                            + "\n").encode("utf-8")
                    t0 = time.perf_counter()
                    conn = http.client.HTTPConnection(
                        "127.0.0.1", port, timeout=60)
                    try:
                        conn.request(
                            "POST", "/score", body=body,
                            headers={"Content-Type": "text/plain"})
                        resp = conn.getresponse()
                        resp.read()
                        if resp.status != 200:
                            raise RuntimeError(f"HTTP {resp.status}")
                    finally:
                        conn.close()
                    with lock:
                        lat.append((time.perf_counter() - t0) * 1e3)
            except Exception as e:  # noqa: BLE001 - surfaced below
                failures.append(repr(e))

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(FLEET_CLIENTS)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if failures:
            raise RuntimeError(
                f"{len(failures)} client failure(s): {failures[:3]}")
        return {
            "replicas": replicas,
            "p50_ms": round(float(np.percentile(lat, 50)), 2),
            "p99_ms": round(float(np.percentile(lat, 99)), 2),
            "requests": len(lat),
            "requests_per_sec": round(len(lat) / dt, 1),
        }

    # Baseline: one replica child served DIRECTLY on its own port —
    # this is `run_tffm.py serve` (no proxy hop in the path).
    solo = ReplicaProc(0, cfg, cfg_path)
    solo.spawn()
    try:
        deadline = time.monotonic() + 300
        while not solo.is_ready():
            if time.monotonic() > deadline:
                raise RuntimeError("baseline replica never became ready")
            time.sleep(0.1)
        single = soak(solo.port, 1)
    finally:
        solo.terminate()
        solo.reap()

    sup = FleetSupervisor(cfg, cfg_path).start()
    try:
        if not sup.wait_ready(FLEET_REPLICAS, timeout=300):
            raise RuntimeError(
                f"fleet never reached {FLEET_REPLICAS} ready replicas")
        fleet = soak(sup.proxy_port, FLEET_REPLICAS)
    finally:
        sup.stop()
    return {
        "single": single,
        "fleet": fleet,
        "clients": FLEET_CLIENTS,
        "requests_per_client": FLEET_REQUESTS_PER_CLIENT,
        "throughput_x": round(fleet["requests_per_sec"]
                              / single["requests_per_sec"], 2)
        if single["requests_per_sec"] else None,
    }


def run_quality_eval_cost(cfg):
    """The per-publish quality loop's cost line (README "SLOs & quality
    gate"): one full validation sweep through train.evaluate WITH the
    QualityStats collector vs without, on the headline corpus shape.
    The collector rides the sweep's own score fetches, so the ratio is
    the whole claim — near 1.0 means the gate's quality numbers are
    effectively free on top of a validation pass the publish settle was
    going to pay anyway. Returns (plain ex/s, collected ex/s, one
    collected-sweep seconds)."""
    from fast_tffm_tpu.models.fm import init_table
    from fast_tffm_tpu.obs.quality import QualityStats
    from fast_tffm_tpu.train import evaluate
    table = init_table(cfg, cfg.seed)
    # untimed warmup: compile the scorer once
    evaluate(cfg, table, cfg.train_files, max_batches=2)

    def sweep(with_stats):
        stats = QualityStats(cfg.loss_type) if with_stats else None
        t0 = time.perf_counter()
        _auc, n = evaluate(cfg, table, cfg.train_files, collect=stats)
        dt = time.perf_counter() - t0
        if with_stats:
            assert stats.loss is not None  # the collector really ran
        return n / dt, dt

    plain = statistics.median(sweep(False)[0] for _ in range(TRIALS))
    pairs = [sweep(True) for _ in range(TRIALS)]
    collected = statistics.median(r for r, _ in pairs)
    secs = statistics.median(dt for _, dt in pairs)
    return plain, collected, secs


def _make_bench_telemetry(cfg):
    """Optional run-telemetry stream (obs/) for the bench: set
    FM_METRICS_FILE to write the same JSONL schema production train/
    predict runs emit, with the bench's measured ceilings as
    ``bench/*`` gauges — so `python -m tools.fmstat` renders the same
    attribution table for a bench artifact and a real run, directly
    comparable. Off (None) without the env var: the bench's timed
    loops then run with zero instrumentation overhead."""
    path = os.environ.get("FM_METRICS_FILE")
    if not path:
        return None
    from fast_tffm_tpu.obs.telemetry import RunTelemetry, run_meta
    return RunTelemetry(path, meta=run_meta(cfg, "bench"),
                        flush_steps=0)


def main():
    import tempfile

    from fast_tffm_tpu.models.fm import ModelSpec, make_train_step

    _enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        lines = synth_lines((N_WARM + N_TIMED) * B, 1 << 20)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        del lines

        # The isolated lines run FIRST, before this process touches the
        # device: on runtimes with exclusive per-process TPU locking a
        # child could not initialize while the parent holds the chip
        # (this tunnel multiplexes, but the artifact must not depend on
        # that), and nothing below needs to have run before them.
        ffm_res = _isolated_line("ffm", path)
        order3_res = _isolated_line("order3", path)
        hashed_res = _isolated_line("hashed", path)
        predict_res = _isolated_line("predict", path)
        k16_res = _isolated_line("k16", path)
        l64_res = _isolated_line("l64", path)

        cfg = make_cfg(path)
        spec = ModelSpec.from_config(cfg)
        step = make_train_step(spec)

        # e2e regime search over the parallel host data plane: one
        # quick trial per worker count picks the best host_threads;
        # the headline then runs its full TRIALS there, and the
        # host_only ceiling is measured at the same setting (the
        # ceiling must describe the loop the headline actually ran).
        search = {w: run_e2e(_with_workers(cfg, w), step, n_warm=3)
                  for w in HOST_WORKER_SWEEP}
        best_workers = max(search, key=search.get)
        cfg = _with_workers(cfg, best_workers)

        tel = _make_bench_telemetry(cfg)
        from fast_tffm_tpu.obs.telemetry import activate
        try:
            with activate(tel):
                # Headline trials run with the pipeline instrumentation
                # ACTIVE when FM_METRICS_FILE is set — the measured
                # number then includes (and bounds) the telemetry
                # overhead.
                e2e = [run_e2e(cfg, step) for _ in range(TRIALS)]
                host = run_host_only(cfg)
            # The 1/2/4-worker host_only sweep: the parallel plane's
            # scaling artifact (1 = the serial pre-parallel pipeline).
            # Every point runs OUTSIDE the activate() block — mixing
            # one instrumented measurement (the ceiling above pays the
            # telemetry overhead deliberately) into the sweep would
            # bias the scaling ratio against the instrumented point.
            host_workers = {
                str(w): run_host_only(_with_workers(cfg, w))
                for w in HOST_WORKER_SWEEP}
            dev = run_device_only(cfg, step)
            h2d, _, _ = run_h2d_only(cfg)
            # Per-worker input rate of the 2-way byte-range sharded
            # fast path (what each process's pipeline sustains in
            # multi-process mode).
            shard = run_host_only(cfg, shard_index=0, num_shards=2,
                                  raw_ids=False)
            if tel is not None:
                tel.set("bench/e2e", statistics.median(e2e))
                tel.set("bench/host_only", host)
                tel.set("bench/device_only", dev)
                tel.set("bench/h2d_only", h2d)
                tel.set("bench/sharded_input_per_worker", shard)
        finally:
            # The sink buffers EVERYTHING until close; without this a
            # mid-measurement crash leaves a zero-byte metrics file
            # (same lifecycle contract train()/predict() keep).
            if tel is not None:
                tel.close()

        # Deferred in-process fallbacks for failed (not wedged) line
        # subprocesses — AFTER the parent's own measurements, so a
        # fallback's compiled programs cannot contaminate the headline
        # (see _isolated_line).
        for name, res in (("ffm", ffm_res), ("order3", order3_res),
                          ("hashed", hashed_res), ("predict", predict_res),
                          ("k16", k16_res), ("l64", l64_res)):
            if res["isolation"] == "failed":
                # A reproducible crash (not a spawn flake) raises here
                # too — record the null line rather than aborting main()
                # and losing the measurements already taken.
                try:
                    res.update(_run_line(name, path))
                    res["isolation"] = "in-process"
                except Exception as e:  # noqa: BLE001 - artifact survival
                    import sys
                    print(f"bench line {name}: in-process fallback also "
                          f"failed ({type(e).__name__}: {e}); recording "
                          f"null", file=sys.stderr)
        ffm, order3 = ffm_res["trials"], order3_res["trials"]
        hashed, pred = hashed_res["trials"], predict_res["trials"]
        k16, k16_dev = k16_res["trials"], k16_res["device"]
        l64 = l64_res["trials"]

        # Serving-path soak (ISSUE 11): the online scorer's request
        # latency under concurrent clients — a LATENCY line beside the
        # throughput lines above (`python bench.py --serve` standalone).
        try:
            serve_res = run_serve_latency(tmp)
        except Exception as e:  # noqa: BLE001 - artifact survival
            import sys
            print(f"bench serve line failed ({type(e).__name__}: {e}); "
                  f"recording null", file=sys.stderr)
            serve_res = None

        # Quality-loop eval cost (ISSUE 13): the publish gate's
        # validation sweep with vs without the QualityStats collector.
        try:
            quality_res = run_quality_eval_cost(cfg)
        except Exception as e:  # noqa: BLE001 - artifact survival
            import sys
            print(f"bench quality line failed ({type(e).__name__}: "
                  f"{e}); recording null", file=sys.stderr)
            quality_res = None

        # Wire-format trio (ISSUE 15): padded-wide vs packed-wide vs
        # packed-narrow on h2d_only and e2e — the ROADMAP item 2
        # bytes-per-example lever, pinned beside the ceilings it moves.
        try:
            wire_res = run_wire_sweep(path)
        except Exception as e:  # noqa: BLE001 - artifact survival
            import sys
            print(f"bench wire sweep failed ({type(e).__name__}: {e}); "
                  f"recording null", file=sys.stderr)
            wire_res = None

    def med(trials):  # None survives a timed-out line (see _isolated_line)
        return round(statistics.median(trials), 1) if trials else None

    eps = statistics.median(e2e)
    print(json.dumps({
        "metric": "train_examples_per_sec_per_chip",
        "value": round(eps, 1),
        "unit": "examples/sec",
        "vs_baseline": round(eps / NORTH_STAR_PER_CHIP, 3),
        # Which cell of BASELINE.md's kernel/bucket matrix the headline
        # measured (see regime_stamp) — and the same per secondary line
        # below, so the JSON is self-describing about its regimes.
        "regime": regime_stamp(cfg),
        "line_regimes": {"ffm": ffm_res.get("regime"),
                         "order3": order3_res.get("regime"),
                         "hashed": hashed_res.get("regime"),
                         "predict": predict_res.get("regime"),
                         "k16": k16_res.get("regime"),
                         "l64": l64_res.get("regime")},
        "e2e_trials": [round(v, 1) for v in e2e],
        # The pipeline's ACTUAL build parallelism (data-plane workers,
        # chosen by the e2e regime search) vs the C++ builder's native
        # feed parse threads — two different axes; r05 conflated them.
        "host_threads": best_workers,
        "host_threads_search": {str(w): round(v, 1)
                                for w, v in search.items()},
        "parse_threads": _parse_threads(),
        "host_only": round(host, 1),
        "host_only_workers": {w: round(v, 1)
                              for w, v in host_workers.items()},
        "device_only": round(dev, 1),
        "h2d_only": round(h2d, 1),
        "sharded_input_per_worker": round(shard, 1),
        "ffm_e2e": med(ffm),
        "ffm_e2e_trials": [round(v, 1) for v in ffm] if ffm else None,
        "order3_e2e": med(order3),
        "order3_e2e_trials":
            [round(v, 1) for v in order3] if order3 else None,
        "hashed_e2e": med(hashed),
        "hashed_e2e_trials":
            [round(v, 1) for v in hashed] if hashed else None,
        "predict_e2e": med(pred),
        "predict_e2e_trials":
            [round(v, 1) for v in pred] if pred else None,
        # The predict gap, PINNED (ISSUE 10 acceptance): predict sweep
        # rate over the train headline on the same chip. BENCH_r05
        # measured 0.068 (65.8k vs 968.7k — the per-file teardown
        # pipeline); the streaming scorer must keep this from silently
        # regressing toward it.
        "predict_vs_train_ratio":
            round(med(pred) / eps, 4) if pred and eps else None,
        # The predict sweep's own data-plane regime search (keep_empty
        # on the parallel host plane).
        "predict_host_threads": predict_res.get("host_threads"),
        "predict_host_threads_search":
            predict_res.get("host_threads_search"),
        # The serving path's latency SLO numbers (README "Serving"):
        # request-latency quantiles over SERVE_CLIENTS concurrent
        # clients through the real admission queue + warmed ladder.
        "serve_p50_ms": serve_res["p50_ms"] if serve_res else None,
        "serve_p99_ms": serve_res["p99_ms"] if serve_res else None,
        "serve_requests_per_sec":
            serve_res["requests_per_sec"] if serve_res else None,
        "serve_examples_per_sec":
            serve_res["examples_per_sec"] if serve_res else None,
        # The per-publish quality loop's cost (README "SLOs & quality
        # gate"): eval sweep rate with the QualityStats collector
        # riding the fetches vs the plain validation sweep, and the
        # one-sweep wall the publish settle pays. Ratio ~1.0 = the
        # gate's quality numbers are free on top of validation.
        "quality_eval_examples_per_sec":
            round(quality_res[1], 1) if quality_res else None,
        "quality_eval_plain_examples_per_sec":
            round(quality_res[0], 1) if quality_res else None,
        "quality_vs_plain_eval_ratio":
            round(quality_res[1] / quality_res[0], 4)
            if quality_res and quality_res[0] else None,
        "quality_eval_sweep_seconds":
            round(quality_res[2], 3) if quality_res else None,
        # The wire-format trio (README "Wire format"): per-variant
        # h2d_only / e2e / bytes-per-example, with the packed savings
        # multiple over the padded layout.
        "wire": wire_res,
        "k16_e2e": med(k16),
        "k16_e2e_trials": [round(v, 1) for v in k16] if k16 else None,
        "l64_e2e": med(l64),
        "l64_e2e_trials": [round(v, 1) for v in l64] if l64 else None,
        "k16_device_pallas": round(k16_dev["pallas"], 1) if k16_dev
        else None,
        "k16_device_xla": round(k16_dev["xla"], 1) if k16_dev else None,
        # Whether each isolated line actually ran in a fresh process
        # (see _isolated_line on the measured in-process cross-program
        # degradation); "in-process" marks a fallback whose number
        # carries that caveat.
        "line_isolation": {"ffm": ffm_res["isolation"],
                           "order3": order3_res["isolation"],
                           "hashed": hashed_res["isolation"],
                           "predict": predict_res["isolation"],
                           "k16": k16_res["isolation"],
                           "l64": l64_res["isolation"]},
    }))


def host_sweep_main():
    """Standalone host-only worker sweep (`make bench-host` /
    `python bench.py --host-sweep`): the parallel data plane's
    1/2/4-worker batch-build rates on the headline corpus shape, no
    device required (raw_ids=False keeps the measurement on the
    host-dedup build — the one multi-process mode must sustain — and
    off any jitted-spec resolution). One JSON line, same spirit as the
    main artifact: the 4v1 ratio is the scaling claim, attributable."""
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        lines = synth_lines((N_WARM + N_TIMED) * B, 1 << 20)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        del lines
        cfg = make_cfg(path)
        rates = {str(w): round(run_host_only(_with_workers(cfg, w),
                                             raw_ids=False), 1)
                 for w in HOST_WORKER_SWEEP}
    print(json.dumps({
        "metric": "host_only_examples_per_sec",
        "unit": "examples/sec",
        "host_only_workers": rates,
        "scaling_4v1": round(rates["4"] / rates["1"], 3)
        if rates.get("1") else None,
        "parse_threads": _parse_threads(),
    }))


def serve_latency_main():
    """Standalone serving-latency line (`python bench.py --serve`):
    the run_serve_latency soak without the ~7 other lines the full
    bench pays for. One JSON line."""
    import tempfile
    _enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        res = run_serve_latency(tmp)
    print(json.dumps({
        "metric": "serve_request_latency_ms",
        "value": res["p99_ms"],
        "unit": "ms (p99)",
        **res,
    }))


def fleet_main():
    """Standalone serving-fleet line (`python bench.py --fleet` /
    `make bench-fleet`): run_fleet_latency without the rest of the
    bench — the fleet's client-side p99 as the headline, with the
    single-replica-behind-the-proxy baseline and the req/s scaling
    factor beside it. One JSON line."""
    import tempfile
    _enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        res = run_fleet_latency(tmp)
    print(json.dumps({
        "metric": "fleet_request_latency_ms",
        "value": res["fleet"]["p99_ms"],
        "unit": f"ms (p99, {FLEET_REPLICAS} replicas behind the proxy)",
        **res,
    }))


def vocab_overhead_main():
    """Standalone admission-path overhead line (`python bench.py
    --vocab` / `make bench-vocab`): train e2e examples/sec at
    ``vocab_mode = admit`` vs ``fixed`` on the same hashed-id corpus —
    the admit run pays the per-batch remap (binary-search over the
    frozen slot map + host re-dedup) and the per-step sketch
    observation, against a map POPULATED by a real warmup pass + one
    barrier (the steady state between barriers, which is what a long
    stream runs in). Target: ratio >= 0.95 (<= 5% regression). One
    JSON line."""
    import dataclasses
    import tempfile
    from fast_tffm_tpu.models.fm import ModelSpec, make_train_step
    from fast_tffm_tpu.vocab.table import VocabRuntime
    _enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        lines = synth_lines((N_WARM + N_TIMED) * B, 1 << 20)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        del lines
        base = dataclasses.replace(make_cfg(path), hash_feature_id=True,
                                   vocabulary_size=1 << 17)
        admit_cfg = dataclasses.replace(
            base, vocab_mode="admit", vocab_admit_threshold=2.0,
            vocab_decay=0.5, vocab_sketch_mb=1.0)
        fixed_step = make_train_step(ModelSpec.from_config(base))
        fixed = [run_e2e(base, fixed_step) for _ in range(TRIALS)]
        vocab = VocabRuntime.from_config(admit_cfg)
        # Populate the slot map the way a running stream would: one
        # untimed observation pass + a barrier, so the timed trials
        # remap through a realistic frozen map instead of an empty one
        # (all-cold lookups would understate the binary-search cost).
        from fast_tffm_tpu.data.pipeline import batch_iterator
        for batch in batch_iterator(admit_cfg, admit_cfg.train_files,
                                    training=True,
                                    raw_ids=_raw_mode(admit_cfg),
                                    vocab=vocab):
            vocab.note_trained(batch)
        vocab.barrier(None)
        admit_step = make_train_step(ModelSpec.from_config(admit_cfg))
        admit = [run_e2e(admit_cfg, admit_step, vocab=vocab)
                 for _ in range(TRIALS)]
    f_med = statistics.median(fixed)
    a_med = statistics.median(admit)
    print(json.dumps({
        "metric": "vocab_admit_vs_fixed_ratio",
        "value": round(a_med / f_med, 3) if f_med else None,
        "unit": "admit/fixed train examples/sec (target >= 0.95)",
        "vocab_fixed_eps": round(f_med, 1),
        "vocab_admit_eps": round(a_med, 1),
        "vocab_fixed_trials": [round(v, 1) for v in fixed],
        "vocab_admit_trials": [round(v, 1) for v in admit],
        "vocab_live_rows": vocab.live_rows,
    }))


def predict_sweep_main():
    """Standalone predict line (`make bench-predict` / `python bench.py
    --predict`): TRIALS full sweeps of the cross-file streaming scorer
    on the headline corpus shape, plus its 1/2/4 ``host_threads``
    regime search — one JSON line, without the ~6 other lines the full
    bench pays for. The pinned ``predict_vs_train_ratio`` lives in the
    full artifact (`python bench.py`), where the train headline it
    divides by is measured in the same run."""
    import tempfile
    _enable_compile_cache()
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.txt")
        lines = synth_lines((N_WARM + N_TIMED) * B, 1 << 20)
        with open(path, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        del lines
        res = _run_line("predict", path)
    trials = res["trials"]
    print(json.dumps({
        "metric": "predict_examples_per_sec_per_chip",
        "value": round(statistics.median(trials), 1),
        "unit": "examples/sec",
        "predict_e2e_trials": [round(v, 1) for v in trials],
        "host_threads": res["host_threads"],
        "host_threads_search": res["host_threads_search"],
        "regime": res["regime"],
    }))


def multihost_main():
    """Standalone multi-host scaling-efficiency line (`python bench.py
    --multihost` / `make bench-multihost`): REAL 1- and 2-process
    localhost clusters (jax.distributed + gloo, the same transport the
    lockstep protocol runs in production CPU smoke clusters) train the
    same line-sharded corpus; the tracked number is per-worker
    efficiency — (2-worker global rate / 2) / 1-worker rate — measured
    from the metrics stream's loop time + example counters, so cluster
    bring-up (tens of seconds of interpreter+join) stays OUT of the
    scaling claim. This is ROADMAP item 4's membership-change number:
    elastic shrink/grow land on exactly this lockstep plane, so a
    regression in the overlap/window protocol moves this row."""
    import subprocess
    import sys
    import tempfile
    import socket as socketlib

    def free_port() -> int:
        with socketlib.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    from fast_tffm_tpu.obs.attribution import (efficiency_table,
                                               summarize)

    def loop_rate(paths) -> float:
        """Examples per WORKER-second: summarize() sums both the
        example counters and the per-shard loop (step_seconds) sums
        across the workers' metrics files, so global examples over
        summed loop seconds is already the per-worker rate — for W=1
        it is simply the single-process rate, so the efficiency below
        is a direct ratio (no extra division by W: that would halve
        the metric, reporting perfect scaling as 0.5)."""
        s = summarize(paths)
        loop = (s["hists"].get("train/step_seconds") or {}).get("sum")
        examples = s["counters"].get("train/examples")
        return (examples / loop) if loop and examples else 0.0

    n_lines, epochs = 9728, 2  # 304 even steps/epoch at B=32
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    with tempfile.TemporaryDirectory() as tmp:
        data = os.path.join(tmp, "train.txt")
        lines = synth_lines(n_lines, 1 << 17)
        with open(data, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        del lines
        results = {}
        for w in (1, 2):
            wdir = os.path.join(tmp, f"w{w}")
            os.makedirs(wdir)
            metrics = os.path.join(wdir, "metrics.jsonl")
            coord = free_port()
            hosts = ",".join(f"localhost:{coord - 1000 + i}"
                             for i in range(w))
            cfg_path = os.path.join(wdir, "bench.cfg")
            with open(cfg_path, "w") as fh:
                fh.write(f"""
[General]
vocabulary_size = {1 << 17}
factor_num = 8
hash_feature_id = True
model_file = {os.path.join(wdir, 'model', 'fm')}

[Train]
train_files = {data}
epoch_num = {epochs}
batch_size = 32
learning_rate = 0.05
shuffle = False
log_steps = 0
metrics_file = {metrics}
trace_spans = True
max_features_per_example = 64

[Cluster]
worker_hosts = {hosts}
""")
            argv = [sys.executable, "run_tffm.py", "train", cfg_path]
            procs = []
            for i in range(w):
                a = argv + (["dist_train", "worker", str(i)]
                            if w > 1 else [])
                procs.append(subprocess.Popen(
                    a, cwd=repo, env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL))
            rcs = [p.wait(timeout=900) for p in procs]
            if any(rcs):
                raise SystemExit(f"multihost bench: {w}-worker run "
                                 f"failed (rcs {rcs})")
            shards = [metrics] + [f"{metrics}.p{i}"
                                  for i in range(1, w)
                                  if os.path.exists(f"{metrics}.p{i}")]
            results[w] = loop_rate(shards)
            if w == 2:
                # Attach the step-anatomy phase breakdown so the
                # efficiency row carries its own WHY: the anatomy/*
                # gauges the workers pre-aggregate at barrier flushes
                # say where the lost fraction went (fmstat EFFICIENCY
                # and fmtrace --anatomy read the same surface).
                eff = efficiency_table(summarize(shards))
                from fast_tffm_tpu.obs import anatomy as anat_mod
                # The 1-worker leg's rate is the baseline that turns
                # the trace replay's coordination efficiency into the
                # ABSOLUTE per-worker number (it prices the stall
                # inside the dispatched program, which host spans
                # cannot see) — directly comparable to this row's
                # counter-derived "value".
                rep = anat_mod.report(shards,
                                      baseline_eps=results.get(1))
                anatomy = {
                    "verdict": rep.get("verdict"),
                    "efficiency": (round(rep["efficiency"], 3)
                                   if "efficiency" in rep else None),
                    "efficiency_vs_single": (
                        round(rep["efficiency_vs_single"], 3)
                        if rep.get("efficiency_vs_single") is not None
                        else None),
                    "straggler_rank": rep.get("straggler_rank"),
                    "per_worker": {
                        f"p{p}": {
                            "efficiency": round(r["efficiency"], 3),
                            "phase_fractions": {
                                k: round(v / r["wall_seconds"], 3)
                                for k, v in r["phases"].items()
                                if v},
                        } for p, r in (eff["ranks"].items()
                                       if eff else ())},
                } if (eff or "efficiency" in rep) else None
    r1, r2 = results.get(1, 0.0), results.get(2, 0.0)
    print(json.dumps({
        "metric": "multihost_scaling_efficiency",
        "value": round(r2 / r1, 3) if r1 and r2 else None,
        "unit": "2-worker per-worker rate / 1-worker rate",
        "single_process_eps": round(r1, 1),
        "two_worker_per_worker_eps": round(r2, 1),
        "examples": n_lines * epochs,
        "anatomy": anatomy,
    }))


# Bench-row names matching one of these fragments are lower-is-better
# (latencies, per-example costs); everything else is a rate or a count
# where bigger is fine. --compare's direction heuristic.
_LOWER_BETTER = ("_ms", "_seconds", "seconds_per", "bytes_per",
                 "latency", "_wait", "p50", "p90", "p99")


def _numeric_leaves(obj, prefix=""):
    """Flatten a bench JSON artifact to {dotted.path: float} rows —
    the nested shape (host_threads_search, e2e_trials, ...) varies by
    line, so --compare diffs whatever numeric leaves both sides
    share rather than hard-coding a schema."""
    rows = {}
    if isinstance(obj, dict):
        for k, v in sorted(obj.items()):
            rows.update(_numeric_leaves(v, f"{prefix}{k}."))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            rows.update(_numeric_leaves(v, f"{prefix}{i}."))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        rows[prefix[:-1]] = float(obj)
    return rows


def _bench_rows(path):
    """Rows from a bench artifact: a raw bench line (the JSON one
    bench.py mode prints), a BENCH_rNN.json wrapper (diffs its
    "parsed" payload; the cmd/rc/tail envelope is not a metric), or a
    JSONL file of several such documents merged."""
    with open(path) as fh:
        text = fh.read()
    try:
        docs = [json.loads(text)]
    except ValueError:
        docs = [json.loads(ln) for ln in text.splitlines()
                if ln.strip()]
    rows = {}
    for doc in docs:
        if isinstance(doc, dict) and isinstance(doc.get("parsed"),
                                                dict):
            doc = doc["parsed"]
        rows.update(_numeric_leaves(doc))
    return rows


def compare_main():
    """Regression diff (`python bench.py --compare OLD.json NEW.json`
    / `make bench-diff`): per-row NEW/OLD ratios with a direction
    heuristic (_LOWER_BETTER) and a tolerance band; exits 1 when any
    shared row regressed past tolerance, so CI can gate on a saved
    BENCH_rNN.json baseline without bespoke parsing."""
    import argparse
    import sys
    ap = argparse.ArgumentParser(
        prog="bench.py --compare",
        description="diff two bench JSON artifacts; exit 1 on "
                    "regression past --tolerance")
    ap.add_argument("old", help="baseline artifact (JSON or JSONL)")
    ap.add_argument("new", help="candidate artifact (JSON or JSONL)")
    ap.add_argument("--tolerance", type=float, default=0.85,
                    help="allowed NEW/OLD degradation ratio "
                         "(default 0.85: a rate may drop to 85%% of "
                         "baseline, a latency may grow to 1/0.85x)")
    args = ap.parse_args(sys.argv[2:])
    old, new = _bench_rows(args.old), _bench_rows(args.new)
    shared = sorted(set(old) & set(new))
    if not shared:
        raise SystemExit("bench --compare: no shared numeric rows "
                         f"between {args.old} and {args.new}")
    regressions = []
    print(f"{'row':<48} {'old':>12} {'new':>12} {'ratio':>8}  "
          f"dir  status")
    for k in shared:
        o, n = old[k], new[k]
        if o == 0:
            continue  # ratio undefined; zero baselines carry no bar
        ratio = n / o
        lower = any(f in k for f in _LOWER_BETTER)
        ok = (ratio <= 1.0 / args.tolerance) if lower \
            else (ratio >= args.tolerance)
        status = "ok" if ok else "REGRESSION"
        if not ok:
            regressions.append(k)
        print(f"{k:<48} {o:>12.4g} {n:>12.4g} {ratio:>8.3f}  "
              f"{'lo' if lower else 'hi'}   {status}")
    for label, only in (("old", set(old) - set(new)),
                        ("new", set(new) - set(old))):
        for k in sorted(only):
            print(f"{k:<48} only in {label}")
    if regressions:
        print(f"{len(regressions)} regression(s) past tolerance "
              f"{args.tolerance}: {', '.join(regressions)}")
        raise SystemExit(1)
    print(f"no regressions across {len(shared)} shared row(s) at "
          f"tolerance {args.tolerance}")


if __name__ == "__main__":
    import sys
    if len(sys.argv) > 1 and sys.argv[1] == "--line":
        if len(sys.argv) != 4:
            raise SystemExit("usage: bench.py --line <name> <train_path>")
        _line_main(sys.argv[2], sys.argv[3])
    elif len(sys.argv) > 1 and sys.argv[1] == "--host-sweep":
        host_sweep_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--predict":
        predict_sweep_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--vocab":
        vocab_overhead_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--serve":
        serve_latency_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--fleet":
        fleet_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--multihost":
        multihost_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--compare":
        compare_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--wire":
        wire_sweep_main()
    elif len(sys.argv) > 1 and sys.argv[1] == "--memory":
        memory_main()
    else:
        main()
