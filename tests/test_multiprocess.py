"""Two-process jax.distributed training smoke — the honest analogue of
the reference's localhost ps/worker cluster test (SURVEY.md §4): spawn
two real worker processes from the same config with different
``dist_train worker <i>`` argv, let them form one SPMD job over a
loopback coordinator, and require both to finish with a shared
checkpoint on disk.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_cfg(cfg_path, data, model, epoch_num):
    # coordinator_address() uses worker port + 1000; pick a free one
    # per launch (rebinding the previous port risks TIME_WAIT).
    coord = _free_port()
    cfg_path.write_text(f"""
[General]
vocabulary_size = 128
factor_num = 4
model_file = {model}

[Train]
train_files = {data}
validation_files = {data}
epoch_num = {epoch_num}
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 4

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")


def _launch(cfg_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, "run_tffm.py", "train", str(cfg_path),
             "dist_train", "worker", str(i)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    return outs


@pytest.mark.slow
def test_two_worker_dist_train_and_resume(tmp_path):
    rng = np.random.default_rng(0)
    # 193 lines over 2 workers with batch_size 32: shards of 97/96 lines
    # -> 4 vs 3 batches. The lockstep filler-batch protocol must absorb
    # the mismatch or the job deadlocks on the unmatched collective.
    lines = []
    for _ in range(193):
        nnz = rng.integers(2, 10)
        ids = rng.choice(128, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")

    model = tmp_path / "model" / "fm"
    cfg = tmp_path / "dist.cfg"
    _write_cfg(cfg, data, model, epoch_num=2)
    outs = _launch(cfg)
    assert any("mesh training" in o for o in outs)
    assert any("training done" in o for o in outs)
    # Per-epoch sharded validation runs inside multi-process training
    # (chief logs it each epoch), plus the chief epilogue's final AUC.
    assert sum("epoch 0 validation AUC" in o for o in outs) == 1
    assert sum("epoch 1 validation AUC" in o for o in outs) == 1
    assert sum("final validation AUC" in o for o in outs) == 1
    assert os.path.exists(str(model) + ".npz")
    # Shared checkpoint written once, restorable by a single process.
    ckpt_dir = str(model) + ".ckpt"
    assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)

    # Resume: a second 2-process job over the same model_file must
    # restore the multi-host checkpoint via the sharded template (the
    # unsharded-template path fails on non-addressable arrays) and
    # continue to the larger epoch budget.
    _write_cfg(cfg, data, model, epoch_num=3)
    outs2 = _launch(cfg)
    assert all("restored checkpoint at step" in o for o in outs2), (
        outs2[0][-2000:])
    assert any("training done" in o for o in outs2)
    assert sum("epoch 2 validation AUC" in o for o in outs2) == 1
