"""Two-process jax.distributed training smoke — the honest analogue of
the reference's localhost ps/worker cluster test (SURVEY.md §4): spawn
two real worker processes from the same config with different
``dist_train worker <i>`` argv, let them form one SPMD job over a
loopback coordinator, and require both to finish with a shared
checkpoint on disk.
"""

import functools
import os
import signal
import socket
import subprocess
import sys
import warnings

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _WorkerSignalDeath(Exception):
    """A spawned worker died on a SIGNAL (SIGSEGV/SIGABRT/SIGBUS) —
    the signature of the KNOWN pre-existing jaxlib restore-then-step
    heap corruption (intermittent, upstream, measured at seed), as
    opposed to a genuine assertion/regression (nonzero exit code,
    which never retries)."""

    def __init__(self, worker: int, sig: int, out: str):
        super().__init__(
            f"worker {worker} died on signal {sig}:\n{out[-2000:]}")
        self.sig = sig


_RERUN_SIGNALS = (signal.SIGSEGV, signal.SIGABRT, signal.SIGBUS)


def _rerun_on_worker_signal(times: int = 2):
    """Bounded rerun guard for the two tests that hit the known jaxlib
    restore-then-step SIGSEGV (PR 5 session note: intermittent on
    test_four_worker_cluster_lifecycle and the 2-proc resume shape at
    seed AND after — upstream heap corruption, not this repo's code).
    ONLY a signal death reruns (each attempt in a fresh subdirectory,
    so leftover checkpoints can't contaminate the retry); assertion
    failures and nonzero worker exits propagate on the first attempt —
    a real regression must never hide behind the retry."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(tmp_path):
            for attempt in range(times + 1):
                sub = tmp_path / f"attempt{attempt}"
                sub.mkdir()
                try:
                    return fn(sub)
                except _WorkerSignalDeath as e:
                    if attempt >= times:
                        raise
                    warnings.warn(
                        f"{fn.__name__}: worker died on signal "
                        f"{e.sig} (known jaxlib flake); rerun "
                        f"{attempt + 1}/{times}")
        return wrapper
    return deco


def _write_cfg(cfg_path, data, model, epoch_num):
    # coordinator_address() uses worker port + 1000; pick a free one
    # per launch (rebinding the previous port risks TIME_WAIT).
    coord = _free_port()
    cfg_path.write_text(f"""
[General]
vocabulary_size = 128
factor_num = 4
model_file = {model}

[Train]
train_files = {data}
validation_files = {data}
epoch_num = {epoch_num}
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 4

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")


def _launch(cfg_path):
    return _launch_mode(cfg_path, "train")


@pytest.mark.slow
@_rerun_on_worker_signal(times=2)
def test_two_worker_dist_train_and_resume(tmp_path):
    rng = np.random.default_rng(0)
    # 193 lines over 2 workers with batch_size 32: shards of 97/96 lines
    # -> 4 vs 3 batches. The lockstep filler-batch protocol must absorb
    # the mismatch or the job deadlocks on the unmatched collective.
    lines = []
    for _ in range(193):
        nnz = rng.integers(2, 10)
        ids = rng.choice(128, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")

    model = tmp_path / "model" / "fm"
    cfg = tmp_path / "dist.cfg"
    _write_cfg(cfg, data, model, epoch_num=2)
    outs = _launch(cfg)
    assert any("mesh training" in o for o in outs)
    assert any("training done" in o for o in outs)
    # Per-epoch sharded validation runs inside multi-process training
    # (chief logs it each epoch), plus the chief epilogue's final AUC.
    assert sum("epoch 0 validation AUC" in o for o in outs) == 1
    assert sum("epoch 1 validation AUC" in o for o in outs) == 1
    assert sum("final validation AUC" in o for o in outs) == 1
    assert os.path.exists(str(model) + ".npz")
    # Shared checkpoint written once, restorable by a single process.
    ckpt_dir = str(model) + ".ckpt"
    assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)

    # Resume: a second 2-process job over the same model_file must
    # restore the multi-host checkpoint via the sharded template (the
    # unsharded-template path fails on non-addressable arrays) and
    # continue to the larger epoch budget.
    _write_cfg(cfg, data, model, epoch_num=3)
    outs2 = _launch(cfg)
    assert all("restored checkpoint at step" in o for o in outs2), (
        outs2[0][-2000:])
    assert any("training done" in o for o in outs2)
    assert sum("epoch 2 validation AUC" in o for o in outs2) == 1


@pytest.mark.slow
def test_two_worker_dist_train_ffm(tmp_path):
    """FFM through the full multi-process path: field-aware C++ fast
    input under byte-range sharding, fields assembled by global_batch,
    the field-bucketed scorer under the sharded jit, per-epoch
    distributed validation."""
    rng = np.random.default_rng(3)
    lines = []
    for _ in range(161):  # odd count: shards differ, filler protocol
        nnz = rng.integers(2, 8)
        ids = rng.choice(128, size=nnz, replace=False)
        toks = [f"{int(rng.integers(0, 4))}:{i}:{rng.random():.3f}"
                for i in ids]
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"] + toks))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")

    model = tmp_path / "model" / "ffm"
    coord = _free_port()
    cfg = tmp_path / "dist.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 128
factor_num = 2
model_type = ffm
field_num = 4
model_file = {model}

[Train]
train_files = {data}
validation_files = {data}
epoch_num = 2
batch_size = 32
learning_rate = 0.1
shuffle = False
max_features_per_example = 8
bucket_ladder = 8

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
    outs = _launch_mode(cfg, "train")
    assert any("mesh training" in o for o in outs)
    assert any("training done" in o for o in outs)
    assert sum("epoch 1 validation AUC" in o for o in outs) == 1
    assert os.path.exists(str(model) + ".npz")
    table = np.load(str(model) + ".npz")["table"]
    assert table.shape == (128, 2 * 4 + 1)  # [vocab, k*F+1] FFM layout
    assert np.abs(table).max() > 0.01       # actually trained


def _launch_mode(cfg_path, mode, n_procs: int = 2,
                 devices_per_proc: int = 1):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    if devices_per_proc > 1:
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                            f"{devices_per_proc}")
    procs = [
        subprocess.Popen(
            [sys.executable, "run_tffm.py", mode, str(cfg_path),
             "dist_train", "worker", str(i)],
            cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for i in range(n_procs)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode is not None and -p.returncode in [
                int(s) for s in _RERUN_SIGNALS]:
            # Signal death: the known upstream jaxlib flake class —
            # raised as its own type so _rerun_on_worker_signal can
            # retry it (bounded) without masking real failures.
            raise _WorkerSignalDeath(i, -p.returncode, out)
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
    return outs


@pytest.mark.slow
@_rerun_on_worker_signal(times=2)
def test_four_worker_cluster_lifecycle(tmp_path):
    """The full job lifecycle at P=4 with REAL transport (round-4
    review: every protocol beyond P=2 ran only simulated through the
    dryrun's offset_local_idx math): 4 jax.distributed processes x 2
    forced CPU devices = an 8-device mesh; train with per-epoch
    distributed validation, resume onto a larger epoch budget, then
    4-part multi-process predict merged against a single-process
    oracle. Line lengths are skewed so the byte-range shards hold
    different line counts — middle processes (1..2 of 4) run dry at
    different steps and ride zero-weight lockstep fillers while the
    others finish, the exact boundary where index/order bugs live."""
    rng = np.random.default_rng(11)
    lines = []
    for i in range(300):
        # first ~quarter long lines, rest short: 4 equal BYTE ranges
        # then hold very different LINE counts per shard
        nnz = int(rng.integers(10, 16)) if i < 75 else int(
            rng.integers(2, 5))
        ids = rng.choice(128, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    pred = tmp_path / "pred.txt"
    pred_lines = lines[:100] + [""] + lines[100:180]  # blank line kept
    pred.write_text("\n".join(pred_lines) + "\n")

    model = tmp_path / "model" / "fm"
    coord = _free_port()
    hosts = ",".join(f"localhost:{coord - 1000 + i}" for i in range(4))

    def write_cfg(epoch_num):
        (tmp_path / "dist.cfg").write_text(f"""
[General]
vocabulary_size = 128
factor_num = 4
model_file = {model}

[Train]
train_files = {data}
validation_files = {data}
epoch_num = {epoch_num}
batch_size = 32
learning_rate = 0.1
shuffle = False
max_features_per_example = 16
bucket_ladder = 16

[Predict]
predict_files = {pred}
score_path = {tmp_path}/score

[Cluster]
worker_hosts = {hosts}
""")

    cfg = tmp_path / "dist.cfg"
    write_cfg(epoch_num=2)
    outs = _launch_mode(cfg, "train", n_procs=4, devices_per_proc=2)
    assert any("8 devices, 4 processes" in o for o in outs), (
        outs[0][-2000:])
    assert any("training done" in o for o in outs)
    for ep in (0, 1):
        assert sum(f"epoch {ep} validation AUC" in o for o in outs) == 1
    assert sum("final validation AUC" in o for o in outs) == 1

    # Resume at P=4: all four processes restore the sharded checkpoint
    # and continue one more epoch.
    write_cfg(epoch_num=3)
    outs2 = _launch_mode(cfg, "train", n_procs=4, devices_per_proc=2)
    assert all("restored checkpoint at step" in o for o in outs2), (
        outs2[0][-2000:])
    assert sum("epoch 2 validation AUC" in o for o in outs2) == 1
    assert any("training done" in o for o in outs2)

    # 4-part predict: >2 part-file merge order with a blank line in a
    # middle shard's range.
    outs3 = _launch_mode(cfg, "predict", n_procs=4, devices_per_proc=2)
    assert sum("merged 4 parts" in o for o in outs3) == 1, (
        outs3[0][-2000:])
    score_file = tmp_path / "score" / "pred.txt.score"
    scores_mp = np.loadtxt(score_file)
    assert len(scores_mp) == len(pred_lines)
    assert not list((tmp_path / "score").glob("*.part*"))

    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.predict import predict
    import dataclasses
    sp_cfg = dataclasses.replace(load_config(str(cfg)),
                                 score_path=str(tmp_path / "score_sp"))
    predict(sp_cfg)
    scores_sp = np.loadtxt(tmp_path / "score_sp" / "pred.txt.score")
    np.testing.assert_allclose(scores_mp, scores_sp, atol=2e-6)


@pytest.mark.slow
def test_two_worker_dist_predict_matches_single(tmp_path):
    """2-process sharded predict must write the same ordered score file
    a single-process predict writes from the same checkpoint — blank
    lines (line-alignment) included."""
    rng = np.random.default_rng(1)
    lines = []
    for _ in range(150):
        nnz = rng.integers(2, 10)
        ids = rng.choice(128, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    pred = tmp_path / "pred.txt"
    pred_lines = lines[:70] + [""] + lines[70:110]   # blank line kept
    pred.write_text("\n".join(pred_lines) + "\n")

    model = tmp_path / "model" / "fm"
    coord = _free_port()
    cfg = tmp_path / "dist.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 128
factor_num = 4
model_file = {model}

[Train]
train_files = {data}
epoch_num = 1
batch_size = 32
learning_rate = 0.1
shuffle = False
max_features_per_example = 16
bucket_ladder = 16

[Predict]
predict_files = {pred}
score_path = {tmp_path}/score

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
    # 2-process train writes the shared checkpoint...
    _launch_mode(cfg, "train")
    # ...then 2-process sharded predict from it.
    outs = _launch_mode(cfg, "predict")
    assert any("multi-process predict" in o for o in outs), outs[0][-2000:]
    assert sum("merged 2 parts" in o for o in outs) == 1
    score_file = tmp_path / "score" / "pred.txt.score"
    scores_mp = np.loadtxt(score_file)
    assert len(scores_mp) == len(pred_lines)   # one per line, blanks too
    assert not list((tmp_path / "score").glob("*.part*"))

    # Single-process predict from the same checkpoint (in-process, on
    # the 8-device CPU mesh) must agree to float-print precision.
    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.predict import predict
    import dataclasses
    sp_cfg = dataclasses.replace(load_config(str(cfg)),
                                 score_path=str(tmp_path / "score_sp"))
    predict(sp_cfg)
    scores_sp = np.loadtxt(tmp_path / "score_sp" / "pred.txt.score")
    np.testing.assert_allclose(scores_mp, scores_sp, atol=2e-6)


@pytest.mark.slow
def test_two_worker_dist_predict_ffm(tmp_path):
    """FFM through multi-process predict: field-aware fixed-shape input
    under byte ranges, fields through global_batch into the sharded
    scorer, chief-merged score file equal to single-process."""
    rng = np.random.default_rng(9)
    lines = []
    for _ in range(90):
        nnz = rng.integers(2, 8)
        ids = rng.choice(128, size=nnz, replace=False)
        toks = [f"{int(rng.integers(0, 4))}:{i}:{rng.random():.3f}"
                for i in ids]
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"] + toks))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")

    model = tmp_path / "model" / "ffm"
    coord = _free_port()
    cfg = tmp_path / "dist.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 128
factor_num = 2
model_type = ffm
field_num = 4
model_file = {model}

[Train]
train_files = {data}
epoch_num = 1
batch_size = 32
learning_rate = 0.1
shuffle = False
max_features_per_example = 8
bucket_ladder = 8

[Predict]
predict_files = {data}
score_path = {tmp_path}/score

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
    _launch_mode(cfg, "train")
    outs = _launch_mode(cfg, "predict")
    assert sum("merged 2 parts" in o for o in outs) == 1
    scores_mp = np.loadtxt(tmp_path / "score" / "train.txt.score")
    assert len(scores_mp) == 90

    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.predict import predict
    import dataclasses
    sp_cfg = dataclasses.replace(load_config(str(cfg)),
                                 score_path=str(tmp_path / "score_sp"))
    predict(sp_cfg)
    scores_sp = np.loadtxt(tmp_path / "score_sp" / "train.txt.score")
    np.testing.assert_allclose(scores_mp, scores_sp, atol=2e-6)


@pytest.mark.slow
def test_two_worker_weighted_validation(tmp_path):
    """validation_weight_files through the REAL multi-process path:
    sidecar byte-range sharding, weights into the lockstep scorer's
    StreamingAUC, weighted bins over the (hi,lo)-f32 histogram
    allgather. The weighted AUC (logged once, by the chief, from the
    merged job-wide histograms — cross-worker value agreement is
    pinned in-process by test_evaluate_distributed_weighted) must
    differ from the unweighted run's on weights built to move the
    rank statistic."""
    import re
    rng = np.random.default_rng(21)
    lines = []
    for _ in range(240):
        nnz = rng.integers(2, 10)
        ids = rng.choice(128, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    # Weights must vary WITHIN a class: class-constant weights scale
    # every (pos, neg) pair uniformly and cancel in the normalized rank
    # statistic (weighted AUC == unweighted, exactly). Heavy-tailed
    # per-line weights concentrate the statistic on a few examples, so
    # it provably moves at this sample size.
    weights = np.exp(rng.normal(0.0, 2.0, size=len(lines)))
    wfile = tmp_path / "val.w"
    wfile.write_text("".join(f"{w:.6f}\n" for w in weights))

    def write_cfg(extra):
        coord = _free_port()
        (tmp_path / "dist.cfg").write_text(f"""
[General]
vocabulary_size = 128
factor_num = 4
model_file = {tmp_path / 'model' / 'fm'}

[Train]
train_files = {data}
validation_files = {data}
{extra}
epoch_num = 1
batch_size = 32
learning_rate = 0.1
shuffle = False
max_features_per_example = 16
bucket_ladder = 16

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")

    cfg = tmp_path / "dist.cfg"

    def final_auc(outs):
        vals = set()
        for out in outs:
            vals.update(re.findall(
                r"epoch 0 validation AUC (\d+\.\d+)", out))
        assert len(vals) == 1, vals  # exactly one (chief-logged) value
        return float(vals.pop())

    write_cfg("")
    auc_u = final_auc(_launch_mode(cfg, "train"))
    import shutil
    shutil.rmtree(tmp_path / "model")
    write_cfg(f"validation_weight_files = {wfile}")
    auc_w = final_auc(_launch_mode(cfg, "train"))
    assert abs(auc_w - auc_u) > 0.005, (auc_u, auc_w)


@pytest.mark.slow
def test_two_process_adagrad_convergence_parity(tmp_path):
    """The documented multi-process Adagrad divergence (an id hot on
    several processes accumulates sum-of-per-process g^2 instead of
    (sum g)^2 — parallel/sharded.py global_batch) must not cost
    convergence: 2-process and 1-process training on the same data must
    reach the same test AUC within a small tolerance."""
    import sys as _sys
    _sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_e2e import make_dataset
    rng = np.random.default_rng(7)
    data = tmp_path / "train.txt"
    test = tmp_path / "test.txt"
    make_dataset(data, 600, rng)
    test_labels = make_dataset(test, 200, rng)

    model_mp = tmp_path / "mmp" / "fm"
    coord = _free_port()
    cfg = tmp_path / "par.cfg"

    def write_cfg(model, cluster):
        cfg.write_text(f"""
[General]
vocabulary_size = 200
factor_num = 4
model_file = {model}

[Train]
train_files = {data}
epoch_num = 6
batch_size = 32
learning_rate = 0.1
shuffle = False
max_features_per_example = 16
bucket_ladder = 16
{cluster}
""")

    write_cfg(model_mp, f"""
[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
    _launch_mode(cfg, "train")
    table_mp = np.load(str(model_mp) + ".npz")["table"]

    model_sp = tmp_path / "msp" / "fm"
    write_cfg(model_sp, "")
    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.train import train
    train(load_config(str(cfg)))
    table_sp = np.load(str(model_sp) + ".npz")["table"]

    from fast_tffm_tpu.metrics import exact_auc
    from fast_tffm_tpu.models.oracle import fm_score
    from fast_tffm_tpu.data.parser import parse_lines

    def auc_of(table):
        block = parse_lines(test.read_text().splitlines(), 200)
        scores = [fm_score(table,
                           block.ids[block.poses[i]:block.poses[i + 1]],
                           block.vals[block.poses[i]:block.poses[i + 1]])
                  for i in range(block.batch_size)]
        return exact_auc(np.asarray(scores), test_labels)

    auc_sp, auc_mp = auc_of(table_sp), auc_of(table_mp)
    assert auc_sp > 0.85, auc_sp
    assert abs(auc_sp - auc_mp) < 0.03, (auc_sp, auc_mp)


@pytest.mark.slow
def test_two_worker_adaptive_uniq_bucket(tmp_path):
    """A dense id cluster the startup probe misses: epoch 1 spills above
    the warn threshold, the epoch-boundary allgather agrees on a raise,
    and BOTH workers double the bucket in lockstep (a process raising
    alone would desynchronize global shapes and deadlock) — the
    multi-process leg of train.adapt_uniq_bucket."""
    lines = []
    next_id = 1000
    for i in range(2000):
        if 900 <= i < 964:  # dense cluster: 20 fresh ids per line,
            ids = range(next_id, next_id + 20)  # hidden from the probe's
            next_id += 20                       # head/middle/tail windows
            lines.append("1 " + " ".join(f"{j}:1" for j in ids))
        else:
            lines.append("0 0:1 1:1 2:1 3:1")
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    coord = _free_port()
    cfg = tmp_path / "dist.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 65536
factor_num = 2
model_file = {tmp_path / 'model' / 'fm'}

[Train]
train_files = {data}
epoch_num = 3
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 0
max_features_per_example = 32
bucket_ladder = 32

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
    outs = _launch(cfg)
    for i, out in enumerate(outs):
        assert "fixed unique-row bucket: 64" in out, f"worker {i}"
        assert "raising uniq_bucket 64 -> 128" in out, f"worker {i}"
        assert "raising uniq_bucket 128 -> 256" in out, f"worker {i}"
    assert any("training done" in o for o in outs)


@pytest.mark.slow
def test_two_worker_stream_mode(tmp_path):
    """run_mode = stream at P=2 with real transport: ledger-index file
    ownership (files i % 2), the per-iteration discovery broadcast
    aligned with the lockstep flags allgather, late-arriving shards
    picked up mid-run, merged watermarks on every save, and a verified
    publish — the compute-plane leg of the streaming run mode."""
    import time
    from tools.fmchaos import _write_corpus
    sd = tmp_path / "stream"
    sd.mkdir()
    n0, per = 4, 160  # 4 shards up front, 2 more arrive mid-run
    for i in range(n0):
        _write_corpus(str(sd / f"part-{i:03d}.txt"), per, i)
        (sd / f"part-{i:03d}.txt.done").touch()
    model = tmp_path / "model" / "fm"
    metrics = tmp_path / "m.jsonl"
    coord = _free_port()
    cfg = tmp_path / "dist.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 200
factor_num = 4
model_file = {model}

[Train]
run_mode = stream
stream_dir = {sd}
stream_poll_seconds = 0.1
seal_policy = done
publish_interval_seconds = 1.0
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 0
metrics_file = {metrics}
metrics_flush_steps = 4

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, "run_tffm.py", "train", str(cfg),
         "dist_train", "worker", str(i)],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for i in range(2)]
    try:
        time.sleep(12)  # past bring-up; the first shards streaming
        for i in range(n0, n0 + 2):  # late arrivals, then STOP
            _write_corpus(str(sd / f"part-{i:03d}.txt"), per, i)
            (sd / f"part-{i:03d}.txt.done").touch()
        (sd / "STOP").touch()
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    assert all("training done" in o for o in outs)
    assert any("part-005" in o for o in outs)  # late arrival consumed
    # 6 files x 160 lines / 32 = 5 batches per file, 3 files per
    # worker: 15 lockstep steps, every shard consumed exactly once.
    from fast_tffm_tpu.checkpoint import (list_step_dirs,
                                          read_published,
                                          read_watermark)
    ckpt_dir = str(model) + ".ckpt"
    steps = list_step_dirs(ckpt_dir)
    assert steps and steps[-1] == 15, steps
    assert read_published(ckpt_dir) == 15
    wm = read_watermark(ckpt_dir, 15)
    assert wm is not None and len(wm["files"]) == 6
    # The merged watermark has every file fully consumed (the owner's
    # positions won the merge for each ledger index).
    for rec in wm["files"]:
        assert rec["sealed"] and rec["bytes"] == rec["end"], rec
        assert rec["lines"] == per, rec


@pytest.mark.slow
def test_two_worker_shrink_oversized_bucket(tmp_path):
    """The shrink leg of adapt_uniq_bucket at P=2 with real transport:
    the startup probe's 2x safety margin lands one power of two above
    what any real batch uses (8 dense lines -> u_max ~132 -> probe
    rounds 2*132 up to 512, while the epoch's densest batch also needs
    ~136, a 27% fill), so after a spill-free epoch both workers must
    halve 512 -> 256 IN LOCKSTEP (a lone shrinker would desynchronize
    global shapes and deadlock) — and then STOP: at 256 the same batch
    fills 53%, above the shrink threshold, so the width must not
    oscillate below what the data needs. This is exactly the ~2x
    collective-width recovery the round-4 review asked for."""
    lines = []
    for i in range(2000):
        if i < 8:  # one dense batch's worth, inside the probe's head window
            ids = range(1000 + i * 16, 1000 + (i + 1) * 16)
            lines.append("1 " + " ".join(f"{j}:1" for j in ids))
        else:
            lines.append("0 0:1 1:1 2:1 3:1")
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    coord = _free_port()
    cfg = tmp_path / "dist.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 65536
factor_num = 2
model_file = {tmp_path / 'model' / 'fm'}

[Train]
train_files = {data}
epoch_num = 3
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 0
max_features_per_example = 16
bucket_ladder = 16

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
""")
    outs = _launch(cfg)
    for i, out in enumerate(outs):
        assert "fixed unique-row bucket: 512" in out, f"worker {i}"
        assert "lowering uniq_bucket 512 -> 256" in out, f"worker {i}"
        assert "lowering uniq_bucket 256 ->" not in out, (
            f"worker {i} shrank below the data's densest batch")
        assert "raising uniq_bucket" not in out, (
            f"worker {i}: the shrink caused spills")
    assert any("training done" in o for o in outs)
