"""tools/fmlint: the hot-loop device-fetch/print rules, suppression
grammar, and the repo-wide lint gate (this file IS the tier-1 wiring —
a hot-loop regression fails the suite here)."""

import os
import textwrap

import pytest

from tools.fmlint.core import run_file, run_paths
from tools.fmlint.rules import is_hot_module


def _hot_file(tmp_path, body):
    """Write ``body`` at a path the rules treat as a hot module."""
    d = tmp_path / "fast_tffm_tpu"
    d.mkdir(exist_ok=True)
    p = d / "train.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_repo_surface_is_clean():
    """THE lint gate: the full default surface — fast_tffm_tpu/,
    tools/ (fmlint lints itself), run_tffm.py, bench.py — must have
    zero findings under every rule, per-file AND whole-program
    (deliberate exceptions carry justified pragmas; the committed
    baseline is empty). R999 parse failures anywhere on this surface
    fail here too."""
    from tools.fmlint.core import default_baseline_path, default_paths
    findings = run_paths(default_paths(),
                         baseline=default_baseline_path())
    assert findings == [], "\n".join(f.render() for f in findings)


def test_default_surface_includes_tools_and_cli():
    """ISSUE 7 satellite: the no-argument lint surface reaches beyond
    the package to the tools and CLI entry points."""
    from tools.fmlint.core import default_paths
    names = [os.path.basename(p) for p in default_paths()]
    assert names == ["fast_tffm_tpu", "tools", "run_tffm.py",
                     "bench.py"]


def test_collect_files_is_deterministic_and_sorted(tmp_path):
    """ISSUE 7 satellite: finding order (and therefore baseline
    diffs) must be stable across filesystems — both the directory
    descent and per-directory file order are sorted."""
    from tools.fmlint.core import collect_files
    for rel in ("b/zz.py", "b/aa.py", "a/x.py", "c/__pycache__/j.py",
                "top.py"):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("x = 1\n")
    got = [os.path.relpath(f, tmp_path)
           for f in collect_files([str(tmp_path)])]
    assert got == ["top.py", "a/x.py", "b/aa.py", "b/zz.py"]
    assert got == [os.path.relpath(f, tmp_path)
                   for f in collect_files([str(tmp_path)])]


def test_is_hot_module_scope():
    assert is_hot_module("x/fast_tffm_tpu/train.py")
    assert is_hot_module("x/fast_tffm_tpu/predict.py")
    assert is_hot_module("x/fast_tffm_tpu/data/pipeline.py")
    assert is_hot_module("x/fast_tffm_tpu/obs/sink.py")
    assert not is_hot_module("x/fast_tffm_tpu/metrics.py")
    assert not is_hot_module("x/bench.py")
    assert not is_hot_module("x/tools/fmstat/__init__.py")


def test_r001_flags_scalar_fetch_in_loop(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(it, step):
            for batch in it:
                loss = step(batch)
                print_loss = float(loss)
            return loss
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R001"]
    assert found[0].line == 4


def test_r001_flags_item_anywhere(tmp_path):
    path = _hot_file(tmp_path, """\
        def read(loss):
            return loss.item()
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R001"]


def test_r001_allows_fetch_outside_loops(tmp_path):
    path = _hot_file(tmp_path, """\
        def final(loss):
            return float(loss)
    """)
    assert run_file(path) == []


def test_r002_flags_bare_print(tmp_path):
    path = _hot_file(tmp_path, """\
        def log(x):
            print(x)
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R002"]


def test_rules_scope_to_hot_modules_only(tmp_path):
    p = tmp_path / "other.py"
    p.write_text("def f(it):\n    for x in it:\n        print(float(x))\n")
    assert run_file(str(p)) == []


def test_inline_pragma_suppresses_with_justification(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(it):
            for x in it:
                v = float(x)  # fmlint: disable=R001 -- host value
            return v
    """)
    assert run_file(path) == []


def test_wholeline_pragma_covers_next_statement(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(it, f):
            for x in it:
                # fmlint: disable=R001 -- host allgather results
                v = f(int(x[0]),
                      int(x[1]),
                      int(x[2]))
            return v
    """)
    assert run_file(path) == []


def test_pragma_without_justification_is_r000(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(it):
            for x in it:
                v = float(x)  # fmlint: disable=R001
            return v
    """)
    rules = sorted(f.rule for f in run_file(path))
    # the naked pragma is reported AND does not suppress
    assert rules == ["R000", "R001"]


def test_disable_file_pragma(tmp_path):
    path = _hot_file(tmp_path, """\
        # fmlint: disable-file=R002 -- exercise harness, prints wanted
        def a(x):
            print(x)
        def b(it):
            for v in it:
                print(v)
    """)
    assert run_file(path) == []


def test_syntax_error_reports_r999(tmp_path):
    path = _hot_file(tmp_path, "def broken(:\n")
    found = run_file(path)
    assert [f.rule for f in found] == ["R999"]


def test_cli_main(tmp_path, capsys):
    from tools.fmlint.core import main
    bad = _hot_file(tmp_path, """\
        def run(it):
            for x in it:
                print(float(x))
    """)
    assert main([bad]) == 1
    out = capsys.readouterr()
    assert "R001" in out.out and "R002" in out.out
    ok = tmp_path / "clean.py"
    ok.write_text("x = 1\n")
    assert main([str(ok)]) == 0


def test_r003_flags_perf_counter_in_loop(tmp_path):
    """ISSUE 3 satellite: hot-loop timing should go through the
    no-op-when-inactive obs.trace.span(), not hand-rolled
    perf_counter pairs."""
    path = _hot_file(tmp_path, """\
        import time
        def run(it):
            for x in it:
                t0 = time.perf_counter()
                do(x)
                dt = time.perf_counter() - t0
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R003", "R003"]
    assert [f.line for f in found] == [4, 6]


def test_r003_allows_perf_counter_outside_loops(tmp_path):
    path = _hot_file(tmp_path, """\
        import time
        def stamp():
            return time.perf_counter()
    """)
    assert run_file(path) == []


def test_r003_flags_bare_name_and_respects_pragma(tmp_path):
    path = _hot_file(tmp_path, """\
        from time import perf_counter
        def run(it):
            for x in it:
                # fmlint: disable=R003 -- feeds an always-on histogram
                t0 = perf_counter()
                t1 = perf_counter()
    """)
    found = run_file(path)
    assert [(f.rule, f.line) for f in found] == [("R003", 6)]


def test_r004_flags_swallowed_broad_except(tmp_path):
    """ISSUE 4 satellite: bare `except Exception: pass` in hot modules
    turns failures the fault-tolerance layer should count/surface into
    silence."""
    path = _hot_file(tmp_path, """\
        def run(it):
            for x in it:
                try:
                    do(x)
                except Exception:
                    pass
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R004"]
    assert found[0].line == 5


def test_r004_flags_bare_except_continue(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(it):
            for x in it:
                try:
                    do(x)
                except:
                    continue
    """)
    assert [f.rule for f in run_file(path)] == ["R004"]


def test_r004_flags_broad_tuple(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(x):
            try:
                do(x)
            except (ValueError, Exception):
                pass
    """)
    assert [f.rule for f in run_file(path)] == ["R004"]


def test_r004_allows_narrow_handlers(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(x):
            try:
                do(x)
            except (OSError, RuntimeError):
                pass
    """)
    assert run_file(path) == []


def test_r004_allows_handled_broad_except(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(x, log):
            try:
                do(x)
            except Exception:
                log.exception("do failed")
    """)
    assert run_file(path) == []


def test_r004_respects_pragma(tmp_path):
    path = _hot_file(tmp_path, """\
        def run(x):
            try:
                do(x)
            except Exception:  # fmlint: disable=R004 -- must outlive
                pass
    """)
    assert run_file(path) == []


def _any_file(tmp_path, body, name="cleanup.py"):
    """R005 applies to every linted module, not just hot ones."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_r005_flags_rmtree_on_ckpt_path(tmp_path):
    """ISSUE 5 satellite: quarantine-not-delete is the state-plane
    invariant — direct deletion of checkpoint state outside
    checkpoint.py is a finding."""
    path = _any_file(tmp_path, """\
        import shutil
        def clean(ckpt_dir):
            shutil.rmtree(ckpt_dir)
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R005"]
    assert "quarantine" in found[0].message


def test_r005_flags_os_remove_on_ckpt_literal(tmp_path):
    path = _any_file(tmp_path, """\
        import os
        def clean(model):
            os.remove(model + ".ckpt/manifest-3.json")
    """)
    assert [f.rule for f in run_file(path)] == ["R005"]


def test_r005_flags_step_dir_unlink(tmp_path):
    path = _any_file(tmp_path, """\
        import os
        def clean(step_dir):
            os.unlink(step_dir)
    """)
    assert [f.rule for f in run_file(path)] == ["R005"]


def test_r005_allows_checkpoint_py_itself(tmp_path):
    path = _any_file(tmp_path, """\
        import shutil
        def clean(ckpt_dir):
            shutil.rmtree(ckpt_dir)
    """, name="checkpoint.py")
    assert run_file(path) == []


def test_r005_allows_non_ckpt_deletes(tmp_path):
    path = _any_file(tmp_path, """\
        import os
        def clean(part_file):
            os.remove(part_file)
    """)
    assert run_file(path) == []


def test_r005_respects_pragma(tmp_path):
    path = _any_file(tmp_path, """\
        import shutil
        def gc(ckpt_dir):
            # fmlint: disable=R005 -- sanctioned operator gc path
            shutil.rmtree(ckpt_dir)
    """)
    assert run_file(path) == []


def _parallel_file(tmp_path, body, name="sharded.py"):
    """Write ``body`` at a path inside R006's cluster-critical scope."""
    d = tmp_path / "fast_tffm_tpu" / "parallel"
    d.mkdir(parents=True, exist_ok=True)
    p = d / name
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_r006_flags_bare_collectives(tmp_path):
    """ISSUE 6 satellite: a bare blocking collective outside
    guarded_collective() in a cluster-critical module is the
    hang-forever-on-a-dead-peer failure mode."""
    path = _parallel_file(tmp_path, """\
        from jax.experimental import multihost_utils
        def sync(x):
            fills = multihost_utils.process_allgather(x)
            v = multihost_utils.broadcast_one_to_all(x)
            multihost_utils.sync_global_devices("tag")
            return fills, v
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R006", "R006", "R006"]
    assert "guarded_collective" in found[0].message


def test_r006_allows_passing_collective_as_argument(tmp_path):
    """The guarded form REFERENCES the collective without calling it —
    that must not be a finding, or the fix itself would be flagged."""
    path = _parallel_file(tmp_path, """\
        from jax.experimental import multihost_utils
        from fast_tffm_tpu.parallel.liveness import guarded_collective
        def sync(x):
            return guarded_collective(
                multihost_utils.process_allgather, x, label="x")
    """)
    assert run_file(path) == []


def test_r006_scope(tmp_path):
    body = """\
        from jax.experimental import multihost_utils
        def sync(x):
            return multihost_utils.process_allgather(x)
    """
    # checkpoint.py and train.py are in scope...
    d = tmp_path / "fast_tffm_tpu"
    d.mkdir(exist_ok=True)
    for name in ("checkpoint.py", "train.py"):
        p = d / name
        p.write_text(textwrap.dedent(body))
        assert [f.rule for f in run_file(str(p))] == ["R006"], name
    # ...the guard's own implementation and non-cluster modules are not
    assert run_file(_parallel_file(tmp_path, body,
                                   name="liveness.py")) == []
    other = d / "metrics.py"
    other.write_text(textwrap.dedent(body))
    assert run_file(str(other)) == []


def test_r006_respects_pragma(tmp_path):
    path = _parallel_file(tmp_path, """\
        from jax.experimental import multihost_utils
        def sync(x):
            # fmlint: disable=R006 -- bring-up path, no guard yet
            return multihost_utils.process_allgather(x)
    """)
    assert run_file(path) == []


# --- pragma edge cases (ISSUE 7 satellite) ---------------------------------

def test_wholeline_pragma_above_decorated_function(tmp_path):
    """A whole-line pragma above a DECORATED function suppresses the
    whole function statement: the decorator is an expression, not a
    statement, so the next statement span is the full def (decorators
    included in neither — the span runs def..end of body)."""
    path = _hot_file(tmp_path, """\
        import functools
        # fmlint: disable=R001 -- whole helper reads host values
        @functools.lru_cache(maxsize=8)
        def read(loss, it):
            for x in it:
                v = float(x)
            return v + loss.item()
    """)
    assert run_file(path) == []


def test_wholeline_pragma_covers_finding_on_last_span_line(tmp_path):
    """Multi-line call spans: the pragma covers findings anchored on
    ANY line of the next statement, including the last."""
    path = _hot_file(tmp_path, """\
        def run(it, f):
            for x in it:
                # fmlint: disable=R001 -- host tuple unpack
                v = f(x[0],
                      x[1],
                      int(x[2]))
            return v
    """)
    assert run_file(path) == []


def test_disable_file_without_justification_is_r000(tmp_path):
    """``disable-file=`` without a ``--`` rationale is itself reported
    AND does not suppress anything."""
    path = _hot_file(tmp_path, """\
        # fmlint: disable-file=R002
        def log(x):
            print(x)
    """)
    rules = sorted(f.rule for f in run_file(path))
    assert rules == ["R000", "R002"]


def test_r999_fails_gate_for_expanded_surface(tmp_path):
    """A syntax error anywhere on a linted surface (e.g. a tools/
    module) surfaces as R999 through the whole-program runner and
    fails the gate."""
    d = tmp_path / "tools" / "fmthing"
    d.mkdir(parents=True)
    (d / "__init__.py").write_text("def broken(:\n")
    (tmp_path / "ok.py").write_text("x = 1\n")
    findings = run_paths([str(tmp_path)])
    assert [f.rule for f in findings] == ["R999"]
    assert findings[0].path.endswith("__init__.py")


def test_r011_flags_raw_table_index(tmp_path):
    """ISSUE 12 satellite: a raw ``table[ids]`` outside lookup.py/
    vocab/ bypasses the slot-indirection seam — under vocab_mode =
    admit it reads rows the slot map may have reassigned or reset."""
    path = _any_file(tmp_path, """\
        def gather(table, ids):
            return table[ids]
    """)
    found = run_file(path)
    assert [f.rule for f in found] == ["R011"]
    assert "slot-indirection" in found[0].message


def test_r011_flags_attribute_table_index(tmp_path):
    path = _any_file(tmp_path, """\
        def gather(self, ids):
            return self.table[ids]
    """)
    assert [f.rule for f in run_file(path)] == ["R011"]


def test_r011_allows_layout_slices_and_fixed_rows(tmp_path):
    """Slices (checkpoint layout trims) and constant rows — negative
    included (the dead tail row) — address LAYOUT, not id routing."""
    path = _any_file(tmp_path, """\
        def trim(table, n):
            head = table[:n]
            row0 = table[0]
            tail = table[-1]
            block = table[0:4, :]
            corner = table[-1, :]
            return head, row0, tail, block, corner
    """)
    assert run_file(path) == []


def test_r011_exempts_lookup_and_vocab_modules(tmp_path):
    """lookup.py and vocab/ ARE the seam — raw indexing there is the
    implementation, not a bypass."""
    body = """\
        def gather(table, ids):
            return table[ids]
    """
    d = tmp_path / "fast_tffm_tpu"
    d.mkdir()
    import textwrap as _tw
    (d / "lookup.py").write_text(_tw.dedent(body))
    v = d / "vocab"
    v.mkdir()
    (v / "table.py").write_text(_tw.dedent(body))
    assert run_file(str(d / "lookup.py")) == []
    assert run_file(str(v / "table.py")) == []


def test_r011_respects_pragma(tmp_path):
    path = _any_file(tmp_path, """\
        def step(table, uniq_ids):
            # fmlint: disable=R011 -- jitted step below the slot seam
            return table[uniq_ids]
    """)
    assert run_file(path) == []


def test_r013_flags_adhoc_device_put_in_dispatch_modules(tmp_path):
    """ISSUE 15 satellite: a raw ``jax.device_put`` in a train/predict/
    scoring/serve module bypasses the wire-format encoder — the packed
    layout, the double buffer, and the h2d byte accounting all miss
    those arrays."""
    path = _hot_file(tmp_path, """\
        import jax
        def dispatch(batch_args):
            return jax.device_put(batch_args)
    """)
    found = [f for f in run_file(path) if f.rule == "R013"]
    assert len(found) == 1
    assert "wire" in found[0].message


def test_r013_flags_bare_imported_device_put(tmp_path):
    path = _hot_file(tmp_path, """\
        from jax import device_put
        def dispatch(args):
            return device_put(args)
    """)
    assert [f.rule for f in run_file(path) if f.rule == "R013"] \
        == ["R013"]


def test_r013_allows_encoder_method_and_other_modules(tmp_path):
    """The sanctioned spelling — the wire encoder's own method — and
    any module outside the dispatch surface pass."""
    path = _hot_file(tmp_path, """\
        def dispatch(enc, wb):
            return enc.device_put(wb)
    """)
    assert [f.rule for f in run_file(path) if f.rule == "R013"] == []
    other = _any_file(tmp_path, """\
        import jax
        def elsewhere(x):
            return jax.device_put(x)
    """, name="helper.py")
    assert [f.rule for f in run_file(other) if f.rule == "R013"] == []


def test_r013_respects_pragma(tmp_path):
    path = _hot_file(tmp_path, """\
        import jax
        def probe():
            # fmlint: disable=R013 -- one-scalar link probe, not a batch
            return jax.device_put(0.0)
    """)
    assert [f.rule for f in run_file(path) if f.rule == "R013"] == []


def test_r018_flags_adhoc_memory_stats(tmp_path):
    """ISSUE 18 satellite: device-memory introspection outside the
    obs/memory seam bypasses the unmeasured-is-None policy, the CPU
    opt-out, and the FM_FAKE_HBM_BYTES test injection."""
    path = _any_file(tmp_path, """\
        import jax

        def probe(dev):
            stats = dev.memory_stats()
            arrays = jax.live_arrays()
            return stats, arrays
    """, name="probe.py")
    found = [f for f in run_file(path) if f.rule == "R018"]
    assert len(found) == 2
    assert "obs/memory.device_memory_stats" in found[0].message


def test_r018_exempts_the_seam_module(tmp_path):
    d = tmp_path / "fast_tffm_tpu" / "obs"
    d.mkdir(parents=True)
    p = d / "memory.py"
    p.write_text(textwrap.dedent("""\
        def device_memory_stats(dev):
            return dev.memory_stats()
    """))
    assert [f.rule for f in run_file(str(p))
            if f.rule == "R018"] == []


def test_r018_respects_pragma(tmp_path):
    path = _any_file(tmp_path, """\
        def raw_probe(dev):
            # fmlint: disable=R018 -- leak hunt, wants raw runtime stats
            return dev.memory_stats()
    """, name="probe.py")
    assert [f.rule for f in run_file(path) if f.rule == "R018"] == []
