"""utils/timing.py: StepTimer window semantics and profile_to's
start/stop lifecycle (ISSUE 2 satellites)."""

import os

import pytest

import fast_tffm_tpu.utils.timing as timing
from fast_tffm_tpu.utils.timing import StepTimer, profile_to


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def clock(monkeypatch):
    c = FakeClock()
    monkeypatch.setattr(timing.time, "perf_counter", c)
    return c


def test_consume_resets_window(clock):
    t = StepTimer()
    clock.advance(2.0)
    t.tick(100)
    assert t.consume_window_rate() == pytest.approx(50.0)
    # window consumed: the next read covers only what came after
    clock.advance(1.0)
    t.tick(10)
    assert t.consume_window_rate() == pytest.approx(10.0)
    # and an immediate re-read sees an empty window, not a repeat
    clock.advance(1.0)
    assert t.consume_window_rate() == 0.0


def test_zero_dt_guard(clock):
    t = StepTimer()
    t.tick(100)  # no clock advance: dt == 0 exactly
    assert t.consume_window_rate() == 0.0
    assert t.total_examples_per_sec == 0.0


def test_total_rate_includes_pauses(clock):
    t = StepTimer()
    clock.advance(1.0)
    t.tick(100)
    t.consume_window_rate()
    clock.advance(9.0)  # a long validation/checkpoint pause
    t.tick(100)
    # window rate excludes everything before its reset...
    assert t.consume_window_rate() == pytest.approx(100 / 9.0)
    # ...total anchors at construction, absorbing the pause
    assert t.total_examples_per_sec == pytest.approx(200 / 10.0)
    assert t.steps == 2


def test_reset_clears_everything(clock):
    t = StepTimer()
    clock.advance(1.0)
    t.tick(50)
    t.reset()
    clock.advance(2.0)
    t.tick(10)
    assert t.steps == 1
    assert t.total_examples_per_sec == pytest.approx(5.0)


# ---------------------------------------------------------------- profile_to

class FakeProfiler:
    def __init__(self, fail_start=False):
        self.starts = []
        self.stops = 0
        self.fail_start = fail_start

    def start_trace(self, log_dir):
        if self.fail_start:
            raise RuntimeError("trace already in progress")
        self.starts.append(log_dir)

    def stop_trace(self):
        self.stops += 1


@pytest.fixture
def profiler(monkeypatch):
    p = FakeProfiler()
    monkeypatch.setattr(timing.jax, "profiler", p)
    return p


def test_profile_to_creates_log_dir_and_stops_once(tmp_path, profiler):
    d = str(tmp_path / "a" / "b")  # parent missing too
    with profile_to(d):
        pass
    assert os.path.isdir(d)
    assert profiler.starts == [d] and profiler.stops == 1


def test_profile_to_stops_once_when_body_raises(tmp_path, profiler):
    d = str(tmp_path / "t")
    with pytest.raises(ValueError, match="body failed"):
        with profile_to(d):
            raise ValueError("body failed")
    assert profiler.stops == 1


def test_profile_to_no_stop_when_start_fails(tmp_path, monkeypatch):
    """start_trace raising must NOT trigger a stop: that would mask
    the original error or stop an outer trace the caller owns."""
    p = FakeProfiler(fail_start=True)
    monkeypatch.setattr(timing.jax, "profiler", p)
    with pytest.raises(RuntimeError, match="trace already in progress"):
        with profile_to(str(tmp_path / "t")):
            pass  # pragma: no cover - never reached
    assert p.stops == 0
