"""utils/retry.py: retryable-vs-fatal classification, backoff/jitter
determinism, telemetry counters, and the open helper under injected
transient failures (fast_tffm_tpu/testing/faults.py)."""

import errno

import pytest

from fast_tffm_tpu.testing.faults import flaky_open
from fast_tffm_tpu.utils.retry import (RetryPolicy, is_retryable,
                                       open_with_retry, retry_io,
                                       retrying)


class Flaky:
    """Callable failing the first n calls with the given error."""

    def __init__(self, n, exc_factory):
        self.n, self.exc_factory = n, exc_factory
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.calls <= self.n:
            raise self.exc_factory()
        return "ok"


def test_transient_oserror_retried():
    sleeps = []
    fn = Flaky(2, lambda: OSError(errno.EIO, "flake"))
    out = retry_io(fn, policy=RetryPolicy(retries=3, backoff_seconds=0.1),
                   op="t", sleep=sleeps.append)
    assert out == "ok"
    assert fn.calls == 3
    assert len(sleeps) == 2
    # Exponential envelope with jitter in [0.5, 1.5): attempt k sleeps
    # within [0.5, 1.5) * 0.1 * 2^k.
    assert 0.05 <= sleeps[0] < 0.15
    assert 0.10 <= sleeps[1] < 0.30


def test_timeout_error_retried():
    fn = Flaky(1, TimeoutError)
    assert retry_io(fn, policy=RetryPolicy(retries=1),
                    op="t", sleep=lambda _: None) == "ok"
    assert fn.calls == 2


@pytest.mark.parametrize("exc_factory", [
    lambda: FileNotFoundError("gone"),
    lambda: PermissionError("no"),
    lambda: IsADirectoryError("dir"),
])
def test_fatal_io_family_never_retried(exc_factory):
    fn = Flaky(5, exc_factory)
    with pytest.raises(OSError):
        retry_io(fn, policy=RetryPolicy(retries=5), op="t",
                 sleep=lambda _: None)
    assert fn.calls == 1


def test_non_io_errors_never_retried():
    fn = Flaky(5, lambda: ValueError("logic bug"))
    with pytest.raises(ValueError):
        retry_io(fn, policy=RetryPolicy(retries=5), op="t",
                 sleep=lambda _: None)
    assert fn.calls == 1


def test_retries_exhausted_reraises_last():
    fn = Flaky(10, lambda: OSError(errno.EIO, "still down"))
    with pytest.raises(OSError, match="still down"):
        retry_io(fn, policy=RetryPolicy(retries=2), op="t",
                 sleep=lambda _: None)
    assert fn.calls == 3  # 1 + retries


def test_jitter_deterministic_per_seed_and_op():
    def run(seed, op):
        sleeps = []
        retry_io(Flaky(3, lambda: OSError(errno.EIO, "x")),
                 policy=RetryPolicy(retries=3, seed=seed), op=op,
                 sleep=sleeps.append)
        return sleeps
    assert run(7, "a") == run(7, "a")       # reruns replay exactly
    assert run(7, "a") != run(7, "b")       # ops de-correlate
    assert run(7, "a") != run(8, "a")       # seeds de-correlate


def test_is_retryable_classification():
    assert is_retryable(OSError(errno.EIO, "x"))
    assert is_retryable(TimeoutError())
    assert is_retryable(ConnectionResetError())  # OSError subclass
    assert not is_retryable(FileNotFoundError("x"))
    assert not is_retryable(KeyboardInterrupt())
    assert not is_retryable(ValueError("x"))


def test_retrying_decorator():
    calls = []

    @retrying("deco", policy=RetryPolicy(retries=1,
                                         backoff_seconds=0.0))
    def sometimes(x):
        calls.append(x)
        if len(calls) == 1:
            raise OSError(errno.EIO, "first")
        return x * 2

    assert sometimes(21) == 42
    assert calls == [21, 21]


def test_open_with_retry_absorbs_flaky_open(tmp_path):
    p = tmp_path / "data.txt"
    p.write_text("hello\n")
    with flaky_open(2, match="data.txt") as state:
        fh = open_with_retry(str(p), "r",
                             policy=RetryPolicy(retries=2,
                                                backoff_seconds=0.0),
                             op="test_open")
        with fh:
            assert fh.read() == "hello\n"
    assert state["failures"] == 2


def test_open_with_retry_missing_file_fails_fast(tmp_path):
    calls = []
    with pytest.raises(FileNotFoundError):
        retry_io(open, str(tmp_path / "nope.txt"),
                 policy=RetryPolicy(retries=3),
                 op="t", sleep=calls.append)
    assert calls == []  # no backoff was paid


def test_retry_counters_reach_active_telemetry(tmp_path):
    from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={})
    with activate(tel):
        retry_io(Flaky(2, lambda: OSError(errno.EIO, "x")),
                 policy=RetryPolicy(retries=2), op="unit",
                 sleep=lambda _: None)
    tel.close(0)
    snap = tel.registry.snapshot()["counters"]
    assert snap["io/retries"] == 2
    assert snap["io/retries/unit"] == 2
    assert snap["io/retry_sleep_seconds"] > 0
