"""Property tests (SURVEY §4 "do better, cheaply"): hypothesis-driven
invariants for the three contracts whose edge cases example tests can't
enumerate — C++/Python parser parity on adversarial tokens, the spill
protocol's no-loss/no-duplication guarantee under random unique budgets,
and the streaming binned AUC against the exact rank statistic.

Parser-parity scope note: the contract is byte-oriented libsvm data
with the separator set pinned to parser.WHITESPACE (space/tab/CR/VT/FF
— the C++ is_ws set). The Python parser tokenizes with that exact set
(not bare str.split(), which would additionally treat ASCII control
separators \\x1c-\\x1f and Unicode whitespace like \\x85 as
separators), so both paths agree on every byte; the token alphabet
below includes the control separators to pin that.
"""

import string

import numpy as np
import pytest

# Capability skip (ISSUE 3 triage): the container may not ship
# hypothesis; without this the module is a COLLECTION ERROR that hides
# real regressions elsewhere in the suite.
pytest.importorskip("hypothesis",
                    reason="hypothesis not installed in this container")
from hypothesis import given, settings, strategies as st  # noqa: E402

from fast_tffm_tpu.data import cparser
from fast_tffm_tpu.data.parser import ParseError, parse_lines
from fast_tffm_tpu.metrics import StreamingAUC, exact_auc, sigmoid

requires_cpp = pytest.mark.skipif(not cparser.available(),
                                  reason="C++ parser failed to build")

# --- parser parity over adversarial tokens ---------------------------------

# Token text: printable ASCII minus whitespace (colons appear explicitly
# so colon-count edge cases are well covered rather than left to chance).
_ID_ALPHABET = "".join(c for c in string.printable
                       if c not in string.whitespace and c != ":")


def _ids(min_size=0):
    return st.text(alphabet=_ID_ALPHABET, min_size=min_size, max_size=8)


_FLOATS = st.one_of(
    st.floats(allow_nan=False, allow_infinity=False,
              width=32).map(lambda f: repr(float(f))),
    st.integers(-int(1e18), int(1e18)).map(str),
    st.sampled_from(["1e3", "-2.5E-4", ".5", "5.", "0", "-0.0", "+1.25"]),
)

_TOKENS = st.one_of(
    _ids(min_size=1),                                        # fid
    st.tuples(_ids(min_size=1), _FLOATS).map(":".join),      # fid:val
    st.tuples(_ids(), _ids(), _ids()).map(":".join),         # adversarial
    st.tuples(_ids(), _ids(), _ids(), _ids()).map(":".join),
    st.sampled_from([":", "::", "a:", ":1", "a::1", "1:2:3:4", "-",
                     "nan", "inf", "+", "0x10", "1_0", "1:0x10",
                     "1:1e400", "1:-1e400", "1:1e-400", "1:Infinity",
                     "1:nan(box)", "1:INF", "1e400", "०:1", "1:१",
                     # ASCII control separators are TOKEN bytes for both
                     # parsers (parser.WHITESPACE), never separators:
                     "1\x1c", "1:1\x1c2", "\x1d", "1:\x1e5", "\x1f:1",
                     "1:1\x85"]),
)

_LINES = st.lists(
    st.tuples(st.one_of(_FLOATS, _ids()),                    # label token
              st.lists(_TOKENS, max_size=6),
              st.sampled_from([" ", "\t", "  "]))            # separator
    .map(lambda t: t[2].join([t[0]] + t[1])),
    min_size=1, max_size=8)


def _run(parse, lines, vocab, **kw):
    try:
        return parse(lines, vocab, **kw)
    except ParseError as e:
        return ("error", )  # compare outcome class only; wording differs


def _assert_same(py, cc):
    assert (py == ("error",)) == (cc == ("error",)), (py, cc)
    if py == ("error",):
        return
    np.testing.assert_array_equal(cc.labels, py.labels)
    np.testing.assert_array_equal(cc.poses, py.poses)
    np.testing.assert_array_equal(cc.ids, py.ids)
    np.testing.assert_array_equal(cc.vals, py.vals)
    if py.fields is None:
        assert cc.fields is None
    else:
        np.testing.assert_array_equal(cc.fields, py.fields)


@requires_cpp
@settings(max_examples=150, deadline=None, derandomize=True)
@given(lines=_LINES, hash_ids=st.booleans(),
       max_feats=st.sampled_from([0, 2, 5]))
def test_parser_parity_adversarial_fm(lines, hash_ids, max_feats):
    """FM grammar: both parsers accept with identical arrays or both
    reject (any malformed token is somewhere in both error paths)."""
    kw = dict(hash_feature_id=hash_ids, max_features_per_example=max_feats)
    _assert_same(_run(parse_lines, lines, 997, **kw),
                 _run(cparser.parse_lines_fast, lines, 997, **kw))


@requires_cpp
@settings(max_examples=150, deadline=None, derandomize=True)
@given(lines=_LINES, hash_ids=st.booleans(),
       field_num=st.sampled_from([1, 3]))
def test_parser_parity_adversarial_ffm(lines, hash_ids, field_num):
    """FFM grammar over the same adversarial token space."""
    kw = dict(hash_feature_id=hash_ids, field_aware=True,
              field_num=field_num)
    _assert_same(_run(parse_lines, lines, 997, **kw),
                 _run(cparser.parse_lines_fast, lines, 997, **kw))


# --- spill invariants -------------------------------------------------------


def _example_key(batch, e, vocab):
    feats = []
    for j in range(batch.local_idx.shape[1]):
        fid = int(batch.uniq_ids[batch.local_idx[e, j]])
        v = float(batch.vals[e, j])
        if fid < vocab and v != 0.0:
            feats.append((fid, round(v, 5)))
    return (float(batch.labels[e]), tuple(sorted(feats)))


@settings(max_examples=25, deadline=None, derandomize=True)
@given(data=st.data())
def test_spill_no_loss_no_duplication(tmp_path_factory, data):
    """fixed_shape + random uniq_bucket: the emitted example stream
    equals the input exactly (order, multiplicity, features) on BOTH the
    C++ fast path and the generic path; every batch respects the unique
    budget; spilled batches are counted."""
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.pipeline import SpillStats, batch_iterator

    rng = np.random.default_rng(data.draw(st.integers(0, 2**32 - 1)))
    vocab = 64
    n_lines = data.draw(st.integers(1, 60))
    uniq_bucket = data.draw(st.sampled_from([64, 128]))
    lines, want = [], []
    for _ in range(n_lines):
        nnz = int(rng.integers(1, 12))
        ids = rng.choice(vocab, size=nnz, replace=False)
        vals = np.round(rng.random(nnz) + 0.5, 3)
        label = float(rng.integers(0, 2))
        lines.append(" ".join([str(int(label))]
                              + [f"{i}:{v}" for i, v in zip(ids, vals)]))
        want.append((label, tuple(sorted(
            (int(i), round(float(v), 5)) for i, v in zip(ids, vals)))))
    tmp = tmp_path_factory.mktemp("spill")
    p = tmp / "d.txt"
    p.write_text("\n".join(lines) + "\n")

    cfg = FmConfig(vocabulary_size=vocab, factor_num=2, batch_size=16,
                   train_files=(str(p),), shuffle=False,
                   bucket_ladder=(16,), max_features_per_example=16,
                   uniq_bucket=uniq_bucket)
    wpath = tmp / "w.txt"
    wpath.write_text("1.0\n" * n_lines)
    for kw in ({}, {"weight_files": (str(wpath),)}):  # fast vs generic
        stats = SpillStats()
        got = []
        for b in batch_iterator(cfg, cfg.train_files, training=True,
                                fixed_shape=True, stats=stats, **kw):
            live = b.uniq_ids[b.uniq_ids < vocab]
            assert len(b.uniq_ids) == uniq_bucket
            assert len(np.unique(live)) == len(live) <= uniq_bucket - 1
            assert b.local_idx.shape == (16, 16)
            got.extend(_example_key(b, e, vocab)
                       for e in range(b.num_real))
        assert got == want, "example stream altered by spill protocol"
        assert stats.real_examples == n_lines
        assert stats.batches >= stats.spilled_batches


# --- streaming AUC vs exact -------------------------------------------------


@settings(max_examples=80, deadline=None, derandomize=True)
@given(data=st.data())
def test_streaming_auc_converges_to_exact(data):
    """Binned AUC == exact rank AUC within the bin-resolution error
    bound, including heavy score ties and arbitrary batch splits."""
    rng = np.random.default_rng(data.draw(st.integers(0, 2**32 - 1)))
    n = data.draw(st.integers(5, 400))
    tie_prone = data.draw(st.booleans())
    if tie_prone:  # scores drawn from a tiny set -> many exact ties
        scores = rng.choice([-1.5, -0.2, 0.0, 0.7], size=n)
    else:
        scores = rng.normal(0.0, 2.0, size=n)
    labels = (rng.random(n) < 0.4).astype(np.float64)
    if labels.min() == labels.max():
        labels[0] = 1.0 - labels[0]  # both classes present

    auc = StreamingAUC(num_bins=1 << 14)
    i = 0
    while i < n:  # arbitrary batch splits must not matter
        j = min(n, i + int(rng.integers(1, 64)))
        auc.update(scores[i:j], labels[i:j])
        i = j
    want = exact_auc(sigmoid(scores), labels)  # sigmoid is monotonic
    assert auc.result() == pytest.approx(want, abs=2e-3)


# --- dedup mode equivalence -------------------------------------------------


@settings(max_examples=20, deadline=None, derandomize=True)
@given(data=st.data())
def test_device_dedup_equals_host_property(tmp_path_factory, data):
    """Random batches: the on-device unique pass (dedup=device, raw-ids
    batches) and the host-side pass produce identical losses, tables,
    and accumulators. Reuses test_device_dedup's harness — one
    equivalence loop, example- and property-tested."""
    import dataclasses
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_device_dedup import _cfg, _train_all
    from fast_tffm_tpu.models.fm import ModelSpec

    rng = np.random.default_rng(data.draw(st.integers(0, 2**32 - 1)))
    vocab = 40
    n_lines = data.draw(st.integers(1, 24))
    lines = []
    for _ in range(n_lines):
        nnz = int(rng.integers(1, 8))
        ids = rng.choice(vocab, size=nnz, replace=False)
        lines.append(" ".join([str(int(rng.integers(0, 2)))]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    p = tmp_path_factory.mktemp("dd") / "d.txt"
    p.write_text("\n".join(lines) + "\n")
    # Fixed shapes (single-rung ladder, small B) so one compiled step
    # serves every drawn example.
    cfg = _cfg(str(p), vocabulary_size=vocab, factor_num=2, batch_size=8,
               bucket_ladder=(8,), max_features_per_example=8)
    host = _train_all(cfg, dataclasses.replace(
        ModelSpec.from_config(cfg), dedup="host"), raw=False)
    dev = _train_all(cfg, dataclasses.replace(ModelSpec.from_config(cfg),
                                              dedup="device"), raw=True)
    np.testing.assert_allclose(dev[2], host[2], rtol=1e-6)
    np.testing.assert_allclose(dev[0], host[0], rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(dev[1], host[1], rtol=1e-6, atol=1e-7)


# --- builder chunking invariance -------------------------------------------


@requires_cpp
@settings(max_examples=40, deadline=None, derandomize=True)
@given(data=st.data())
def test_batch_builder_chunking_invariance(data):
    """The streaming BatchBuilder must produce IDENTICAL batches no
    matter how the byte stream is chunked (1-byte feeds included): the
    consumed-offset/partial-line protocol cannot depend on where chunk
    boundaries fall."""
    from fast_tffm_tpu.data.cparser import BatchBuilder

    rng = np.random.default_rng(data.draw(st.integers(0, 2**32 - 1)))
    n_lines = data.draw(st.integers(1, 20))
    raw_ids = data.draw(st.booleans())
    lines = []
    for _ in range(n_lines):
        nnz = int(rng.integers(0, 6))
        ids = rng.choice(50, size=nnz, replace=False)
        lines.append(" ".join([str(int(rng.integers(0, 2)))]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    blob = ("\n".join(lines) + "\n").encode()

    def run(chunks):
        bb = BatchBuilder(4, 8, 50, raw_ids=raw_ids,
                          max_features_per_example=8)
        out = []

        def feed_all(dat):
            off = 0
            while True:
                full, consumed = bb.feed(dat, off)
                off += consumed
                if not full:
                    break
                out.append(bb.finish())
            return dat[off:]

        tail = b""
        for c in chunks:
            tail = feed_all(tail + c)
        assert tail == b""  # blob ends in newline: nothing left over
        final = bb.finish()
        if final[0]:
            out.append(final)
        return out

    # Reference: one big feed. Adversary: random split points (possibly
    # 1-byte chunks, splits inside tokens and newlines).
    want = run([blob])
    n_cuts = data.draw(st.integers(0, min(24, len(blob) - 1)))
    cuts = sorted(set(
        int(rng.integers(1, len(blob)))
        for _ in range(n_cuts))) if n_cuts else []
    chunks = [blob[a:b] for a, b in
              zip([0] + cuts, cuts + [len(blob)])]
    got = run(chunks)

    assert len(got) == len(want)
    for g, w in zip(got, want):
        n = g[0]
        assert n == w[0]  # n examples
        # labels past n are undefined in the raw finish() contract
        # (np.empty slots the builder never wrote; pipeline emit()
        # zeroes them) — compare the defined region.
        np.testing.assert_array_equal(g[1][:n], w[1][:n])
        if g[2] is None:
            assert w[2] is None
        else:
            np.testing.assert_array_equal(g[2], w[2])  # uniq
        np.testing.assert_array_equal(g[3], w[3])      # local_idx
        np.testing.assert_array_equal(g[4], w[4])      # vals
