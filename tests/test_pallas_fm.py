"""Pallas fused FM kernel == XLA path, values and gradients.

Runs in interpret mode on the CPU mesh (the kernel compiles for real on
TPU; bench.py / the driver exercise that). Parity tolerances are tight
because both paths accumulate in f32.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fast_tffm_tpu.ops.interaction import fm_batch_scores
from fast_tffm_tpu.ops.pallas_fm import fm_batch_scores_pallas


def _rand_case(rng, B=64, L=16, U=128, K=8):
    params = jnp.asarray(rng.normal(size=(U, K + 1)) * 0.1,
                         dtype=jnp.float32)
    local_idx = jnp.asarray(rng.integers(0, U, size=(B, L)), dtype=jnp.int32)
    vals = jnp.asarray(rng.random(size=(B, L)) *
                       (rng.random(size=(B, L)) > 0.3),  # real padding zeros
                       dtype=jnp.float32)
    return params, local_idx, vals


@pytest.mark.parametrize("shape", [(64, 16, 128, 8), (32, 64, 512, 4),
                                   (8, 8, 16, 16)])
def test_forward_parity(rng, shape):
    B, L, U, K = shape
    params, idx, vals = _rand_case(rng, B, L, U, K)
    ref = fm_batch_scores(params, idx, vals)
    out = fm_batch_scores_pallas(params, idx, vals)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_gradient_parity(rng):
    params, idx, vals = _rand_case(rng)

    def loss_ref(p, v):
        return jnp.sum(jnp.tanh(fm_batch_scores(p, idx, v)))

    def loss_pal(p, v):
        return jnp.sum(jnp.tanh(fm_batch_scores_pallas(p, idx, v)))

    gp_ref, gv_ref = jax.grad(loss_ref, argnums=(0, 1))(params, vals)
    gp_pal, gv_pal = jax.grad(loss_pal, argnums=(0, 1))(params, vals)
    np.testing.assert_allclose(np.asarray(gp_pal), np.asarray(gp_ref),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gv_pal), np.asarray(gv_ref),
                               rtol=1e-4, atol=1e-6)


def test_jit_and_odd_batch_blocks(rng):
    # B with a small power-of-two factor exercises the block chooser.
    params, idx, vals = _rand_case(rng, B=24, L=8, U=64, K=8)
    f = jax.jit(fm_batch_scores_pallas)
    out = f(params, idx, vals)
    ref = fm_batch_scores(params, idx, vals)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_train_step_with_pallas_kernel(tmp_path):
    """End-to-end: ModelSpec(kernel='pallas') trains and matches the XLA
    kernel's losses step for step."""
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.data.pipeline import batch_iterator
    from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                         init_accumulator, init_table,
                                         make_train_step)
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(64):
        nnz = rng.integers(1, 10)
        ids = rng.choice(64, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    p = tmp_path / "t.txt"
    p.write_text("\n".join(lines) + "\n")
    base = dict(vocabulary_size=64, factor_num=4, batch_size=16,
                train_files=(str(p),), shuffle=False, learning_rate=0.1)
    cfg_x = FmConfig(**base, kernel="xla")
    cfg_p = FmConfig(**base, kernel="pallas")
    states = {}
    for cfg in (cfg_x, cfg_p):
        spec = ModelSpec.from_config(cfg)
        table, acc = init_table(cfg, 0), init_accumulator(cfg)
        step = make_train_step(spec)
        losses = []
        for batch in batch_iterator(cfg, cfg.train_files, training=True):
            table, acc, loss, _ = step(table, acc, **batch_args(batch))
            losses.append(float(loss))
        states[cfg.kernel] = (np.asarray(table), losses)
    np.testing.assert_allclose(states["pallas"][1], states["xla"][1],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(states["pallas"][0], states["xla"][0],
                               rtol=1e-4, atol=1e-6)
