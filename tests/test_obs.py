"""obs/ telemetry: registry semantics (merge, quantiles), JSONL sink
link-safety (one bulk fetch per barrier, zero fetches per flush),
end-to-end train/predict event streams, and fmstat's attribution
rendering over them."""

import json
import os

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.obs.registry import (Counter, Gauge, Histogram,
                                        MetricsRegistry)
from fast_tffm_tpu.obs.sink import JsonlSink, read_events
from fast_tffm_tpu.obs.telemetry import (RunTelemetry, activate, active,
                                         make_telemetry,
                                         resolve_metrics_path, run_meta)

from tests.test_e2e import make_dataset


# ---------------------------------------------------------------- registry

def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    r.count("a", 2)
    r.count("a")
    r.set("g", 0.5)
    for v in (0.001, 0.002, 0.004, 10.0):
        r.observe("h", v)
    snap = r.snapshot()
    assert snap["counters"]["a"] == 3
    assert snap["gauges"]["g"] == 0.5
    h = snap["hists"]["h"]
    assert h["count"] == 4
    assert h["min"] == 0.001 and h["max"] == 10.0
    assert h["sum"] == pytest.approx(10.007)
    # p50 falls in the bucket holding the 2nd point; p99 in the max's.
    assert h["p50"] <= 0.004
    assert h["p99"] == pytest.approx(10.0)


def test_histogram_merge_and_roundtrip():
    a, b = Histogram(bounds=(1, 2, 4)), Histogram(bounds=(1, 2, 4))
    for v in (0.5, 1.5, 3.0):
        a.observe(v)
    for v in (8.0, 0.1):
        b.observe(v)
    a.merge(Histogram.from_summary(b.summary()))
    assert a.count == 5
    assert a.min == 0.1 and a.max == 8.0
    assert a.sum == pytest.approx(13.1)
    assert sum(a.counts) == 5
    with pytest.raises(ValueError, match="different bounds"):
        a.merge(Histogram(bounds=(1, 2)))


def test_registry_merge_counters_add_hists_fold():
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.count("c", 5)
    r2.count("c", 7)
    r2.count("only2", 1)
    r1.observe("h", 0.01, bounds=(0.1, 1.0))
    r2.observe("h", 0.5, bounds=(0.1, 1.0))
    r2.set("g", 3.0)
    r1.merge(r2)
    snap = r1.snapshot()
    assert snap["counters"]["c"] == 12
    assert snap["counters"]["only2"] == 1
    assert snap["hists"]["h"]["count"] == 2
    assert snap["gauges"]["g"] == 3.0


# ------------------------------------------------------------------- sink

def test_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "m.jsonl")
    sink = JsonlSink(path, meta={"kind": "test", "config_hash": "abc"})
    sink.emit("metrics", {"step": 4, "counters": {"x": 1.5}})
    sink.flush()
    sink.close()
    evs = list(read_events(path))
    assert [e["event"] for e in evs] == ["run_start", "metrics",
                                        "run_end"]
    assert evs[0]["meta"]["config_hash"] == "abc"
    assert evs[1]["step"] == 4 and evs[1]["counters"] == {"x": 1.5}
    # numpy values must serialize, not crash the flush
    sink2 = JsonlSink(str(tmp_path / "n.jsonl"), meta={})
    sink2.emit("metrics", {"v": np.float32(1.25), "a": np.arange(3)})
    sink2.close()
    ev = [e for e in read_events(str(tmp_path / "n.jsonl"))
          if e["event"] == "metrics"][0]
    assert ev["v"] == 1.25 and ev["a"] == [0, 1, 2]


def test_read_events_tolerates_torn_tail(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with open(path, "w") as fh:
        fh.write('{"event": "metrics", "step": 1}\n{"event": "met')
    evs = list(read_events(path))
    assert len(evs) == 1 and evs[0]["step"] == 1


def test_scalar_buffer_single_bulk_fetch(tmp_path, monkeypatch):
    """Buffered device scalars flush in exactly ONE bulk_fetch per
    barrier, and a plain flush() performs none (link-safety)."""
    import jax
    import fast_tffm_tpu.utils.fetch as fetch
    calls = []
    real = fetch.bulk_fetch

    def counting(pairs, consume):
        calls.append(len(pairs))
        return real(pairs, consume)

    monkeypatch.setattr(fetch, "bulk_fetch", counting)
    sink = JsonlSink(str(tmp_path / "m.jsonl"), meta={})
    for i in range(5):
        sink.add_scalar("loss", i, jax.numpy.float32(i))
    sink.flush()          # host flush: must NOT touch the device
    assert calls == []
    sink.barrier()        # ONE grouped transfer for all 5
    assert calls == [5]
    sink.close()
    assert calls == [5]   # nothing left to fetch at close
    evs = [e for e in read_events(str(tmp_path / "m.jsonl"))
           if e["event"] == "scalar"]
    assert [(e["step"], e["value"]) for e in evs] == [
        (i, float(i)) for i in range(5)]


def test_scalar_buffer_cap_forces_drain(tmp_path, monkeypatch):
    import fast_tffm_tpu.obs.sink as sink_mod
    monkeypatch.setattr(sink_mod, "SCALAR_BUFFER_MAX", 3)
    sink = JsonlSink(str(tmp_path / "m.jsonl"), meta={})
    for i in range(4):
        sink.add_scalar("x", i, float(i))
    assert len(sink._scalars) == 1  # cap hit drained the first 3
    sink.close()


# -------------------------------------------------------------- telemetry

def test_activate_scopes_active():
    assert active() is None
    t = RunTelemetry.__new__(RunTelemetry)  # no sink needed for scoping
    with activate(t) as got:
        assert got is t and active() is t
        with activate(None):
            assert active() is t  # None passes through
    assert active() is None


def test_resolve_metrics_path(tmp_path):
    cfg = FmConfig(metrics_file="")
    assert resolve_metrics_path(cfg) is None
    cfg = FmConfig(metrics_file="auto",
                   model_file=str(tmp_path / "m" / "fm"))
    assert resolve_metrics_path(cfg) == str(
        tmp_path / "m" / "fm") + ".metrics.jsonl"
    cfg = FmConfig(metrics_file=str(tmp_path / "x.jsonl"))
    assert resolve_metrics_path(cfg) == str(tmp_path / "x.jsonl")


def test_run_meta_fields(tmp_path):
    cfg = FmConfig(metrics_file="auto")
    meta = run_meta(cfg, "train")
    assert meta["kind"] == "train"
    assert meta["backend"] == "cpu" and meta["device_count"] == 8
    assert meta["process_count"] == 1
    assert len(meta["config_hash"]) == 12
    # same config -> same hash; different config -> different
    assert meta["config_hash"] == run_meta(cfg, "x")["config_hash"]
    assert (run_meta(FmConfig(factor_num=9), "x")["config_hash"]
            != meta["config_hash"])


def test_flush_cadence_writes_metrics_events(tmp_path):
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={"kind": "t"}, flush_steps=2)
    for step in range(1, 7):
        tel.count("steps")
        tel.maybe_flush(step)
    tel.close(6)
    evs = [e for e in read_events(path) if e["event"] == "metrics"]
    # steps 2, 4, 6 flushed + the close event
    assert [e["step"] for e in evs] == [2, 4, 6, 6]
    # cumulative counters: each later event >= the earlier
    vals = [e["counters"]["steps"] for e in evs]
    assert vals == sorted(vals) and vals[-1] == 6
    # run metadata rides every metrics event
    assert all(e["run"] == {"kind": "t"} for e in evs)


# ------------------------------------------------- end-to-end train/predict

def _train_cfg(tmp_path, rng, **kw):
    make_dataset(tmp_path / "train.txt", 128, rng)
    make_dataset(tmp_path / "val.txt", 64, rng)
    base = dict(vocabulary_size=200, factor_num=4, batch_size=32,
                learning_rate=0.1, epoch_num=2, shuffle=False,
                train_files=(str(tmp_path / "train.txt"),),
                validation_files=(str(tmp_path / "val.txt"),),
                model_file=str(tmp_path / "m" / "fm"),
                metrics_file="auto", metrics_flush_steps=2, log_steps=0)
    base.update(kw)
    return FmConfig(**base)


def test_train_emits_parseable_jsonl_with_all_stages(tmp_path, rng):
    cfg = _train_cfg(tmp_path, rng)
    from fast_tffm_tpu.train import train
    train(cfg)
    path = cfg.model_file + ".metrics.jsonl"
    evs = list(read_events(path))
    kinds = {e["event"] for e in evs}
    assert {"run_start", "metrics", "scalar", "run_end"} <= kinds
    last = [e for e in evs if e["event"] == "metrics"][-1]
    c, g, h = last["counters"], last["gauges"], last["hists"]
    # pipeline counters (train 4 batches x 2 epochs + validation)
    assert c["pipeline/examples"] >= 256
    assert c["pipeline/feature_nnz"] > 0
    assert c["pipeline/batches"] >= 8
    # step-time histogram summary: 8 train steps
    assert h["train/step_seconds"]["count"] == 8
    assert h["train/step_seconds"]["p50"] > 0
    assert c["train/steps"] == 8
    assert c["train/examples"] == 256
    assert c["train/h2d_bytes"] > 0
    assert c["train/epochs"] == 2
    # examples/sec gauges from the shared StepTimer window
    assert g["train/examples_per_sec_window"] > 0
    assert g["train/examples_per_sec_total"] > 0
    assert 0.0 <= g["validation/auc"] <= 1.0
    # run metadata on the event itself
    assert last["run"]["kind"] == "train"
    assert last["run"]["backend"] == "cpu"
    # buffered scalars landed with step attribution (flush cadence 2)
    loss_steps = [e["step"] for e in evs
                  if e["event"] == "scalar" and e["name"] == "train/loss"]
    assert loss_steps == [2, 4, 6, 8]
    auc_steps = [e["step"] for e in evs
                 if e["event"] == "scalar"
                 and e["name"] == "validation/auc"]
    assert auc_steps == [4, 8]


def test_train_metrics_zero_midstream_fetches(tmp_path, rng,
                                              monkeypatch):
    """Link-safety acceptance: with metrics on at a step-level flush
    cadence, bulk_fetch runs ONLY at epoch barriers — one grouped
    transfer per epoch, nothing per step/flush."""
    import fast_tffm_tpu.utils.fetch as fetch
    calls = []
    real = fetch.bulk_fetch

    def counting(pairs, consume):
        calls.append(len(pairs))
        return real(pairs, consume)

    monkeypatch.setattr(fetch, "bulk_fetch", counting)
    cfg = _train_cfg(tmp_path, rng, metrics_flush_steps=1)
    from fast_tffm_tpu.train import train
    train(cfg)
    # 2 epochs: each barrier drains (loss x4/epoch + auc x1) in ONE call
    assert calls == [5, 5]


def test_metrics_off_writes_nothing(tmp_path, rng):
    cfg = _train_cfg(tmp_path, rng, metrics_file="")
    from fast_tffm_tpu.train import train
    train(cfg)
    assert not os.path.exists(cfg.model_file + ".metrics.jsonl")
    # and nothing left active after the run
    assert active() is None


def test_sink_closes_on_midrun_crash(tmp_path, rng, monkeypatch):
    """Satellite: a crash mid-epoch must still flush the sink — the
    JSONL ends with the close-time metrics event, not silence."""
    cfg = _train_cfg(tmp_path, rng)
    from fast_tffm_tpu import train as train_mod

    def boom(*a, **k):
        raise RuntimeError("mid-epoch crash")

    # evaluate runs at the first epoch barrier, after 4 steps
    monkeypatch.setattr(train_mod, "evaluate", boom)
    with pytest.raises(RuntimeError, match="mid-epoch crash"):
        train_mod.train(cfg)
    assert active() is None  # popped even on the error path
    evs = list(read_events(cfg.model_file + ".metrics.jsonl"))
    assert evs[-1]["event"] == "run_end"
    last = [e for e in evs if e["event"] == "metrics"][-1]
    assert last["counters"]["train/steps"] == 4
    # the buffered loss scalars since the last barrier survived too
    assert [e["step"] for e in evs if e["event"] == "scalar"
            and e["name"] == "train/loss"] == [2, 4]


def test_predict_emits_rate_and_depth(tmp_path, rng):
    cfg = _train_cfg(tmp_path, rng)
    from fast_tffm_tpu.train import train
    from fast_tffm_tpu.predict import predict
    train(cfg)
    import dataclasses
    cfgp = dataclasses.replace(
        cfg, predict_files=(str(tmp_path / "val.txt"),),
        score_path=str(tmp_path / "score"),
        metrics_file=str(tmp_path / "predict.jsonl"))
    predict(cfgp)
    evs = list(read_events(str(tmp_path / "predict.jsonl")))
    pf = [e for e in evs if e["event"] == "predict_file"]
    assert len(pf) == 1
    assert pf[0]["examples"] == 64 and pf[0]["examples_per_sec"] > 0
    last = [e for e in evs if e["event"] == "metrics"][-1]
    assert last["run"]["kind"] == "predict"
    assert last["counters"]["predict/examples"] == 64
    assert last["hists"]["predict/fetch_depth"]["count"] == 2
    # fmstat surfaces predict streams too (not just train loops)
    from fast_tffm_tpu.obs.attribution import attribution, summarize
    att = attribution(summarize([str(tmp_path / "predict.jsonl")]))
    assert att["predict_examples"] == 64
    assert att["predict_examples_per_sec"] > 0
    assert att["verdict"].startswith("predict:")


# ----------------------------------------------------------------- fmstat

def test_fmstat_renders_attribution(tmp_path, rng, capsys):
    cfg = _train_cfg(tmp_path, rng)
    from fast_tffm_tpu.train import train
    train(cfg)
    path = cfg.model_file + ".metrics.jsonl"
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([path]) == 0
    out = capsys.readouterr().out
    assert "kind=train" in out and "backend=cpu" in out
    assert "examples/sec" in out
    assert "dedup hit rate" in out
    assert "padding-waste fraction" in out
    assert "verdict:" in out
    # --json mode round-trips
    assert fmstat_main(["--json", path]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["attribution"]["examples"] == 256
    assert d["attribution"]["verdict"]


def test_fmstat_merges_worker_shards(tmp_path):
    """Per-worker shard files merge: counters add, hists fold, gauges
    keyed by process index — the sharded path's read-time merge."""
    from fast_tffm_tpu.obs.attribution import summarize
    for p in range(2):
        path = str(tmp_path / ("m.jsonl" if p == 0
                               else f"m.jsonl.p{p}"))
        tel = RunTelemetry(path, meta={"kind": "train",
                                       "process_index": p,
                                       "pid": 100 + p,
                                       "start_time": 1.0},
                           flush_steps=0)
        tel.count("train/examples", 100 * (p + 1))
        tel.observe("train/step_seconds", 0.01 * (p + 1))
        tel.set("predict/examples_per_sec", 50.0 + p)
        tel.close(5)
    s = summarize([str(tmp_path / "m.jsonl"),
                   str(tmp_path / "m.jsonl.p1")])
    assert s["counters"]["train/examples"] == 300
    assert s["hists"]["train/step_seconds"]["count"] == 2
    assert s["gauges_by_process"][0]["predict/examples_per_sec"] == 50.0
    assert s["gauges_by_process"][1]["predict/examples_per_sec"] == 51.0


def test_lockstep_counters_feed_active_telemetry(tmp_path, rng):
    """The sharded scoring protocol counts rounds/batches/examples into
    the active run's stream (single-process on the fake 8-device mesh;
    real multi-worker shard files are covered by the merge test)."""
    import jax
    from fast_tffm_tpu.data.pipeline import (batch_iterator,
                                             probe_uniq_bucket)
    from fast_tffm_tpu.models.fm import ModelSpec
    from fast_tffm_tpu.parallel.sharded import (init_sharded_state,
                                                lockstep_score_batches,
                                                make_mesh,
                                                make_sharded_score_fn)
    lines = []
    for _ in range(40):
        ids = rng.choice(64, size=4, replace=False)
        lines.append("1 " + " ".join(f"{i}:1" for i in sorted(ids)))
    data = tmp_path / "d.txt"
    data.write_text("\n".join(lines) + "\n")
    cfg = FmConfig(vocabulary_size=64, factor_num=4, batch_size=8,
                   shuffle=False, bucket_ladder=(8,), dedup="host",
                   model_file=str(tmp_path / "m" / "fm"))
    mesh = make_mesh(jax.devices()[:8])
    table, _ = init_sharded_state(cfg, mesh)
    score_fn = make_sharded_score_fn(ModelSpec.from_config(cfg), mesh)
    ub = probe_uniq_bucket(cfg, [str(data)])
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={"kind": "t"})
    with activate(tel):
        it = batch_iterator(cfg, [str(data)], training=False, epochs=1,
                            fixed_shape=True, uniq_bucket=ub)
        n = sum(b.num_real for b, _ in lockstep_score_batches(
            cfg, it, mesh, score_fn, table, ub))
    snap = tel.registry.snapshot()["counters"]
    assert n == 40
    assert snap["lockstep/examples"] == 40
    assert snap["lockstep/real_batches"] == 5
    assert snap["lockstep/filler_batches"] == 0  # one process, no peers
    assert snap["lockstep/windows"] >= 1
    # the cross-check invariant: real + filler == collective programs
    assert (snap["lockstep/real_batches"]
            + snap["lockstep/filler_batches"]
            == snap["lockstep/programs"])
    # the pipeline wrapper fed batch counters on the same stream
    assert snap["pipeline/batches"] == 5
    tel.close()


def test_attribution_bench_verdict():
    from fast_tffm_tpu.obs.attribution import attribution
    summary = {"counters": {}, "hists": {}, "gauges": {
        "bench/e2e": 450_000.0, "bench/host_only": 470_000.0,
        "bench/device_only": 4_000_000.0, "bench/h2d_only": 900_000.0}}
    att = attribution(summary)
    assert att["verdict"].startswith("host-bound")
    assert att["ceilings"]["e2e"] == 450_000.0
