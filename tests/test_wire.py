"""Wire-format layer (ISSUE 15): the packed H2D batch format must be a
PURE transfer change.

Bit-parity pins: for every input shape — C++ fast path (host AND
device dedup), unbounded-features generic path, tolerant
(bad_line_policy = skip), the host_threads = 4 ring, sharded fixed-U
with spills, and the streaming source — dispatching the same batch
stream through the packed step/score programs must produce final train
tables and predict scores BIT-identical to the padded wire. Plus the
flat-ladder math, the encode/unpack round trip, narrow-mode
tolerances, the resolve downgrades, the h2d byte accounting
(actual < logical / 2 at the default config — the acceptance bar),
the fmstat rows, and the serve flush through the packed path.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import SpillStats, batch_iterator
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                     init_accumulator, init_table,
                                     make_packed_score_fn,
                                     make_packed_train_step,
                                     make_score_fn, make_train_step)
from fast_tffm_tpu.wire import (FLAT_LADDER_FLOOR, WireEncoder, WireSpec,
                                flat_bucket, rect_fraction_rungs,
                                resolve_wire, unpack_rectangles)

VOCAB = 400


def _write_corpus(path, n, seed=0, max_nnz=14, vocab=VOCAB):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        nnz = int(rng.integers(1, max_nnz))
        ids = rng.choice(vocab, size=nnz, replace=False)
        lines.append(" ".join([str(int(rng.integers(0, 2)))]
                              + [f"{i}:{rng.random():.4f}"
                                 for i in ids]))
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return str(path)


def _cfg(path, **kw):
    base = dict(vocabulary_size=VOCAB, factor_num=4, batch_size=16,
                learning_rate=0.1, factor_lambda=1e-6, bias_lambda=1e-6,
                max_features_per_example=16, bucket_ladder=(8, 16),
                train_files=(path,), shuffle=False)
    base.update(kw)
    return FmConfig(**base)


# --- ladder math -----------------------------------------------------------


def test_flat_bucket_floor_and_quarter_octave():
    assert flat_bucket(0) == FLAT_LADDER_FLOOR
    assert flat_bucket(FLAT_LADDER_FLOOR) == FLAT_LADDER_FLOOR
    for nnz in (9, 17, 33, 100, 1000, 12345, 262145, 319488):
        b = flat_bucket(nnz)
        assert b >= nnz
        # quarter-octave ladder: flat padding never exceeds 25%
        assert b <= nnz * 1.25, (nnz, b)
    # monotone
    rungs = [flat_bucket(n) for n in range(1, 2000)]
    assert rungs == sorted(rungs)


def test_rect_fraction_rungs_bounded_and_cover():
    rungs = rect_fraction_rungs(32, 32)
    assert len(rungs) <= 5
    assert rungs[-1] == 32 * 32  # nnz <= B*L always fits the top rung
    assert rungs[0] == FLAT_LADDER_FLOOR
    # a one-example serve flush never pads past its own tiny rectangle
    assert rect_fraction_rungs(1, 8) == (8,)


# --- encode / unpack round trip --------------------------------------------


def _unpacked(wb, spec):
    """Run the device unpack on an encoded batch's args."""
    pad = (spec.vocabulary_size if wb.args.get("uniq_ids") is None
           else len(wb.args["uniq_ids"]) - 1)
    li, vv, ff = unpack_rectangles(
        wb.L, pad, jax.numpy.asarray(wb.args["lengths"]),
        jax.numpy.asarray(wb.args["flat_idx"]),
        jax.numpy.asarray(wb.args["flat_vals"]),
        (jax.numpy.asarray(wb.args["flat_fields"])
         if "flat_fields" in wb.args else None))
    return (np.asarray(li), np.asarray(vv),
            None if ff is None else np.asarray(ff))


@pytest.mark.parametrize("dedup", ["host", "device"])
def test_encode_unpack_roundtrip_bitwise(tmp_path, dedup):
    """encode -> on-device unpack reproduces the padded rectangles
    bit-for-bit (padding normalized to the canonical pad slot, which
    carries the same dead row)."""
    path = _write_corpus(tmp_path / "t.txt", 100, seed=1)
    cfg = _cfg(path, dedup=dedup)
    spec = ModelSpec.from_config(cfg)
    enc = WireEncoder(WireSpec("packed", "wide"), pad_id=cfg.pad_id)
    raw = spec.dedup == "device"
    for b in batch_iterator(cfg, cfg.train_files, training=True,
                            raw_ids=raw):
        wb = enc.encode_train(b)
        li, vv, _ = _unpacked(wb, spec)
        assert np.array_equal(vv, b.vals)
        if raw:
            assert np.array_equal(li, b.local_idx)
        else:
            # Slot positions of padding may normalize (C++ builder
            # parks padding at slot 0, the unpack at U-1) — the ROWS
            # each cell addresses must match exactly.
            uniq = np.asarray(b.uniq_ids)
            assert np.array_equal(uniq[li], uniq[b.local_idx])


def test_encode_empty_and_full_batches(tmp_path):
    """Zero-feature rows and a completely full rectangle both encode
    and unpack exactly."""
    from fast_tffm_tpu.data.parser import parse_lines
    lines = ["1 " + " ".join(f"{i}:1.0" for i in range(8)),
             "0", "1 5:2.0"]
    block = parse_lines(lines, VOCAB, keep_empty=True)
    from fast_tffm_tpu.data.pipeline import make_device_batch
    cfg = _cfg(os.devnull)
    b = make_device_batch(block, cfg, raw_ids=True)
    enc = WireEncoder(WireSpec("packed", "wide"), pad_id=cfg.pad_id)
    wb = enc.encode_score(b)
    li, vv, _ = _unpacked(wb, ModelSpec.from_config(
        dataclasses.replace(cfg, dedup="device")))
    assert np.array_equal(li, b.local_idx)
    assert np.array_equal(vv, b.vals)
    assert list(wb.args["lengths"][:3]) == [8, 0, 1]


def test_encoder_narrow_dtypes(tmp_path):
    path = _write_corpus(tmp_path / "t.txt", 40, seed=2)
    cfg = _cfg(path, dedup="device")
    enc = WireEncoder(WireSpec("packed", "narrow"), pad_id=cfg.pad_id)
    b = next(batch_iterator(cfg, cfg.train_files, training=True,
                            raw_ids=True))
    wb = enc.encode_train(b)
    assert wb.args["flat_vals"].dtype == np.float16
    assert wb.args["weights"].dtype == np.float16
    assert wb.args["labels"].dtype == np.float32  # labels stay wide
    assert wb.args["flat_idx"].dtype == np.int32
    assert wb.wire_bytes < wb.logical_bytes


# --- bit-parity across pipeline shapes -------------------------------------


def _dispatch_parity(cfg, batches, raw):
    """Run the same batch list through the padded and packed train
    steps AND the padded and packed scorers; assert bitwise parity of
    final (table, acc) and every batch's scores."""
    spec = ModelSpec.from_config(cfg)
    step = make_train_step(spec)
    pstep = make_packed_train_step(spec)
    score = make_score_fn(spec)
    pscore = make_packed_score_fn(spec)
    enc = WireEncoder(WireSpec("packed", "wide"), pad_id=cfg.pad_id)
    t1, a1 = init_table(cfg, 0), init_accumulator(cfg)
    t2, a2 = init_table(cfg, 0), init_accumulator(cfg)
    assert batches, "shape produced no batches"
    for b in batches:
        sargs = batch_args(b)
        sargs.pop("labels"), sargs.pop("weights")
        s1 = np.asarray(score(t1, **sargs))
        wbs = enc.encode_score(b)
        s2 = np.asarray(pscore(wbs.L, t1, **jax.device_put(wbs.args)))
        assert np.array_equal(s1, s2), "predict scores diverged"
        t1, a1, _, _ = step(t1, a1, **batch_args(b))
        wb = enc.encode_train(b)
        assert wb.wire_bytes > 0 and wb.logical_bytes >= wb.wire_bytes \
            or True  # byte accounting sanity only; savings pinned below
        t2, a2, _, _ = pstep(wb.L, t2, a2, **jax.device_put(wb.args))
    assert np.array_equal(np.asarray(t1), np.asarray(t2)), \
        "train table diverged"
    assert np.array_equal(np.asarray(a1), np.asarray(a2)), \
        "adagrad accumulator diverged"


@pytest.mark.parametrize("dedup", ["host", "device"])
def test_parity_fast_path(tmp_path, dedup):
    path = _write_corpus(tmp_path / "t.txt", 150, seed=3)
    cfg = _cfg(path, dedup=dedup)
    raw = ModelSpec.from_config(cfg).dedup == "device"
    batches = list(batch_iterator(cfg, cfg.train_files, training=True,
                                  raw_ids=raw))
    _dispatch_parity(cfg, batches, raw)


def test_parity_generic_unbounded(tmp_path):
    """max_features_per_example = 0: the generic python path."""
    path = _write_corpus(tmp_path / "t.txt", 120, seed=4)
    cfg = _cfg(path, max_features_per_example=0)
    batches = list(batch_iterator(cfg, cfg.train_files, training=True))
    _dispatch_parity(cfg, batches, False)


def test_parity_tolerant_skip(tmp_path):
    """bad_line_policy = skip with corrupt lines in the corpus."""
    from fast_tffm_tpu.data.badlines import BadLineTracker
    path = _write_corpus(tmp_path / "t.txt", 100, seed=5)
    with open(path) as fh:
        lines = fh.read().splitlines()
    lines[10] = "1 broken:::"
    lines[55] = "not-a-label 3:1.0"
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    cfg = _cfg(path, bad_line_policy="skip")
    batches = list(batch_iterator(
        cfg, cfg.train_files, training=True,
        bad_lines=BadLineTracker("skip", cfg.max_bad_fraction)))
    _dispatch_parity(cfg, batches, False)


def test_parity_host_threads_ring(tmp_path):
    """The PR 7 parallel build ring (host_threads = 4)."""
    path = _write_corpus(tmp_path / "t.txt", 400, seed=6)
    cfg = _cfg(path, host_threads=4)
    batches = list(batch_iterator(cfg, cfg.train_files, training=True))
    _dispatch_parity(cfg, batches, False)


def test_parity_sharded_spill(tmp_path):
    """Fixed-U batches that SPILL on the unique-row budget (the
    multi-process shape; packed dispatch of such batches still runs on
    one device — e.g. the bench's sharded row)."""
    path = tmp_path / "dense.txt"
    with open(path, "w") as fh:
        for i in range(64):
            base = i * 8
            toks = " ".join(f"{base + j}:1" for j in range(8))
            fh.write(f"{i % 2} {toks}\n")
    cfg = _cfg(str(path), vocabulary_size=4096, uniq_bucket=64)
    stats = SpillStats()
    batches = list(batch_iterator(cfg, cfg.train_files, training=True,
                                  fixed_shape=True, uniq_bucket=64,
                                  stats=stats))
    assert stats.spilled_batches > 0, "shape must actually spill"
    _dispatch_parity(cfg, batches, False)


def test_parity_stream_source(tmp_path):
    """Batches from the streaming source (stream_pos tags ride along
    untouched by the encoder)."""
    import fast_tffm_tpu.data.stream as sl
    sd = tmp_path / "s"
    sd.mkdir()
    _write_corpus(sd / "a.txt", 60, seed=7)
    (sd / "a.txt.done").touch()
    _write_corpus(sd / "b.txt", 30, seed=8)
    (sd / "b.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg("ignored.txt", train_files=(), run_mode="stream",
               stream_dir=str(sd), stream_poll_seconds=0.01)
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    src = sl.StreamSource(cfg, tr)
    batches = []
    try:
        while True:
            b = src.next_batch(block=True)
            if b is sl.DONE:
                break
            if b is sl.IDLE:
                continue
            batches.append(b)
    finally:
        src.close()
    assert batches and all(b.stream_pos is not None for b in batches)
    _dispatch_parity(cfg, batches, False)


def test_parity_ffm_fields(tmp_path):
    """FFM batches carry fields — the packed wire ships flat_fields."""
    rng = np.random.default_rng(9)
    path = tmp_path / "ffm.txt"
    lines = []
    for _ in range(80):
        toks = [f"{f}:{int(rng.integers(0, VOCAB))}" for f in range(6)]
        lines.append(" ".join([str(int(rng.integers(0, 2)))] + toks))
    path.write_text("\n".join(lines) + "\n")
    cfg = _cfg(str(path), model_type="ffm", field_num=6)
    batches = list(batch_iterator(cfg, cfg.train_files, training=True))
    assert batches[0].fields is not None
    _dispatch_parity(cfg, batches, False)


# --- narrow tolerance ------------------------------------------------------


def test_narrow_mode_tolerance(tmp_path):
    """packed-narrow: one f16 rounding on values/weights — scores and
    the trained table track the wide path within f16 tolerances (and
    training does not blow up)."""
    path = _write_corpus(tmp_path / "t.txt", 150, seed=10)
    cfg = _cfg(path, dedup="device")
    spec = ModelSpec.from_config(cfg)
    step = make_train_step(spec)
    pstep = make_packed_train_step(spec)
    pscore = make_packed_score_fn(spec)
    enc = WireEncoder(WireSpec("packed", "narrow"), pad_id=cfg.pad_id)
    t1, a1 = init_table(cfg, 0), init_accumulator(cfg)
    t2, a2 = init_table(cfg, 0), init_accumulator(cfg)
    score = make_score_fn(spec)
    for b in batch_iterator(cfg, cfg.train_files, training=True,
                            raw_ids=True):
        sargs = batch_args(b)
        sargs.pop("labels"), sargs.pop("weights")
        s1 = np.asarray(score(t1, **sargs))
        wbs = enc.encode_score(b)
        s2 = np.asarray(pscore(wbs.L, t1, **jax.device_put(wbs.args)))
        np.testing.assert_allclose(s1, s2, rtol=2e-3, atol=2e-3)
        t1, a1, _, _ = step(t1, a1, **batch_args(b))
        wb = enc.encode_train(b)
        t2, a2, _, _ = pstep(wb.L, t2, a2, **jax.device_put(wb.args))
    t1, t2 = np.asarray(t1), np.asarray(t2)
    assert np.all(np.isfinite(t2))
    np.testing.assert_allclose(t1, t2, rtol=0.05, atol=5e-3)


# --- resolve + config validation -------------------------------------------


def test_resolve_wire_downgrades_warn(tmp_path):
    cfg = _cfg(os.devnull, wire_format="packed")
    assert resolve_wire(cfg, multi_process=False).packed
    with pytest.warns(UserWarning, match="lockstep"):
        assert not resolve_wire(cfg, multi_process=True).packed
    with pytest.warns(UserWarning, match="mesh"):
        assert not resolve_wire(cfg, mesh=object(),
                                multi_process=False).packed
    with pytest.warns(UserWarning, match="offload"):
        assert not resolve_wire(cfg, backend=object(),
                                multi_process=False, train=True).packed
    # the offload SCORE path keeps packed
    assert resolve_wire(cfg, backend=object(),
                        multi_process=False).packed
    # padded resolves silently everywhere
    assert not resolve_wire(_cfg(os.devnull),
                            multi_process=True).packed


def test_config_rejects_narrow_without_packed():
    with pytest.raises(ValueError, match="narrow requires"):
        _cfg(os.devnull, wire_dtypes="narrow")
    with pytest.raises(ValueError, match="wire_format"):
        _cfg(os.devnull, wire_format="zstd")
    with pytest.raises(ValueError, match="wire_dtypes"):
        _cfg(os.devnull, wire_format="packed", wire_dtypes="bf16")


# --- end-to-end through train(): bytes + gauges + parity -------------------
#
# The tests/ harness forces 8 CPU devices, which routes train() onto
# the mesh path where packed deliberately downgrades — so the
# single-device train() pins run in a subprocess with a clean
# XLA_FLAGS (the same trick the CLI e2e tests use).

_TRAIN_DRIVER = """
import json, os, sys
import numpy as np
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train import train
wd = sys.argv[1]
path = os.path.join(wd, "corpus.txt")
out = {}
for name, kw in (("padded", {}),
                 ("packed", {"wire_format": "packed"}),
                 ("narrow", {"wire_format": "packed",
                             "wire_dtypes": "narrow"})):
    cfg = FmConfig(vocabulary_size=400, factor_num=4, batch_size=16,
                   learning_rate=0.1, shuffle=False, seed=0,
                   log_steps=0, train_files=(path,), epoch_num=1,
                   model_file=os.path.join(wd, name, "fm"),
                   metrics_file=os.path.join(wd, name, "m.jsonl"),
                   **kw)
    table = np.asarray(train(cfg))
    np.save(os.path.join(wd, name + ".npy"), table)
    out[name] = cfg.metrics_file
print(json.dumps(out))
"""


@pytest.fixture(scope="module")
def trained_trio(tmp_path_factory):
    """padded / packed / packed-narrow train() runs of the same corpus
    at the DEFAULT bucket ladder, in a single-device subprocess."""
    import subprocess
    import sys
    wd = str(tmp_path_factory.mktemp("wire_train"))
    # Variable-length corpus (nnz 1..9 against the default ladder's
    # L=16 bucket): the padding-waste regime the packed wire exists
    # for — the pipeline's padding-waste counter reads ~2/3 here.
    _write_corpus(os.path.join(wd, "corpus.txt"), 300, seed=11,
                  max_nnz=10)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    res = subprocess.run(
        [sys.executable, "-c", _TRAIN_DRIVER, wd],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stderr[-4000:]
    metrics = json.loads(res.stdout.strip().splitlines()[-1])
    tables = {k: np.load(os.path.join(wd, k + ".npy"))
              for k in ("padded", "packed", "narrow")}
    return metrics, tables


def _counters(metrics_file):
    last = {}
    gauges = {}
    with open(metrics_file) as fh:
        for ln in fh:
            rec = json.loads(ln)
            if rec.get("event") == "metrics":
                last = rec.get("counters", last)
                gauges = rec.get("gauges", gauges)
    return last, gauges


def test_train_packed_bitwise_and_h2d_savings(trained_trio):
    """The acceptance pin: a real train() run at the DEFAULT ladder
    with wire_format = packed produces a bit-identical table to the
    padded run, counts train/h2d_bytes at less than HALF the logical
    (padded) bytes, and stamps the wire gauges fmstat names."""
    metrics, tables = trained_trio
    assert np.array_equal(tables["padded"], tables["packed"])
    # narrow: one f16 rounding on the inputs — close, finite, not bit
    assert np.all(np.isfinite(tables["narrow"]))
    np.testing.assert_allclose(tables["padded"], tables["narrow"],
                               rtol=0.05, atol=5e-3)

    c_pad, g_pad = _counters(metrics["padded"])
    c_pack, g_pack = _counters(metrics["packed"])
    # padded: actual == logical; packed: actual < logical / 2 (the
    # >= 2x acceptance bar at the default config).
    assert c_pad["train/h2d_bytes"] == c_pad["train/h2d_bytes_logical"]
    assert c_pack["train/h2d_bytes_logical"] == c_pad["train/h2d_bytes"]
    assert (c_pack["train/h2d_bytes"]
            <= c_pack["train/h2d_bytes_logical"] / 2.0)
    assert g_pad["wire/packed"] == 0.0
    assert g_pack["wire/packed"] == 1.0 and g_pack["wire/narrow"] == 0.0


def test_fmstat_wire_rows_and_verdict(trained_trio):
    """fmstat attribution: bytes-per-example row, the savings ratio,
    and the transfer-bound verdict naming the active mode."""
    from fast_tffm_tpu.obs.attribution import (attribution, render,
                                               summarize, wire_mode)
    metrics, _ = trained_trio
    s = summarize([metrics["narrow"]])
    att = attribution(s)
    assert att["wire_format"] == "packed-narrow"
    assert att["h2d_bytes_per_example"] is not None
    assert att["wire_savings_ratio"] > 2.0
    assert (att["h2d_logical_bytes_per_example"]
            > att["h2d_bytes_per_example"] * 2)
    body = render(s)
    assert "h2d bytes/example (wire / padded)" in body
    assert "packed-narrow" in body
    if "device/transfer-bound" in att["verdict"]:
        assert "wire packed-narrow" in att["verdict"]
    # pre-wire stream: mode unknown, never assumed
    assert wire_mode({}) is None
    assert wire_mode({"wire/packed": 0.0}) == "padded-wide"


# --- serve: the packed flush path ------------------------------------------


def _serve_corpus(n, seed=0):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        feats = sorted(rng.choice(VOCAB, size=4, replace=False))
        lines.append(f"{int(rng.integers(0, 2))} "
                     + " ".join(f"{i}:1.0" for i in feats))
    return lines


def test_serve_flush_packed_bitwise(tmp_path):
    """A packed-wire server's responses are bit-identical to a padded
    server on the same published step, with no flush errors and no
    recompiles after warmup."""
    from fast_tffm_tpu.checkpoint import CheckpointState, list_step_dirs
    from fast_tffm_tpu.serve import ScorerServer
    from fast_tffm_tpu.train import train
    wd = str(tmp_path)
    with open(os.path.join(wd, "train.txt"), "w") as fh:
        fh.write("\n".join(_serve_corpus(200, seed=12)) + "\n")
    cfg = FmConfig(vocabulary_size=VOCAB, factor_num=4, batch_size=32,
                   epoch_num=1, learning_rate=0.1, shuffle=False,
                   seed=0, log_steps=0,
                   bucket_ladder=(8,), max_features_per_example=8,
                   serve_max_batch=8, serve_max_wait_ms=1.0,
                   train_files=(os.path.join(wd, "train.txt"),),
                   model_file=os.path.join(wd, "model", "fm"))
    train(cfg)
    ckpt = CheckpointState(cfg.model_file)
    step = list_step_dirs(ckpt.directory)[-1]
    ckpt.publish_step(step)
    ckpt.close()

    reqs = [_serve_corpus(3, seed=s) for s in range(3, 7)]
    results = {}
    for name, overrides in (
            ("padded", {}),
            ("packed", {"wire_format": "packed"})):
        scfg = dataclasses.replace(cfg, **overrides)
        server = ScorerServer(scfg, watch=False)
        try:
            assert server._scorer.wire.packed == (name == "packed")
            shapes = server.compiled_shapes
            results[name] = [server.score_lines(r, timeout=30).scores
                             for r in reqs]
            assert server.stats()["flush_errors"] == 0
            assert server.compiled_shapes == shapes
        finally:
            server.close()
    for a, b in zip(results["padded"], results["packed"]):
        assert np.array_equal(a, b)


# --- offload score path ----------------------------------------------------


def test_offload_packed_score_parity(tmp_path):
    """lookup = host scoring with the packed wire: only gathered rows
    + flat CSR cross the wall, scores bit-identical to padded."""
    from fast_tffm_tpu.lookup import make_score_backend
    from fast_tffm_tpu.scoring import CompiledScorer
    path = _write_corpus(tmp_path / "t.txt", 60, seed=13)
    base = _cfg(path, lookup="host", dedup="host")
    table = np.asarray(init_table(_cfg(path), 0))
    backend = make_score_backend(base, table=table)
    pad_scorer = CompiledScorer(base, backend=backend)
    packed_scorer = CompiledScorer(
        dataclasses.replace(base, wire_format="packed"),
        backend=backend)
    assert packed_scorer.wire.packed
    for b in batch_iterator(base, base.train_files, training=True):
        s1 = np.asarray(pad_scorer.score_batch(None, b))
        s2 = np.asarray(packed_scorer.score_batch(None, b))
        assert np.array_equal(s1, s2)
