import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import parse_lines
from fast_tffm_tpu.data.pipeline import (batch_iterator, expand_files,
                                         make_device_batch)

CFG = FmConfig(vocabulary_size=1000, factor_num=4, batch_size=4,
               bucket_ladder=(4, 8, 16), shuffle=False)


def test_padding_invariants():
    block = parse_lines(["1 3:0.5 7:2 9", "0 3:1.0"], 1000)
    b = make_device_batch(block, CFG)
    B, L = b.local_idx.shape
    assert B == 4 and L == 4                      # bucket of max nnz 3 -> 4
    # last uniq slot is always padding
    assert b.uniq_ids[-1] == CFG.pad_id
    # real uniques present, sorted, no dupes
    assert set(b.uniq_ids.tolist()) == {3, 7, 9, CFG.pad_id}
    # local_idx resolves back to global ids; padding points at pad slot
    resolved = b.uniq_ids[b.local_idx]
    assert resolved[0, 0] == 3 and resolved[0, 1] == 7 and resolved[0, 2] == 9
    assert resolved[0, 3] == CFG.pad_id
    assert (resolved[2:] == CFG.pad_id).all()     # dummy examples
    # padded vals are zero; dummy examples have weight 0
    assert b.vals[0, 3] == 0.0
    np.testing.assert_array_equal(b.weights, [1, 1, 0, 0])
    assert b.num_real == 2


def test_uniq_dedup_across_examples():
    block = parse_lines(["1 5 6", "0 5 6", "1 5"], 1000)
    b = make_device_batch(block, CFG)
    real = b.uniq_ids[b.uniq_ids != CFG.pad_id]
    assert sorted(real.tolist()) == [5, 6]


def test_batch_iterator_epochs_and_order(tmp_path):
    p = tmp_path / "d.txt"
    p.write_text("".join(f"{i % 2} {i}:1\n" for i in range(10)))
    cfg = FmConfig(vocabulary_size=100, batch_size=4, shuffle=False,
                   bucket_ladder=(4,))
    batches = list(batch_iterator(cfg, [str(p)], training=True, epochs=2))
    # 10 examples -> 3 batches/epoch (4+4+2), 2 epochs
    assert len(batches) == 6
    assert [b.num_real for b in batches] == [4, 4, 2, 4, 4, 2]
    # order preserved without shuffle
    ids0 = batches[0].uniq_ids[batches[0].local_idx[:, 0]]
    np.testing.assert_array_equal(ids0, [0, 1, 2, 3])


def test_shuffle_deterministic_and_complete(tmp_path):
    p = tmp_path / "d.txt"
    n = 57
    p.write_text("".join(f"1 {i}:1\n" for i in range(n)))
    cfg = FmConfig(vocabulary_size=100, batch_size=8, shuffle=True,
                   queue_size=16, seed=42, bucket_ladder=(4,))

    def collect():
        seen = []
        for b in batch_iterator(cfg, [str(p)], training=True, epochs=1):
            ids = b.uniq_ids[b.local_idx[:b.num_real, 0]]
            seen.extend(ids.tolist())
        return seen

    a, b = collect(), collect()
    assert a == b                                  # deterministic
    assert sorted(a) == list(range(n))             # complete, no dupes
    assert a != list(range(n))                     # actually shuffled


def test_sharding_disjoint_complete(tmp_path):
    p = tmp_path / "d.txt"
    n = 37
    p.write_text("".join(f"1 {i}:1\n" for i in range(n)))
    cfg = FmConfig(vocabulary_size=100, batch_size=4, shuffle=False,
                   bucket_ladder=(4,))
    all_seen = []
    for shard in range(3):
        for b in batch_iterator(cfg, [str(p)], training=True, epochs=1,
                                shard_index=shard, num_shards=3):
            all_seen.extend(
                b.uniq_ids[b.local_idx[:b.num_real, 0]].tolist())
    assert sorted(all_seen) == list(range(n))


def test_weight_files(tmp_path):
    d = tmp_path / "d.txt"
    w = tmp_path / "w.txt"
    d.write_text("1 1:1\n0 2:1\n")
    w.write_text("0.5\n2.0\n")
    cfg = FmConfig(vocabulary_size=10, batch_size=2, shuffle=False,
                   bucket_ladder=(4,))
    (b,) = list(batch_iterator(cfg, [str(d)], training=True,
                               weight_files=[str(w)], epochs=1))
    np.testing.assert_allclose(b.weights, [0.5, 2.0])


def test_expand_files(tmp_path):
    for name in ("a1.txt", "a2.txt"):
        (tmp_path / name).write_text("x")
    got = expand_files([str(tmp_path / "a*.txt"), "no_such_literal.txt"])
    assert got == [str(tmp_path / "a1.txt"), str(tmp_path / "a2.txt"),
                   "no_such_literal.txt"]


def test_oversize_block_rejected():
    block = parse_lines(["1 1", "1 2", "1 3", "1 4", "1 5"], 10)
    with pytest.raises(ValueError):
        make_device_batch(block, CFG)  # 5 examples > batch_size 4


def test_prefetch_worker_exits_on_abandoned_consumer(monkeypatch):
    """Breaking out of a prefetch loop must not strand the worker thread
    blocked on a full queue (it holds file handles and batches)."""
    import os
    import threading
    import time
    from fast_tffm_tpu.data.pipeline import prefetch

    monkeypatch.setattr(os, "sched_getaffinity",
                        lambda pid: set(range(4)), raising=False)
    before = threading.active_count()
    for _ in range(5):
        gen = prefetch(iter(range(100)), depth=1)
        assert next(gen) == 0
        gen.close()  # abandons mid-stream -> stop flag must fire
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 1
