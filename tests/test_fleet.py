"""Serving-fleet units (README "Serving fleet"): the failover proxy's
retry/affinity/canary routing, the restart policy's capped backoff,
the stagger protocol's >= 1-other-ready invariant, the reload
watcher's jittered cadence, the canary checkpoint pointer, and the
fmstat FLEET section — everything driven through the public seams
(ScoreProxy.forward_score, staggered_reload over fakes, RestartPolicy
over a fake clock) so no test spawns a replica child process."""

import http.server
import json
import os
import threading

import pytest

from fast_tffm_tpu.serve.fleet import RestartPolicy, staggered_reload
from fast_tffm_tpu.serve.proxy import (FleetView, FractionSplitter,
                                       Replica, ScoreProxy,
                                       rendezvous_choose)

pytestmark = pytest.mark.filterwarnings(
    "ignore::DeprecationWarning")


# --- back-end stubs ------------------------------------------------------


class _StubHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_POST(self):  # noqa: N802 - http.server contract
        length = int(self.headers.get("Content-Length") or 0)
        self.rfile.read(length)
        srv = self.server
        srv.hits += 1
        body = srv.body
        self.send_response(srv.status)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        if srv.step is not None:
            self.send_header("X-FM-Step", str(srv.step))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # noqa: A003 - silence
        pass


class _Stub(http.server.ThreadingHTTPServer):
    """One fake replica back end: scripted status/body/step."""

    daemon_threads = True

    def __init__(self, status=200, body=b"0.500000\n", step=7):
        self.status, self.body, self.step = status, body, step
        self.hits = 0
        super().__init__(("127.0.0.1", 0), _StubHandler)
        self.thread = threading.Thread(target=self.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def port(self):
        return self.server_address[1]

    def close(self):
        self.shutdown()
        self.thread.join()
        self.server_close()


def _ready_replica(index, port, canary=False):
    r = Replica(index, "127.0.0.1", port, canary=canary)
    r.set_health(alive=True, ready=True, served_step=7)
    return r


def _dead_port():
    """A loopback port with nothing listening (bound then released)."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --- proxy retry / failover ---------------------------------------------


def test_proxy_fails_over_on_connection_refused():
    good = _Stub()
    try:
        bad = _ready_replica(0, _dead_port())
        ok = _ready_replica(1, good.port)
        proxy = ScoreProxy(FleetView([bad, ok]), retry_budget=2,
                           backoff_seconds=0.0)
        # Force the first pick onto the dead replica: the round-robin
        # cursor is deterministic, so route by affinity instead and
        # pin the key to the dead one.
        key = next(k for k in (f"k{i}" for i in range(64))
                   if rendezvous_choose(k, [bad, ok]) is bad)
        status, body, extra = proxy.forward_score(b"1 0:1.0\n", key)
        assert status == 200 and body == b"0.500000\n"
        assert extra["X-FM-Replica"] == "1"
        assert extra["X-FM-Step"] == "7"
        snap = proxy.registry.snapshot()["counters"]
        assert snap["proxy/transport_errors"] == 1
        assert snap["proxy/retries"] == 1
        # Fast-path demotion: the dead replica is routed around NOW,
        # before any health poll.
        assert not bad.is_ready()
    finally:
        good.close()


def test_proxy_fails_over_on_upstream_5xx():
    sick = _Stub(status=500, body=b"boom\n", step=None)
    good = _Stub()
    try:
        r_sick = _ready_replica(0, sick.port)
        r_good = _ready_replica(1, good.port)
        proxy = ScoreProxy(FleetView([r_sick, r_good]), retry_budget=2,
                           backoff_seconds=0.0)
        key = next(k for k in (f"k{i}" for i in range(64))
                   if rendezvous_choose(k, [r_sick, r_good]) is r_sick)
        status, body, _ = proxy.forward_score(b"1 0:1.0\n", key)
        assert status == 200 and body == b"0.500000\n"
        snap = proxy.registry.snapshot()["counters"]
        assert snap["proxy/upstream_5xx"] == 1
        assert not r_sick.is_ready()
        assert sick.hits == 1 and good.hits == 1
    finally:
        sick.close()
        good.close()


def test_proxy_exhausted_budget_is_503_with_retry_after():
    replicas = [_ready_replica(i, _dead_port()) for i in range(3)]
    proxy = ScoreProxy(FleetView(replicas), retry_budget=2,
                       backoff_seconds=0.0)
    status, body, extra = proxy.forward_score(b"1 0:1.0\n", None)
    assert status == 503
    assert extra["Retry-After"] == "1"
    assert b"no replica could score" in body
    snap = proxy.registry.snapshot()["counters"]
    assert snap["proxy/unrouted_503"] == 1
    # budget + 1 attempts, each on a DIFFERENT replica
    assert snap["proxy/transport_errors"] == 3


def test_proxy_4xx_passes_through_unretried():
    """Client errors are not the replica's fault: resending a
    malformed request buys nothing and must not burn the budget."""
    bad_req = _Stub(status=400, body=b"parse error\n", step=None)
    try:
        proxy = ScoreProxy(
            FleetView([_ready_replica(0, bad_req.port)]),
            retry_budget=3, backoff_seconds=0.0)
        status, body, _ = proxy.forward_score(b"garbage\n", None)
        assert status == 400 and body == b"parse error\n"
        assert bad_req.hits == 1
        snap = proxy.registry.snapshot()["counters"]
        assert "proxy/retries" not in snap
    finally:
        bad_req.close()


def test_proxy_front_end_sheds_at_max_inflight():
    """Beyond serve_proxy_max_inflight the front door answers 503 +
    Retry-After immediately instead of queueing blocked threads."""
    import http.client
    good = _Stub()
    proxy = ScoreProxy(FleetView([_ready_replica(0, good.port)]),
                       max_inflight=1)
    port = proxy.start(0)
    try:
        assert proxy.inflight.acquire(blocking=False)  # fill the slot
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("POST", "/score", body=b"1 0:1.0\n",
                         headers={"Content-Type": "text/plain"})
            resp = conn.getresponse()
            out = resp.read()
            assert resp.status == 503
            assert resp.getheader("Retry-After") == "1"
            assert b"max in-flight" in out
        finally:
            conn.close()
        snap = proxy.registry.snapshot()["counters"]
        assert snap["proxy/shed_503"] == 1
        proxy.inflight.release()
    finally:
        proxy.shutdown()
        good.close()


def test_proxy_healthz_aggregates_and_degrades():
    import http.client
    r0 = _ready_replica(0, 1)
    r1 = Replica(1, "127.0.0.1", 2)
    r1.set_health(alive=True, ready=False)
    proxy = ScoreProxy(FleetView([r0, r1]))
    port = proxy.start(0)

    def get_healthz():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        try:
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read())
        finally:
            conn.close()

    try:
        status, payload = get_healthz()
        assert status == 200 and payload["status"] == "ok"
        assert (payload["replicas"], payload["alive"],
                payload["ready"]) == (2, 2, 1)
        assert [row["ready"] for row in payload["per_replica"]] \
            == [True, False]
        r0.mark_failed()
        status, payload = get_healthz()
        assert status == 503 and payload["status"] == "degraded"
    finally:
        proxy.shutdown()


# --- rendezvous affinity -------------------------------------------------


def test_rendezvous_affinity_is_stable_and_minimal():
    """The HRW property the proxy buys over modulo hashing: removing
    one replica only remaps the keys that were ON it."""
    replicas = [_ready_replica(i, 9000 + i) for i in range(4)]
    keys = [f"user-{i}" for i in range(300)]
    before = {k: rendezvous_choose(k, replicas) for k in keys}
    # Deterministic: the same key always lands on the same replica.
    assert all(rendezvous_choose(k, replicas) is before[k]
               for k in keys)
    gone = replicas[2]
    survivors = [r for r in replicas if r is not gone]
    moved = 0
    for k in keys:
        after = rendezvous_choose(k, survivors)
        if before[k] is gone:
            moved += 1
            assert after is not gone
        else:
            assert after is before[k], (
                f"key {k} moved off a surviving replica")
    # The departed replica owned SOME keys (sanity: the test bites).
    assert moved > 0


def test_proxy_affinity_header_coalesces_bursts():
    good = _Stub()
    other = _Stub()
    try:
        replicas = [_ready_replica(0, good.port),
                    _ready_replica(1, other.port)]
        proxy = ScoreProxy(FleetView(replicas), retry_budget=0)
        hits = set()
        for _ in range(8):
            status, _, extra = proxy.forward_score(b"1 0:1.0\n",
                                                   "user-42")
            assert status == 200
            hits.add(extra["X-FM-Replica"])
        assert len(hits) == 1, f"affinity key split across {hits}"
    finally:
        good.close()
        other.close()


# --- canary routing ------------------------------------------------------


def test_fraction_splitter_is_exact():
    s = FractionSplitter(0.25)
    takes = sum(s.take() for _ in range(400))
    assert takes == 100
    assert sum(FractionSplitter(0.0).take() for _ in range(50)) == 0
    assert sum(FractionSplitter(1.0).take() for _ in range(50)) == 50


def test_canary_fraction_routes_exactly():
    """With a ready canary, pick() sends exactly the configured
    fraction of unkeyed traffic to it — deterministically."""
    primaries = [_ready_replica(i, 9100 + i) for i in range(2)]
    canary = _ready_replica(2, 9200, canary=True)
    proxy = ScoreProxy(FleetView(primaries + [canary]),
                       canary_fraction=0.25)
    chosen = [proxy.pick(None) for _ in range(200)]
    assert sum(1 for r in chosen if r is canary) == 50
    snap = proxy.registry.snapshot()["counters"]
    assert snap["proxy/canary_requests"] == 50


def test_canary_not_primary_routed_and_degraded_fallback():
    primaries = [_ready_replica(i, 9100 + i) for i in range(2)]
    canary = _ready_replica(2, 9200, canary=True)
    proxy = ScoreProxy(FleetView(primaries + [canary]),
                       canary_fraction=0.0)
    # fraction 0: unkeyed traffic never touches the canary...
    assert all(proxy.pick(None) is not canary for _ in range(50))
    # ...until every primary is down — then a ready canary beats an
    # outage.
    for r in primaries:
        r.mark_failed()
    assert proxy.pick(None) is canary


# --- restart backoff -----------------------------------------------------


def test_restart_policy_caps_and_resets():
    clock = [0.0]
    p = RestartPolicy(1.0, cap_factor=16.0, clock=lambda: clock[0])
    assert p.can_restart()
    delays = [p.record_death() for _ in range(6)]
    assert delays == [1.0, 2.0, 4.0, 8.0, 16.0, 16.0]  # capped
    assert p.failures == 6
    assert not p.can_restart()  # last death scheduled t+16
    clock[0] = 15.9
    assert not p.can_restart()
    clock[0] = 16.0
    assert p.can_restart()
    p.record_healthy()
    assert p.failures == 0
    assert p.record_death() == 1.0  # streak reset: back to base


# --- staggered reload ----------------------------------------------------


class _FakeHandle:
    """ReplicaProc's reload surface: reload() takes the handle
    not-ready (synchronously, like the real POST /reload) and a later
    is_ready() poll brings it back — with the test recording how many
    OTHER handles were ready at every reload instant."""

    def __init__(self, name, fleet, fail=False,
                 ready_after_polls=2):
        self.name = name
        self.fleet = fleet
        self.fail = fail
        self.ready = True
        self.step = 0
        self._polls_left = 0
        self._ready_after = ready_after_polls

    def is_ready(self):
        if not self.ready and self._polls_left > 0:
            self._polls_left -= 1
            if self._polls_left == 0:
                self.ready = True
        return self.ready

    def reload(self, step):
        others_ready = sum(1 for h in self.fleet
                           if h is not self and h.ready)
        self.fleet.observed_min = min(self.fleet.observed_min,
                                      others_ready)
        if self.fail:
            return False
        self.ready = False
        self._polls_left = self._ready_after
        self.step = step
        return True


class _Fleet(list):
    observed_min = 99


def test_staggered_reload_keeps_one_other_ready():
    fleet = _Fleet()
    fleet.extend(_FakeHandle(f"r{i}", fleet) for i in range(4))
    done = staggered_reload(fleet, step=11, sleep=lambda _s: None)
    assert done == 4
    assert all(h.step == 11 and h.ready for h in fleet)
    # The invariant: at every reload instant >= 1 OTHER replica ready.
    assert fleet.observed_min >= 1


def test_staggered_reload_counts_failures_and_continues():
    fleet = _Fleet()
    fleet.extend([_FakeHandle("r0", fleet),
                  _FakeHandle("r1", fleet, fail=True),
                  _FakeHandle("r2", fleet)])
    seen = []
    done = staggered_reload(fleet, step=5,
                            reloaded=lambda h, ok: seen.append(
                                (h.name, ok)),
                            sleep=lambda _s: None)
    assert done == 2
    assert seen == [("r0", True), ("r1", False), ("r2", True)]
    # The failed handle keeps serving its previous step — no outage.
    assert fleet[1].ready and fleet[1].step == 0


def test_staggered_reload_timeout_reloads_anyway():
    """A fleet whose OTHER replicas never come ready must not wedge
    forever serving stale state: past the wait budget the stagger
    logs and reloads anyway."""
    fleet = _Fleet()
    fleet.extend([_FakeHandle("r0", fleet), _FakeHandle("r1", fleet)])
    fleet[1].ready = False
    fleet[1]._polls_left = 0  # never recovers on its own
    clock = [0.0]

    def tick(_s):
        clock[0] += 1.0

    done = staggered_reload([fleet[0]], step=3, min_other_ready=1,
                            wait_seconds=5.0, sleep=tick,
                            clock=lambda: clock[0])
    # r1 stayed down, yet r0 still got its reload after the budget.
    assert done in (0, 1)
    assert fleet[0].step == 3


# --- reload watcher jitter ----------------------------------------------


def test_reload_watcher_jitter_bounds_and_determinism():
    from fast_tffm_tpu.serve.reload import ReloadWatcher
    a = ReloadWatcher(None, poll_seconds=10.0, jitter=0.2, seed=4242,
                      auto_reload=False)
    waits = [a.next_wait() for _ in range(200)]
    assert all(8.0 <= w <= 12.0 for w in waits)
    assert len(set(round(w, 6) for w in waits)) > 1  # actually jitters
    b = ReloadWatcher(None, poll_seconds=10.0, jitter=0.2, seed=4242,
                      auto_reload=False)
    assert [b.next_wait() for _ in range(200)] == waits  # per-seed
    c = ReloadWatcher(None, poll_seconds=10.0, jitter=0.2, seed=4243,
                      auto_reload=False)
    assert [c.next_wait() for _ in range(200)] != waits  # decorrelates
    z = ReloadWatcher(None, poll_seconds=10.0, jitter=0.0, seed=1,
                      auto_reload=False)
    assert z.next_wait() == 10.0


# --- canary pointer ------------------------------------------------------


def test_canary_pointer_round_trip(tmp_path):
    from fast_tffm_tpu.checkpoint import (read_canary, read_pointer,
                                          write_canary)
    d = str(tmp_path)
    assert read_canary(d) is None
    path = write_canary(d, 42)
    assert os.path.basename(path) == "published-canary"
    assert read_canary(d) == 42
    assert read_pointer(d, "canary") == 42
    assert read_pointer(d, "published") is None  # independent pointers
    write_canary(d, 43)  # atomic repoint
    assert read_canary(d) == 43


# --- fmstat FLEET section ------------------------------------------------


def _fleet_metrics_file(tmp_path, ready, total):
    recs = [
        {"event": "run_start", "meta": {"mode": "serve-fleet"}},
        {"event": "metrics", "run": {"process_index": 0},
         "counters": {"proxy/requests": 120, "proxy/retries": 4,
                      "fleet/restarts": 1, "fleet/deaths": 1},
         "gauges": dict(
             {"fleet/replicas": total, "fleet/alive": total,
              "fleet/ready": ready},
             **{f"fleet/replica{i}_alive": 1.0 for i in range(total)},
             **{f"fleet/replica{i}_ready":
                float(i < ready) for i in range(total)},
             **{f"fleet/replica{i}_step": 40.0 for i in range(total)},
             **{f"fleet/replica{i}_queue_depth": 0.0
                for i in range(total)})},
        {"event": "run_end"},
    ]
    p = tmp_path / "fleet_metrics.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in recs))
    return str(p)


def test_fmstat_fleet_degraded_verdict(tmp_path):
    from fast_tffm_tpu.obs.attribution import (fleet_degraded,
                                               health_verdict,
                                               summarize)
    s = summarize([_fleet_metrics_file(tmp_path, ready=2, total=3)])
    assert fleet_degraded(s) == (2, 3)
    assert health_verdict(s)["verdict"] == "FLEET DEGRADED (2/3 ready)"


def test_fmstat_fleet_full_strength_is_ok_with_rows(tmp_path):
    from fast_tffm_tpu.obs.attribution import (fleet_degraded,
                                               fleet_table,
                                               health_verdict, render,
                                               summarize)
    s = summarize([_fleet_metrics_file(tmp_path, ready=3, total=3)])
    assert fleet_degraded(s) is None
    assert health_verdict(s)["verdict"] == "OK"
    rows = fleet_table(s)
    assert len(rows) == 3
    assert rows[0].startswith("r0: ready")
    text = render(s)
    assert "FLEET (serve --replicas)" in text
    assert "r2:" in text
