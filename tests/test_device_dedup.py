"""dedup=device (raw-ids) mode: the pipeline ships raw feature ids and
the jitted step runs jnp.unique on device — must be bit-equivalent to
the host-dedup path and wired end-to-end through the CLI."""

import dataclasses
import os
import subprocess
import sys

import numpy as np
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import batch_iterator
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args, init_accumulator,
                                     init_table, make_score_fn,
                                     make_train_step)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write(tmp_path, n=96, seed=5, ffm=False, field_num=4):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        nnz = rng.integers(1, 12)
        ids = rng.choice(300, size=nnz, replace=False)
        if ffm:
            toks = [f"{int(rng.integers(0, field_num))}:{i}:"
                    f"{rng.random():.4f}" for i in ids]
        else:
            toks = [f"{i}:{rng.random():.4f}" for i in ids]
        lines.append(" ".join(["1" if rng.random() < 0.4 else "0"] + toks))
    p = tmp_path / "d.txt"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _cfg(path, **kw):
    base = dict(vocabulary_size=300, factor_num=4, batch_size=16,
                train_files=(path,), shuffle=False,
                bucket_ladder=(4, 8, 16), max_features_per_example=16,
                learning_rate=0.1, factor_lambda=1e-4, bias_lambda=1e-4)
    base.update(kw)
    return FmConfig(**base)


def _train_all(cfg, spec, raw):
    table, acc = init_table(cfg, 0), init_accumulator(cfg)
    step = make_train_step(spec)
    losses = []
    for b in batch_iterator(cfg, cfg.train_files, training=True,
                            raw_ids=raw):
        table, acc, loss, scores = step(table, acc, **batch_args(b))
        losses.append(float(loss))
    return np.asarray(table), np.asarray(acc), losses


def test_device_dedup_matches_host(tmp_path):
    """Same data, host- vs device-side unique: identical losses, table,
    and accumulator (the unique pass location must be invisible)."""
    path = _write(tmp_path)
    cfg = _cfg(path)
    host = _train_all(cfg, dataclasses.replace(
        ModelSpec.from_config(cfg), dedup="host"), raw=False)
    dev_spec = dataclasses.replace(ModelSpec.from_config(cfg),
                                   dedup="device")
    dev = _train_all(cfg, dev_spec, raw=True)
    np.testing.assert_allclose(dev[2], host[2], rtol=1e-6)
    np.testing.assert_allclose(dev[0], host[0], rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(dev[1], host[1], rtol=1e-6, atol=1e-7)


def test_device_dedup_ffm_matches_host(tmp_path):
    """FFM raw-ids mode: fields ride along unchanged."""
    path = _write(tmp_path, ffm=True)
    cfg = _cfg(path, model_type="ffm", field_num=4)
    host = _train_all(cfg, dataclasses.replace(
        ModelSpec.from_config(cfg), dedup="host"), raw=False)
    dev_spec = dataclasses.replace(ModelSpec.from_config(cfg),
                                   dedup="device")
    dev = _train_all(cfg, dev_spec, raw=True)
    np.testing.assert_allclose(dev[2], host[2], rtol=1e-6)
    np.testing.assert_allclose(dev[0], host[0], rtol=1e-6, atol=1e-7)


def test_device_dedup_score_parity(tmp_path):
    path = _write(tmp_path, seed=9)
    cfg = _cfg(path)
    table = init_table(cfg, 3)
    spec_h = dataclasses.replace(ModelSpec.from_config(cfg), dedup="host")
    spec_d = dataclasses.replace(spec_h, dedup="device")
    sh, sd = [], []
    for raw, spec, out in ((False, spec_h, sh), (True, spec_d, sd)):
        fn = make_score_fn(spec)
        for b in batch_iterator(cfg, cfg.train_files, training=False,
                                raw_ids=raw):
            args = batch_args(b)
            args.pop("labels"), args.pop("weights")
            out.append(np.asarray(fn(table, **args))[:b.num_real])
    np.testing.assert_allclose(np.concatenate(sd), np.concatenate(sh),
                               rtol=1e-5, atol=1e-7)


def test_raw_batches_reconstruct_host_stream(tmp_path):
    """The raw-ids pipeline (C++ builder with dedup skipped) must carry
    exactly the ids the host-dedup pipeline encodes via uniq[li]."""
    path = _write(tmp_path, seed=11)
    cfg = _cfg(path)
    host = list(batch_iterator(cfg, cfg.train_files, training=True))
    raw = list(batch_iterator(cfg, cfg.train_files, training=True,
                              raw_ids=True))
    assert len(host) == len(raw)
    for h, r in zip(host, raw):
        assert r.uniq_ids is None
        want = np.asarray(h.uniq_ids)[h.local_idx]  # decode slot -> id
        np.testing.assert_array_equal(r.local_idx, want)
        np.testing.assert_array_equal(r.vals, h.vals)
        np.testing.assert_array_equal(r.labels, h.labels)


def test_mode_mismatch_raises(tmp_path):
    """A host-deduped batch into a device-dedup step must fail loudly at
    trace time — slot indices silently read as feature ids is the
    corruption this guard exists for."""
    import pytest
    path = _write(tmp_path, seed=13)
    cfg = _cfg(path)
    spec_d = dataclasses.replace(ModelSpec.from_config(cfg),
                                 dedup="device")
    step = make_train_step(spec_d)
    b = next(batch_iterator(cfg, cfg.train_files, training=True))
    with pytest.raises(ValueError, match="raw_ids"):
        step(init_table(cfg, 0), init_accumulator(cfg), **batch_args(b))
    with pytest.raises(ValueError, match="fixed-U"):
        next(batch_iterator(cfg, cfg.train_files, training=True,
                            raw_ids=True, fixed_shape=True))


def test_cli_e2e_device_dedup_auto(tmp_path):
    """On a single device, dedup=auto resolves to device mode; the full
    CLI train->predict must work and produce sane scores (run in a
    subprocess with exactly one CPU device — the in-process test env
    pins 8 virtual devices, which resolves auto to host)."""
    path = _write(tmp_path, n=64, seed=17)
    cfg_path = tmp_path / "dd.cfg"
    cfg_path.write_text(f"""
[General]
vocabulary_size = 300
factor_num = 4
model_file = {tmp_path}/model/fm

[Train]
train_files = {path}
epoch_num = 2
batch_size = 16
learning_rate = 0.1
shuffle = False

[Predict]
predict_files = {path}
score_path = {tmp_path}/score
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    code = (
        "import jax, numpy as np, run_tffm\n"
        "from fast_tffm_tpu.config import load_config\n"
        "from fast_tffm_tpu.models.fm import ModelSpec\n"
        "assert jax.device_count() == 1, jax.device_count()\n"
        f"cfg = load_config(r'{cfg_path}')\n"
        "assert ModelSpec.from_config(cfg).dedup == 'device'\n"
        f"assert run_tffm.main(['train', r'{cfg_path}']) == 0\n"
        f"assert run_tffm.main(['predict', r'{cfg_path}']) == 0\n"
    )
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    scores = np.loadtxt(tmp_path / "score" / "d.txt.score")
    assert len(scores) == 64
    assert np.isfinite(scores).all() and (0 <= scores).all() \
        and (scores <= 1).all()


def test_checkpoint_crosses_dedup_modes(tmp_path):
    """A checkpoint is mode-free state: training saved under dedup=host
    must resume under dedup=device with the identical continued
    trajectory — the unique-pass location cannot leak into persistence.
    Runs in a 1-CPU-device subprocess (dedup=device is single-device;
    the in-process env pins 8)."""
    path = _write(tmp_path, n=64, seed=21)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    code = f"""
import shutil
import numpy as np
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.train import train

def cfg_for(dedup, epochs, model):
    return FmConfig(vocabulary_size=300, factor_num=4, batch_size=16,
                    train_files=(r'{path}',), shuffle=False,
                    bucket_ladder=(4, 8, 16),
                    max_features_per_example=16, learning_rate=0.1,
                    epoch_num=epochs, dedup=dedup,
                    model_file=r'{tmp_path}' + '/' + model + '/fm')

import logging
records = []
class Grab(logging.Handler):
    def emit(self, r):
        records.append(r.getMessage())
_lg = logging.getLogger('fast_tffm_tpu')
_lg.addHandler(Grab())
_lg.setLevel(logging.INFO)  # get_logger skips setup once handlers exist

train(cfg_for('host', 1, 'a'))
shutil.copytree(r'{tmp_path}/a', r'{tmp_path}/b')
t_host = np.asarray(train(cfg_for('host', 3, 'a')))
t_dev = np.asarray(train(cfg_for('device', 3, 'b')))
# Guard against vacuous success: both resumed runs must actually have
# RESTORED (a fresh-start pair would also match, trivially).
restores = [m for m in records if m.startswith('restored checkpoint')]
assert len(restores) == 2, records
np.testing.assert_allclose(t_dev, t_host, rtol=1e-6, atol=1e-7)
print('cross-mode resume ok')
"""
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "cross-mode resume ok" in out.stdout
