"""Golden MurmurHash64A values — pin the hash forever.

If these change, every trained model's row assignment silently shifts
(SURVEY §7 hard part #5), so they are locked to explicit constants.
"""

from fast_tffm_tpu.data.hashing import hash_feature, murmur64


# Self-consistent goldens computed once from the reference Python
# implementation of MurmurHash64A (seed 0) and frozen.
GOLDENS = {
    b"": 0x0000000000000000,
    b"a": 0x071717D2D36B6B11,
    b"ab": 0x62BE85B2FE53D1F8,
    b"abc": 0x9CC9C33498A95EFB,
    b"abcdefgh": 0xAFDB0257FF41AA98,
    b"abcdefghi": 0xC9B9D84356146AC2,
    b"1234567890abcdef": 0xE087B8DB03D15846,
    b"feature:42": 0x98D61945C6B545B2,
}


def test_empty():
    assert murmur64(b"") == 0


def test_mixing_and_determinism():
    seen = set()
    for s in [b"", b"a", b"b", b"aa", b"ab", b"ba", b"feature_1",
              b"feature_2", b"x" * 100]:
        h = murmur64(s)
        assert 0 <= h < (1 << 64)
        assert h == murmur64(s)
        seen.add(h)
    assert len(seen) == 9  # no collisions among these


def test_goldens_locked():
    for data, expect in GOLDENS.items():
        got = murmur64(data)
        assert got == expect, (
            f"murmur64({data!r}) = {got:#018x}, expected {expect:#018x} — "
            "the hash changed; this breaks every existing model!")


def test_hash_feature_range():
    for v in (1, 7, 1000, 10**9):
        for s in ("a", "b", "click_id=123", ""):
            assert 0 <= hash_feature(s, v) < v


def test_distribution_roughly_uniform():
    import numpy as np
    n, buckets = 20000, 16
    counts = np.zeros(buckets)
    for i in range(n):
        counts[hash_feature(f"feat_{i}", buckets)] += 1
    assert counts.min() > n / buckets * 0.8
    assert counts.max() < n / buckets * 1.2
