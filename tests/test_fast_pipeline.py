"""C++ chunked fast path == generic per-line path.

Same files, shuffle off -> the two pipelines must yield the same example
stream (labels, per-example feature multisets) and identical model
behavior, even though their internal padding conventions differ (fast
path pads unique slot 0, generic pads the last slot).
"""

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import batch_iterator
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args, init_accumulator,
                                     init_table, make_train_step)


def _write(tmp_path, n=200, seed=1, trailing_newline=True, name="d.txt"):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        nnz = rng.integers(1, 14)
        ids = rng.choice(300, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.4 else "0"]
                              + [f"{i}:{rng.random():.4f}" for i in ids]))
    p = tmp_path / name
    p.write_text("\n".join(lines) + ("\n" if trailing_newline else ""))
    return str(p)


def _cfg(path, **kw):
    base = dict(vocabulary_size=300, factor_num=4, batch_size=16,
                train_files=(path,), shuffle=False,
                bucket_ladder=(4, 8, 16), max_features_per_example=16)
    base.update(kw)
    return FmConfig(**base)


def _example_stream(cfg, **kw):
    out = []
    for b in batch_iterator(cfg, cfg.train_files, training=True, **kw):
        for e in range(b.num_real):
            feats = []
            for j in range(b.local_idx.shape[1]):
                fid = int(b.uniq_ids[b.local_idx[e, j]])
                v = float(b.vals[e, j])
                fld = int(b.fields[e, j]) if b.fields is not None else 0
                if fid < cfg.vocabulary_size and v != 0.0:
                    feats.append((fid, fld, round(v, 6)))
            out.append((float(b.labels[e]), tuple(sorted(feats))))
    return out


def test_fast_matches_generic_stream(tmp_path):
    path = _write(tmp_path)
    cfg = _cfg(path)
    fast = _example_stream(cfg)
    # weight_files force the generic per-line path; weights of 1.0 keep
    # semantics identical.
    wpath = tmp_path / "w.txt"
    wpath.write_text("1.0\n" * 300)
    generic = _example_stream(cfg, weight_files=(str(wpath),))
    assert fast == generic
    assert len(fast) == 200


def test_fast_handles_missing_trailing_newline(tmp_path):
    path = _write(tmp_path, n=37, seed=3, trailing_newline=False)
    cfg = _cfg(path)
    stream = _example_stream(cfg)
    assert len(stream) == 37


def test_fast_multi_file_and_epochs(tmp_path):
    p1 = _write(tmp_path, n=23, seed=5)
    p2 = _write(tmp_path, n=10, seed=6, name="e.txt")
    cfg = _cfg(p1)
    stream = _example_stream(
        FmConfig(**{**cfg.__dict__,
                    "train_files": (p1, p2)}), epochs=2)
    assert len(stream) == 2 * 33


def test_fast_training_matches_generic_losses(tmp_path):
    path = _write(tmp_path, n=128, seed=7)
    cfg = _cfg(path)
    spec = ModelSpec.from_config(cfg)
    wpath = tmp_path / "w.txt"
    wpath.write_text("1.0\n" * 128)
    losses = {}
    for name, kw in [("fast", {}),
                     ("generic", {"weight_files": (str(wpath),)})]:
        table, acc = init_table(cfg, 0), init_accumulator(cfg)
        step = make_train_step(spec)
        ls = []
        for b in batch_iterator(cfg, cfg.train_files, training=True, **kw):
            table, acc, loss, _ = step(table, acc, **batch_args(b))
            ls.append(float(loss))
        losses[name] = ls
    np.testing.assert_allclose(losses["fast"], losses["generic"],
                               rtol=1e-6, atol=1e-7)


def _write_ffm(tmp_path, n=120, seed=2, field_num=5, name="ffm.txt"):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        nnz = rng.integers(1, 10)
        ids = rng.choice(300, size=nnz, replace=False)
        toks = [f"{int(rng.integers(0, field_num))}:{i}:{rng.random():.4f}"
                for i in ids]
        lines.append(" ".join(["1" if rng.random() < 0.4 else "0"] + toks))
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_ffm_fast_matches_generic_stream(tmp_path):
    """FFM rides the C++ BatchBuilder now (field-aware tokens); the
    stream — including per-feature fields — must match the generic
    Python-parser path exactly."""
    path = _write_ffm(tmp_path)
    cfg = _cfg(path, model_type="ffm", field_num=5)
    fast = _example_stream(cfg)
    wpath = tmp_path / "w.txt"
    wpath.write_text("1.0\n" * 300)
    generic = _example_stream(cfg, weight_files=(str(wpath),))
    assert fast == generic
    assert len(fast) == 120
    assert any(f[1] != 0 for _, feats in fast for f in feats)


def test_ffm_fast_training_matches_generic_losses(tmp_path):
    path = _write_ffm(tmp_path, n=64, seed=8)
    cfg = _cfg(path, model_type="ffm", field_num=5)
    spec = ModelSpec.from_config(cfg)
    wpath = tmp_path / "w.txt"
    wpath.write_text("1.0\n" * 64)
    losses = {}
    for name, kw in [("fast", {}),
                     ("generic", {"weight_files": (str(wpath),)})]:
        table, acc = init_table(cfg, 0), init_accumulator(cfg)
        step = make_train_step(spec)
        ls = []
        for b in batch_iterator(cfg, cfg.train_files, training=True, **kw):
            table, acc, loss, _ = step(table, acc, **batch_args(b))
            ls.append(float(loss))
        losses[name] = ls
    np.testing.assert_allclose(losses["fast"], losses["generic"],
                               rtol=1e-6, atol=1e-7)


def test_fast_shuffle_complete_and_deterministic(tmp_path):
    path = _write(tmp_path, n=100, seed=9)
    cfg = _cfg(path, shuffle=True, queue_size=32, seed=11)
    a = sorted(_example_stream(cfg))
    b = sorted(_example_stream(cfg))
    c = sorted(_example_stream(_cfg(path)))
    assert a == b == c  # complete coverage, deterministic given seed


def test_shuffle_mixes_file_order_per_epoch(tmp_path):
    """With shuffle on, file visit order reshuffles per epoch (the
    reference's filename-queue behavior) from a dedicated (seed, epoch)
    rng — independent of shard-local stream-rng state, deterministic,
    and actually varying across epochs."""
    from fast_tffm_tpu.data.pipeline import epoch_file_order
    files = [f"f{i}" for i in range(4)]
    orders = [tuple(epoch_file_order(files, True, seed=3, epoch=e))
              for e in range(8)]
    assert len(set(orders)) > 1                # varies across epochs
    assert all(sorted(o) == sorted(files) for o in orders)
    # Deterministic per (seed, epoch): what multi-process lockstep needs.
    assert orders[5] == tuple(epoch_file_order(files, True, 3, 5))
    assert tuple(epoch_file_order(files, False, 3, 5)) == tuple(files)

    # Integration: epoch 0's stream leads with whichever file the
    # (seed=7, epoch=0) order puts first — distinct labels per file make
    # the order observable in the emitted batches.
    a = tmp_path / "a.txt"
    a.write_text("\n".join("0 1:1" for _ in range(40)) + "\n")
    b = tmp_path / "b.txt"
    b.write_text("\n".join("1 2:1" for _ in range(40)) + "\n")
    cfg = _cfg(str(a), train_files=(str(a), str(b)), shuffle=True,
               queue_size=8, seed=0, batch_size=8)
    first = next(batch_iterator(cfg, cfg.train_files, training=True,
                                epochs=1, seed=7))
    lead = epoch_file_order([str(a), str(b)], True, 7, 0)[0]
    want = 0.0 if lead == str(a) else 1.0
    # queue_size 8 <= one batch window: the first batch is drawn from
    # the leading file only.
    assert set(first.labels[:first.num_real].tolist()) == {want}
