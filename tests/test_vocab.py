"""Unbounded-vocabulary admission (fast_tffm_tpu/vocab/; README
"Unbounded vocabulary"): count-min sketch properties, the remap seam's
batch invariants, barrier admission/eviction determinism, sidecar
payload round-trips, the fixed-mode parity pin, and the acceptance
run — admit-mode AUC strictly beats plain modulo collisions on a
heavy-tailed corpus whose distinct-id count exceeds the table 10x.
"""

import dataclasses
import gzip
import json
import os
import re
from types import SimpleNamespace

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.vocab.sketch import HASH_SPACE, CountMinSketch
from fast_tffm_tpu.vocab.table import (COLD_ROW, RESET_CHUNK, VocabMap,
                                       VocabRuntime, payload_crc_ok)


# --- sketch properties ---------------------------------------------------


def test_sketch_no_false_negative():
    """The count-min estimate NEVER undercounts: an id observed k times
    estimates >= k, whatever else collided into its cells."""
    sk = CountMinSketch(width=256, depth=4)
    rng = np.random.default_rng(0)
    truth = {}
    for _ in range(50):
        ids = rng.integers(0, HASH_SPACE, size=rng.integers(1, 40))
        sk.observe(np.unique(ids))
        for i in np.unique(ids).tolist():
            truth[i] = truth.get(i, 0) + 1
    keys = np.fromiter(truth.keys(), np.int64, len(truth))
    est = sk.estimate(keys)
    true = np.asarray([truth[int(k)] for k in keys], np.float32)
    assert (est >= true).all(), "count-min undercounted an observed id"


def test_sketch_bounded_overestimate():
    """Overestimate is bounded by colliding mass per row (~n/width in
    expectation) — pinned empirically on a fixed id set (the hashing is
    constant-multiplier, so this is deterministic) — and shrinks as the
    configured width grows."""
    rng = np.random.default_rng(7)
    ids = np.unique(rng.integers(0, 1 << 30, size=2000))
    over = {}
    for w in (1024, 4096):
        sk = CountMinSketch(width=w, depth=4)
        sk.observe(ids)
        over[w] = sk.estimate(ids) - 1.0
        assert (over[w] >= 0).all()
    assert over[1024].max() <= 6.0, over[1024].max()
    assert over[1024].mean() <= 1.0, over[1024].mean()
    assert over[4096].sum() < over[1024].sum(), (
        "4x the width did not reduce total overestimate")


def test_sketch_decay_monotone():
    """No estimate ever grows from a decay; factor 1.0 is a no-op;
    out-of-range factors are rejected."""
    sk = CountMinSketch(width=128, depth=2)
    ids = np.arange(50, dtype=np.int64) * 977 + 13
    sk.observe(ids, count=4.0)
    before = sk.estimate(ids)
    sk.decay(1.0)
    assert (sk.estimate(ids) == before).all()
    for _ in range(5):
        prev = sk.estimate(ids)
        sk.decay(0.5)
        cur = sk.estimate(ids)
        assert (cur <= prev).all()
        assert (cur == prev * np.float32(0.5)).all()
    for bad in (0.0, -0.5, 1.5):
        with pytest.raises(ValueError):
            sk.decay(bad)


def test_sketch_state_round_trip_exact():
    """state() -> from_state() is bit-exact — including through a JSON
    encode/decode, which is how the payload actually travels inside the
    vocab-<step>.json.gz sidecar."""
    sk = CountMinSketch(width=64, depth=3)
    sk.observe(np.array([3, 99, HASH_SPACE - 1], np.int64), count=2.5)
    sk.decay(0.7)
    state = json.loads(json.dumps(sk.state()))
    back = CountMinSketch.from_state(state)
    assert back.width == sk.width and back.depth == sk.depth
    assert back.counts.tobytes() == sk.counts.tobytes()


def test_sketch_constructor_bounds():
    with pytest.raises(ValueError):
        CountMinSketch(width=32)
    with pytest.raises(ValueError):
        CountMinSketch(width=64, depth=0)
    with pytest.raises(ValueError):
        CountMinSketch(width=64, depth=7)
    assert CountMinSketch.from_mb(1.0, depth=4).width == (1 << 20) // 16


# --- slot map / remap seam -----------------------------------------------


def _runtime(capacity=8, threshold=2.0, decay=0.5):
    return VocabRuntime(capacity, pad_id=capacity, threshold=threshold,
                        decay=decay, sketch=CountMinSketch(width=256))


def _admit(rt, ids, batches=4):
    """Observe ``ids`` in ``batches`` stepped batches, then barrier."""
    ids = np.asarray(ids, np.int64)
    for _ in range(batches):
        rt.note_trained(SimpleNamespace(vocab_obs=ids))
    return rt.barrier(None)


def _decay_barrier(rt, reset_rows=None):
    """A REAL barrier (one decay tick) without touching the ids under
    test: barriers with nothing trained behind them are no-ops (the
    stream is the clock), so aging out an id takes a throwaway
    observation per tick — exactly what a live stream provides."""
    rt.note_trained(SimpleNamespace(
        vocab_obs=np.array([999_999_937], np.int64)))
    return rt.barrier(reset_rows)


def test_lookup_cold_until_admitted():
    rt = _runtime()
    ids = np.array([11, 22, 33], np.int64)
    assert (rt.lookup(ids) == COLD_ROW).all()
    st = _admit(rt, ids)
    assert st["admitted"] == 3 and st["live"] == 3
    rows = rt.lookup(ids)
    assert len(set(rows.tolist())) == 3
    assert (rows >= 1).all() and (rows < 8).all()
    # Unseen ids and the hash-space pad sentinel keep their routes.
    assert rt.lookup(np.array([44], np.int64))[0] == COLD_ROW
    assert rt.lookup(np.array([HASH_SPACE], np.int64))[0] == rt.pad_id


def test_remap_host_dedup_invariants():
    """The remap seam's contract on host-deduped batches: same shapes,
    uniq slots unique among real rows, pad fill holds pad_id, the last
    slot is padding, and every cell still routes to its id's row."""
    rt = _runtime(capacity=8)
    _admit(rt, [100, 200])
    # uniq: 2 admitted, 2 unadmitted (collapse to one cold slot), pad x2
    orig_uniq = np.array([100, 300, 200, 400, HASH_SPACE, HASH_SPACE],
                         np.int64)
    local_idx = np.array([[0, 1, 4], [2, 3, 4]], np.int32)
    batch = SimpleNamespace(uniq_ids=orig_uniq.copy(),
                            local_idx=local_idx.copy())
    out = rt.remap(batch)
    assert out is batch
    assert batch.uniq_ids.shape == orig_uniq.shape
    assert batch.local_idx.shape == local_idx.shape
    real = batch.uniq_ids != rt.pad_id
    assert len(np.unique(batch.uniq_ids[real])) == int(real.sum())
    assert batch.uniq_ids[-1] == rt.pad_id, "last slot must stay padding"
    # Cell-level routing equals the scalar lookup of the original ids.
    want = rt.lookup(orig_uniq[local_idx])
    got = batch.uniq_ids[batch.local_idx]
    assert (got == want).all()
    # vocab_obs carries the distinct REAL hashed ids for note_trained.
    assert sorted(batch.vocab_obs.tolist()) == [100, 200, 300, 400]


def test_remap_raw_ids_batch():
    """dedup=device batches (uniq_ids None) remap cellwise."""
    rt = _runtime(capacity=8)
    _admit(rt, [7])
    cells = np.array([[7, 5, HASH_SPACE]], np.int64)
    batch = SimpleNamespace(uniq_ids=None, local_idx=cells.copy())
    rt.remap(batch)
    assert batch.local_idx[0, 0] == rt.lookup(np.array([7]))[0] != COLD_ROW
    assert batch.local_idx[0, 1] == COLD_ROW
    assert batch.local_idx[0, 2] == rt.pad_id
    assert sorted(batch.vocab_obs.tolist()) == [5, 7]


def test_barrier_admits_at_documented_threshold():
    """An id appearing in EXACTLY vocab_admit_threshold batches is
    admitted at the next barrier: the re-check compares against the
    decay-scaled floor, so the barrier's own decay doesn't silently
    raise the effective admission rate to threshold/decay."""
    for decay in (0.25, 0.5, 1.0):
        rt = _runtime(capacity=8, threshold=2.0, decay=decay)
        st = _admit(rt, [70, 80], batches=2)  # the documented floor
        assert st["admitted"] == 2, (decay, st)


def test_barrier_admits_hottest_first_and_bounds_table():
    """More threshold-crossing candidates than rows: the hottest win,
    the table never exceeds capacity - 1 live rows."""
    rt = _runtime(capacity=4)  # 3 live rows
    hot = np.array([1, 2, 3], np.int64)
    warm = np.array([4, 5], np.int64)
    for _ in range(5):
        rt.note_trained(SimpleNamespace(vocab_obs=hot))
    for _ in range(2):
        rt.note_trained(SimpleNamespace(vocab_obs=warm))
    st = rt.barrier(None)
    assert st["admitted"] == 3 and st["free"] == 0
    assert rt.live_rows == 3
    assert (rt.lookup(hot) != COLD_ROW).all()
    assert (rt.lookup(warm) == COLD_ROW).all()


def test_barrier_evicts_decayed_rows_and_resets_them():
    """An id that stops appearing decays below the floor and is
    evicted: its row lands in the reset hook (cold-start), returns to
    the free list, and a later admission reuses it."""
    rt = _runtime(capacity=4, threshold=2.0, decay=0.25)
    _admit(rt, [10, 20], batches=8)  # est 8 -> decayed 2.0, admitted
    assert rt.live_rows == 2
    old_rows = set(rt.lookup(np.array([10, 20], np.int64)).tolist())
    freed = []
    for _ in range(8):
        if not rt.live_rows:
            break
        _decay_barrier(rt, lambda rows: freed.extend(rows.tolist()))
    assert rt.live_rows == 0
    assert set(freed) == old_rows, (freed, old_rows)
    assert (rt.lookup(np.array([10, 20], np.int64)) == COLD_ROW).all()
    st = _admit(rt, [30, 40, 50], batches=8)
    assert st["admitted"] == 3
    new_rows = rt.lookup(np.array([30, 40, 50], np.int64))
    assert old_rows <= set(new_rows.tolist()), "freed rows not reused"


def test_barrier_deterministic_given_stream():
    """Two runtimes fed the identical observation stream freeze the
    identical slot map — the property that makes a checkpoint replay
    land on the same rows."""
    streams = [np.array([5, 6], np.int64), np.array([6, 7, 8], np.int64),
               np.array([5, 8], np.int64)]
    maps = []
    for _ in range(2):
        rt = _runtime(capacity=6)
        for ids in streams:
            rt.note_trained(SimpleNamespace(vocab_obs=ids))
        rt.barrier(None)
        maps.append(rt._frozen)
    assert (maps[0][0] == maps[1][0]).all()
    assert (maps[0][1] == maps[1][1]).all()


def test_candidate_buffer_bounded_and_deduped():
    """An adversarial flood of threshold-crossers can't grow the
    candidate buffer past its cap — and an ever-present hot id queues
    ONCE per interval, not once per batch (duplicates would exhaust
    the cap and spuriously drop late crossers)."""
    rt = _runtime(capacity=4)
    flood = np.arange(1000, dtype=np.int64)
    for _ in range(3):
        rt.note_trained(SimpleNamespace(vocab_obs=flood))
    assert rt._cand_len <= rt._candidate_cap
    rt2 = _runtime(capacity=1024)
    hot = np.arange(5, dtype=np.int64)
    for _ in range(10):
        rt2.note_trained(SimpleNamespace(vocab_obs=hot))
    assert rt2._cand_len == 5, rt2._cand_len  # queued exactly once
    rt2.barrier(None)  # barrier clears the membership set too
    assert not rt2._queued
    # Re-crossing after eviction re-queues (membership is per
    # interval, not per lifetime).
    for _ in range(12):
        if not rt2.live_rows:
            break
        _decay_barrier(rt2)
    assert rt2.live_rows == 0
    for _ in range(10):
        rt2.note_trained(SimpleNamespace(vocab_obs=hot))
    assert rt2._cand_len == 5


# --- payload / durability ------------------------------------------------


def test_payload_round_trip_bit_exact():
    rt = _runtime(capacity=8)
    _admit(rt, [100, 200, 300])
    rt.barrier(None)  # a decay pass too
    payload = rt.state_payload()
    assert payload_crc_ok(payload)
    cfg = FmConfig(vocabulary_size=8, train_files=("x",))
    back = VocabRuntime(8, pad_id=8, threshold=2.0, decay=0.5,
                        sketch=CountMinSketch(width=256))
    back.load(cfg, json.loads(json.dumps(payload)))
    assert back.state_payload() == payload
    ids = np.array([100, 200, 300, 400, HASH_SPACE], np.int64)
    assert (back.lookup(ids) == rt.lookup(ids)).all()


def test_payload_rejects_tampering_and_mismatch():
    rt = _runtime(capacity=8)
    _admit(rt, [100])
    payload = rt.state_payload()
    cfg = FmConfig(vocabulary_size=8, train_files=("x",))
    torn = json.loads(json.dumps(payload))
    torn["state"]["total_admitted"] = 999
    assert not payload_crc_ok(torn)
    with pytest.raises(ValueError, match="crc32"):
        VocabMap.from_payload(cfg, torn)
    wrong = FmConfig(vocabulary_size=16, train_files=("x",))
    with pytest.raises(ValueError, match="vocabulary_size"):
        VocabMap.from_payload(wrong, payload)


def test_vocab_map_is_inference_half():
    """VocabMap.from_payload reproduces the runtime's frozen routing
    without the sketch — what predict/serve load from the sidecar."""
    rt = _runtime(capacity=8)
    _admit(rt, [100, 200])
    cfg = FmConfig(vocabulary_size=8, train_files=("x",))
    vm = VocabMap.from_payload(cfg, rt.state_payload())
    assert vm.live_rows == rt.live_rows
    ids = np.array([100, 150, 200, HASH_SPACE], np.int64)
    # cfg.pad_id is vocabulary_size (8) == the runtime's pad here
    assert (vm.lookup(ids) == rt.lookup(ids)).all()


def test_reset_table_rows_cold_starts_only_the_given_rows():
    import jax.numpy as jnp
    from fast_tffm_tpu.vocab.table import reset_table_rows
    V, D = 10, 3
    table = jnp.asarray(np.arange(V * D, dtype=np.float32).reshape(V, D)
                        + 1.0)
    acc = jnp.full((V, D), 7.0, jnp.float32)
    before = np.asarray(table).copy()
    rows = np.array([2, 5], np.int32)
    table2, acc2 = reset_table_rows(table, acc, rows, pad_row=V - 1,
                                    adagrad_init=0.1)
    t2, a2 = np.asarray(table2), np.asarray(acc2)
    assert (t2[rows] == 0.0).all()
    assert (a2[rows] == np.float32(0.1)).all()
    untouched = [r for r in range(V - 1) if r not in rows.tolist()]
    assert (t2[untouched] == before[untouched]).all()
    assert (a2[untouched] == 7.0).all()
    assert len(rows) < RESET_CHUNK  # exercised the pad-to-chunk path


def test_backend_reset_rows_hook():
    """The lookup backends' half of the eviction seam."""
    from fast_tffm_tpu.lookup import HostOffloadLookup
    cfg = FmConfig(vocabulary_size=16, factor_num=2,
                   train_files=("x",), lookup="host")
    lk = HostOffloadLookup(cfg, seed=0)
    lk.table[:] = 3.0
    lk.acc[:] = 9.0
    lk.reset_rows(np.array([1, 4], np.int32), adagrad_init=0.5)
    assert (lk.table[[1, 4]] == 0.0).all()
    assert (lk.acc[[1, 4]] == np.float32(0.5)).all()
    assert (lk.table[2] == 3.0).all()


# --- fixed-mode parity + the acceptance run ------------------------------


def _write_corpus(path, rng, n_lines, n_tail, informative=8,
                  tail_repeats=10):
    """Heavy-tailed hashed-string corpus: the label is decided by ONE
    of ``informative`` hot ids per line; ``n_tail`` distinct tail ids
    appear ~``tail_repeats`` times each with random labels — pure
    collision noise for a modulo table."""
    tails = [f"tail{i}" for i in range(n_tail)]
    lines = []
    for k in range(n_lines):
        y = k % 2
        hot = f"hot{(k % informative) // 2 * 2 + y}"
        noise = " ".join(
            f"{tails[int(rng.integers(0, n_tail))]}:0.5"
            for _ in range(max(1, tail_repeats * n_tail // n_lines)))
        lines.append(f"{y} {hot}:1 {noise}")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return {f"hot{i}" for i in range(informative)} | set(tails)


def _base_cfg(tmp_path, name, **overrides):
    base = dict(
        vocabulary_size=16, factor_num=4, batch_size=32, epoch_num=4,
        learning_rate=0.1, init_value_range=0.01, shuffle=False, seed=3,
        log_steps=0, hash_feature_id=True,
        train_files=(str(tmp_path / "train.txt"),),
        validation_files=(str(tmp_path / "val.txt"),),
        model_file=str(tmp_path / name / "model" / "fm"),
        log_file=str(tmp_path / name / "fm.log"))
    base.update(overrides)
    return FmConfig(**base)


def test_fixed_mode_parity_and_admit_beats_modulo(tmp_path, rng):
    """The PR's two acceptance pins in one corpus:

    1. ``vocab_mode = fixed`` (the default) is BIT-IDENTICAL to the
       pre-vocab pipeline — the batch stream through the public
       batch_iterator equals the unwrapped historical iterator array
       for array, and a fixed-mode train leaves no vocab sidecar.
    2. With distinct hashed ids >= 10x vocabulary_size, admit-mode
       validation AUC strictly beats plain modulo collisions on the
       same corpus, while the slot map stays bounded by the table.
    """
    import dataclasses as dc

    from fast_tffm_tpu.data.pipeline import (_batch_iterator_impl,
                                             batch_iterator)
    from fast_tffm_tpu.train import train

    distinct = _write_corpus(tmp_path / "train.txt", rng, 600, 300)
    _write_corpus(tmp_path / "val.txt", rng, 200, 300)
    assert len(distinct) >= 10 * 16

    # -- parity pin: the wrapper with vocab=None IS the old pipeline --
    pcfg = _base_cfg(tmp_path, "parity")
    new = list(batch_iterator(pcfg, pcfg.train_files, training=True,
                              epochs=1))
    old = list(_batch_iterator_impl(pcfg, pcfg.train_files,
                                    training=True, epochs=1))
    assert len(new) == len(old) and len(new) > 0
    for a, b in zip(new, old):
        assert a.vocab_obs is None
        for f in dataclasses.fields(a):
            va, vb = getattr(a, f.name), getattr(b, f.name)
            if isinstance(va, np.ndarray):
                assert va.dtype == vb.dtype and (va == vb).all(), f.name
            else:
                assert va == vb, f.name

    def final_auc(cfg):
        assert train(cfg) is None or True
        log = open(cfg.log_file).read()
        m = re.findall(r"validation AUC (\d\.\d+)", log)
        assert m, "no validation AUC in the log"
        return float(m[-1])

    fixed_cfg = _base_cfg(tmp_path, "fixed")
    admit_cfg = _base_cfg(tmp_path, "admit", vocab_mode="admit",
                          vocab_admit_threshold=2.0, vocab_decay=0.5,
                          vocab_sketch_mb=0.25)
    auc_fixed = final_auc(fixed_cfg)
    auc_admit = final_auc(admit_cfg)

    # Fixed mode leaves no vocab sidecar; admit mode leaves a
    # crc-covered one, bounded by the table.
    fixed_dir = fixed_cfg.model_file + ".ckpt"
    assert not [n for n in os.listdir(fixed_dir)
                if n.startswith("vocab-")]
    admit_dir = admit_cfg.model_file + ".ckpt"
    sidecars = sorted(n for n in os.listdir(admit_dir)
                      if n.startswith("vocab-"))
    assert sidecars, os.listdir(admit_dir)
    with gzip.open(os.path.join(admit_dir, sidecars[-1]), "rt") as fh:
        payload = json.load(fh)
    assert payload_crc_ok(payload)
    vm = VocabMap.from_payload(admit_cfg, payload)
    assert 0 < vm.live_rows <= 16 - 1

    assert auc_admit > auc_fixed, (
        f"admit AUC {auc_admit} did not beat modulo collisions "
        f"{auc_fixed} with {len(distinct)} distinct ids in a 16-row "
        "table")
    assert auc_admit > 0.9, auc_admit


# `dataclasses` is imported at module scope for fields() above.


def test_ensure_current_remaps_stale_batches():
    """A batch remapped under generation G must be re-routed if a
    barrier moves the slot map before the batch is stepped — otherwise
    its gradients scatter into rows the barrier evicted, reset, or
    reassigned to other ids."""
    rt = _runtime(capacity=4, threshold=2.0, decay=0.25)
    _admit(rt, [10], batches=8)
    orig_uniq = np.array([10, 20, HASH_SPACE], np.int64)
    local_idx = np.array([[0, 1, 2]], np.int32)
    batch = SimpleNamespace(uniq_ids=orig_uniq.copy(),
                            local_idx=local_idx.copy())
    rt.remap(batch)
    # Same generation: one int compare, same object, untouched arrays.
    u_before = batch.uniq_ids
    assert rt.ensure_current(batch) is batch
    assert batch.uniq_ids is u_before
    # Barrier churn: 10 decays out, 30 takes over (and reuses the row).
    for _ in range(8):
        if not rt.live_rows:
            break
        _decay_barrier(rt)
    assert rt.live_rows == 0
    _admit(rt, [30], batches=8)
    assert rt.lookup(np.array([30], np.int64))[0] in (1, 2, 3)
    stale_cells = batch.uniq_ids[batch.local_idx]
    rt.ensure_current(batch)
    fresh_cells = batch.uniq_ids[batch.local_idx]
    want = rt.lookup(orig_uniq[local_idx])
    assert (fresh_cells == want).all()
    # The stale routing really was wrong (id 10's old private row).
    assert (stale_cells != fresh_cells).any()
    assert fresh_cells[0, 0] == COLD_ROW  # 10 is evicted now
    # Raw-ids batches carry their source too.
    raw = SimpleNamespace(uniq_ids=None,
                          local_idx=np.array([[30, 10]], np.int64))
    rt.remap(raw)
    _decay_barrier(rt)  # a real barrier moves the generation
    rt.ensure_current(raw)
    assert raw.vocab_gen == rt.generation


def test_eval_view_shares_routing_without_counting():
    """Validation sweeps remap through a telemetry-silent snapshot so
    held-out tails don't skew the training cold-hit rate."""
    rt = _runtime(capacity=8)
    _admit(rt, [100, 200])
    view = rt.eval_view()
    assert view.count_telemetry is False and rt.count_telemetry is True
    ids = np.array([100, 150, 200, HASH_SPACE], np.int64)
    assert (view.lookup(ids) == rt.lookup(ids)).all()


def test_stream_admit_requires_publish_interval(tmp_path):
    """run_mode = stream + vocab_mode = admit without publishing would
    never run a single barrier — nothing would ever be admitted."""
    with pytest.raises(ValueError, match="publish_interval_seconds"):
        FmConfig(vocabulary_size=16, train_files=(),
                 run_mode="stream", stream_dir=str(tmp_path),
                 vocab_mode="admit",
                 model_file=str(tmp_path / "m" / "fm"))
    # With an interval it is legal.
    FmConfig(vocabulary_size=16, train_files=(), run_mode="stream",
             stream_dir=str(tmp_path), vocab_mode="admit",
             publish_interval_seconds=1.0,
             model_file=str(tmp_path / "m" / "fm"))


def test_fixed_mode_refuses_admit_checkpoint(tmp_path, rng):
    """The loud-failure inverse of admit-without-sidecar: an
    admit-trained checkpoint loaded under vocab_mode = fixed would
    silently gather arbitrary rows — train resume AND predict must
    both refuse."""
    import dataclasses as dc

    from fast_tffm_tpu.predict import predict
    from fast_tffm_tpu.train import train

    _write_corpus(tmp_path / "train.txt", rng, 64, 30)
    admit_cfg = _base_cfg(tmp_path, "m", epoch_num=1,
                          validation_files=(), vocab_mode="admit",
                          vocab_admit_threshold=2.0)
    train(admit_cfg)
    fixed_cfg = dc.replace(admit_cfg, vocab_mode="fixed", epoch_num=2)
    with pytest.raises(ValueError, match="vocab admission sidecar"):
        train(fixed_cfg)
    pcfg = dc.replace(fixed_cfg,
                      predict_files=(str(tmp_path / "train.txt"),),
                      score_path=str(tmp_path / "score"))
    with pytest.raises(ValueError, match="vocab admission sidecar"):
        predict(pcfg)


def test_fresh_admission_over_restored_table_cold_starts_rows(
        tmp_path, rng):
    """A lost/garbled sidecar on an admit-mode resume starts admission
    fresh — and must also cold-start the assignable rows, or newly
    admitted ids would inherit the lost mapping's trained
    embeddings."""
    import dataclasses as dc

    from fast_tffm_tpu.checkpoint import vocab_sidecar_path
    from fast_tffm_tpu.train import train

    _write_corpus(tmp_path / "train.txt", rng, 64, 30)
    cfg = _base_cfg(tmp_path, "m", epoch_num=1, validation_files=(),
                    vocab_mode="admit", vocab_admit_threshold=2.0)
    train(cfg)
    directory = cfg.model_file + ".ckpt"
    sidecars = [n for n in os.listdir(directory)
                if n.startswith("vocab-")]
    assert sidecars
    for n in sidecars:
        os.remove(os.path.join(directory, n))
    train(dc.replace(cfg, epoch_num=2))
    log = open(cfg.log_file).read()
    assert "admission state starts FRESH" in log
    assert re.search(r"cold-started \d+ table rows", log), (
        "fresh admission over a restored table must reset the rows")
