"""Test harness: force an 8-device CPU platform BEFORE jax initialises.

SURVEY.md §4: the honest JAX analogue of the reference's "localhost PS
cluster" smoke tests is a single-host fake mesh via
``--xla_force_host_platform_device_count``. Everything in tests/ runs on
CPU so the suite is hermetic and fast; TPU-only paths (real Pallas
lowering) are exercised by bench.py / the driver on hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
