"""Test harness: force an 8-device CPU platform BEFORE jax initialises.

SURVEY.md §4: the honest JAX analogue of the reference's "localhost PS
cluster" smoke tests is a single-host fake mesh via
``--xla_force_host_platform_device_count``. Everything in tests/ runs on
CPU so the suite is hermetic and fast; TPU-only paths (real Pallas
lowering) are exercised by bench.py / the driver on hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The environment's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS=axon (the tunnelled TPU), so the env vars above are too
# late for platform selection; jax.config still works, and the CPU client
# is created lazily so the forced host device count applies.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.device_count() == 8, (
    f"expected 8 forced CPU devices, got {jax.devices()}")


@pytest.fixture
def rng():
    return np.random.default_rng(0)
