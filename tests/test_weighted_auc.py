"""Weighted validation AUC: training weights its loss by weight_files,
so validation must weight its AUC the same way (round-4 review: the
plumbing existed in StreamingAUC but evaluate never passed weights —
loss and metric disagreed about what an example is worth). The
reference has no AUC at all (SURVEY.md §5 "Metrics"), so this is a
within-framework consistency contract, not upstream parity."""

import os

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.metrics import StreamingAUC, exact_auc
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args, init_table,
                                     make_batch_scorer)
from fast_tffm_tpu.data.pipeline import batch_iterator
from fast_tffm_tpu.train import evaluate, evaluate_distributed


def _brute_auc(scores, labels, weights=None):
    """O(n^2) pair loop — the definitionally-obvious oracle for the
    O(n log n) exact_auc."""
    w = np.ones_like(scores) if weights is None else weights
    pos = [(s, wi) for s, y, wi in zip(scores, labels, w) if y >= 0.5]
    neg = [(s, wi) for s, y, wi in zip(scores, labels, w) if y < 0.5]
    num = sum(wp * wn * (1.0 if sp > sn else 0.5 if sp == sn else 0.0)
              for sp, wp in pos for sn, wn in neg)
    den = sum(wp for _, wp in pos) * sum(wn for _, wn in neg)
    return num / den


def test_exact_auc_weighted_matches_brute(rng):
    scores = rng.normal(size=120).round(1)       # rounding forces ties
    labels = (rng.uniform(size=120) < 0.5).astype(float)
    weights = rng.uniform(0.1, 4.0, size=120)
    assert exact_auc(scores, labels, weights) == pytest.approx(
        _brute_auc(scores, labels, weights), abs=1e-12)
    # unweighted path must be unchanged by the weighted generalization
    assert exact_auc(scores, labels) == pytest.approx(
        _brute_auc(scores, labels), abs=1e-12)


def test_exact_auc_integer_weight_equals_repetition(rng):
    scores = rng.normal(size=60)
    labels = (rng.uniform(size=60) < 0.5).astype(float)
    reps = rng.integers(1, 4, size=60)
    got = exact_auc(scores, labels, reps.astype(float))
    want = exact_auc(np.repeat(scores, reps), np.repeat(labels, reps))
    assert got == pytest.approx(want, abs=1e-12)


def test_streaming_weighted_matches_exact(rng):
    scores = rng.normal(size=4000)
    labels = (rng.uniform(size=4000) < 0.5).astype(float)
    weights = rng.uniform(0.1, 5.0, size=4000)
    auc = StreamingAUC()
    for i in range(0, 4000, 513):
        auc.update(scores[i:i + 513], labels[i:i + 513],
                   weights[i:i + 513])
    assert auc.result() == pytest.approx(
        exact_auc(scores, labels, weights), abs=2e-3)


def _weighted_eval_data(tmp_path, rng, n):
    """Dataset + deterministic table only — no scoring pass."""
    vocab = 64
    lines, labels = [], []
    for _ in range(n):
        y = int(rng.integers(0, 2))
        ids = rng.choice(vocab, size=4, replace=False)
        toks = " ".join(f"{i}:{round(float(rng.uniform(0.5, 1.5)), 3)}"
                        for i in sorted(ids))
        lines.append(f"{y} {toks}\n")
        labels.append(y)
    data = tmp_path / "val.txt"
    data.write_text("".join(lines))
    cfg = FmConfig(vocabulary_size=vocab, factor_num=4, batch_size=32,
                   shuffle=False, init_value_range=0.5,
                   bucket_ladder=(8,), dedup="host",
                   model_file=str(tmp_path / "m" / "fm"))
    return cfg, init_table(cfg), data, np.asarray(labels, np.float64)


def _weighted_eval_setup(tmp_path, rng, n=256):
    """Dataset + weight sidecar engineered so weighted and unweighted
    AUC measurably differ: score the (deterministic) init table first,
    then up-weight the examples the model happens to rank correctly."""
    cfg, table, data, labels = _weighted_eval_data(tmp_path, rng, n)
    spec = ModelSpec.from_config(cfg)
    score_fn = make_batch_scorer(spec)
    scores = []
    for b in batch_iterator(cfg, [str(data)], training=False, epochs=1):
        args = batch_args(b)
        args.pop("labels"), args.pop("weights")
        scores.append(np.asarray(score_fn(table, args))[:b.num_real])
    scores = np.concatenate(scores)
    labels = np.asarray(labels, dtype=np.float64)
    med = np.median(scores)
    weights = np.where((scores > med) == (labels >= 0.5), 5.0, 0.25)
    wfile = tmp_path / "val.weights.txt"
    wfile.write_text("".join(f"{w}\n" for w in weights))
    return cfg, table, str(data), str(wfile), scores, labels, weights


def test_evaluate_weighted_sidecar(tmp_path, rng):
    (cfg, table, data, wfile, scores, labels,
     weights) = _weighted_eval_setup(tmp_path, rng)
    auc_u, n_u = evaluate(cfg, table, (data,))
    auc_w, n_w = evaluate(cfg, table, (data,), weight_files=(wfile,))
    assert n_u == n_w == len(labels)
    assert abs(auc_w - auc_u) > 0.02, (
        "weights constructed to shift AUC had no effect — sidecar not "
        "reaching StreamingAUC")
    assert auc_u == pytest.approx(exact_auc(scores, labels), abs=2e-3)
    assert auc_w == pytest.approx(exact_auc(scores, labels, weights),
                                  abs=2e-3)


def test_evaluate_distributed_weighted(tmp_path, rng):
    """Same contract through the mesh lockstep + histogram-allgather
    path (weighted bins ride the (hi, lo) f32 transport unchanged)."""
    import jax
    from fast_tffm_tpu.parallel.sharded import make_mesh
    (cfg, _, data, wfile, scores, labels,
     weights) = _weighted_eval_setup(tmp_path, rng)
    mesh = make_mesh(jax.devices()[:8])
    from fast_tffm_tpu.parallel.sharded import init_sharded_state
    table, _ = init_sharded_state(cfg, mesh)
    # re-score through the mesh scorer so the oracle matches this table
    from fast_tffm_tpu.parallel.sharded import make_sharded_score_fn
    spec = ModelSpec.from_config(cfg)
    score_fn = make_sharded_score_fn(spec, mesh)
    auc_w, n = evaluate_distributed(cfg, table, (data,), mesh,
                                    shard_index=0, num_shards=1,
                                    weight_files=(wfile,))
    auc_u, _ = evaluate_distributed(cfg, table, (data,), mesh,
                                    shard_index=0, num_shards=1)
    assert n == len(labels)
    # oracle: score every example through the same mesh fn
    got = []
    ub = cfg.uniq_bucket or 0
    from fast_tffm_tpu.data.pipeline import probe_uniq_bucket
    ub = ub or probe_uniq_bucket(cfg, (data,))
    from fast_tffm_tpu.parallel.sharded import lockstep_score_batches
    it = batch_iterator(cfg, (data,), training=False, epochs=1,
                        fixed_shape=True, uniq_bucket=ub)
    ys = []
    for batch, local in lockstep_score_batches(cfg, it, mesh, score_fn,
                                               table, ub):
        got.append(local[:batch.num_real])
        ys.append(batch.labels[:batch.num_real])
    got = np.concatenate(got)
    ys = np.concatenate(ys)
    # weights were built for the single-device table's scores; rebuild
    # them for the mesh table's scores by line position (same file)
    assert auc_u == pytest.approx(exact_auc(got, ys), abs=2e-3)
    assert auc_w == pytest.approx(exact_auc(got, ys, weights), abs=2e-3)
    assert abs(auc_w - auc_u) > 1e-6


def test_evaluate_surfaces_divergence_through_overlap(tmp_path, rng):
    """A diverged model (NaN scores) must still raise StreamingAUC's
    diagnostic out of evaluate() — the round-5 overlap moved consume
    onto a background thread, and a swallowed error there would turn
    'model diverged' into a silently-wrong AUC."""
    cfg, table, data, _ = _weighted_eval_data(tmp_path, rng, n=64)
    import jax.numpy as jnp
    bad = jnp.asarray(np.full(np.asarray(table).shape, np.nan,
                              np.float32))
    with pytest.raises(ValueError, match="NaN"):
        evaluate(cfg, bad, (str(data),))


def test_config_validation_weight_files(tmp_path):
    from fast_tffm_tpu.config import load_config
    p = tmp_path / "c.cfg"
    p.write_text("""
[General]
vocabulary_size = 100
[Train]
train_files = a.txt
validation_files = v.txt
validation_weight_files = vw.txt
""")
    cfg = load_config(str(p))
    assert cfg.validation_weight_files == ("vw.txt",)
    with pytest.raises(ValueError, match="validation_weight_files"):
        FmConfig(validation_weight_files=("w.txt",))
    # literal-list length mismatch fails at config time, not hours into
    # the run at the first validation sweep
    with pytest.raises(ValueError, match="1:1"):
        FmConfig(validation_files=("a.txt", "b.txt"),
                 validation_weight_files=("w.txt",))
    with pytest.raises(ValueError, match="1:1"):
        FmConfig(train_files=("a.txt", "b.txt"),
                 weight_files=("w.txt",))
    # globbed lists defer to the iteration-time post-expansion check
    FmConfig(train_files=("shard-*.txt",), weight_files=("w.txt",))


def test_weight_sidecar_glob_pairing_per_pattern(tmp_path):
    """ISSUE 3 satellite (ADVICE round 5): sidecar globs expand PER
    PATTERN PAIR — per-pattern count mismatches fail loudly instead of
    positionally zipping weights onto the wrong files, and matched
    pairs line up by construction."""
    from fast_tffm_tpu.data.pipeline import expand_paired_files
    for i in range(3):
        (tmp_path / f"day{i}.txt").write_text(f"1 {i}:1\n")
        (tmp_path / f"day{i}.w").write_text("2.0\n")
    (tmp_path / "extra.txt").write_text("0 9:1\n")
    (tmp_path / "extra.w").write_text("3.0\n")

    # parallel naming schemes pair correctly pattern by pattern
    files, sidecars = expand_paired_files(
        [str(tmp_path / "day*.txt"), str(tmp_path / "extra.txt")],
        [str(tmp_path / "day*.w"), str(tmp_path / "extra.w")])
    assert [os.path.basename(f) for f in files] == [
        "day0.txt", "day1.txt", "day2.txt", "extra.txt"]
    assert [os.path.basename(s) for s in sidecars] == [
        "day0.w", "day1.w", "day2.w", "extra.w"]

    # per-pattern count mismatch: 3 data files vs 1 sidecar — the old
    # flat zip would only have caught a TOTAL-length mismatch
    (tmp_path / "day1.w").unlink()
    (tmp_path / "day2.w").unlink()
    with pytest.raises(ValueError, match="mismatched counts"):
        expand_paired_files([str(tmp_path / "day*.txt")],
                            [str(tmp_path / "day*.w")])

    # pattern-LIST length mismatch is its own loud failure
    with pytest.raises(ValueError, match="pattern per data pattern"):
        expand_paired_files(["a.txt", "b.txt"], ["w.txt"])

    # and the check fires on the real iteration path too: one data
    # pattern (3 hits) against two sidecar patterns whose TOTAL could
    # never pair pattern-wise — the old flat zip would have compared
    # totals only. batch_iterator is lazy, so force it.
    (tmp_path / "w_a.w").write_text("1.0\n")
    (tmp_path / "w_b.w").write_text("1.0\n")
    cfg = FmConfig(vocabulary_size=100, batch_size=4, shuffle=False)
    with pytest.raises(ValueError, match="pattern per data pattern"):
        list(batch_iterator(
            cfg, [str(tmp_path / "day*.txt")],
            weight_files=[str(tmp_path / "w_a.w"),
                          str(tmp_path / "w_b.w")],
            epochs=1))


def test_weight_files_without_train_files_raises():
    """ISSUE 3 satellite: mirror of the validation-side pairing check —
    weight_files with empty train_files is a config mistake, caught at
    validation time."""
    with pytest.raises(ValueError, match="weight_files given without"):
        FmConfig(weight_files=("w.txt",))
