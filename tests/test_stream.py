"""Streaming run mode (data/stream.py; README "Streaming / online
learning"): tracker hostile-filesystem behavior (torn-tail holdback,
seal policies, truncation/rotation/deletion), exactly-once watermark
checkpointing — including through a quarantine walk-back to an older
step — serial-vs-parallel stream parity, publishing, and the fmstat
STREAMING surface. The end-to-end soaks (live writer, SIGTERM+resume,
flaky opens) live in tools/fmchaos (`stream-soak` / `stream-truncate`)
and run under tier-1 via tests/test_chaos.py."""

import json
import os
import time

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data import stream as sl
from fast_tffm_tpu.data.badlines import BadLineTracker


def _write_lines(path, lines, append=False, newline_end=True):
    with open(path, "a" if append else "w") as fh:
        fh.write("\n".join(lines) + ("\n" if newline_end else ""))


def _numbered(lo, hi):
    """Distinct one-feature lines: line j carries exactly feature j,
    so a batch's uniq_ids names exactly the lines it holds."""
    return [f"{j % 2} {j}:1" for j in range(lo, hi)]


def _cfg(stream_dir, **kw):
    base = dict(vocabulary_size=4096, factor_num=2, batch_size=8,
                run_mode="stream", stream_dir=stream_dir,
                stream_poll_seconds=0.01, seal_policy="done",
                shuffle=False, seed=0)
    base.update(kw)
    return FmConfig(**base)


def _drain(src, limit=10000):
    out = []
    while len(out) < limit:
        b = src.next_batch(block=True)
        if b is sl.DONE:
            return out
        out.append(b)
    raise AssertionError("stream never drained")


def _batch_ids(batch, pad_id):
    if batch.uniq_ids is None:
        ids = np.asarray(batch.local_idx).ravel()
    else:
        ids = np.asarray(batch.uniq_ids)
    return sorted(int(i) for i in ids[ids != pad_id])


# --- config surface -------------------------------------------------------


def test_stream_config_validation():
    with pytest.raises(ValueError, match="requires stream_dir"):
        FmConfig(run_mode="stream")
    with pytest.raises(ValueError, match="run_mode is 'epochs'"):
        FmConfig(stream_dir="/tmp/x")
    with pytest.raises(ValueError, match="seal_policy"):
        FmConfig(run_mode="stream", stream_dir="/tmp/x",
                 seal_policy="nope")
    with pytest.raises(ValueError, match="weight_files"):
        FmConfig(run_mode="stream", stream_dir="/tmp/x",
                 weight_files=("w",))
    with pytest.raises(ValueError, match="train_files"):
        FmConfig(run_mode="stream", stream_dir="/tmp/x",
                 train_files=("a",))
    with pytest.raises(ValueError, match="stream_poll_seconds"):
        FmConfig(run_mode="stream", stream_dir="/tmp/x",
                 stream_poll_seconds=0)


def test_stream_knobs_load_from_ini(tmp_path):
    from fast_tffm_tpu.config import load_config
    p = tmp_path / "s.cfg"
    p.write_text("""
[Train]
run_mode = stream
stream_dir = /data/arriving
stream_poll_seconds = 7.5
seal_policy = quiet
publish_interval_seconds = 120
""")
    cfg = load_config(str(p))
    assert cfg.run_mode == "stream"
    assert cfg.stream_dir == "/data/arriving"
    assert cfg.stream_poll_seconds == 7.5
    assert cfg.seal_policy == "quiet"
    assert cfg.publish_interval_seconds == 120.0


# --- tracker: hostile filesystem ------------------------------------------


def test_torn_trailing_line_held_back(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    p.write_text("1 1:1\n0 2:1\n1 3:")  # torn third line
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    chunks = tr.poll()
    released = b"".join(c for _, c in chunks)
    assert released == b"1 1:1\n0 2:1\n"  # torn tail held back
    time.sleep(0.02)
    assert tr.poll() == []  # still torn: nothing new
    with open(p, "a") as fh:
        fh.write("1\n0 4:1\n")  # complete the line + one more
    time.sleep(0.02)
    chunks = tr.poll()
    assert b"".join(c for _, c in chunks) == b"1 3:1\n0 4:1\n"


def test_seal_done_marker_flushes_newlineless_tail(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    p.write_text("1 1:1\n0 2:1")  # final line has no newline
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    assert b"".join(c for _, c in tr.poll()) == b"1 1:1\n"
    (sd / "a.txt.done").touch()
    time.sleep(0.02)
    # Sealed: the newline-less final line is released with a
    # synthesized terminator, and the file reaches EOF state.
    assert b"".join(c for _, c in tr.poll()) == b"0 2:1\n"
    assert tr.files[0].sealed and tr.files[0].eof
    assert tr.files[0].end == p.stat().st_size


def test_seal_quiet_mtime(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    p.write_text("1 1:1\n")
    tr = sl.StreamTracker(str(sd), 0.01, "quiet")
    tr.poll()
    assert not tr.files[0].sealed  # mtime is fresh
    old = time.time() - 10  # far beyond 3 x poll_seconds
    os.utime(p, (old, old))
    time.sleep(0.02)
    tr.poll()
    assert tr.files[0].sealed


def test_truncation_detected_and_quarantined(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    p.write_text("\n".join(f"1 {i}:1" for i in range(20)) + "\n")
    bad = BadLineTracker("quarantine", 0.9,
                         quarantine_file=str(tmp_path / "q.jsonl"))
    tr = sl.StreamTracker(str(sd), 0.01, "done", bad_lines=bad)
    released = b"".join(c for _, c in tr.poll())
    assert released.count(b"\n") == 20
    with open(p, "r+") as fh:
        fh.truncate(10)  # shrink WAY below what was read
    time.sleep(0.02)
    assert tr.poll() == []
    fs = tr.files[0]
    assert fs.dead and fs.eof
    assert bad.bad == 1
    recs = [json.loads(ln)
            for ln in open(tmp_path / "q.jsonl") if ln.strip()]
    assert recs[0]["file"] == str(p)
    assert "truncated" in recs[0]["error"]
    bad.close()


def test_restored_sealed_file_shrunk_below_end_goes_dead(tmp_path):
    """A SEALED file that shrank below its recorded size while the run
    was down must go dead (quarantine-grade), not wedge the
    strict-order stream in silent IDLE forever waiting for bytes that
    will never exist."""
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    _write_lines(p, _numbered(0, 20))
    size = p.stat().st_size
    wm = {"format": 1, "files": [
        {"path": str(p), "bytes": 40, "lines": 8, "sealed": True,
         "dead": False, "end": size}]}
    with open(p, "r+") as fh:
        fh.truncate(60)  # below end, above the resume offset
    tr = sl.StreamTracker(str(sd), 0.01, "done", watermark=wm)
    assert tr.poll() == []
    assert tr.files[0].dead and tr.files[0].eof
    (sd / "STOP").touch()
    time.sleep(0.02)
    tr.poll()
    assert tr.finished  # the stream can still end


def test_poll_budget_streams_backlog_in_bounded_rounds(tmp_path,
                                                      monkeypatch):
    """A large sealed backlog is read across polls under
    MAX_POLL_BYTES, never materialized whole — and the reassembled
    bytes are exact."""
    monkeypatch.setattr(sl, "MAX_POLL_BYTES", 64)
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    _write_lines(p, _numbered(0, 30))  # ~200 bytes >> 64
    (sd / "a.txt.done").touch()
    tr = sl.StreamTracker(str(sd), 0.001, "done")
    got = b""
    rounds = 0
    while not tr.files or not tr.files[0].eof:
        time.sleep(0.002)
        chunks = tr.poll()
        for _, c in chunks:
            assert len(c) <= 64 + 80  # budget + one held-back line
            got += c
        rounds += 1
        assert rounds < 100
    assert rounds > 2  # genuinely split across polls
    assert got == p.read_bytes()
    assert tr.files[0].end == p.stat().st_size  # seal size = full size


def test_deleted_file_skipped_not_crashed(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    p.write_text("1 1:1\n")
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    tr.poll()
    p.unlink()
    time.sleep(0.02)
    assert tr.poll() == []
    assert tr.files[0].dead  # logged + frozen, never raised


def test_strict_ledger_order_blocks_behind_open_head(tmp_path):
    """A sealed later shard must NOT be consumed past an open head —
    the stream is a log (and the bit-identity-with-control contract
    depends on it)."""
    sd = tmp_path / "s"
    sd.mkdir()
    (sd / "a.txt").write_text("1 1:1\n")  # open (unsealed) head
    (sd / "b.txt").write_text("1 2:1\n")
    (sd / "b.txt.done").touch()
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    chunks = tr.poll()
    paths = [tr.path(i) for i, _ in chunks]
    assert paths == [str(sd / "a.txt")]  # b waits behind the open head


def test_stop_marker_force_seals_and_finishes(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    (sd / "a.txt").write_text("1 1:1\n0 2:1\n")
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    tr.poll()
    assert not tr.finished
    (sd / "STOP").touch()
    time.sleep(0.02)
    tr.poll()
    assert tr.files[0].sealed
    assert tr.finished


# --- source: exactly-once watermarks --------------------------------------


def test_batches_carry_exact_positions(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    _write_lines(sd / "a.txt", _numbered(0, 20))
    (sd / "a.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd))
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    src = sl.StreamSource(cfg, tr)
    batches = _drain(src)
    assert [b.num_real for b in batches] == [8, 8, 4]
    for k, b in enumerate(batches):
        rec = b.stream_pos["files"][0]
        want_lines = min((k + 1) * 8, 20)
        assert rec["lines"] == want_lines
        assert rec["bytes"] == sum(
            len(ln) + 1 for ln in _numbered(0, want_lines))
        assert _batch_ids(b, cfg.pad_id) == list(
            range(k * 8, want_lines))
    src.close()


def test_resume_from_mid_file_watermark_exact_next_batch(tmp_path):
    """The satellite contract: restore at an arbitrary mid-file offset
    and the next emitted batch starts at EXACTLY the right line."""
    sd = tmp_path / "s"
    sd.mkdir()
    _write_lines(sd / "a.txt", _numbered(0, 30))
    (sd / "a.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd))
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    src = sl.StreamSource(cfg, tr)
    b1 = src.next_batch(block=True)
    wm = b1.stream_pos  # mid-file: 8 of 30 lines
    src.close()
    tr2 = sl.StreamTracker(str(sd), 0.01, "done", watermark=wm)
    src2 = sl.StreamSource(cfg, tr2)
    b2 = src2.next_batch(block=True)
    assert _batch_ids(b2, cfg.pad_id) == list(range(8, 16))
    src2.close()


def test_watermark_checkpoint_roundtrip_and_walkback(tmp_path):
    """Watermarks ride checkpoints: save at a mid-file offset, restore,
    and the stream resumes at exactly the right line — INCLUDING
    through the PR 4 quarantine walk-back to an older step, whose
    older watermark re-reads (never skips)."""
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          read_watermark)
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    from fast_tffm_tpu.train import checkpoint_template
    sd = tmp_path / "s"
    sd.mkdir()
    _write_lines(sd / "a.txt", _numbered(0, 40))
    (sd / "a.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd), model_file=str(tmp_path / "m" / "fm"))
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    src = sl.StreamSource(cfg, tr)
    batches = _drain(src)
    src.close()
    wm5 = batches[0].stream_pos   # after line 8
    wm10 = batches[2].stream_pos  # after line 24
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), np.float32)
    acc = np.full((cfg.ckpt_rows, cfg.row_dim), 0.1, np.float32)
    ckpt = CheckpointState(cfg.model_file)
    ckpt.save(5, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, stream_state=wm5)
    ckpt.save(10, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, stream_state=wm10)
    ckpt.close()
    ckpt_dir = cfg.model_file + ".ckpt"
    assert read_watermark(ckpt_dir, 5) == wm5
    assert read_watermark(ckpt_dir, 10) == wm10
    # Clean restore: newest step's watermark.
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(restored["step"]) == 10
    assert restored["stream"] == wm10
    # Tear step 10; the verified restore must quarantine it, fall back
    # to step 5, and hand back the OLDER watermark (re-reads, never
    # skips) — its sidecar travels into the quarantine dir.
    truncate_checkpoint(cfg.model_file, step=10)
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(restored["step"]) == 5
    assert restored["stream"] == wm5
    assert read_watermark(ckpt_dir, 10) is None
    assert os.path.exists(os.path.join(ckpt_dir, "corrupt-10",
                                       "watermark-10.json"))
    # And the resumed source starts at exactly wm5's next line.
    tr2 = sl.StreamTracker(str(sd), 0.01, "done",
                           watermark=restored["stream"])
    src2 = sl.StreamSource(cfg, tr2)
    nxt = src2.next_batch(block=True)
    assert _batch_ids(nxt, cfg.pad_id) == list(range(8, 16))
    src2.close()


def test_epoch_mode_checkpoints_carry_no_watermark(tmp_path):
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.train import checkpoint_template
    cfg = FmConfig(vocabulary_size=256, factor_num=2,
                   model_file=str(tmp_path / "m" / "fm"))
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), np.float32)
    ckpt = CheckpointState(cfg.model_file)
    ckpt.save(3, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert restored["stream"] is None


# --- serial vs parallel stream parity -------------------------------------


def test_host_threads_parity_bit_identical(tmp_path):
    """host_threads > 1 in stream mode (sealed groups through the PR 7
    ring) must emit the BIT-IDENTICAL batch stream — arrays and
    watermark tags — as the serial stream path."""
    from fast_tffm_tpu.data import cparser
    if not cparser.available():
        pytest.skip("C++ extension unavailable")
    sd = tmp_path / "s"
    sd.mkdir()
    rng = np.random.default_rng(3)
    for i in range(3):
        lines = []
        for j in range(60):
            nnz = int(rng.integers(1, 6))
            ids = rng.choice(500, size=nnz, replace=False)
            lines.append(" ".join([str(j % 2)]
                                  + [f"{k}:{rng.random():.3f}"
                                     for k in ids]))
        _write_lines(sd / f"p{i}.txt", lines)
        (sd / f"p{i}.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd), vocabulary_size=512, batch_size=16)

    def run(workers):
        tr = sl.StreamTracker(str(sd), 0.01, "done")
        src = sl.StreamSource(cfg, tr, workers=workers)
        out = _drain(src)
        src.close()
        return out

    serial, parallel = run(1), run(4)
    assert len(serial) == len(parallel)
    for a, b in zip(serial, parallel):
        assert a.num_real == b.num_real
        np.testing.assert_array_equal(a.labels, b.labels)
        np.testing.assert_array_equal(a.local_idx, b.local_idx)
        np.testing.assert_array_equal(a.vals, b.vals)
        np.testing.assert_array_equal(a.uniq_ids, b.uniq_ids)
        assert a.stream_pos == b.stream_pos


def test_stream_workers_routing():
    cfg = _cfg("/tmp/x", host_threads=4)
    from fast_tffm_tpu.data import cparser
    want = 4 if cparser.available() else 1
    assert sl.stream_workers(cfg) == want
    # fixed-U lockstep and tolerant policies stay serial-feed
    assert sl.stream_workers(cfg, fixed_shape=True) == 1
    assert sl.stream_workers(
        _cfg("/tmp/x", host_threads=4,
             bad_line_policy="skip")) == 1


def test_unlimited_features_routes_generic(tmp_path):
    """max_features_per_example = 0 ("unlimited") must ride the
    generic path in stream mode exactly as it does under epochs: the
    C++ builder writes fixed-stride rows and would silently truncate
    long examples at the ladder cap — the same corpus must train the
    same model regardless of run_mode."""
    sd = tmp_path / "s"
    sd.mkdir()
    # One example wider than the default ladder top (256).
    wide = "1 " + " ".join(f"{i}:1" for i in range(300))
    _write_lines(sd / "a.txt", [wide] + _numbered(1000, 1007))
    (sd / "a.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd), max_features_per_example=0,
               vocabulary_size=4096)
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    src = sl.StreamSource(cfg, tr)
    assert not src._fast  # generic route: no silent truncation
    b = src.next_batch(block=True)
    src.close()
    # All 300 features of the wide example survive.
    assert 300 + 7 == len(_batch_ids(b, cfg.pad_id))


def test_probe_accepts_quiet_sealed_backlog(tmp_path):
    """Under seal_policy = quiet the startup probe must treat an
    mtime-quiet backlog as probeable — fs.sealed is always False
    before any tracker service, and falling back to the default
    bucket on a dense non-empty backlog means chronic spills."""
    sd = tmp_path / "s"
    sd.mkdir()
    # Dense lines: ~40 uniques per 8-example batch per line cluster.
    lines = []
    for j in range(64):
        ids = range(j * 40, j * 40 + 40)
        lines.append("1 " + " ".join(f"{i}:1" for i in ids))
    _write_lines(sd / "a.txt", lines)
    old = time.time() - 60
    os.utime(sd / "a.txt", (old, old))
    cfg = _cfg(str(sd), seal_policy="quiet", vocabulary_size=1 << 14,
               max_features_per_example=64, bucket_ladder=(64,))
    tr = sl.StreamTracker(str(sd), 0.01, "quiet")
    bucket = sl.probe_stream_uniq_bucket(cfg, tr)
    # 8 examples x 40 fresh ids = 320 uniques -> probe picks >= 2x,
    # never the empty-stream fallback driven by density it never saw.
    assert bucket >= 512, bucket


# --- generic tolerant path ------------------------------------------------


def test_tolerant_stream_skips_bad_lines_with_exact_positions(tmp_path):
    sd = tmp_path / "s"
    sd.mkdir()
    lines = _numbered(0, 16)
    lines[5] = "##bad## nope"
    _write_lines(sd / "a.txt", lines)
    (sd / "a.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd), bad_line_policy="skip")
    bad = BadLineTracker("skip", 0.9)
    tr = sl.StreamTracker(str(sd), 0.01, "done", bad_lines=bad)
    src = sl.StreamSource(cfg, tr, bad_lines=bad)
    batches = _drain(src)
    src.close()
    assert [b.num_real for b in batches] == [7, 8]
    assert bad.bad == 1 and bad.total == 16
    got = sorted(i for b in batches
                 for i in _batch_ids(b, cfg.pad_id))
    assert got == [i for i in range(16) if i != 5]
    # Final watermark covers the whole file despite the dropped line.
    assert batches[-1].stream_pos["files"][0]["lines"] == 16


def test_tolerant_stream_positions_across_polls(tmp_path):
    """The generic path's decode cursor must CONTINUE across poll
    rounds: a file released in several chunks (the normal tailing
    case) tags later lines with absolute offsets, not offsets
    restarted at the last emitted batch."""
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    _write_lines(p, _numbered(0, 6))  # below one batch: no emission
    cfg = _cfg(str(sd), bad_line_policy="skip")
    bad = BadLineTracker("skip", 0.9)
    tr = sl.StreamTracker(str(sd), 0.01, "done", bad_lines=bad)
    src = sl.StreamSource(cfg, tr, bad_lines=bad)
    assert src.next_batch() is sl.IDLE  # 6 pending lines buffered
    _write_lines(p, _numbered(6, 20), append=True)  # second chunk
    (sd / "a.txt.done").touch()
    (sd / "STOP").touch()
    time.sleep(0.02)
    batches = _drain(src)
    src.close()
    assert [b.num_real for b in batches] == [8, 8, 4]
    total_bytes = p.stat().st_size
    for k, b in enumerate(batches):
        rec = b.stream_pos["files"][0]
        want = min((k + 1) * 8, 20)
        assert rec["lines"] == want, (k, rec)
        assert rec["bytes"] == sum(
            len(ln) + 1 for ln in _numbered(0, want)), (k, rec)
    assert batches[-1].stream_pos["files"][0]["bytes"] == total_bytes


# --- publishing -----------------------------------------------------------


def test_publish_step_verified_pointer_flip(tmp_path):
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          read_published)
    cfg = FmConfig(vocabulary_size=256, factor_num=2,
                   model_file=str(tmp_path / "m" / "fm"))
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), np.float32)
    ckpt = CheckpointState(cfg.model_file)
    ckpt.save(1, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    assert ckpt.publish_step(1) is not None
    ckpt_dir = cfg.model_file + ".ckpt"
    assert read_published(ckpt_dir) == 1
    ckpt.save(2, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    assert ckpt.publish_step(2) is not None
    assert read_published(ckpt_dir) == 2
    # A torn step must NOT be published: pointer stays at the last
    # good step.
    ckpt.save(3, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    truncate_checkpoint(cfg.model_file, step=3)
    assert ckpt.publish_step(3) is None
    assert read_published(ckpt_dir) == 2
    ckpt.close()


def test_published_at_risk_tracks_retention(tmp_path):
    """Retention must never lap the published pointer: at_risk fires
    one save BEFORE max_to_keep eviction would delete the published
    step (and immediately when the pointer already dangles)."""
    from fast_tffm_tpu.checkpoint import CheckpointState
    cfg = FmConfig(vocabulary_size=256, factor_num=2,
                   model_file=str(tmp_path / "m" / "fm"))
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), np.float32)
    ckpt = CheckpointState(cfg.model_file)  # max_to_keep = 3
    assert not ckpt.published_at_risk()  # nothing published yet
    ckpt.save(1, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    ckpt.publish_step(1)
    assert not ckpt.published_at_risk()
    ckpt.save(2, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    assert not ckpt.published_at_risk()  # 1 newer step: still safe
    ckpt.save(3, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    # 2 newer steps with max_to_keep=3: the NEXT save evicts step 1.
    assert ckpt.published_at_risk()
    ckpt.publish_step(3)
    assert not ckpt.published_at_risk()
    ckpt.close()


def test_rotated_file_detected_across_restart(tmp_path):
    """The watermark persists each file's inode, so a same-path
    rewrite while the run was DOWN is caught like an in-run rotation
    (dead + quarantine-grade) instead of resuming mid-file into
    unrelated content."""
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    _write_lines(p, _numbered(0, 20))
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    cfg = _cfg(str(sd))
    src = sl.StreamSource(cfg, tr)
    wm = src.next_batch(block=True).stream_pos
    src.close()
    assert wm["files"][0]["ino"] == p.stat().st_ino
    # Rewrite the path with NEW content on a NEW inode, same-or-larger
    # size (the case a size check alone cannot see). The hardlink
    # keeps the old inode allocated so the filesystem can't recycle
    # it for the replacement (it would in this fresh tmpdir).
    os.link(p, sd / ".pin-old-inode")  # dotfile: discovery skips it
    p.unlink()
    _write_lines(p, ["0 777:1"] * 40)
    bad = BadLineTracker("skip", 0.9)
    tr2 = sl.StreamTracker(str(sd), 0.01, "done", bad_lines=bad,
                           watermark=wm)
    assert tr2.poll() == []
    assert tr2.files[0].dead
    assert bad.bad == 1
    bad.close()


def test_fmckpt_ls_shows_published_and_watermark(tmp_path, capsys):
    from fast_tffm_tpu.checkpoint import CheckpointState
    from tools.fmckpt import cmd_ls, scan
    cfg = FmConfig(vocabulary_size=256, factor_num=2,
                   model_file=str(tmp_path / "m" / "fm"))
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), np.float32)
    ckpt = CheckpointState(cfg.model_file)
    ckpt.save(1, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True,
              stream_state={"format": 1, "files": []})
    ckpt.publish_step(1)
    ckpt.close()
    ckpt_dir = cfg.model_file + ".ckpt"
    state = scan(ckpt_dir)
    assert state["published"] == 1
    assert state["steps"][0]["watermark"] is True
    cmd_ls(ckpt_dir)
    out = capsys.readouterr().out
    assert "PUBLISHED" in out and "+watermark" in out


# --- fmstat / health ------------------------------------------------------


def _stream_summary(age, interval, run_end=True):
    return {"counters": {"stream/files_discovered": 3,
                         "stream/publishes": 2},
            "gauges": {"stream/last_publish_age_seconds": age,
                       "stream/publish_interval_seconds": interval},
            "hists": {}, "health_events": [], "crash_events": [],
            "run_starts": 1, "run_ends": 1 if run_end else 0,
            "gauges_by_process": {}, "scalars": [], "meta": {}}


def test_stale_publish_verdict():
    from fast_tffm_tpu.obs.attribution import health_verdict
    ok = health_verdict(_stream_summary(age=100.0, interval=60.0))
    assert ok["verdict"] == "OK"
    stale = health_verdict(_stream_summary(age=400.0, interval=60.0))
    assert stale["verdict"] == "STALE PUBLISH"
    assert "400" in stale["detail"]
    # A LIVE stream (no run_end) with stale publishes reads STALE
    # PUBLISH (actionable), not the unclosed-stream CRASHED heuristic.
    live = health_verdict(_stream_summary(age=400.0, interval=60.0,
                                          run_end=False))
    assert live["verdict"] == "STALE PUBLISH"
    assert "no run_end" in live["detail"]
    # No publishing configured: the gauge pair is absent, never stale.
    none = health_verdict(_stream_summary(age=None, interval=None))
    assert none["verdict"] == "OK"


def test_fmstat_render_streaming_section():
    from fast_tffm_tpu.obs.attribution import render
    out = render(_stream_summary(age=10.0, interval=60.0))
    assert "STREAMING" in out
    assert "files discovered / sealed" in out
    assert "last publish age / interval" in out


# --- watermark exchange / broadcast (single-process identity) -------------


def test_exchange_and_broadcast_identity():
    wm = {"format": 1, "files": [{"path": "a", "bytes": 3, "lines": 1,
                                  "sealed": True, "dead": False,
                                  "end": 3}]}
    assert sl.exchange_watermarks(wm, num_shards=1) == wm
    assert sl.broadcast_blob({"x": 1}, label="t") == {"x": 1}


def _rec(path, b):
    return {"path": path, "bytes": b, "lines": b, "sealed": True,
            "dead": False, "end": 100}


def test_merge_watermark_payloads_owner_wins_over_stale_chief():
    """Ledger entry i comes from its OWNER (i % P) and a stale/short
    chief payload must not truncate the merge — the bug class: the
    chief stepped only fillers, ships {files: []}, and the owner's
    advanced positions for its files would be dropped."""
    chief = {"format": 1, "files": []}  # never adopted a tag
    owner = {"format": 1, "files": [_rec("f0", 0), _rec("f1", 60)]}
    merged = sl.merge_watermark_payloads([chief, owner], num_shards=2)
    assert [f["path"] for f in merged["files"]] == ["f0", "f1"]
    assert merged["files"][1]["bytes"] == 60   # owner (1 % 2) wins
    assert merged["files"][0]["bytes"] == 0    # f0's owner is the
    # chief, which has no entry: the fallback takes any payload's
    # zero-position record
    # And per-index ownership: worker 0 owns even indices.
    w0 = {"format": 1, "files": [_rec("f0", 25), _rec("f1", 0)]}
    w1 = {"format": 1, "files": [_rec("f0", 0), _rec("f1", 60)]}
    merged = sl.merge_watermark_payloads([w0, w1], num_shards=2)
    assert merged["files"][0]["bytes"] == 25
    assert merged["files"][1]["bytes"] == 60


def test_merge_watermark_ownership_reagrees_on_membership_change():
    """Elastic membership changes re-agree ledger ownership simply by
    merging under the NEW num_shards: every member's tracker was
    rebuilt from the same restored merged payload, so the entries a
    worker does not own hold the restored positions — merging with the
    grown membership picks each entry from whoever advances it NOW,
    and a fresh joiner's still-empty payload can never drop restored
    positions (the any-payload fallback has them)."""
    # Restored state after a 1-worker (shrunken) phase: f0/f1 fully
    # consumed, carried identically by the survivor.
    consumed = [_rec("f0", 100), _rec("f1", 100)]
    # Grown back to 2 workers: survivor (shard 0) advanced f2; the
    # joiner (shard 1) has stepped nothing yet — short payload.
    w0 = {"format": 1, "files": consumed + [_rec("f2", 40)]}
    w1 = {"format": 1, "files": []}
    merged = sl.merge_watermark_payloads([w0, w1], num_shards=2)
    assert [f["path"] for f in merged["files"]] == ["f0", "f1", "f2"]
    assert [f["bytes"] for f in merged["files"]] == [100, 100, 40]
    # Once the joiner adopts a tag for its owned f3, IT wins entry 3.
    w1 = {"format": 1,
          "files": consumed + [_rec("f2", 0), _rec("f3", 60)]}
    merged = sl.merge_watermark_payloads([w0, w1], num_shards=2)
    assert [f["bytes"] for f in merged["files"]] == [100, 100, 40, 60]


def test_generic_batch_spanning_files_records_both_positions(tmp_path):
    """A tolerant-path batch spanning a file boundary must advance
    EVERY file it touched in the watermark — not just the last one —
    or a mid-stream checkpoint resumes earlier files at 0 and
    double-trains them."""
    sd = tmp_path / "s"
    sd.mkdir()
    _write_lines(sd / "a.txt", _numbered(0, 3))  # 3 lines
    _write_lines(sd / "b.txt", _numbered(3, 20))
    for n in ("a.txt", "b.txt"):
        (sd / f"{n}.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd), bad_line_policy="skip")
    bad = BadLineTracker("skip", 0.9)
    tr = sl.StreamTracker(str(sd), 0.01, "done", bad_lines=bad)
    src = sl.StreamSource(cfg, tr, bad_lines=bad)
    first = src.next_batch(block=True)  # 3 lines of a + 5 of b
    recs = {os.path.basename(f["path"]): f
            for f in first.stream_pos["files"]}
    assert recs["a.txt"]["lines"] == 3  # fully consumed, recorded
    assert recs["b.txt"]["lines"] == 5
    src.close()


def test_restored_sealed_file_never_reads_late_bytes(tmp_path):
    """Bytes appended after a file sealed are IGNORED, including on a
    restore that resumes the sealed file mid-way — the watermark's
    `end` caps the read."""
    sd = tmp_path / "s"
    sd.mkdir()
    p = sd / "a.txt"
    _write_lines(p, _numbered(0, 10))
    (sd / "a.txt.done").touch()
    (sd / "STOP").touch()
    cfg = _cfg(str(sd))
    tr = sl.StreamTracker(str(sd), 0.01, "done")
    src = sl.StreamSource(cfg, tr)
    wm = src.next_batch(block=True).stream_pos  # 8 of 10 lines
    src.close()
    assert wm["files"][0]["sealed"] and wm["files"][0]["end"]
    _write_lines(p, ["1 999:1"], append=True)  # late post-seal bytes
    tr2 = sl.StreamTracker(str(sd), 0.01, "done", watermark=wm)
    src2 = sl.StreamSource(cfg, tr2)
    batches = _drain(src2)
    src2.close()
    got = sorted(i for b in batches for i in _batch_ids(b, cfg.pad_id))
    assert got == list(range(8, 10))  # never feature 999
    assert batches[-1].stream_pos["files"][0]["bytes"] == \
        wm["files"][0]["end"]
