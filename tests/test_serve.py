"""Online serving subsystem units (README "Serving"): admission
batching, ladder padding, hot-reload swap, client round-trips over the
in-process and HTTP front ends, and the published-pointer edge cases
the reload loop leans on (garbled pointer heals, repoint is atomic
under a concurrent reader, a GC'd published step degrades to a counted
reload failure — never an outage)."""

import dataclasses
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import ParseError, parse_lines

pytestmark = pytest.mark.filterwarnings(
    "ignore::DeprecationWarning")


def _corpus_lines(n, seed=0, vocab=200):
    rng = np.random.default_rng(seed)
    lines = []
    for _ in range(n):
        y = int(rng.integers(0, 2))
        feats = sorted(rng.choice(vocab, size=4, replace=False))
        lines.append(f"{y} " + " ".join(f"{i}:1.0" for i in feats))
    return lines


def _serve_cfg(workdir, **overrides):
    base = dict(
        vocabulary_size=200, factor_num=4, batch_size=32, epoch_num=1,
        learning_rate=0.1, shuffle=True, seed=0, log_steps=0,
        save_steps=5,
        bucket_ladder=(8, 16), max_features_per_example=16,
        serve_max_batch=8, serve_max_wait_ms=2.0,
        serve_poll_seconds=0.02,
        model_file=os.path.join(workdir, "model", "fm"))
    base.update(overrides)
    return FmConfig(train_files=(os.path.join(workdir, "train.txt"),),
                    **base)


@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One trained model with several retained checkpoint steps; the
    first is published. Shared across the module — every test builds
    its servers against this directory."""
    from fast_tffm_tpu.checkpoint import CheckpointState, list_step_dirs
    from fast_tffm_tpu.train import train
    wd = str(tmp_path_factory.mktemp("serve"))
    lines = _corpus_lines(400, seed=3)
    with open(os.path.join(wd, "train.txt"), "w") as fh:
        fh.write("\n".join(lines) + "\n")
    cfg = _serve_cfg(wd, epoch_num=2)
    train(cfg)
    ckpt = CheckpointState(cfg.model_file)
    steps = list_step_dirs(ckpt.directory)
    assert len(steps) >= 2
    ckpt.publish_step(steps[0])
    ckpt.close()
    return cfg, steps, wd


def _server(cfg, **kw):
    from fast_tffm_tpu.serve import ScorerServer
    kw.setdefault("watch", False)
    return ScorerServer(cfg, **kw)


# --- pure helpers ----------------------------------------------------------


def test_batch_rung_ladder():
    from fast_tffm_tpu.serve.server import batch_rung_ladder
    assert batch_rung_ladder(1) == (1,)
    assert batch_rung_ladder(8) == (1, 2, 4, 8)
    assert batch_rung_ladder(100) == (1, 2, 4, 8, 16, 32, 64, 128)


def test_concat_blocks_roundtrip():
    from fast_tffm_tpu.serve.server import _concat_blocks
    a = parse_lines(["1 3:1.0 5:2.0", "0 7:1.0"], 200)
    b = parse_lines(["", "1 9:0.5"], 200, keep_empty=True)
    cat = _concat_blocks([a, b])
    assert cat.batch_size == 4
    assert list(cat.poses) == [0, 2, 3, 3, 4]
    assert list(cat.ids) == [3, 5, 7, 9]
    assert list(cat.sizes) == [2, 1, 0, 1]
    # Single block passes through untouched.
    assert _concat_blocks([a]) is a


# --- request path ----------------------------------------------------------


def test_score_matches_batch_predict(trained):
    """The serving contract: a request's scores are bit-identical to
    batch predict against the published step, whatever padded shapes
    the admission queue picked."""
    from fast_tffm_tpu.metrics import sigmoid
    from fast_tffm_tpu.predict import load_table, predict_scores
    cfg, steps, wd = trained
    server = _server(cfg)
    try:
        lines = _corpus_lines(7, seed=11)
        res = server.score_lines(lines, timeout=30)
        assert res.step == steps[0]
        assert len(res.scores) == len(lines)
        req = os.path.join(wd, "req_parity.txt")
        with open(req, "w") as fh:
            fh.write("\n".join(lines) + "\n")
        table = load_table(cfg, step=steps[0])
        want = sigmoid(predict_scores(
            dataclasses.replace(cfg, metrics_file=""), table, [req]))
        assert np.array_equal(want, res.scores)
    finally:
        server.close()


def test_admission_coalesces_and_pads_to_rung(trained):
    """Concurrent submissions inside one wait window flush as ONE
    padded micro-batch; the padding is exactly rung - examples."""
    cfg, steps, _wd = trained
    server = _server(dataclasses.replace(cfg, serve_max_wait_ms=250.0))
    try:
        pendings = [server.submit([ln]) for ln in _corpus_lines(3, 7)]
        for p in pendings:
            p.result(timeout=30)
        st = server.stats()
        assert st["requests"] == 3
        assert st["examples"] == 3
        assert st["flushes"] == 1, "requests inside one admission " \
            "window must score as one micro-batch"
        # 3 examples pad to the 4-rung: 1 padded slot counted.
        c = server._reg.snapshot()["counters"]
        assert c.get("serve/padded_examples") == 1.0
    finally:
        server.close()


def test_flush_splits_at_max_batch(trained):
    """A window never exceeds serve_max_batch: 3 requests of 3 lines
    against max_batch=8 split 2+1 (the third becomes the next window's
    head — the carry path)."""
    cfg, steps, _wd = trained
    server = _server(dataclasses.replace(cfg, serve_max_wait_ms=250.0))
    try:
        lines = _corpus_lines(9, seed=13)
        pendings = [server.submit(lines[i:i + 3]) for i in (0, 3, 6)]
        got = [p.result(timeout=30) for p in pendings]
        assert all(len(r.scores) == 3 for r in got)
        assert server.stats()["flushes"] == 2
    finally:
        server.close()


def test_empty_and_blank_lines(trained):
    """Zero-line requests complete inline; blank lines keep predict's
    one-score-per-line alignment (they score as the model bias)."""
    cfg, steps, _wd = trained
    server = _server(cfg)
    try:
        empty = server.score_lines([], timeout=10)
        assert empty.scores.shape == (0,)
        assert empty.step == steps[0]
        lines = _corpus_lines(2, seed=17)
        res = server.score_lines([lines[0], "", lines[1]], timeout=30)
        assert len(res.scores) == 3
        blank = server.score_lines([""], timeout=30)
        assert res.scores[1] == blank.scores[0]
    finally:
        server.close()


def test_bad_request_fails_alone(trained):
    """A malformed line raises at submit, to that caller only — the
    server keeps serving the next request."""
    cfg, steps, _wd = trained
    server = _server(cfg)
    try:
        with pytest.raises(ParseError):
            server.submit(["1 not-a-feature"])
        with pytest.raises(ValueError, match="serve_max_batch"):
            server.submit(_corpus_lines(9, seed=23))
        res = server.score_lines(_corpus_lines(2, seed=19), timeout=30)
        assert len(res.scores) == 2
    finally:
        server.close()


def test_no_new_shapes_after_warmup(trained):
    """The no-recompile guarantee: every flushed device shape is a
    member of the pre-compiled [B rung, L rung] matrix, for request
    sizes spanning the whole ladder."""
    from fast_tffm_tpu.data.pipeline import _ladder_fit
    cfg, steps, _wd = trained
    server = _server(cfg)
    try:
        compiled = set(server.compiled_shapes)
        rng = np.random.default_rng(5)
        for k in (1, 2, 3, 5, 8):
            lines = _corpus_lines(k, seed=int(rng.integers(1 << 30)))
            server.score_lines(lines, timeout=30)
            rung = next(b for b in server._b_ladder if b >= k)
            block = server._parse(lines)
            L = _ladder_fit(max(int(block.sizes.max()), 1),
                            cfg.bucket_ladder)
            assert (rung, L) in compiled
    finally:
        server.close()


# --- hot reload ------------------------------------------------------------


def test_reload_swaps_and_tags_responses(trained):
    from fast_tffm_tpu.checkpoint import write_published
    from fast_tffm_tpu.serve.reload import ReloadWatcher
    cfg, steps, _wd = trained
    s_old, s_new = steps[0], steps[-1]
    write_published(cfg.model_file + ".ckpt", s_old)
    server = _server(cfg)
    watcher = ReloadWatcher(server, poll_seconds=60)  # driven by hand
    try:
        lines = _corpus_lines(4, seed=29)
        before = server.score_lines(lines, timeout=30)
        assert before.step == s_old
        assert not watcher.poll_once()  # pointer unchanged: no reload
        write_published(cfg.model_file + ".ckpt", s_new)
        assert watcher.poll_once()
        assert server.served_step == s_new
        after = server.score_lines(lines, timeout=30)
        assert after.step == s_new
        # Different checkpoints genuinely score differently.
        assert not np.array_equal(before.scores, after.scores)
        assert server.stats()["reloads"] == 1
    finally:
        write_published(cfg.model_file + ".ckpt", s_old)
        server.close()


def test_reload_failure_keeps_serving(trained):
    """A published step that cannot be restored (GC'd, quarantined, or
    never existed) is a counted failure; the old table keeps serving
    and the next poll can heal."""
    from fast_tffm_tpu.checkpoint import write_published
    from fast_tffm_tpu.serve.reload import ReloadWatcher
    cfg, steps, _wd = trained
    write_published(cfg.model_file + ".ckpt", steps[0])
    server = _server(cfg)
    watcher = ReloadWatcher(server, poll_seconds=60)
    try:
        write_published(cfg.model_file + ".ckpt", 999999)  # gone step
        assert watcher.poll_once()
        st = server.stats()
        assert st["reload_failures"] == 1
        assert st["served_step"] == steps[0]  # unharmed
        assert st["published_step"] == 999999  # honest gauge: fmstat
        # reads this pair as STALE MODEL until the reload lands
        res = server.score_lines(_corpus_lines(2, seed=31), timeout=30)
        assert res.step == steps[0]
        # Heal: repoint at a real step, the next poll swaps.
        write_published(cfg.model_file + ".ckpt", steps[0])
        watcher.poll_once()
        assert server.stats()["published_step"] == steps[0]
    finally:
        write_published(cfg.model_file + ".ckpt", steps[0])
        server.close()


def test_server_requires_published_pointer(tmp_path, trained):
    from fast_tffm_tpu.serve import ScorerServer
    cfg, _steps, _wd = trained
    lonely = dataclasses.replace(
        cfg, model_file=str(tmp_path / "nothing" / "fm"))
    with pytest.raises(FileNotFoundError, match="published"):
        ScorerServer(lonely, watch=False)


# --- front ends ------------------------------------------------------------


def test_http_round_trip(trained):
    from fast_tffm_tpu.serve.frontend import make_http_server
    cfg, steps, _wd = trained
    server = _server(cfg)
    httpd = make_http_server(server, port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        host, port = httpd.server_address[:2]
        base = f"http://{host}:{port}"
        lines = _corpus_lines(3, seed=37)
        body = ("\n".join(lines) + "\n").encode()
        with urllib.request.urlopen(
                urllib.request.Request(f"{base}/score", data=body),
                timeout=30) as resp:
            assert resp.status == 200
            step = int(resp.headers["X-FM-Step"])
            text = resp.read().decode()
        assert step == steps[0]
        # The wire format is the .score file format: %.6f per line —
        # and matches the in-process client byte for byte.
        res = server.score_lines(lines, timeout=30)
        assert text == "".join(f"{v:.6f}\n" for v in res.scores)
        with urllib.request.urlopen(f"{base}/healthz",
                                    timeout=30) as resp:
            health = json.loads(resp.read().decode())
        assert health["served_step"] == steps[0]
        assert health["requests"] >= 2
        assert health["latency_p50_ms"] is not None
        # A malformed line is the CALLER's 400, not a server death.
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                urllib.request.Request(f"{base}/score",
                                       data=b"1 nope\n"), timeout=30)
        assert ei.value.code == 400
        with urllib.request.urlopen(
                urllib.request.Request(f"{base}/score", data=body),
                timeout=30) as resp:
            assert resp.status == 200
        # Keep-alive stays in sync across a 404'd POST: the body must
        # be drained before the routing reply, or the SAME connection's
        # next request parses mid-body.
        import http.client
        conn = http.client.HTTPConnection(host, port, timeout=30)
        try:
            conn.request("POST", "/scores", body=body)
            r404 = conn.getresponse()
            assert r404.status == 404
            r404.read()  # consume so the connection can be reused
            conn.request("POST", "/score", body=body)
            resp2 = conn.getresponse()
            assert resp2.status == 200
            assert len(resp2.read().decode().splitlines()) == 3
        finally:
            conn.close()
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.close()


def test_metrics_endpoint_prometheus(trained):
    """GET /metrics serves the obs registry in Prometheus text
    exposition format: counters/gauges bare, histograms as cumulative
    le-buckets + _sum/_count, correct content type — scrapeable
    without parsing JSONL."""
    from fast_tffm_tpu.serve.frontend import make_http_server
    cfg, steps, _wd = trained
    server = _server(cfg)
    httpd = make_http_server(server, port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        host, port = httpd.server_address[:2]
        server.score_lines(_corpus_lines(3, seed=53), timeout=30)
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=30) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4")
            text = resp.read().decode()
        lines = text.splitlines()
        assert "# TYPE fm_serve_requests counter" in lines
        assert "fm_serve_requests 1" in lines
        assert "# TYPE fm_serve_served_step gauge" in lines
        assert f"fm_serve_served_step {steps[0]}" in lines
        # Histogram convention: cumulative buckets, +Inf, sum, count.
        assert ("# TYPE fm_serve_request_latency_ms histogram"
                in lines)
        buckets = [ln for ln in lines if ln.startswith(
            'fm_serve_request_latency_ms_bucket{le="')]
        assert buckets and buckets[-1].startswith(
            'fm_serve_request_latency_ms_bucket{le="+Inf"}')
        counts = [int(b.rsplit(" ", 1)[1]) for b in buckets]
        assert counts == sorted(counts)  # cumulative
        assert counts[-1] == 1
        assert any(ln.startswith("fm_serve_request_latency_ms_sum ")
                   for ln in lines)
        assert "fm_serve_request_latency_ms_count 1" in lines
        # The endpoint reflects the live registry: another request
        # bumps the counter on the next scrape.
        server.score_lines(_corpus_lines(2, seed=54), timeout=30)
        with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=30) as resp:
            assert "fm_serve_requests 2" in resp.read().decode()
    finally:
        httpd.shutdown()
        httpd.server_close()
        server.close()


def test_close_is_clean_and_idempotent(trained):
    cfg, _steps, _wd = trained
    server = _server(cfg)
    server.score_lines(_corpus_lines(2, seed=41), timeout=30)
    server.close()
    server.close()
    with pytest.raises(RuntimeError, match="closed"):
        server.submit(["1 3:1.0"])
    leaked = [t.name for t in threading.enumerate()
              if t.is_alive() and t.name.startswith("fm-serve")]
    assert not leaked, leaked


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_run_tffm_serve_process(trained):
    """The real `run_tffm.py serve` process end to end: starts against
    the published step, answers /score and /healthz over HTTP, and a
    SIGTERM drains to exit 0."""
    import signal
    import subprocess
    import sys
    cfg, steps, wd = trained
    port = _free_port()
    cfg_path = os.path.join(wd, "serve.cfg")
    with open(cfg_path, "w") as fh:
        fh.write(f"""
[General]
vocabulary_size = {cfg.vocabulary_size}
factor_num = {cfg.factor_num}
model_file = {cfg.model_file}
[Train]
max_features_per_example = {cfg.max_features_per_example}
bucket_ladder = 8,16
[Serve]
serve_port = {port}
serve_max_batch = 8
serve_max_wait_ms = 2
serve_poll_seconds = 0.1
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "run_tffm.py"), "serve", cfg_path],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 90
        health = None
        while time.monotonic() < deadline:
            assert proc.poll() is None, (
                f"serve process died: "
                f"{proc.stdout.read().decode()[-2000:]}")
            try:
                with urllib.request.urlopen(f"{base}/healthz",
                                            timeout=5) as resp:
                    health = json.loads(resp.read().decode())
                break
            except (urllib.error.URLError, ConnectionError):
                time.sleep(0.2)
        assert health is not None, "server never came up"
        assert health["served_step"] == steps[0]
        lines = _corpus_lines(3, seed=43)
        body = ("\n".join(lines) + "\n").encode()
        with urllib.request.urlopen(
                urllib.request.Request(f"{base}/score", data=body),
                timeout=30) as resp:
            assert resp.status == 200
            assert int(resp.headers["X-FM-Step"]) == steps[0]
            assert len(resp.read().decode().splitlines()) == 3
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0, (
            proc.stdout.read().decode()[-2000:])
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


# --- published-pointer edge cases (satellite) ------------------------------


def test_garbled_pointer_heals_on_next_poll(trained):
    from fast_tffm_tpu.checkpoint import read_published, write_published
    cfg, steps, _wd = trained
    d = cfg.model_file + ".ckpt"
    write_published(d, steps[0])
    with open(os.path.join(d, "published"), "w") as fh:
        fh.write("not a step")
    assert read_published(d) is None  # garbled reads as "nothing yet"
    server = None
    try:
        # ...and the reload poll treats it the same way: no crash, no
        # reload attempt, previous step keeps serving.
        write_published(d, steps[0])
        from fast_tffm_tpu.serve.reload import ReloadWatcher
        server = _server(cfg)
        with open(os.path.join(d, "published"), "w") as fh:
            fh.write("")
        watcher = ReloadWatcher(server, poll_seconds=60)
        assert not watcher.poll_once()
        assert server.served_step == steps[0]
        write_published(d, steps[0])  # heal
        assert read_published(d) == steps[0]
    finally:
        write_published(d, steps[0])
        if server is not None:
            server.close()


def test_repoint_is_atomic_under_concurrent_reader(tmp_path):
    """A reader polling the pointer during rapid repoints only ever
    sees complete values (the atomic-rename write): never a torn/empty
    read, never a step that was not written."""
    from fast_tffm_tpu.checkpoint import read_published, write_published
    d = str(tmp_path)
    write_published(d, 1)
    stop = threading.Event()
    seen = set()
    bad = []

    def reader():
        while not stop.is_set():
            v = read_published(d)
            if v is None:
                bad.append("torn/unreadable read")
            else:
                seen.add(v)

    t = threading.Thread(target=reader)
    t.start()
    for i in range(400):
        write_published(d, 1 if i % 2 else 2)
    stop.set()
    t.join()
    assert not bad, bad[:3]
    assert seen <= {1, 2}


def test_wait_for_published_blocks_until_flip(tmp_path):
    from fast_tffm_tpu.checkpoint import wait_for_published, \
        write_published
    d = str(tmp_path)
    assert wait_for_published(d, timeout=0.05,
                              poll_seconds=0.01) is None
    write_published(d, 7)
    assert wait_for_published(d, timeout=5, poll_seconds=0.01) == 7
    # ``last`` semantics: the current value does not count as news.
    assert wait_for_published(d, last=7, timeout=0.05,
                              poll_seconds=0.01) is None
    t = threading.Timer(0.05, write_published, args=(d, 9))
    t.start()
    try:
        assert wait_for_published(d, last=7, timeout=5,
                                  poll_seconds=0.01) == 9
    finally:
        t.join()


def test_retention_never_strands_reload(tmp_path):
    """The retention contract end to end: published_at_risk fires
    BEFORE max_to_keep would evict the published step, and a pointer
    that does dangle (the at-risk signal ignored) degrades to a
    counted reload failure on the server — staleness, not an outage.
    """
    from fast_tffm_tpu.checkpoint import (CheckpointState,
                                          list_step_dirs,
                                          read_published)
    cfg = FmConfig(vocabulary_size=256, factor_num=2,
                   model_file=str(tmp_path / "m" / "fm"))
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), np.float32)
    ckpt = CheckpointState(cfg.model_file, max_to_keep=2)
    ckpt.save(1, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    ckpt.publish_step(1)
    assert not ckpt.published_at_risk()
    ckpt.save(2, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    # One more save evicts step 1: the stream driver must republish
    # FIRST (train.py's publish_due) — at_risk is that signal.
    assert ckpt.published_at_risk()
    ckpt.save(3, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    assert 1 not in list_step_dirs(ckpt.directory)  # evicted
    assert read_published(ckpt.directory) == 1      # dangling pointer
    assert ckpt.published_at_risk()  # still firing: republish heals
    ckpt.publish_step(3)
    assert not ckpt.published_at_risk()
    ckpt.close()


# --- fmckpt publish (satellite) --------------------------------------------


def test_fmckpt_publish_cli(trained, capsys):
    from fast_tffm_tpu.checkpoint import read_published
    from tools.fmckpt import main as fmckpt_main
    cfg, steps, _wd = trained
    d = cfg.model_file + ".ckpt"
    assert fmckpt_main(["publish", cfg.model_file,
                        str(steps[-1])]) == 0
    assert read_published(d) == steps[-1]
    out = capsys.readouterr().out
    assert "verified" in out
    # A missing step never moves the pointer.
    assert fmckpt_main(["publish", cfg.model_file, "424242"]) == 1
    assert read_published(d) == steps[-1]
    # Restore the module fixture's published step for later tests.
    assert fmckpt_main(["publish", cfg.model_file,
                        str(steps[0])]) == 0


def test_fmckpt_publish_refuses_torn_step(tmp_path, capsys):
    from fast_tffm_tpu.checkpoint import CheckpointState, read_published
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    from tools.fmckpt import cmd_publish
    cfg = FmConfig(vocabulary_size=256, factor_num=2,
                   model_file=str(tmp_path / "m" / "fm"))
    table = np.zeros((cfg.ckpt_rows, cfg.row_dim), np.float32)
    ckpt = CheckpointState(cfg.model_file)
    ckpt.save(1, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    ckpt.save(2, table, table, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    ckpt.close()
    d = cfg.model_file + ".ckpt"
    assert cmd_publish(d, 1) == 0
    truncate_checkpoint(cfg.model_file, step=2)
    assert cmd_publish(d, 2) == 1
    assert read_published(d) == 1  # pointer still names verified bytes


# --- fmstat SERVING --------------------------------------------------------


def test_stale_model_verdict():
    from fast_tffm_tpu.obs.attribution import health_verdict, stale_model
    base = {"counters": {"serve/requests": 10}, "hists": {},
            "health_events": [], "crash_events": [],
            "run_starts": 1, "run_ends": 1}
    fresh = dict(base, gauges={"serve/served_step": 26.0,
                               "serve/published_step": 26.0})
    assert stale_model(fresh) is None
    assert health_verdict(fresh)["verdict"] == "OK"
    lagging = dict(base, gauges={"serve/served_step": 20.0,
                                 "serve/published_step": 26.0})
    assert stale_model(lagging) == (20.0, 26.0)
    hv = health_verdict(lagging)
    assert hv["verdict"] == "STALE MODEL"
    assert "reload" in hv["detail"]
    # No serve gauges at all: not a serving stream, no verdict.
    assert stale_model(dict(base, gauges={})) is None


def test_serving_render_section():
    from fast_tffm_tpu.obs.attribution import render
    from fast_tffm_tpu.obs.registry import Histogram
    lat = Histogram(bounds=(1.0, 5.0, 50.0))
    for v in (0.5, 2.0, 2.5, 40.0):
        lat.observe(v)
    summary = {
        "meta": {"kind": "serve"}, "metas": [], "runs": 1,
        "events": 5, "spans": 0, "run_starts": 1, "run_ends": 1,
        "health_events": [], "crash_events": [], "scalars": [],
        "counters": {"serve/requests": 4, "serve/examples": 9,
                     "serve/flushes": 3, "serve/reloads": 1},
        "hists": {"serve/request_latency_ms": lat.summary()},
        "gauges": {"serve/served_step": 26.0,
                   "serve/published_step": 26.0},
        "gauges_by_process": {},
    }
    text = render(summary)
    assert "SERVING (run_tffm.py serve):" in text
    assert "request latency p50 / p99" in text
    assert "hot reloads (failed)" in text
    assert "served / published step" in text
