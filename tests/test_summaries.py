"""save_summaries_steps writes real TensorBoard scalars (the reference's
TF1 summary-writer knob; utils/summaries.py): train loss at the knob's
cadence, per-epoch validation AUC — buffered and flushed only at epoch
barriers so the cadence adds zero mid-stream device fetches."""

import dataclasses
import glob

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig

tf = pytest.importorskip("tensorflow")

from tests.test_e2e import make_dataset  # noqa: E402


def _read_scalars(logdir):
    from tensorflow.python.summary.summary_iterator import summary_iterator
    out = {}
    for path in glob.glob(logdir + "/events.*"):
        for e in summary_iterator(path):
            for v in e.summary.value:
                out.setdefault(v.tag, []).append(
                    (e.step, float(tf.make_ndarray(v.tensor))))
    return {k: sorted(v) for k, v in out.items()}


def test_train_writes_summary_scalars(tmp_path, rng):
    make_dataset(tmp_path / "train.txt", 128, rng)
    make_dataset(tmp_path / "val.txt", 64, rng)
    cfg = FmConfig(vocabulary_size=200, factor_num=4, batch_size=32,
                   learning_rate=0.1, epoch_num=2, shuffle=False,
                   train_files=(str(tmp_path / "train.txt"),),
                   validation_files=(str(tmp_path / "val.txt"),),
                   model_file=str(tmp_path / "m" / "fm"),
                   save_summaries_steps=2, log_steps=0)
    from fast_tffm_tpu.train import train
    train(cfg)
    scalars = _read_scalars(cfg.model_file + ".tb")
    # 2 epochs x 4 batches = 8 steps; cadence 2 -> steps 2,4,6,8.
    assert [s for s, _ in scalars["train/loss"]] == [2, 4, 6, 8]
    losses = [v for _, v in scalars["train/loss"]]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
    assert [s for s, _ in scalars["validation/auc"]] == [4, 8]
    assert all(0.0 <= v <= 1.0 for _, v in scalars["validation/auc"])
    assert len(scalars["train/examples_per_sec"]) == 4


def test_summaries_off_by_default(tmp_path, rng):
    make_dataset(tmp_path / "train.txt", 64, rng)
    cfg = FmConfig(vocabulary_size=200, factor_num=4, batch_size=32,
                   epoch_num=1, shuffle=False,
                   train_files=(str(tmp_path / "train.txt"),),
                   model_file=str(tmp_path / "m2" / "fm"), log_steps=0)
    from fast_tffm_tpu.train import train
    train(cfg)
    assert not glob.glob(cfg.model_file + ".tb/*")


def test_make_summaries_warns_without_tf(monkeypatch):
    import builtins
    real_import = builtins.__import__

    def no_tf(name, *a, **k):
        if name == "tensorflow" or name.startswith("tensorflow."):
            raise ImportError("forced absent")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_tf)
    import sys
    monkeypatch.delitem(sys.modules, "tensorflow", raising=False)
    from fast_tffm_tpu.utils.summaries import make_summaries
    cfg = FmConfig(save_summaries_steps=5)
    with pytest.warns(UserWarning, match="summaries are disabled"):
        assert make_summaries(cfg) is None
