"""Auxiliary subsystems (SURVEY.md §5): preemption save + profiler dump."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_cfg(tmp_path, n_lines=4096, extra=""):
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(n_lines):
        nnz = rng.integers(2, 10)
        ids = rng.choice(256, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    cfg = tmp_path / "t.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 256
factor_num = 4
model_file = {tmp_path}/model/fm

[Train]
train_files = {data}
epoch_num = 500
batch_size = 64
shuffle = False
log_steps = 2
{extra}
""")
    return cfg


@pytest.mark.slow
def test_sigterm_saves_checkpoint(tmp_path):
    cfg = _write_cfg(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen([sys.executable, "run_tffm.py", "train", str(cfg)],
                         cwd=REPO, env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    # Wait for training to be mid-flight, then preempt.
    deadline = time.time() + 120
    saw_step = False
    while time.time() < deadline:
        line = p.stdout.readline()
        if "step " in line:
            saw_step = True
            break
    assert saw_step, "no training step observed before deadline"
    p.send_signal(signal.SIGTERM)
    out = p.stdout.read()
    p.wait(timeout=120)
    assert p.returncode == 0, out
    assert "preemption signalled" in out
    assert "training done" in out
    ckpt = str(tmp_path / "model" / "fm.ckpt")
    assert os.path.isdir(ckpt) and os.listdir(ckpt)


def test_profile_trace_dump(tmp_path):
    """profile_dir writes a TensorBoard/Perfetto trace of a step window."""

    from fast_tffm_tpu.config import load_config
    from fast_tffm_tpu.train import train
    prof = tmp_path / "prof"
    cfg_path = _write_cfg(tmp_path, n_lines=512, extra=f"""
profile_dir = {prof}
profile_start_step = 2
profile_num_steps = 3
""")
    cfg = load_config(str(cfg_path))
    cfg = type(cfg)(**{**cfg.__dict__, "epoch_num": 1})
    train(cfg)
    dumped = []
    for root, _, files in os.walk(prof):
        dumped += files
    assert dumped, "no profiler trace files written"


def test_validation_max_batches_caps_eval(tmp_path, rng):
    """validation_max_batches bounds the per-epoch validation sweep
    (full Criteo-scale validation every epoch is a whole extra data
    pass); the final AUC still logs over the capped sample."""
    from tests.test_e2e import make_dataset
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.train import evaluate, train
    from fast_tffm_tpu.models.fm import init_table
    make_dataset(tmp_path / "train.txt", 64, rng)
    make_dataset(tmp_path / "val.txt", 320, rng)
    cfg = FmConfig(vocabulary_size=200, factor_num=4, batch_size=32,
                   epoch_num=1, shuffle=False,
                   train_files=(str(tmp_path / "train.txt"),),
                   validation_files=(str(tmp_path / "val.txt"),),
                   validation_max_batches=2,
                   model_file=str(tmp_path / "m" / "fm"),
                   log_file=str(tmp_path / "fm.log"))
    _, n = evaluate(cfg, init_table(cfg), cfg.validation_files,
                    max_batches=2)
    assert n == 64  # 2 batches x 32, not all 320
    train(cfg)
    log = (tmp_path / "fm.log").read_text()
    assert "validation AUC" in log
    assert "over 64 examples" in log


def test_deferred_loss_logging_emits_every_line(tmp_path, monkeypatch):
    """Forcing the slow-link path: every per-interval loss line must
    still be emitted (at epoch boundaries) with correct step numbers and
    real loss values — nothing dropped, nothing stale."""
    import re
    import numpy as np
    from fast_tffm_tpu import train as train_mod
    from fast_tffm_tpu.config import FmConfig

    rng = np.random.default_rng(5)
    lines = []
    for _ in range(64):
        ids = rng.choice(50, size=4, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:1" for i in ids]))
    p = tmp_path / "d.txt"
    p.write_text("\n".join(lines) + "\n")
    log_file = tmp_path / "t.log"
    cfg = FmConfig(vocabulary_size=50, factor_num=2, batch_size=16,
                   train_files=(str(p),), epoch_num=2, log_steps=1,
                   shuffle=False, learning_rate=0.1,
                   log_file=str(log_file),
                   model_file=str(tmp_path / "m" / "fm"))
    monkeypatch.setattr(train_mod, "LIVE_FETCH_BUDGET_S", -1.0)
    train_mod.train(cfg)
    text = log_file.read_text()
    assert "deferring loss log lines" in text
    steps = [int(m) for m in re.findall(r"step (\d+) epoch \d+ loss", text)]
    assert steps == list(range(1, 9)), steps  # 2 epochs x 4 batches
    losses = [float(m) for m in
              re.findall(r"loss (\d+\.\d+) examples/sec", text)]
    assert len(set(losses)) > 1  # real per-step values, not one repeated


def test_chunked_fetcher_stacked_and_mixed_paths():
    """ChunkedFetcher.flush: same-shape device arrays ride the
    stack-then-single-fetch branch, mixed shapes the per-array branch —
    both must deliver (value, meta) pairs in add order (the stacked
    branch exists because a list device_get is one link event PER
    array on a tunnelled device: 44x the transfers of one stacked
    fetch)."""
    import jax.numpy as jnp

    from fast_tffm_tpu.utils.fetch import ChunkedFetcher

    got = []
    f = ChunkedFetcher(lambda arr, meta: got.append((arr.copy(), meta)),
                       chunk=4)
    # Same-shape: 10 adds with chunk=4 -> two mid-stream flushes (the
    # stacked branch) plus a 2-element final flush.
    arrs = [jnp.full((3,), i, dtype=jnp.float32) for i in range(10)]
    for i, a in enumerate(arrs):
        f.add(a, meta=i)
    f.flush()
    assert [m for _, m in got] == list(range(10))
    for i, (arr, _) in enumerate(got):
        np.testing.assert_array_equal(arr, np.full((3,), i, np.float32))
    # Mixed shapes in one chunk: the fall-through per-array branch.
    got.clear()
    f.add(jnp.ones((2,), jnp.float32), meta="a")
    f.add(jnp.zeros((5,), jnp.float32), meta="b")
    f.flush()
    assert [(m, arr.shape) for arr, m in got] == [("a", (2,)), ("b", (5,))]


def test_chunked_fetcher_overlap_mode():
    """overlap=True: chunks fetch+consume on a background thread while
    the producer keeps adding; order, values, and the flush barrier
    (results fully consumed when flush returns) must all hold, and a
    consumer exception must surface at flush, not vanish with the
    thread."""
    import threading

    import jax.numpy as jnp
    import pytest

    from fast_tffm_tpu.utils.fetch import ChunkedFetcher

    got = []
    threads = set()

    def consume(arr, meta):
        threads.add(threading.current_thread().name)
        got.append((arr.copy(), meta))

    f = ChunkedFetcher(consume, chunk=4, overlap=True)
    for i in range(23):
        f.add(jnp.full((3,), i, dtype=jnp.float32), meta=i)
    f.flush()
    assert [m for _, m in got] == list(range(23))
    for i, (arr, _) in enumerate(got):
        np.testing.assert_array_equal(arr, np.full((3,), i, np.float32))
    assert threading.current_thread().name not in threads, (
        "overlap consume ran on the producer thread")
    # reusable after flush: the worker restarts on the next add
    got.clear()
    f.add(jnp.ones((2,), jnp.float32), meta="z")
    f.flush()
    assert [m for _, m in got] == ["z"]

    # consumer exception propagates at flush
    def boom(arr, meta):
        raise RuntimeError("consumer exploded")

    g = ChunkedFetcher(boom, chunk=2, overlap=True)
    g.add(jnp.ones((2,), jnp.float32))
    g.add(jnp.ones((2,), jnp.float32))
    with pytest.raises(RuntimeError, match="consumer exploded"):
        # the error may land on this add or the flush barrier
        g.add(jnp.ones((2,), jnp.float32))
        g.add(jnp.ones((2,), jnp.float32))
        g.flush()
    # the re-raising flush resets the fetcher; if the error landed on
    # an add instead, one more flush delivers-and-clears it
    try:
        g.flush()
    except RuntimeError:
        pass
    g.flush()  # clean: no stale error poisons reuse


def test_chunked_fetcher_close_unparks_worker(tmp_path):
    """ISSUE 3 satellite (ADVICE round 5): close() from a finally must
    drain and join the overlap worker — without it an exception
    mid-sweep leaves the thread parked on queue.get forever with a
    queued chunk pinned in device memory — and must NOT raise (an
    original error is usually propagating). Idempotent, and the
    fetcher stays reusable."""
    import threading

    import jax.numpy as jnp

    from fast_tffm_tpu.utils.fetch import ChunkedFetcher

    got = []
    f = ChunkedFetcher(lambda arr, meta: got.append(meta), chunk=2,
                       overlap=True)
    for i in range(4):  # two full chunks -> worker thread running
        f.add(jnp.full((3,), i, dtype=jnp.float32), meta=i)
    worker = f._worker
    assert worker is not None and worker.is_alive()
    f.close()                      # abandon path: no flush first
    assert f._worker is None
    worker.join(timeout=5)
    assert not worker.is_alive(), "close() left the worker parked"
    # a worker error present at close is swallowed, not raised
    f2 = ChunkedFetcher(lambda arr, meta: 1 / 0, chunk=1, overlap=True)
    f2.add(jnp.zeros((2,), jnp.float32))
    t0 = time.perf_counter()
    while not f2._err and time.perf_counter() - t0 < 5:
        time.sleep(0.01)
    f2.close()                     # no ZeroDivisionError escapes
    # ... and close() after a clean flush is a no-op
    f.add(jnp.ones((3,), jnp.float32), meta="x")
    f.flush()
    f.close()
    assert "x" in got


def test_evaluate_closes_fetcher_on_midsweep_error(tmp_path, rng):
    """evaluate() must re-raise a mid-sweep scoring error AND leave no
    fetcher worker behind (the try/finally satellite)."""
    import threading

    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.train import evaluate
    from tests.test_e2e import make_dataset

    make_dataset(tmp_path / "val.txt", 96, rng)
    cfg = FmConfig(vocabulary_size=200, factor_num=4, batch_size=16,
                   shuffle=False,
                   model_file=str(tmp_path / "m" / "fm"))
    # thread IDENTITIES, not names: every fetcher worker is named
    # "fetcher", so a name-based check is vacuous whenever an earlier
    # test left one alive
    before = set(threading.enumerate())
    table = np.zeros((cfg.num_rows, cfg.row_dim), np.float32)
    # a missing second file raises out of the input iterator after the
    # first file's batches are already queued behind the fetcher
    with pytest.raises(FileNotFoundError):
        evaluate(cfg, table, (str(tmp_path / "val.txt"),
                              str(tmp_path / "nope.txt")))
    time.sleep(0.2)
    leaked = [t for t in threading.enumerate()
              if t not in before and t.name == "fetcher"
              and t.is_alive()]
    assert not leaked, f"leaked fetcher threads: {leaked}"
