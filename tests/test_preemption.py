"""train.py's SIGTERM/SIGINT preemption path, exercised by REAL signal
delivery (testing/faults.preempt_after_steps raises the signal
in-process at a deterministic step): durable final save, epoch
metadata round-tripping through restore/resume_start_epoch, handler
teardown, and the PREEMPTED health verdict."""

import signal

import pytest

from fast_tffm_tpu.checkpoint import CheckpointState
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.testing.faults import preempt_after_steps
from fast_tffm_tpu.train import (checkpoint_template,
                                 resume_start_epoch, train)

N_LINES = 240
BATCH = 16
STEPS_PER_EPOCH = N_LINES // BATCH  # 15


def _cfg(tmp_path, **overrides):
    import numpy as np
    rng = np.random.default_rng(3)
    lines = []
    for _ in range(N_LINES):
        y = int(rng.integers(0, 2))
        lines.append(f"{y} {int(rng.integers(0, 50))}:1.0 "
                     f"{int(rng.integers(0, 50))}:0.5")
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    base = dict(vocabulary_size=50, factor_num=2, batch_size=BATCH,
                epoch_num=4, shuffle=False, log_steps=0,
                train_files=(str(data),),
                model_file=str(tmp_path / "model" / "fm"))
    base.update(overrides)
    return FmConfig(**base)


@pytest.mark.parametrize("sig", [signal.SIGTERM, signal.SIGINT])
def test_preemption_saves_durably_and_resumes(tmp_path, sig):
    cfg = _cfg(tmp_path)
    prev = signal.getsignal(sig)
    # Fire mid-epoch 1 (steps 16..30 belong to epoch index 1).
    with preempt_after_steps(STEPS_PER_EPOCH + 3, sig=sig) as state:
        train(cfg)
    assert state["fired"]
    # Handlers must be restored: a later real signal must not land in
    # train()'s dead flag list.
    assert signal.getsignal(sig) is prev

    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert restored is not None, "preemption save never landed"
    step = int(restored["step"])
    epoch = int(restored["epoch"])
    # The save is cut mid-schedule: exactly 1 completed epoch, and the
    # step counter reflects the interrupted position (the signal lands
    # at tick N; the loop drains it at the next step boundary).
    assert epoch == 1
    assert STEPS_PER_EPOCH < step <= STEPS_PER_EPOCH + 4
    # resume_start_epoch round-trip: the restart begins at the first
    # incomplete epoch, not zero and not done.
    assert resume_start_epoch(epoch, cfg.epoch_num) == 1

    # The restarted run completes the remaining schedule.
    train(cfg)
    ckpt = CheckpointState(cfg.model_file)
    final = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(final["epoch"]) == cfg.epoch_num
    assert int(final["step"]) >= 4 * STEPS_PER_EPOCH - 1


def test_preempted_health_event_and_fmstat_verdict(tmp_path, capsys):
    metrics = str(tmp_path / "m.jsonl")
    cfg = _cfg(tmp_path, metrics_file=metrics, metrics_flush_steps=5)
    with preempt_after_steps(STEPS_PER_EPOCH + 2):
        train(cfg)
    from fast_tffm_tpu.obs.attribution import health_verdict, summarize
    summary = summarize([metrics])
    hv = health_verdict(summary)
    assert hv["verdict"] == "PREEMPTED", hv
    assert "resume" in hv["detail"]
    # A clean preemption is not a crash: run_end was written.
    assert summary["run_ends"] == summary["run_starts"]

    # fmstat surfaces it in both text and --json modes.
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([metrics]) == 0
    assert "health: PREEMPTED" in capsys.readouterr().out
    import json
    assert fmstat_main(["--json", metrics]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["health"]["verdict"] == "PREEMPTED"


@pytest.mark.slow
def test_multiworker_sigterm_coordinates_group_stop(tmp_path):
    """ISSUE 6 satellite: a SIGTERM delivered to ONE worker of a
    lockstep group must stop, save, and exit EVERY worker at the same
    boundary — the flag rides the per-step and per-window (validation)
    allgathers, so the un-signalled worker sees it in the same
    gathered result instead of desyncing when its peer bails."""
    import os
    import signal
    import socket
    import subprocess
    import sys
    import time

    import numpy as np

    from fast_tffm_tpu.testing.faults import committed_steps, wait_until

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rng = np.random.default_rng(5)
    lines = []
    for _ in range(1600):
        nnz = rng.integers(2, 8)
        ids = rng.choice(50, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:{rng.random():.3f}" for i in ids]))
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    model = tmp_path / "model" / "fm"
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = s.getsockname()[1]
    cfg = tmp_path / "dist.cfg"
    cfg.write_text(f"""
[General]
vocabulary_size = 50
factor_num = 2
model_file = {model}

[Train]
train_files = {data}
validation_files = {data}
epoch_num = 40
batch_size = 32
learning_rate = 0.1
shuffle = False
log_steps = 0
save_steps = 10
metrics_file = {tmp_path}/metrics.jsonl
metrics_flush_steps = 2

[Cluster]
worker_hosts = localhost:{coord - 1000},localhost:{coord - 999}
heartbeat_seconds = 1.0
collective_timeout_seconds = 60
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    outs = [open(tmp_path / f"w{i}.out", "w") for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, "run_tffm.py", "train", str(cfg),
         "dist_train", "worker", str(i)],
        cwd=repo, env=env, stdout=outs[i], stderr=subprocess.STDOUT)
        for i in range(2)]
    try:
        # SIGTERM the NON-chief once the group is demonstrably
        # stepping in lockstep (a committed checkpoint step).
        wait_until(lambda: len(committed_steps(str(model))) >= 1,
                   timeout=240, message="first committed step")
        procs[1].send_signal(signal.SIGTERM)
        deadline = time.time() + 240
        while (any(p.poll() is None for p in procs)
               and time.time() < deadline):
            time.sleep(0.5)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=60)
        for fh in outs:
            fh.close()
    texts = [(tmp_path / f"w{i}.out").read_text() for i in range(2)]
    for i, text in enumerate(texts):
        assert procs[i].returncode == 0, f"worker {i}:\n{text[-2000:]}"
        # BOTH workers take the coordinated save-and-exit path, not
        # just the one that received the signal.
        assert "preemption signalled; saving and exiting" in text, (
            f"worker {i} missed the group stop:\n{text[-2000:]}")
        assert "training done" in text
    # the preemption save is durable and carries a mid-schedule epoch
    restored = CheckpointState(str(model)).restore(
        template=checkpoint_template(load_cfg_for(model, data)))
    assert restored is not None
    assert 0 <= int(restored["epoch"]) < 40
    # fmstat over both shards reads PREEMPTED (a clean exit), never
    # CRASHED/DEGRADED
    from fast_tffm_tpu.obs.attribution import health_verdict, summarize
    shards = [str(tmp_path / "metrics.jsonl")]
    p1 = str(tmp_path / "metrics.jsonl.p1")
    import os.path
    if os.path.exists(p1):
        shards.append(p1)
    assert health_verdict(summarize(shards))["verdict"] == "PREEMPTED"


def load_cfg_for(model, data):
    return FmConfig(vocabulary_size=50, factor_num=2, batch_size=32,
                    epoch_num=40, train_files=(str(data),),
                    model_file=str(model))


def test_second_signal_during_save_window_is_absorbed(tmp_path):
    """Handlers stay installed until the final save is on disk; a
    signal raised by the test right after train() returns must hit the
    ORIGINAL disposition (restored), while signals during the run are
    absorbed into the flag list."""
    cfg = _cfg(tmp_path, epoch_num=2)
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        with preempt_after_steps(3):
            train(cfg)
        assert seen == []  # train's handler owned the signal
        signal.raise_signal(signal.SIGTERM)
        assert seen == [signal.SIGTERM]  # ours is back
    finally:
        signal.signal(signal.SIGTERM, prev)
