"""train.py's SIGTERM/SIGINT preemption path, exercised by REAL signal
delivery (testing/faults.preempt_after_steps raises the signal
in-process at a deterministic step): durable final save, epoch
metadata round-tripping through restore/resume_start_epoch, handler
teardown, and the PREEMPTED health verdict."""

import signal

import pytest

from fast_tffm_tpu.checkpoint import CheckpointState
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.testing.faults import preempt_after_steps
from fast_tffm_tpu.train import (checkpoint_template,
                                 resume_start_epoch, train)

N_LINES = 240
BATCH = 16
STEPS_PER_EPOCH = N_LINES // BATCH  # 15


def _cfg(tmp_path, **overrides):
    import numpy as np
    rng = np.random.default_rng(3)
    lines = []
    for _ in range(N_LINES):
        y = int(rng.integers(0, 2))
        lines.append(f"{y} {int(rng.integers(0, 50))}:1.0 "
                     f"{int(rng.integers(0, 50))}:0.5")
    data = tmp_path / "train.txt"
    data.write_text("\n".join(lines) + "\n")
    base = dict(vocabulary_size=50, factor_num=2, batch_size=BATCH,
                epoch_num=4, shuffle=False, log_steps=0,
                train_files=(str(data),),
                model_file=str(tmp_path / "model" / "fm"))
    base.update(overrides)
    return FmConfig(**base)


@pytest.mark.parametrize("sig", [signal.SIGTERM, signal.SIGINT])
def test_preemption_saves_durably_and_resumes(tmp_path, sig):
    cfg = _cfg(tmp_path)
    prev = signal.getsignal(sig)
    # Fire mid-epoch 1 (steps 16..30 belong to epoch index 1).
    with preempt_after_steps(STEPS_PER_EPOCH + 3, sig=sig) as state:
        train(cfg)
    assert state["fired"]
    # Handlers must be restored: a later real signal must not land in
    # train()'s dead flag list.
    assert signal.getsignal(sig) is prev

    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert restored is not None, "preemption save never landed"
    step = int(restored["step"])
    epoch = int(restored["epoch"])
    # The save is cut mid-schedule: exactly 1 completed epoch, and the
    # step counter reflects the interrupted position (the signal lands
    # at tick N; the loop drains it at the next step boundary).
    assert epoch == 1
    assert STEPS_PER_EPOCH < step <= STEPS_PER_EPOCH + 4
    # resume_start_epoch round-trip: the restart begins at the first
    # incomplete epoch, not zero and not done.
    assert resume_start_epoch(epoch, cfg.epoch_num) == 1

    # The restarted run completes the remaining schedule.
    train(cfg)
    ckpt = CheckpointState(cfg.model_file)
    final = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(final["epoch"]) == cfg.epoch_num
    assert int(final["step"]) >= 4 * STEPS_PER_EPOCH - 1


def test_preempted_health_event_and_fmstat_verdict(tmp_path, capsys):
    metrics = str(tmp_path / "m.jsonl")
    cfg = _cfg(tmp_path, metrics_file=metrics, metrics_flush_steps=5)
    with preempt_after_steps(STEPS_PER_EPOCH + 2):
        train(cfg)
    from fast_tffm_tpu.obs.attribution import health_verdict, summarize
    summary = summarize([metrics])
    hv = health_verdict(summary)
    assert hv["verdict"] == "PREEMPTED", hv
    assert "resume" in hv["detail"]
    # A clean preemption is not a crash: run_end was written.
    assert summary["run_ends"] == summary["run_starts"]

    # fmstat surfaces it in both text and --json modes.
    from tools.fmstat import main as fmstat_main
    assert fmstat_main([metrics]) == 0
    assert "health: PREEMPTED" in capsys.readouterr().out
    import json
    assert fmstat_main(["--json", metrics]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["health"]["verdict"] == "PREEMPTED"


def test_second_signal_during_save_window_is_absorbed(tmp_path):
    """Handlers stay installed until the final save is on disk; a
    signal raised by the test right after train() returns must hit the
    ORIGINAL disposition (restored), while signals during the run are
    absorbed into the flag list."""
    cfg = _cfg(tmp_path, epoch_num=2)
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        with preempt_after_steps(3):
            train(cfg)
        assert seen == []  # train's handler owned the signal
        signal.raise_signal(signal.SIGTERM)
        assert seen == [signal.SIGTERM]  # ours is back
    finally:
        signal.signal(signal.SIGTERM, prev)
