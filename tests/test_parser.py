import numpy as np
import pytest

from fast_tffm_tpu.data.hashing import hash_feature
from fast_tffm_tpu.data.parser import ParseError, parse_lines


def test_basic_fm():
    block = parse_lines(["1 3:0.5 7:2.0 1", "0 2", "1 9:1.5"], 100)
    np.testing.assert_array_equal(block.labels, [1, 0, 1])
    np.testing.assert_array_equal(block.poses, [0, 3, 4, 5])
    np.testing.assert_array_equal(block.ids, [3, 7, 1, 2, 9])
    np.testing.assert_allclose(block.vals, [0.5, 2.0, 1.0, 1.0, 1.5])
    assert block.fields is None
    np.testing.assert_array_equal(block.sizes, [3, 1, 1])


def test_default_val_is_one():
    block = parse_lines(["1 5"], 10)
    np.testing.assert_allclose(block.vals, [1.0])


def test_blank_lines_skipped():
    block = parse_lines(["", "1 2", "   ", "0 3"], 10)
    assert block.batch_size == 2


def test_hashing_mode():
    block = parse_lines(["1 user_a:2.0 item_b"], 1000, hash_feature_id=True)
    assert block.ids[0] == hash_feature("user_a", 1000)
    assert block.ids[1] == hash_feature("item_b", 1000)
    np.testing.assert_allclose(block.vals, [2.0, 1.0])


def test_hashing_mode_accepts_ints_as_strings():
    a = parse_lines(["1 123"], 1000, hash_feature_id=True)
    assert a.ids[0] == hash_feature("123", 1000)


def test_ffm_format():
    block = parse_lines(["1 0:3:0.5 2:7", "0 1:2:1.5"], 100,
                        field_aware=True, field_num=3)
    np.testing.assert_array_equal(block.fields, [0, 2, 1])
    np.testing.assert_array_equal(block.ids, [3, 7, 2])
    np.testing.assert_allclose(block.vals, [0.5, 1.0, 1.5])


def test_errors():
    with pytest.raises(ParseError):
        parse_lines(["x 1:2"], 10)                       # bad label
    with pytest.raises(ParseError):
        parse_lines(["1 a:2"], 10)                       # string id, no hash
    with pytest.raises(ParseError):
        parse_lines(["1 50"], 10)                        # id out of range
    with pytest.raises(ParseError):
        parse_lines(["1 1:2:3"], 10)                     # 3 parts, not ffm
    with pytest.raises(ParseError):
        parse_lines(["1 9:1:0.5"], 10, field_aware=True, field_num=3)
    with pytest.raises(ParseError):
        parse_lines(["1 1:xyz"], 10)                     # bad value


def test_truncation():
    line = "1 " + " ".join(f"{i}:1" for i in range(50))
    block = parse_lines([line], 100, max_features_per_example=8)
    assert block.sizes[0] == 8
    np.testing.assert_array_equal(block.ids, np.arange(8))


def test_negative_and_float_labels():
    block = parse_lines(["-1 2", "0.5 3"], 10)
    np.testing.assert_allclose(block.labels, [-1.0, 0.5])
