"""Self-healing checkpoints (README "Checkpoint integrity & fallback"):
save-side integrity manifests, verified restore with quarantine +
last-good fallback, and the satellite coverage ISSUE 5 calls out
(export_npz pad-row slicing, the legacy-epoch both-attempts-fail path,
the fallback health verdict)."""

import json
import os

import numpy as np
import pytest

from fast_tffm_tpu.checkpoint import (CheckpointState, QUARANTINE_PREFIX,
                                      _restore_tolerating_legacy_epoch,
                                      compute_manifest, export_npz,
                                      list_step_dirs, manifest_path,
                                      read_manifest, verify_step_dir,
                                      write_manifest)
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import init_accumulator, init_table
from fast_tffm_tpu.train import checkpoint_template, ckpt_state
from tests.orbax_caps import orbax_supports_partial_restore


def _mk_state(tmp_path, vocab=1000, **kw):
    cfg = FmConfig(vocabulary_size=vocab, factor_num=4,
                   model_file=str(tmp_path / "m" / "fm"))
    table, acc = ckpt_state(cfg, init_table(cfg), init_accumulator(cfg))
    ckpt = CheckpointState(cfg.model_file, **kw)
    return cfg, table, acc, ckpt


def _save(ckpt, cfg, table, acc, step, epoch=0, **kw):
    ckpt.save(step, table, acc, vocabulary_size=cfg.vocabulary_size,
              epoch=epoch, **kw)


# --- save-side: manifests --------------------------------------------------


def test_committed_save_writes_manifest_with_payload_echo(tmp_path):
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 5, epoch=2, wait=True)
    man = read_manifest(ckpt.directory, 5)
    assert man is not None
    assert man["step"] == 5 and man["epoch"] == 2
    assert man["vocab"] == cfg.vocabulary_size
    # every manifest entry matches the bytes on disk exactly
    step_dir = os.path.join(ckpt.directory, "5")
    assert man["files"], "manifest must list the step's files"
    for rel, info in man["files"].items():
        p = os.path.join(step_dir, rel)
        assert os.path.getsize(p) == info["size"]
    ckpt.close()


def test_async_save_manifest_flushes_on_close_and_next_save(tmp_path):
    """The manifest can only describe a FINALIZED step dir, so an async
    save owes its manifest until the commit is certain: the next save
    dispatches it (on a background thread — the hash is a full re-read
    that must not stall the train loop), and the synchronous settle
    points (wait_until_finished, close) guarantee it is on disk."""
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1)           # async: manifest owed
    _save(ckpt, cfg, table, acc, 2)           # dispatches step 1's
    ckpt.wait_until_finished()                # joins 1's, settles 2's
    assert read_manifest(ckpt.directory, 1) is not None
    assert read_manifest(ckpt.directory, 2) is not None
    _save(ckpt, cfg, table, acc, 3)           # async again
    ckpt.close()                              # close settles step 3's
    assert read_manifest(ckpt.directory, 3) is not None


def test_manifests_pruned_with_gc_and_fresh_same_step_save(tmp_path):
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    ckpt2 = None
    try:
        for s in (10, 20, 30, 40):            # max_to_keep=3 drops 10
            _save(ckpt, cfg, table, acc, s, wait=True)
        assert not os.path.exists(manifest_path(ckpt.directory, 10))
        assert os.path.exists(manifest_path(ckpt.directory, 40))
    finally:
        ckpt.close()
    # cleared-and-reused dir: a stale same-step manifest describes the
    # OLD bytes and would brand the fresh save corrupt — it must go
    # before the fresh save's own manifest lands.
    stale = {"format": 1, "step": 50, "files": {"bogus": {
        "size": 1, "crc32": 0}}}
    write_manifest(ckpt.directory, 50, stale)
    ckpt2 = CheckpointState(cfg.model_file)
    try:
        _save(ckpt2, cfg, table, acc, 50, wait=True)
        man = read_manifest(ckpt2.directory, 50)
        assert "bogus" not in man["files"]
        assert ckpt2.verify_step(50) is None
    finally:
        ckpt2.close()


# --- verify ---------------------------------------------------------------


def test_verify_modes_size_and_full(tmp_path):
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    assert ckpt.verify_step(1) is None
    assert verify_step_dir(ckpt.directory, 1, "full") is None
    # same-size bit flip: invisible to the size pass, caught by full
    man = read_manifest(ckpt.directory, 1)
    rel = max(man["files"], key=lambda r: man["files"][r]["size"])
    p = os.path.join(ckpt.directory, "1", rel)
    with open(p, "r+b") as fh:
        fh.seek(os.path.getsize(p) - 1)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))
    assert verify_step_dir(ckpt.directory, 1, "size") is None
    reason = verify_step_dir(ckpt.directory, 1, "full")
    assert reason and "crc32 mismatch" in reason
    assert verify_step_dir(ckpt.directory, 1, "off") is None
    # truncation: caught by the cheap size pass
    truncate_checkpoint(cfg.model_file, step=1)
    reason = ckpt.verify_step(1)
    assert reason and "size mismatch" in reason
    ckpt.close()


def test_verify_without_manifest_is_unverifiable_not_fail(tmp_path):
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    os.remove(manifest_path(ckpt.directory, 1))
    assert ckpt.verify_step(1) is None  # pre-manifest steps restore
    ckpt.close()


def test_garbled_manifest_reads_as_corrupt(tmp_path):
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    with open(manifest_path(ckpt.directory, 1), "w") as fh:
        fh.write("{not json")
    reason = ckpt.verify_step(1)
    assert reason and "manifest" in reason
    ckpt.close()


# --- restore: fallback + quarantine ---------------------------------------


def test_restore_falls_back_and_quarantines_torn_step(tmp_path):
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, epoch=0, wait=True)
    _save(ckpt, cfg, table, acc, 2, epoch=1, wait=True)
    victim = truncate_checkpoint(cfg.model_file)
    assert victim
    restored = ckpt.restore(template=checkpoint_template(cfg))
    assert int(restored["step"]) == 1
    assert int(restored["epoch"]) == 0
    # the bad step is renamed, never deleted — bytes survive for
    # forensics, and the torn file itself travels with the dir
    qdir = os.path.join(ckpt.directory, f"{QUARANTINE_PREFIX}2")
    assert os.path.isdir(qdir)
    rel = os.path.relpath(victim, os.path.join(ckpt.directory, "2"))
    assert os.path.exists(os.path.join(qdir, rel))
    assert os.path.exists(os.path.join(qdir, "QUARANTINE"))
    assert os.path.exists(os.path.join(qdir, "manifest-2.json"))
    assert list_step_dirs(ckpt.directory) == [1]
    # the manager's view follows: latest_step no longer offers step 2
    assert ckpt.latest_step() == 1
    ckpt.close()


def test_restore_exception_walks_back_without_manifest(tmp_path):
    """Steps too old to carry a manifest: verification can't see the
    tear, so the orbax restore error itself triggers quarantine +
    walk-back."""
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    _save(ckpt, cfg, table, acc, 2, wait=True)
    for s in (1, 2):
        os.remove(manifest_path(ckpt.directory, s))
    truncate_checkpoint(cfg.model_file)  # tears step 2
    restored = ckpt.restore(template=checkpoint_template(cfg))
    assert int(restored["step"]) == 1
    assert os.path.isdir(os.path.join(ckpt.directory,
                                      f"{QUARANTINE_PREFIX}2"))
    ckpt.close()


def test_restore_last_candidate_error_raises_without_quarantine(tmp_path):
    """A restore failure on the LAST remaining step must stay a loud,
    actionable error (on a config mismatch it is the diagnosis for
    every step) — not a quarantine followed by a silent fresh start."""
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    os.remove(manifest_path(ckpt.directory, 1))  # hide it from verify
    truncate_checkpoint(cfg.model_file, step=1)
    with pytest.raises(ValueError, match="could not be restored"):
        ckpt.restore(template=checkpoint_template(cfg))
    # still there, still named as a step — nothing was quarantined
    assert list_step_dirs(ckpt.directory) == [1]
    ckpt.close()


def test_restore_all_steps_failing_verification_raises(tmp_path):
    """Every step failing INTEGRITY must not silently turn into a
    fresh start: quarantine them, then raise naming fmckpt."""
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    _save(ckpt, cfg, table, acc, 2, wait=True)
    truncate_checkpoint(cfg.model_file, step=1)
    truncate_checkpoint(cfg.model_file, step=2)
    with pytest.raises(ValueError, match="failed integrity"):
        ckpt.restore(template=checkpoint_template(cfg))
    assert list_step_dirs(ckpt.directory) == []
    names = sorted(os.listdir(ckpt.directory))
    assert f"{QUARANTINE_PREFIX}1" in names
    assert f"{QUARANTINE_PREFIX}2" in names
    ckpt.close()


def test_restore_empty_directory_still_fresh_start(tmp_path):
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    assert ckpt.restore(template=checkpoint_template(cfg)) is None
    ckpt.close()


def test_restore_explicit_step_verify_failure_raises_no_quarantine(
        tmp_path):
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    _save(ckpt, cfg, table, acc, 2, wait=True)
    truncate_checkpoint(cfg.model_file, step=2)
    with pytest.raises(ValueError, match="never quarantined"):
        ckpt.restore(step=2, template=checkpoint_template(cfg))
    assert list_step_dirs(ckpt.directory) == [1, 2]
    ckpt.close()


def test_verify_off_restores_historical_behavior(tmp_path):
    """ckpt_verify=off: the torn newest step raises on restore (there
    is an older step, so the restore-exception walk-back still heals —
    off only disables the MANIFEST pass, not the exception fallback)."""
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path, verify="off")
    _save(ckpt, cfg, table, acc, 1, wait=True)
    _save(ckpt, cfg, table, acc, 2, wait=True)
    truncate_checkpoint(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    assert int(restored["step"]) == 1
    ckpt.close()


def test_quarantine_suffix_on_repeat(tmp_path):
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    ckpt.quarantine_step(1, "test A")
    _save(ckpt, cfg, table, acc, 1, wait=True, force=True)
    ckpt.quarantine_step(1, "test B")
    names = sorted(os.listdir(ckpt.directory))
    assert f"{QUARANTINE_PREFIX}1" in names
    assert f"{QUARANTINE_PREFIX}1.1" in names
    ckpt.close()


@pytest.mark.skipif(
    not orbax_supports_partial_restore(),
    reason="installed orbax lacks PyTreeRestore(partial_restore=)")
def test_restore_partial_skips_bad_latest(tmp_path):
    """The offload read path (restore_partial) goes through the same
    verified step decision: a torn latest step is quarantined and the
    previous one serves the partial read."""
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    _save(ckpt, cfg, table, acc, 1, wait=True)
    _save(ckpt, cfg, table, acc, 2, wait=True)
    truncate_checkpoint(cfg.model_file)
    template = checkpoint_template(cfg, host=True)
    template.pop("acc")
    restored = ckpt.restore_partial(template)
    assert int(restored["step"]) == 1
    assert "acc" not in restored
    ckpt.close()


# --- telemetry: the ckpt_fallback health event + counters -----------------


def test_fallback_emits_health_event_and_counters(tmp_path):
    from fast_tffm_tpu.obs.sink import read_events
    from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    stream = str(tmp_path / "metrics.jsonl")
    tel = RunTelemetry(stream, meta={"kind": "test"})
    with activate(tel):
        _save(ckpt, cfg, table, acc, 1, wait=True)
        _save(ckpt, cfg, table, acc, 2, wait=True)
        truncate_checkpoint(cfg.model_file)
        restored = ckpt.restore(template=checkpoint_template(cfg))
    assert int(restored["step"]) == 1
    tel.close(step=2)
    ckpt.close()
    events = list(read_events(stream))
    health = [e for e in events if e.get("event") == "health"]
    assert [h["status"] for h in health] == ["ckpt_fallback"]
    assert health[0]["step"] == 2
    assert "size mismatch" in health[0]["reason"]
    assert QUARANTINE_PREFIX + "2" in health[0]["quarantined"]
    last = [e for e in events if e.get("event") == "metrics"][-1]
    c = last["counters"]
    assert c["checkpoint/saves"] == 2
    assert c["checkpoint/fallbacks"] == 1
    assert c["checkpoint/quarantined_steps"] == 1


def test_same_step_collision_not_counted_as_save(tmp_path):
    """fmstat's "checkpoint saves" row means saves that WROTE state:
    the final save colliding with the last periodic save (orbax
    no-op) must not inflate it."""
    from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
    cfg, table, acc, ckpt = _mk_state(tmp_path)
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={"kind": "test"})
    with activate(tel):
        _save(ckpt, cfg, table, acc, 7, epoch=0, wait=True)
        _save(ckpt, cfg, table, acc, 7, epoch=1, wait=True, force=True,
              rewrite_stale_metadata=True)
    c = tel.registry.snapshot()["counters"]
    assert c["checkpoint/saves"] == 1
    tel.close(step=7)
    ckpt.close()


def test_health_verdict_ok_with_fallback_annotation():
    """ISSUE 5 satellite: a run that healed itself must not read as
    silently green — OK, but annotated — while real failures keep
    their severity."""
    from fast_tffm_tpu.obs.attribution import health_verdict
    summary = {
        "health_events": [{"status": "ckpt_fallback", "step": 13,
                           "quarantined": "/m/fm.ckpt/corrupt-13"}],
        "run_starts": 1, "run_ends": 1,
    }
    hv = health_verdict(summary)
    assert hv["verdict"] == "OK (ckpt fallback x1)"
    assert "13" in hv["detail"] and "fmckpt" in hv["detail"]
    crashed = dict(summary, crash_events=[{"error": "boom"}])
    assert health_verdict(crashed)["verdict"] == "CRASHED"
    preempted = dict(summary)
    preempted["health_events"] = summary["health_events"] + [
        {"status": "preempted", "step": 20, "epoch": 1}]
    assert health_verdict(preempted)["verdict"] == "PREEMPTED"


def test_fmstat_render_shows_checkpoint_rows():
    from fast_tffm_tpu.obs.attribution import attribution, render
    summary = {
        "counters": {"checkpoint/saves": 7, "checkpoint/fallbacks": 1,
                     "checkpoint/quarantined_steps": 2},
        "gauges": {}, "hists": {}, "health_events": [], "meta": {},
        "run_starts": 1, "run_ends": 1,
    }
    att = attribution(summary)
    assert att["checkpoint_saves"] == 7
    assert att["checkpoint_fallbacks"] == 1
    assert att["checkpoint_quarantined"] == 2
    text = render(summary)
    assert "checkpoint saves" in text
    assert "ckpt fallbacks / quarantined steps" in text


# --- ISSUE 5 satellite coverage -------------------------------------------


def test_export_npz_slices_mesh_divisibility_pad_rows(tmp_path):
    """vocabulary_size slicing must drop BOTH the sentinel pad row and
    the 4096-alignment pad rows a mesh-sharded table carries
    (documented in export_npz; previously untested)."""
    cfg = FmConfig(vocabulary_size=5000, factor_num=4,
                   model_file=str(tmp_path / "m" / "fm"))
    assert cfg.ckpt_rows == 8192  # 5001 rounded up — real pad tail
    D = cfg.row_dim
    table = np.arange(cfg.ckpt_rows * D,
                      dtype=np.float32).reshape(cfg.ckpt_rows, D)
    path = str(tmp_path / "sharded.npz")
    export_npz(table, path, vocabulary_size=cfg.vocabulary_size)
    arr = np.load(path)["table"]
    assert arr.shape == (cfg.vocabulary_size, D)
    np.testing.assert_array_equal(arr, table[:cfg.vocabulary_size])
    # without vocabulary_size only the single trailing pad row drops —
    # valid for unsharded [num_rows, D] tables only
    path2 = str(tmp_path / "unsharded.npz")
    export_npz(table[:cfg.num_rows], path2)
    arr2 = np.load(path2)["table"]
    assert arr2.shape == (cfg.vocabulary_size, D)
    np.testing.assert_array_equal(arr2, table[:cfg.vocabulary_size])


def test_restore_tolerating_legacy_epoch_both_attempts_fail():
    """Both the full-template attempt AND the epoch-less legacy retry
    fail: the caller gets the ORIGINAL error (the legacy retry's error
    would misdiagnose a genuine config mismatch), and exactly two
    attempts are made."""
    calls = []

    def do_restore(t):
        calls.append(frozenset(t))
        raise ValueError(f"attempt {len(calls)}")

    template = {"table": 1, "acc": 2, "epoch": 0}
    restored, err = _restore_tolerating_legacy_epoch(template, do_restore)
    assert restored is None
    assert str(err) == "attempt 1"
    assert calls == [frozenset({"table", "acc", "epoch"}),
                     frozenset({"table", "acc"})]
    # no epoch leaf -> no legacy retry to try: one attempt, same error
    calls.clear()
    restored, err = _restore_tolerating_legacy_epoch({"table": 1},
                                                     do_restore)
    assert restored is None and str(err) == "attempt 1"
    assert len(calls) == 1


def test_compute_manifest_matches_disk(tmp_path):
    d = tmp_path / "c.ckpt" / "7" / "sub"
    d.mkdir(parents=True)
    (d / "a.bin").write_bytes(b"x" * 1000)
    (d.parent / "b.bin").write_bytes(b"y" * 10)
    man = compute_manifest(str(tmp_path / "c.ckpt"), 7,
                           payload={"epoch": 3, "vocab": 9})
    assert man["epoch"] == 3 and man["vocab"] == 9
    assert man["files"]["sub/a.bin"]["size"] == 1000
    assert man["files"]["b.bin"]["size"] == 10
    assert json.dumps(man)  # JSON-serializable as written
