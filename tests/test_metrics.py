import numpy as np
import pytest

from fast_tffm_tpu.metrics import StreamingAUC, exact_auc, sigmoid


def test_sigmoid_stable():
    x = np.array([-1000.0, -1.0, 0.0, 1.0, 1000.0])
    s = sigmoid(x)
    assert s[0] == 0.0 and s[-1] == 1.0
    assert s[2] == pytest.approx(0.5)
    assert np.all(np.diff(s) >= 0)


def test_exact_auc_known_values():
    assert exact_auc(np.array([0.1, 0.9]), np.array([0, 1])) == 1.0
    assert exact_auc(np.array([0.9, 0.1]), np.array([0, 1])) == 0.0
    assert exact_auc(np.array([0.5, 0.5]), np.array([0, 1])) == 0.5
    # perfect separation among many
    s = np.concatenate([np.arange(10), 100 + np.arange(10)])
    y = np.concatenate([np.zeros(10), np.ones(10)])
    assert exact_auc(s, y) == 1.0


def test_streaming_matches_exact(rng):
    scores = rng.normal(size=5000)
    labels = (rng.uniform(size=5000) < sigmoid(scores * 0.7)).astype(float)
    auc = StreamingAUC()
    for i in range(0, 5000, 617):           # uneven chunks
        auc.update(scores[i:i + 617], labels[i:i + 617])
    assert auc.result() == pytest.approx(exact_auc(scores, labels),
                                         abs=2e-3)


def test_streaming_weights_drop_padding(rng):
    scores = rng.normal(size=200)
    labels = (rng.uniform(size=200) < 0.5).astype(float)
    w = np.ones(200)
    a = StreamingAUC()
    a.update(scores, labels, w)
    # adding zero-weight garbage must not change the result
    b = StreamingAUC()
    b.update(np.concatenate([scores, rng.normal(size=50)]),
             np.concatenate([labels, np.ones(50)]),
             np.concatenate([w, np.zeros(50)]))
    assert a.result() == pytest.approx(b.result(), abs=1e-12)


def test_degenerate_labels():
    a = StreamingAUC()
    a.update(np.array([0.5, 0.7]), np.array([1.0, 1.0]))
    assert np.isnan(a.result())


def test_streaming_auc_survives_confident_logits(rng):
    """Logits far past the sigmoid's resolvable range must still rank:
    sigmoid binning collapsed everything beyond ~ln(num_bins) (~9.7)
    into one tie bin, reading AUC ~0.5 for a confidently-separating
    model (review finding; the arctan squash resolves to |x| ~ 21k)."""
    scores = rng.normal(40.0, 1.0, size=20000)
    labels = (rng.random(20000) < 1 / (1 + np.exp(-(scores - 40.0) * 2))
              ).astype(np.float64)
    auc = StreamingAUC()
    auc.update(scores, labels)
    want = exact_auc(scores, labels)
    assert abs(auc.result() - want) < 5e-3, (auc.result(), want)


def test_streaming_auc_rejects_nan_scores(rng):
    auc = StreamingAUC()
    with pytest.raises(ValueError, match="NaN"):
        auc.update(np.array([0.1, np.nan]), np.array([1.0, 0.0]))
