import numpy as np
import pytest

from fast_tffm_tpu.metrics import StreamingAUC, exact_auc, sigmoid


def test_sigmoid_stable():
    x = np.array([-1000.0, -1.0, 0.0, 1.0, 1000.0])
    s = sigmoid(x)
    assert s[0] == 0.0 and s[-1] == 1.0
    assert s[2] == pytest.approx(0.5)
    assert np.all(np.diff(s) >= 0)


def test_exact_auc_known_values():
    assert exact_auc(np.array([0.1, 0.9]), np.array([0, 1])) == 1.0
    assert exact_auc(np.array([0.9, 0.1]), np.array([0, 1])) == 0.0
    assert exact_auc(np.array([0.5, 0.5]), np.array([0, 1])) == 0.5
    # perfect separation among many
    s = np.concatenate([np.arange(10), 100 + np.arange(10)])
    y = np.concatenate([np.zeros(10), np.ones(10)])
    assert exact_auc(s, y) == 1.0


def test_streaming_matches_exact(rng):
    scores = rng.normal(size=5000)
    labels = (rng.uniform(size=5000) < sigmoid(scores * 0.7)).astype(float)
    auc = StreamingAUC()
    for i in range(0, 5000, 617):           # uneven chunks
        auc.update(scores[i:i + 617], labels[i:i + 617])
    assert auc.result() == pytest.approx(exact_auc(scores, labels),
                                         abs=2e-3)


def test_streaming_weights_drop_padding(rng):
    scores = rng.normal(size=200)
    labels = (rng.uniform(size=200) < 0.5).astype(float)
    w = np.ones(200)
    a = StreamingAUC()
    a.update(scores, labels, w)
    # adding zero-weight garbage must not change the result
    b = StreamingAUC()
    b.update(np.concatenate([scores, rng.normal(size=50)]),
             np.concatenate([labels, np.ones(50)]),
             np.concatenate([w, np.zeros(50)]))
    assert a.result() == pytest.approx(b.result(), abs=1e-12)


def test_degenerate_labels():
    a = StreamingAUC()
    a.update(np.array([0.5, 0.7]), np.array([1.0, 1.0]))
    assert np.isnan(a.result())
