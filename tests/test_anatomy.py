"""Step anatomy (obs/anatomy.py + the anatomy/* gauge surface):
synthetic two-rank streams with KNOWN injected clock offset/drift and
a known straggler must come back out of the clock fit and the verdict;
the pre-aggregated gauges must never touch the device; and the fmstat
EFFICIENCY / bench --compare consumers must read the same surfaces."""

import json
import os
import subprocess
import sys

import pytest

from fast_tffm_tpu.obs import anatomy
from fast_tffm_tpu.obs.attribution import efficiency_table
from fast_tffm_tpu.obs.telemetry import (RunTelemetry, anatomy_gauges,
                                         make_telemetry)
from fast_tffm_tpu.obs.sink import read_events


# ------------------------------------------------- synthetic streams

def _clock(offset, drift, t_ref=0.0):
    """A rank's wall clock as a function of true time: true + offset
    + drift * (true - t_ref). Rank 0 uses (0, 0) = truth."""
    return lambda true: true + offset + drift * (true - t_ref)


def _rank_events(pid, clock, barriers, locals_=()):
    """One rank's event list: run_start with the pid, then span events
    stamped in the rank's OWN clock. ``barriers`` is a list of
    (name, arrival_true, release_true); ``locals_`` of
    (name, start_true, dur_true)."""
    evs = [{"event": "run_start", "t": clock(0.0),
            "meta": {"process_index": pid}}]
    spans = [(n, a, r - a) for (n, a, r) in barriers] + list(locals_)
    for name, start, dur in sorted(spans, key=lambda s: s[1]):
        ts = clock(start)
        evs.append({"event": "span", "name": name, "t": ts, "ts": ts,
                    "dur": clock(start + dur) - ts, "tid": "main"})
    return evs


def _straggler_streams(offset=0.0, drift=0.0, n_steps=20,
                       late=0.04, transport=0.002):
    """Two ranks, flags barrier each 0.1 s step: rank 1 arrives
    ``late`` seconds after rank 0 (rank 1 is the straggler), release
    ``transport`` after the last arrival. Rank 1's stream is written
    in a clock offset/drifted from rank 0's."""
    b0, b1, l0, l1 = [], [], [], []
    for k in range(n_steps):
        t = 0.1 * k
        l0.append(("train/h2d", t, 0.005))
        l1.append(("train/h2d", t, 0.005))
        arr0, arr1 = t + 0.01, t + 0.01 + late
        rel = max(arr0, arr1) + transport
        b0.append(("train/step_flags", arr0, rel))
        b1.append(("train/step_flags", arr1, rel))
    return {
        0: _rank_events(0, _clock(0.0, 0.0), b0, l0),
        1: _rank_events(1, _clock(offset, drift), b1, l1),
    }


# ---------------------------------------------------- clock alignment

def test_clock_fit_recovers_injected_offset_and_drift():
    off, dr = 3.7, 50e-6  # 3.7 s offset, 50 ppm drift
    ranks = _straggler_streams(offset=off, drift=dr)
    rep = anatomy.build_report(ranks)
    c = rep["clock"][1]
    # The release edges are exactly affine in the synthetic streams,
    # so the fit is essentially exact: offset recovered to ~the drift
    # accumulated over the 2 s window, residual near zero.
    assert c["sync_points"] == 20
    assert c["offset_ms"] == pytest.approx(-off * 1e3, abs=1.0)
    assert c["drift_ppm"] == pytest.approx(-dr * 1e6, rel=0.1)
    assert c["residual_ms"] < 0.01
    # Round trip: rank 1's local release edges align onto rank 0's.
    fits = anatomy.align_clocks(ranks)
    clock1 = _clock(off, dr)
    for k in range(20):
        rel = 0.1 * k + 0.01 + 0.04 + 0.002
        assert fits[1].aligned(clock1(rel)) == pytest.approx(
            rel, abs=1e-6)


def test_identity_fit_for_reference_rank():
    rep = anatomy.build_report(_straggler_streams())
    assert rep["clock"][0]["offset_ms"] == 0.0
    assert rep["clock"][0]["drift_ppm"] == 0.0


# ------------------------------------------------ straggler anatomy

def test_straggler_attributed_through_skewed_clocks():
    """Rank 1 arrives 40 ms late at every flags barrier; its stream is
    written 3.7 s + 50 ppm away from rank 0's clock. Raw timestamps
    would call rank ONE the early one (its clock runs ahead) — only
    the aligned view names it."""
    rep = anatomy.build_report(
        _straggler_streams(offset=3.7, drift=50e-6))
    assert rep["straggler_rank"] == 1
    assert rep["ranks"][1]["last_arrivals"] == 20
    assert rep["ranks"][0]["last_arrivals"] == 0
    # Rank 0 pays the straggler wait (40 ms of each ~100 ms step);
    # rank 1 pays none.
    assert rep["ranks"][0]["phases"]["straggler wait"] == pytest.approx(
        0.04 * 20, rel=0.05)
    assert rep["ranks"][1]["phases"]["straggler wait"] == pytest.approx(
        0.0, abs=1e-3)
    assert rep["top_barrier"] == "train/step_flags"
    assert "straggler" in rep["verdict"]
    assert "rank 1" in rep["verdict"]
    # Efficiency: rank 0 loses the 42 ms wait of each ~100 ms step.
    assert rep["ranks"][0]["efficiency"] == pytest.approx(0.58,
                                                          abs=0.05)
    out = anatomy.render(rep)
    assert "STEP ANATOMY" in out and "straggler" in out


def test_transport_dominant_verdict():
    """Both ranks arrive together but the release comes 30 ms later:
    the wall is the collective itself, not a straggler."""
    rep = anatomy.build_report(
        _straggler_streams(late=0.0, transport=0.03))
    assert rep["transport_fraction"] > 0.15
    assert rep["straggler_wait_fraction"] < 0.05
    assert "transport" in rep["verdict"]


def test_baseline_eps_prices_the_in_program_stall():
    """With a single-process baseline rate, the report computes the
    ABSOLUTE per-worker efficiency (useful compute time / wall) —
    the number comparable to bench --multihost's counter-derived
    value, which also counts stalls inside the dispatched program."""
    ranks = _straggler_streams()
    # 2 s wall per rank; 400 examples at a 1000 eps baseline = 0.4 s
    # of useful compute -> efficiency_vs_single = 0.2.
    for pid in (0, 1):
        ranks[pid].append({"event": "metrics", "t": 2.1, "step": 20,
                           "counters": {"train/examples": 400.0},
                           "gauges": {}, "hists": {}})
    rep = anatomy.build_report(ranks, baseline_eps=1000.0)
    for pid in (0, 1):
        assert rep["ranks"][pid]["examples"] == 400.0
        assert rep["ranks"][pid]["efficiency_vs_single"] == \
            pytest.approx(0.2, rel=0.1)
    assert rep["efficiency_vs_single"] == pytest.approx(0.2, rel=0.1)
    assert "vs single-process rate" in rep["verdict"]
    assert "0.2" in anatomy.render(rep)
    # Without a baseline the field stays out of the report rows.
    rep2 = anatomy.build_report(_straggler_streams())
    assert rep2["efficiency_vs_single"] is None
    assert "efficiency_vs_single" not in rep2["ranks"][0]


def test_in_program_wall_verdict():
    """Dominant 'step dispatch' on a multi-rank run: the verdict must
    say the wall is inside the dispatched program (the host cannot
    split in-program allreduce from compute), not claim efficiency."""
    b0, b1, l0, l1 = [], [], [], []
    for k in range(10):
        t = 0.1 * k
        # 80 ms of every 100 ms step inside the dispatched program.
        l0.append(("train/step", t, 0.08))
        l1.append(("train/step", t, 0.08))
        b0.append(("train/step_flags", t + 0.085, t + 0.09))
        b1.append(("train/step_flags", t + 0.085, t + 0.09))
    ranks = {0: _rank_events(0, _clock(0.0, 0.0), b0, l0),
             1: _rank_events(1, _clock(0.0, 0.0), b1, l1)}
    rep = anatomy.build_report(ranks)
    assert "inside the dispatched program" in rep["verdict"]


def test_empty_input_is_an_error_report():
    rep = anatomy.build_report({})
    assert "error" in rep
    assert "trace_spans" in anatomy.render(rep)


# -------------------------------------------------- fmtrace --anatomy

def _write_streams(tmp_path, ranks):
    paths = []
    for pid, evs in ranks.items():
        p = str(tmp_path / (f"m.jsonl" if pid == 0
                            else f"m.jsonl.p{pid}"))
        with open(p, "w") as fh:
            for e in evs:
                fh.write(json.dumps(e) + "\n")
        paths.append(p)
    return paths


def test_fmtrace_anatomy_cli(tmp_path, capsys):
    from tools.fmtrace import main
    paths = _write_streams(tmp_path,
                           _straggler_streams(offset=1.25))
    assert main(["--anatomy"] + paths) == 0
    out = capsys.readouterr().out
    assert "STEP ANATOMY" in out and "verdict:" in out
    assert main(["--anatomy", "--json"] + paths) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["straggler_rank"] == 1
    assert rep["clock"]["1"]["offset_ms"] == pytest.approx(-1250.0,
                                                           abs=1.0)


# ------------------------------------------------- anatomy/* gauges

def test_anatomy_gauges_derive_from_snapshot():
    snap = {
        "counters": {"train/input_wait_seconds": 1.5,
                     "pipeline/build_seconds": 0.5,
                     "train/step_flags_seconds": 2.0,
                     "train/examples": 640.0},
        "gauges": {},
        "hists": {"train/step_seconds":
                  {"count": 20, "sum": 10.0}},
    }
    rows = anatomy_gauges(snap)
    assert rows["anatomy/input_wait_seconds"] == 1.5
    assert rows["anatomy/host_build_seconds"] == 0.5
    assert rows["anatomy/flags_wait_seconds"] == 2.0
    assert rows["anatomy/step_wall_seconds"] == 10.0
    assert rows["anatomy/steps"] == 20.0
    assert rows["anatomy/examples"] == 640.0
    # Phases the run never recorded stay absent, not zero rows.
    assert "anatomy/h2d_seconds" not in rows


def test_anatomy_gauges_add_zero_device_fetches(tmp_path, monkeypatch):
    """The EFFICIENCY surface is pre-aggregated host floats: a flush
    with anatomy on performs NO bulk_fetch (the scalar barrier remains
    the only fetch point, exactly as without anatomy)."""
    import fast_tffm_tpu.utils.fetch as fetch
    calls = []
    monkeypatch.setattr(fetch, "bulk_fetch",
                        lambda pairs, consume: calls.append(len(pairs))
                        or [])
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={},
                       flush_steps=1, anatomy=True)
    tel.count("train/step_flags_seconds", 0.25)
    tel.count("lockstep/allgather_seconds", 0.5)
    tel.count("train/examples", 64)
    tel.observe("train/step_seconds", 0.1)
    tel.maybe_flush(1)
    tel.barrier_flush(2)
    tel.close()
    assert calls == []  # no buffered scalars -> no fetch, ever
    evs = [e for e in read_events(str(tmp_path / "m.jsonl"))
           if e.get("event") == "metrics"]
    assert evs
    g = evs[-1]["gauges"]
    assert g["anatomy/flags_wait_seconds"] == 0.25
    assert g["anatomy/allgather_seconds"] == 0.5
    assert g["anatomy/step_wall_seconds"] == pytest.approx(0.1)


def test_anatomy_off_emits_no_gauges(tmp_path):
    tel = RunTelemetry(str(tmp_path / "m.jsonl"), meta={},
                       flush_steps=1, anatomy=False)
    tel.count("train/step_flags_seconds", 0.25)
    tel.observe("train/step_seconds", 0.1)
    tel.maybe_flush(1)
    tel.close()
    evs = [e for e in read_events(str(tmp_path / "m.jsonl"))
           if e.get("event") == "metrics"]
    assert not any(k.startswith("anatomy/")
                   for k in evs[-1]["gauges"])


def test_make_telemetry_reads_anatomy_knob(tmp_path):
    from fast_tffm_tpu.config import FmConfig
    cfg = FmConfig(vocabulary_size=16, factor_num=2,
                   train_files=("x",),
                   model_file=str(tmp_path / "fm"),
                   metrics_file=str(tmp_path / "m.jsonl"))
    tel = make_telemetry(cfg, "train")
    assert tel is not None and tel.anatomy is True
    tel.close()
    cfg2 = FmConfig(vocabulary_size=16, factor_num=2,
                    train_files=("x",),
                    model_file=str(tmp_path / "fm2"),
                    metrics_file=str(tmp_path / "m2.jsonl"),
                    anatomy=False)
    tel2 = make_telemetry(cfg2, "train")
    assert tel2 is not None and tel2.anatomy is False
    tel2.close()


# -------------------------------------------- fmstat EFFICIENCY rows

def _proc_gauges(wall, flags, allgather, examples, build=0.0):
    return {"anatomy/step_wall_seconds": wall,
            "anatomy/flags_wait_seconds": flags,
            "anatomy/allgather_seconds": allgather,
            "anatomy/host_build_seconds": build,
            "anatomy/examples": examples}


def test_efficiency_table_names_the_straggler():
    # Rank 1 waits the LEAST -> everyone else waits on rank 1.
    summary = {"gauges_by_process": {
        0: _proc_gauges(10.0, 4.0, 1.0, 640.0),
        1: _proc_gauges(10.0, 0.5, 0.5, 640.0, build=6.0),
    }}
    eff = efficiency_table(summary)
    assert eff is not None
    assert eff["straggler_rank"] == 1
    assert eff["ranks"][0]["efficiency"] == pytest.approx(0.5)
    assert eff["ranks"][1]["efficiency"] == pytest.approx(0.9)
    assert "rank 1" in eff["verdict"]
    assert "host build" in eff["verdict"]


def test_efficiency_table_absent_without_coordination():
    # Single-process run: anatomy gauges but no collective waits.
    summary = {"gauges_by_process": {
        0: {"anatomy/step_wall_seconds": 10.0,
            "anatomy/examples": 640.0}}}
    assert efficiency_table(summary) is None
    assert efficiency_table({"gauges_by_process": {}}) is None


def test_fmstat_renders_efficiency_section(tmp_path, capsys):
    """A merged stream whose processes carry anatomy/* gauges gets the
    EFFICIENCY section, verdict line included."""
    from tools.fmstat import main as fmstat_main
    for pid in (0, 1):
        p = str(tmp_path / ("m.jsonl" if pid == 0
                            else f"m.jsonl.p{pid}"))
        with open(p, "w") as fh:
            fh.write(json.dumps(
                {"event": "run_start", "t": 0.0,
                 "meta": {"kind": "train",
                          "process_index": pid}}) + "\n")
            fh.write(json.dumps(
                {"event": "metrics", "t": 10.0, "step": 100,
                 "run": {"kind": "train", "process_index": pid},
                 "counters": {"train/examples": 640.0},
                 "gauges": _proc_gauges(10.0, 4.0 - 3.0 * pid, 1.0,
                                        640.0),
                 "hists": {}}) + "\n")
    rc = fmstat_main([str(tmp_path / "m.jsonl"),
                      str(tmp_path / "m.jsonl.p1")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "EFFICIENCY (step anatomy):" in out
    assert "collective wait" in out


# --------------------------------------------------- bench --compare

def _run_compare(args):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "bench.py", "--compare"] + args,
        cwd=repo, env=env, capture_output=True, text=True)


@pytest.mark.slow
def test_bench_compare_flags_regressions(tmp_path):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    # Wrapper form (BENCH_rNN.json): the parsed payload is the metric.
    old.write_text(json.dumps({
        "n": 1, "cmd": "python bench.py", "rc": 0,
        "parsed": {"metric": "examples_per_sec", "value": 1000.0,
                   "step_p50_ms": 10.0}}))
    new.write_text(json.dumps({"metric": "examples_per_sec",
                               "value": 990.0, "step_p50_ms": 10.5}))
    r = _run_compare([str(old), str(new)])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no regressions" in r.stdout
    # A 40% rate drop and a 2x latency blowup both trip the gate.
    new.write_text(json.dumps({"metric": "examples_per_sec",
                               "value": 600.0, "step_p50_ms": 25.0}))
    r = _run_compare([str(old), str(new)])
    assert r.returncode == 1
    assert "REGRESSION" in r.stdout
    assert "value" in r.stdout and "step_p50_ms" in r.stdout
    # ...and a generous tolerance waves the same diff through.
    r = _run_compare([str(old), str(new), "--tolerance", "0.1"])
    assert r.returncode == 0


# ------------------------------------------- real 2-process anatomy

@pytest.mark.slow
def test_two_process_run_names_the_collective_wall(tmp_path):
    """A REAL 2-process gloo cluster with tracing on: fmtrace
    --anatomy must align the shards, match barriers, and name the
    collective wall this container actually has (the flags allgather
    and the transport that absorbs queued device compute)."""
    import socket as socketlib
    with socketlib.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    data = tmp_path / "train.txt"
    lines = ["%d %d:1 %d:1" % (i % 2, i % 97, 97 + (i * 7) % 89)
             for i in range(1920)]
    data.write_text("\n".join(lines) + "\n")
    metrics = str(tmp_path / "metrics.jsonl")
    cfg = tmp_path / "anatomy.cfg"
    hosts = ",".join(f"localhost:{coord - 1000 + i}" for i in range(2))
    cfg.write_text(f"""
[General]
vocabulary_size = 256
factor_num = 4
model_file = {tmp_path / 'model' / 'fm'}

[Train]
train_files = {data}
epoch_num = 1
batch_size = 32
learning_rate = 0.05
shuffle = False
log_steps = 0
metrics_file = {metrics}
trace_spans = True

[Cluster]
worker_hosts = {hosts}
""")
    procs = [subprocess.Popen(
        [sys.executable, "run_tffm.py", "train", str(cfg),
         "dist_train", "worker", str(i)],
        cwd=repo, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL) for i in range(2)]
    rcs = [p.wait(timeout=300) for p in procs]
    assert rcs == [0, 0]
    shards = [metrics, metrics + ".p1"]
    assert all(os.path.exists(p) for p in shards)
    rep = anatomy.report(shards)
    assert "error" not in rep
    assert rep["matched_barriers"] > 0
    assert rep["top_barrier"] in anatomy.BARRIER_SPANS
    assert set(rep["ranks"]) == {0, 1}
    for r in rep["ranks"].values():
        assert 0.0 <= r["efficiency"] <= 1.0
    # Localhost gloo: the clock fit must land far under a step.
    for c in rep["clock"].values():
        assert c["residual_ms"] < 50.0
    # The verdict names the wall this container actually has: the
    # in-program allreduce inside the dispatched step program, or (on
    # a loaded machine) a straggler/transport-dominated barrier.
    assert ("inside the dispatched program" in rep["verdict"]
            or "straggler" in rep["verdict"]
            or "transport" in rep["verdict"])
    out = anatomy.render(rep)
    assert "verdict:" in out
    # The JSONL-only EFFICIENCY surface sees the same run: per-worker
    # efficiency from pre-aggregated gauges within 25% (absolute) of
    # the trace-replay number (different denominators: gauges use the
    # step-wall histogram, the replay uses span coverage).
    from fast_tffm_tpu.obs.attribution import summarize
    eff = efficiency_table(summarize(shards))
    assert eff is not None
    for pid, row in eff["ranks"].items():
        assert abs(row["efficiency"]
                   - rep["ranks"][pid]["efficiency"]) < 0.25
