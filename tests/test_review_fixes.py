"""Regression tests for review findings: bucket-ladder overflow, blank-
line alignment in predict, kernel validation, zero-step train runs."""

import textwrap

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import parse_lines
from fast_tffm_tpu.data.pipeline import make_device_batch
from tests.orbax_caps import orbax_enforces_template_shapes


def test_example_longer_than_ladder_gets_pow2_bucket():
    cfg = FmConfig(vocabulary_size=5000, batch_size=2,
                   bucket_ladder=(4, 8), max_features_per_example=0)
    line = "1 " + " ".join(f"{i}:1" for i in range(300))
    block = parse_lines([line], 5000)
    b = make_device_batch(block, cfg)
    assert b.local_idx.shape[1] == 512        # next pow2 above 300
    assert b.num_real == 1


def test_keep_empty_preserves_line_alignment():
    lines = ["1 3:1", "", "0 4:1", "   "]
    block = parse_lines(lines, 10, keep_empty=True)
    assert block.batch_size == 4
    np.testing.assert_array_equal(block.sizes, [1, 0, 1, 0])
    # without keep_empty blanks are dropped (training path)
    assert parse_lines(lines, 10).batch_size == 2


def test_predict_blank_line_scores(tmp_path, rng):
    import run_tffm
    train = tmp_path / "train.txt"
    train.write_text("".join(
        f"{i % 2} {1 if i % 2 else 2}:1\n" for i in range(64)))
    pred = tmp_path / "pred.txt"
    pred.write_text("1 1:1\n\n0 2:1\n")
    cfg = tmp_path / "c.cfg"
    cfg.write_text(textwrap.dedent(f"""
        [General]
        vocabulary_size = 10
        factor_num = 2
        model_file = {tmp_path}/m/fm
        [Train]
        train_files = {train}
        epoch_num = 2
        batch_size = 16
        learning_rate = 0.1
        [Predict]
        predict_files = {pred}
        score_path = {tmp_path}/score
    """))
    assert run_tffm.main(["train", str(cfg)]) == 0
    assert run_tffm.main(["predict", str(cfg)]) == 0
    scores = (tmp_path / "score" / "pred.txt.score").read_text().splitlines()
    assert len(scores) == 3                   # one per input line, blank too
    assert float(scores[1]) == pytest.approx(0.5)  # empty example -> sigmoid(0)


def test_kernel_validated():
    with pytest.raises(ValueError):
        FmConfig(kernel="cuda")


def test_multiprocess_rejects_unlimited_features(tmp_path, monkeypatch):
    # max_features_per_example = 0 ("unlimited") must be refused up front
    # in multi-process mode: an over-long example caught lazily mid-run
    # would kill one worker between collectives and hang its peers.
    import jax
    from fast_tffm_tpu.train import train
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    data = tmp_path / "t.txt"
    data.write_text("1 1:1\n0 2:1\n")
    cfg = FmConfig(vocabulary_size=8, batch_size=2,
                   train_files=(str(data),),
                   model_file=str(tmp_path / "m" / "fm"),
                   max_features_per_example=0)
    with pytest.raises(ValueError, match="max_features_per_example"):
        train(cfg)


def test_fast_path_extends_ladder_like_generic(tmp_path):
    # max_features_per_example past the ladder top: the fast path must
    # emit the same extended power-of-two bucket the generic path
    # compiles for (512 here), not a non-ladder width.
    from fast_tffm_tpu.data.cparser import available
    from fast_tffm_tpu.data.pipeline import batch_iterator
    if not available():
        pytest.skip("C++ parser unavailable")
    data = tmp_path / "t.txt"
    long_line = "1 " + " ".join(f"{i}:1" for i in range(300))
    data.write_text(long_line + "\n0 1:1\n")
    cfg = FmConfig(vocabulary_size=5000, batch_size=2,
                   bucket_ladder=(4, 8), max_features_per_example=300,
                   shuffle=False)
    batches = list(batch_iterator(cfg, [str(data)], training=True,
                                  epochs=1))
    assert batches[0].local_idx.shape[1] == 512
    assert batches[0].num_real == 2


def test_ignored_reference_knobs_warn(tmp_path):
    from fast_tffm_tpu.config import load_config
    p = tmp_path / "c.cfg"
    p.write_text("[General]\nvocabulary_block_num = 100\n"
                 "[Train]\nshuffle_threads = 4\n")
    with pytest.warns(UserWarning, match="vocabulary_block_num"):
        cfg = load_config(str(p))
    # shuffle_threads is no longer a warned no-op: it maps to the input
    # pipeline's prefetch lookahead (clamped to [2, 8]).
    assert cfg.prefetch_depth == 4
    import dataclasses
    assert dataclasses.replace(cfg, shuffle_threads=99).prefetch_depth == 8
    assert dataclasses.replace(cfg, shuffle_threads=0).prefetch_depth == 2


@pytest.mark.skipif(
    not orbax_enforces_template_shapes(),
    reason="installed orbax silently restores shape-mismatched "
           "templates (sharding-from-file path), so the actionable "
           "error can never trigger (ISSUE 3 triage)")
def test_checkpoint_shape_mismatch_is_actionable(tmp_path):
    # A checkpoint written under one config restored under another must
    # fail with a message naming the shapes and the fix, not orbax's
    # internal shape error.
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.models.fm import init_accumulator, init_table
    from fast_tffm_tpu.train import checkpoint_template, ckpt_state
    model = str(tmp_path / "m" / "fm")
    cfg = FmConfig(vocabulary_size=64, factor_num=4, model_file=model)
    ckpt = CheckpointState(model)
    ckpt.save(1, *ckpt_state(cfg, init_table(cfg), init_accumulator(cfg)),
              vocabulary_size=cfg.vocabulary_size, force=True)
    ckpt.close()
    cfg2 = FmConfig(vocabulary_size=64, factor_num=8, model_file=model)
    ckpt2 = CheckpointState(model)
    with pytest.raises(ValueError, match="different config"):
        ckpt2.restore(template=checkpoint_template(cfg2))
    ckpt2.close()


def test_checkpoint_vocab_change_same_bucket_rejected(tmp_path):
    # vocabulary_size changes within the same 4096-row storage bucket
    # keep the stored shape identical, so the shape check can't fire;
    # the stored vocab leaf must catch it (a silent restore would turn
    # a trained row into the pad row).
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.models.fm import init_accumulator, init_table
    from fast_tffm_tpu.train import (check_restored_vocab,
                                     checkpoint_template, ckpt_state)
    model = str(tmp_path / "m" / "fm")
    cfg = FmConfig(vocabulary_size=2000, factor_num=4, model_file=model)
    ckpt = CheckpointState(model)
    ckpt.save(1, *ckpt_state(cfg, init_table(cfg), init_accumulator(cfg)),
              vocabulary_size=cfg.vocabulary_size, force=True)
    ckpt.close()
    cfg2 = FmConfig(vocabulary_size=1000, factor_num=4, model_file=model)
    assert cfg2.ckpt_rows == cfg.ckpt_rows  # same storage bucket
    ckpt2 = CheckpointState(model)
    restored = ckpt2.restore(template=checkpoint_template(cfg2))
    ckpt2.close()
    with pytest.raises(ValueError, match="vocabulary_size=2000"):
        check_restored_vocab(cfg2, restored)


def test_profiler_closed_when_loop_raises(tmp_path):
    # A parse error mid-loop with the profiler window open must still
    # stop the trace (finally), or the next start_trace in this process
    # fails with "trace already in progress".
    import jax
    from fast_tffm_tpu.data.parser import ParseError
    from fast_tffm_tpu.train import train
    data = tmp_path / "t.txt"
    good = "".join(f"{i % 2} {i % 5}:1\n" for i in range(8))
    data.write_text(good + "1 not_an_id:1\n")
    cfg = FmConfig(vocabulary_size=8, batch_size=8, epoch_num=1,
                   shuffle=False, train_files=(str(data),),
                   model_file=str(tmp_path / "m" / "fm"),
                   profile_dir=str(tmp_path / "prof"),
                   profile_start_step=0, profile_num_steps=10)
    with pytest.raises(ParseError):
        train(cfg)
    jax.profiler.start_trace(str(tmp_path / "prof2"))  # must not raise
    jax.profiler.stop_trace()


def test_cluster_wiring_surface():
    from fast_tffm_tpu.parallel.distributed import (coordinator_address,
                                                    init_from_cluster)
    # Single-host cluster: no jax.distributed, trivial shard.
    assert init_from_cluster(FmConfig(), "worker", 0) == (0, 1)
    cfg = FmConfig(worker_hosts=("a:2230", "b:2230"))
    # Coordinator is chief worker's host on a shifted port (the worker
    # port itself belongs to the reference's gRPC surface).
    assert coordinator_address(cfg) == "a:3230"
    assert coordinator_address(FmConfig(worker_hosts=("a",))) == "a:8476"
    with pytest.raises(ValueError, match="out of range"):
        init_from_cluster(cfg, "worker", 5)
    with pytest.raises(ValueError, match="job_name"):
        init_from_cluster(cfg, "ps", 0)
