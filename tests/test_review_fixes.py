"""Regression tests for review findings: bucket-ladder overflow, blank-
line alignment in predict, kernel validation, zero-step train runs."""

import textwrap

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.parser import parse_lines
from fast_tffm_tpu.data.pipeline import make_device_batch


def test_example_longer_than_ladder_gets_pow2_bucket():
    cfg = FmConfig(vocabulary_size=5000, batch_size=2,
                   bucket_ladder=(4, 8), max_features_per_example=0)
    line = "1 " + " ".join(f"{i}:1" for i in range(300))
    block = parse_lines([line], 5000)
    b = make_device_batch(block, cfg)
    assert b.local_idx.shape[1] == 512        # next pow2 above 300
    assert b.num_real == 1


def test_keep_empty_preserves_line_alignment():
    lines = ["1 3:1", "", "0 4:1", "   "]
    block = parse_lines(lines, 10, keep_empty=True)
    assert block.batch_size == 4
    np.testing.assert_array_equal(block.sizes, [1, 0, 1, 0])
    # without keep_empty blanks are dropped (training path)
    assert parse_lines(lines, 10).batch_size == 2


def test_predict_blank_line_scores(tmp_path, rng):
    import run_tffm
    train = tmp_path / "train.txt"
    train.write_text("".join(
        f"{i % 2} {1 if i % 2 else 2}:1\n" for i in range(64)))
    pred = tmp_path / "pred.txt"
    pred.write_text("1 1:1\n\n0 2:1\n")
    cfg = tmp_path / "c.cfg"
    cfg.write_text(textwrap.dedent(f"""
        [General]
        vocabulary_size = 10
        factor_num = 2
        model_file = {tmp_path}/m/fm
        [Train]
        train_files = {train}
        epoch_num = 2
        batch_size = 16
        learning_rate = 0.1
        [Predict]
        predict_files = {pred}
        score_path = {tmp_path}/score
    """))
    assert run_tffm.main(["train", str(cfg)]) == 0
    assert run_tffm.main(["predict", str(cfg)]) == 0
    scores = (tmp_path / "score" / "pred.txt.score").read_text().splitlines()
    assert len(scores) == 3                   # one per input line, blank too
    assert float(scores[1]) == pytest.approx(0.5)  # empty example -> sigmoid(0)


def test_kernel_validated():
    with pytest.raises(ValueError):
        FmConfig(kernel="cuda")


def test_cluster_wiring_surface():
    from fast_tffm_tpu.parallel.distributed import (coordinator_address,
                                                    init_from_cluster)
    # Single-host cluster: no jax.distributed, trivial shard.
    assert init_from_cluster(FmConfig(), "worker", 0) == (0, 1)
    cfg = FmConfig(worker_hosts=("a:2230", "b:2230"))
    # Coordinator is chief worker's host on a shifted port (the worker
    # port itself belongs to the reference's gRPC surface).
    assert coordinator_address(cfg) == "a:3230"
    assert coordinator_address(FmConfig(worker_hosts=("a",))) == "a:8476"
    with pytest.raises(ValueError, match="out of range"):
        init_from_cluster(cfg, "worker", 5)
    with pytest.raises(ValueError, match="job_name"):
        init_from_cluster(cfg, "ps", 0)
