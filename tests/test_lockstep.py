"""Edge cases of the windowed lockstep scoring protocol
(parallel/sharded.lockstep_score_batches) — the deadlock-sensitive
loop shared by distributed validation and multi-process predict. Real
transport is covered at P=2/P=4 in test_multiprocess.py; these pin the
window-boundary arithmetic (empty iterators, max_batches at/over/under
the window size, multi-window sweeps) single-process on the fake
8-device mesh, where a miscount shows up as a wrong yield count or
score mismatch instead of a cluster hang."""

import numpy as np
import pytest

import jax

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import batch_iterator, probe_uniq_bucket
from fast_tffm_tpu.models.fm import ModelSpec
from fast_tffm_tpu.parallel import sharded
from fast_tffm_tpu.parallel.sharded import (init_sharded_state,
                                            lockstep_score_batches,
                                            make_mesh,
                                            make_sharded_score_fn)


@pytest.fixture(scope="module")
def rig(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("lockstep")
    rng = np.random.default_rng(3)
    lines = []
    for _ in range(23 * 16):  # 23 batches at B=16: crosses 2 windows
        ids = rng.choice(64, size=int(rng.integers(2, 6)), replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                              + [f"{i}:1" for i in sorted(ids)]))
    data = tmp / "d.txt"
    data.write_text("\n".join(lines) + "\n")
    cfg = FmConfig(vocabulary_size=64, factor_num=4, batch_size=16,
                   shuffle=False, bucket_ladder=(8,), dedup="host",
                   model_file=str(tmp / "m" / "fm"))
    mesh = make_mesh(jax.devices()[:8])
    table, _ = init_sharded_state(cfg, mesh)
    spec = ModelSpec.from_config(cfg)
    score_fn = make_sharded_score_fn(spec, mesh)
    ub = probe_uniq_bucket(cfg, [str(data)])
    return cfg, mesh, table, score_fn, str(data), ub


def _sweep(rig_t, max_batches=None):
    cfg, mesh, table, score_fn, data, ub = rig_t
    it = batch_iterator(cfg, [data], training=False, epochs=1,
                        fixed_shape=True, uniq_bucket=ub)
    out = []
    for batch, local in lockstep_score_batches(cfg, it, mesh, score_fn,
                                               table, ub,
                                               max_batches=max_batches):
        assert batch.num_real > 0  # fillers are never yielded
        out.append((batch, local[:batch.num_real]))
    return out


def test_multi_window_sweep_scores_everything(rig):
    out = _sweep(rig)
    assert len(out) == 23  # 2 full windows + a 7-batch tail
    assert sum(b.num_real for b, _ in out) == 23 * 16
    # scores match a direct (non-lockstep) mesh scoring of each batch
    cfg, mesh, table, score_fn, data, ub = rig
    from fast_tffm_tpu.models.fm import batch_args
    from fast_tffm_tpu.parallel.sharded import global_batch, local_rows
    it = batch_iterator(cfg, [data], training=False, epochs=1,
                        fixed_shape=True, uniq_bucket=ub)
    for (batch, local), ref_batch in zip(out, it):
        args = batch_args(ref_batch)
        args.pop("labels"), args.pop("weights")
        gargs = global_batch(mesh, len(ref_batch.uniq_ids), **args)
        want = local_rows(score_fn(table, **gargs))
        np.testing.assert_allclose(local, want[:ref_batch.num_real],
                                   rtol=1e-6)


@pytest.mark.parametrize("cap", [
    1,                                # far below the window
    sharded.LOCKSTEP_WINDOW,          # exactly one window
    sharded.LOCKSTEP_WINDOW + 3,      # mid-second-window
    2 * sharded.LOCKSTEP_WINDOW,      # exact multiple
    1000,                             # cap above the data
])
def test_max_batches_boundaries(rig, cap):
    # the contract: every real batch up to the cap, regardless of how
    # the cap aligns with LOCKSTEP_WINDOW (expectation derived, so the
    # test survives a retuned window constant)
    assert len(_sweep(rig, max_batches=cap)) == min(cap, 23)


def test_empty_iterator_yields_nothing(rig):
    cfg, mesh, table, score_fn, _, ub = rig
    for batch, local in lockstep_score_batches(cfg, iter(()), mesh,
                                               score_fn, table, ub):
        raise AssertionError("empty iterator must not yield")


def test_window_constant_is_sane():
    assert sharded.LOCKSTEP_WINDOW >= 2


def test_preempt_flag_stops_at_window_boundary(rig):
    """ISSUE 6 satellite: the preemption flag rides the fill allgather
    — a raised flag ends the sweep BEFORE any of that window's
    collective programs dispatch, so every process (all of them see
    the same gathered flags) stops at the same boundary.

    Since the window-deferred score fetch (ISSUE 10), window W's
    results reach the consumer only after window W+1 dispatched — so a
    CONSUMER-DRIVEN flag like this one is first visible to the
    allgather one window later than the consumer raised it, and the
    sweep ends exactly one window past the flag (a real preemption
    flag is signal-driven, not consumer-driven, so its boundary is
    unchanged). Dispatched-but-undelivered windows still drain on the
    preempt path: their work completed and is yielded, never redone."""
    cfg, mesh, table, score_fn, data, ub = rig
    windows_seen = []

    def preempt():
        # flips true once the consumer has SEEN a full window — which,
        # with the deferred fetch, happens while window 3 is agreed on
        return len(windows_seen) >= 1

    it = batch_iterator(cfg, [data], training=False, epochs=1,
                        fixed_shape=True, uniq_bucket=ub)
    out = []
    for batch, local in lockstep_score_batches(cfg, it, mesh, score_fn,
                                               table, ub,
                                               preempt=preempt):
        out.append(batch)
        if len(out) % sharded.LOCKSTEP_WINDOW == 0:
            windows_seen.append(len(out))
    # windows 1 and 2 were scored (2 was in flight when the flag became
    # visible and drains on the preempt path); window 3 was cut at the
    # boundary, before dispatch
    assert len(out) == 2 * sharded.LOCKSTEP_WINDOW


def test_preempt_flag_before_first_window_yields_nothing(rig):
    cfg, mesh, table, score_fn, data, ub = rig
    it = batch_iterator(cfg, [data], training=False, epochs=1,
                        fixed_shape=True, uniq_bucket=ub)
    out = list(lockstep_score_batches(cfg, it, mesh, score_fn, table,
                                      ub, preempt=lambda: True))
    assert out == []


def test_no_preempt_scores_everything(rig):
    """preempt=None and a never-true preempt are both full sweeps."""
    cfg, mesh, table, score_fn, data, ub = rig
    it = batch_iterator(cfg, [data], training=False, epochs=1,
                        fixed_shape=True, uniq_bucket=ub)
    out = list(lockstep_score_batches(cfg, it, mesh, score_fn, table,
                                      ub, preempt=lambda: False))
    assert len(out) == 23
