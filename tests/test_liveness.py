"""Compute-plane fault tolerance unit tests (parallel/liveness.py +
the fmstat DEGRADED surface): heartbeat-lease staleness math and the
collective deadline guard under fake clocks — no real multi-process
spawn (the end-to-end legs live in the fmchaos kill-worker-midwindow /
hang-worker scenarios)."""

import json
import threading
import time

import pytest

from fast_tffm_tpu.obs.attribution import (health_verdict, summarize,
                                           worker_table)
from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
from fast_tffm_tpu.parallel import liveness as lv
from fast_tffm_tpu.parallel.liveness import (HeartbeatLease, PeerInfo,
                                             WorkerLostError,
                                             check_deadline,
                                             guarded_collective,
                                             install_guard,
                                             restore_guard)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def guard_teardown():
    """Whatever a test installs, the process-global guard is clean
    after — a leaked guard would silently wrap unrelated tests'
    collectives."""
    yield
    restore_guard(None)


def _lease(tmp_path, clock, index=0, members=(0, 1),
           hb=5.0) -> HeartbeatLease:
    return HeartbeatLease(str(tmp_path / "hb"), process_index=index,
                          members=members, heartbeat_seconds=hb,
                          host=f"host{index}", pid=100 + index,
                          clock=clock)


def _events(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


# --- lease staleness math -------------------------------------------------


def test_missing_lease_reads_as_lost(tmp_path):
    clock = FakeClock()
    lease = _lease(tmp_path, clock)
    lease.renew()
    stale = lease.stale_peers()
    assert [p.process_index for p in stale] == [1]
    assert stale[0].age_seconds is None  # never wrote a lease
    assert "no lease on disk" in stale[0].describe()


def test_staleness_threshold_math(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    assert me.stale_peers() == []
    # stale_after defaults to 4 heartbeats = 20s here: 19s fresh,
    # 21s stale.
    clock.t += 19.0
    assert me.stale_peers() == []
    clock.t += 2.0
    stale = me.stale_peers()
    assert [p.process_index for p in stale] == [1]
    assert stale[0].age_seconds == pytest.approx(21.0)
    assert stale[0].host == "host1"
    # our OWN lease is never reported (the monitor runs in-process)
    assert all(p.process_index != 0 for p in stale)


def test_lease_renewal_races_staleness_check(tmp_path):
    """A peer that renews between two checks must drop off the stale
    list — staleness is re-evaluated from the file every time, never
    latched."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    clock.t += 30.0
    assert [p.process_index for p in me.stale_peers()] == [1]
    peer.renew()  # the "race": renewal lands right after a check
    assert me.stale_peers() == []
    assert me.live_members() == [0, 1]


def test_live_members_and_shrunken_membership(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0, 1, 2))
    p2 = _lease(tmp_path, clock, index=2, members=(0, 1, 2))
    me.renew()
    p2.renew()
    clock.t += 30.0
    me.renew()
    p2.renew()
    assert me.live_members() == [0, 2]  # 1 never wrote a lease
    # elastic reform shrinks the expected membership: 1 stops being
    # reported lost forever after
    me.members = (0, 2)
    assert me.stale_peers() == []


def test_check_peers_one_event_per_episode(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    with activate(tel):
        clock.t += 30.0
        assert [p.process_index for p in me.check_peers()] == [1]
        assert me.check_peers() == []  # same episode: no second event
        peer.renew()                   # recovery re-arms
        assert me.check_peers() == []
        clock.t += 30.0
        assert [p.process_index for p in me.check_peers()] == [1]
    tel.close()
    lost = [e for e in _events(path)
            if e.get("event") == "health"
            and e.get("status") == "worker_lost"]
    assert len(lost) == 2
    assert lost[0]["lost"][0]["process_index"] == 1
    assert lost[0]["lost"][0]["host"] == "host1"


def test_torn_lease_file_reads_as_never_heard(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    me.renew()
    (tmp_path / "hb" / "worker-1.hb").write_text("{torn")
    stale = me.stale_peers()
    assert [p.process_index for p in stale] == [1]
    assert stale[0].age_seconds is None


def test_reform_announcements(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0, 1, 2))
    p2 = _lease(tmp_path, clock, index=2, members=(0, 1, 2))
    me.announce_reform(1)
    p2.announce_reform(1)
    assert me.reform_members(1) == [0, 2]
    assert me.reform_members(2) == []  # per-generation files


def test_stop_removes_own_lease(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    me.renew()
    assert me.read(0) is not None
    me.stop()
    assert me.read(0) is None


# --- guarded_collective: inline conversion --------------------------------


def test_no_guard_is_plain_call(guard_teardown):
    assert guarded_collective(lambda a, b: a + b, 1, 2) == 3


def test_exception_converts_when_peer_dead(tmp_path, guard_teardown):
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0, hb=0.01)
    lease.renew()  # peer 1 never does; tiny hb -> tiny staleness grace
    install_guard(lease, 30.0)
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})

    def boom():
        raise RuntimeError("Gloo AllGather failed: connection closed")

    with activate(tel):
        with pytest.raises(WorkerLostError) as ei:
            guarded_collective(boom, label="lockstep/window_fill")
    tel.close()
    assert "process 1" in str(ei.value)
    assert "lockstep/window_fill" in str(ei.value)
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert [p.process_index for p in ei.value.lost] == [1]
    events = [e for e in _events(path)
              if e.get("status") == "worker_lost"]
    assert events and events[0]["lost"][0]["process_index"] == 1


def test_exception_reraised_when_everyone_alive(tmp_path,
                                                guard_teardown):
    # real clocks: the conversion's staleness grace actually polls
    # (~1s bounded by stale_after + one heartbeat at hb=0.2)
    me = HeartbeatLease(str(tmp_path / "hb"), process_index=0,
                        members=(0, 1), heartbeat_seconds=0.2)
    peer = HeartbeatLease(str(tmp_path / "hb"), process_index=1,
                          members=(0, 1), heartbeat_seconds=0.2)
    me.renew()
    peer.start()  # live renew thread: stays fresh through the
    # conversion's grace poll
    install_guard(me, 30.0)

    def boom():
        raise ValueError("not a peer problem")

    try:
        with pytest.raises(ValueError, match="not a peer problem"):
            guarded_collective(boom, label="x")
    finally:
        peer.stop()


def test_worker_lost_error_passes_through_unwrapped(tmp_path,
                                                    guard_teardown):
    lease = _lease(tmp_path, FakeClock(), index=0)
    install_guard(lease, 30.0)
    original = WorkerLostError("already diagnosed",
                               lost=[PeerInfo(3, host="h3")])

    def reraise():
        raise original

    with pytest.raises(WorkerLostError) as ei:
        guarded_collective(reraise, label="x")
    assert ei.value is original


# --- the deadline sentinel ------------------------------------------------


def test_deadline_fires_before_collective_returns(tmp_path,
                                                  guard_teardown):
    """The acceptance shape: a guarded collective is STILL BLOCKED
    when the monitor's deadline check runs — the check must escalate
    with the named diagnosis while the call sits in flight, not wait
    for it to return."""
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0)
    lease.renew()  # peer 1 stale (never wrote)
    hits = []
    install_guard(lease, 0.2, escalate=hits.append)
    release = threading.Event()
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})

    def blocked_collective():
        release.wait(10)

    t = threading.Thread(
        target=lambda: guarded_collective(blocked_collective,
                                          label="train/step_flags"))
    with activate(tel):
        t.start()
        deadline = time.monotonic() + 5
        while lv.current_guard().in_flight is None:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        time.sleep(0.25)  # cross the 0.2s deadline
        assert check_deadline() == "escalated"
    assert len(hits) == 1
    assert "WorkerLostError" in hits[0]
    assert "process 1" in hits[0]
    assert str(lv.EXIT_WORKER_LOST) in hits[0]
    # the collective had NOT returned when the guard fired
    assert lv.current_guard().in_flight is not None
    release.set()
    t.join()
    tel.close()
    events = [e for e in _events(path)
              if e.get("status") == "worker_lost"]
    assert events and events[0]["label"] == "train/step_flags"
    assert events[0]["timeout_seconds"] == 0.2


def test_deadline_quiet_within_budget(tmp_path, guard_teardown):
    lease = _lease(tmp_path, FakeClock(), index=0)
    lease.renew()
    install_guard(lease, 100.0, escalate=lambda m: (_ for _ in ()
                                                    ).throw(
        AssertionError("must not escalate")))
    st = lv.current_guard()
    st.in_flight = ("x", time.monotonic())
    assert check_deadline() is None


def test_deadline_slow_warning_when_everyone_alive(tmp_path,
                                                   guard_teardown):
    """Deadline exceeded but every peer still heartbeating: a one-shot
    collective_slow warning, never an escalation — a slow save or
    compile must not kill a healthy cluster."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    hits = []
    install_guard(me, 0.1, escalate=hits.append)
    st = lv.current_guard()
    st.in_flight = ("checkpoint/final_save", time.monotonic() - 1.0)
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    with activate(tel):
        assert check_deadline() == "slow"
        assert check_deadline() == "slow"  # warn-once, re-checks fine
    tel.close()
    assert hits == []
    slow = [e for e in _events(path)
            if e.get("status") == "collective_slow"]
    assert len(slow) == 1  # one event despite two ticks
    assert slow[0]["label"] == "checkpoint/final_save"


def test_deadline_covers_unguarded_sync_points(tmp_path,
                                               guard_teardown):
    """No guarded call in flight, but none has COMPLETED within the
    deadline either (async dispatch can park the thread in a
    device_put or result unpack): the sentinel still escalates when a
    peer is stale."""
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0)
    lease.renew()  # peer 1 stale
    hits = []
    install_guard(lease, 0.1, escalate=hits.append)
    st = lv.current_guard()
    st.last_progress = time.monotonic() - 1.0
    assert check_deadline() == "escalated"
    assert "no guarded collective completing" in hits[0]
    # a completing guarded call resets the progress clock
    hits.clear()
    guarded_collective(lambda: None, label="x")
    assert check_deadline() is None


def test_guard_progress_beat_on_completion(tmp_path, guard_teardown):
    lease = _lease(tmp_path, FakeClock(), index=0)
    lease.renew()
    install_guard(lease, 5.0)
    st = lv.current_guard()
    before = st.last_progress
    time.sleep(0.01)
    assert guarded_collective(lambda: 7, label="x") == 7
    assert st.in_flight is None
    assert st.last_progress > before


# --- fmstat: DEGRADED verdict + worker table ------------------------------


def _summary(health=(), crashes=(), starts=1, ends=1, gauges=None):
    return {"health_events": list(health), "crash_events": list(crashes),
            "run_starts": starts, "run_ends": ends,
            "gauges_by_process": gauges or {}}


def _lost_event(*pids):
    return {"event": "health", "status": "worker_lost",
            "label": "lockstep/window_fill",
            "lost": [{"process_index": p, "host": f"h{p}",
                      "age_seconds": 3.2} for p in pids]}


def test_degraded_verdict_names_count():
    hv = health_verdict(_summary(health=[
        _lost_event(1),
        {"event": "health", "status": "elastic_recovered",
         "generation": 1, "members": [0], "lost": [1]}]))
    assert hv["verdict"] == "DEGRADED (1 worker lost)"
    assert "process 1" in hv["detail"]
    assert "elastic shrink recovered" in hv["detail"]


def test_degraded_verdict_plural_and_unrecovered():
    hv = health_verdict(_summary(health=[_lost_event(1, 2)]))
    assert hv["verdict"] == "DEGRADED (2 workers lost)"
    assert "no elastic recovery recorded" in hv["detail"]


def test_degraded_outranked_by_preempted_and_crash():
    pre = {"event": "health", "status": "preempted", "step": 5,
           "epoch": 0}
    hv = health_verdict(_summary(health=[_lost_event(1), pre]))
    assert hv["verdict"] == "PREEMPTED"
    hv = health_verdict(_summary(
        health=[_lost_event(1)],
        crashes=[{"event": "crash", "error": "WorkerLostError: x"}]))
    assert hv["verdict"] == "CRASHED"
    assert "WorkerLostError" in hv["detail"]


def test_degraded_beats_unclosed_stream_heuristic():
    """The dead worker's shard has no run_end; that must read as part
    of the DEGRADED story, not flip the verdict to CRASHED."""
    hv = health_verdict(_summary(health=[_lost_event(1)], starts=2,
                                 ends=1))
    assert hv["verdict"].startswith("DEGRADED")
    assert "no run_end" in hv["detail"]


def test_degraded_ranked_below_stalled_is_above():
    stall = {"event": "health", "status": "stalled",
             "stalled_seconds": 9.0, "stacks_file": "s"}
    hv = health_verdict(_summary(health=[_lost_event(1), stall]))
    assert hv["verdict"].startswith("DEGRADED")


def test_worker_table_rows_and_lost_flag():
    rows = worker_table(_summary(
        health=[_lost_event(1)],
        gauges={0: {"worker/heartbeat_age_seconds": 0.4,
                    "worker/windows": 12.0,
                    "worker/examples": 3072.0},
                1: {"worker/heartbeat_age_seconds": 0.5,
                    "worker/windows": 5.0,
                    "worker/examples": 1280.0},
                2: {"train/examples_per_sec_window": 1.0}}))
    assert len(rows) == 2  # proc 2 published no worker gauges
    assert rows[0].startswith("p0:") and "LOST" not in rows[0]
    assert rows[1].startswith("p1:") and rows[1].endswith("LOST")
    assert "windows 5" in rows[1]


def test_worker_gauges_ride_metrics_flush(tmp_path):
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0)
    lease.renew()
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    tel.lease = lease
    tel.count("lockstep/windows", 3)
    tel.count("train/examples", 96)
    tel.barrier_flush(7)
    tel.close()
    summary = summarize([path])
    g = summary["gauges_by_process"][0]
    assert g["worker/windows"] == 3
    assert g["worker/examples"] == 96
    assert g["worker/heartbeat_age_seconds"] >= 0
    assert worker_table(summary)


# --- elastic GROW: join tickets + rendezvous (fake clock) -----------------


def _ticket(tmp_path, clock, name, t=None):
    """Write a join ticket record by hand (the fake-clock tests never
    start the renew thread)."""
    tk = lv.JoinTicket(str(tmp_path / "hb"), heartbeat_seconds=1.0,
                       clock=clock, name=name, pid=500)
    if t is not None:
        old = clock.t
        clock.t = t
        tk.renew()
        clock.t = old
    else:
        tk.renew()
    return tk


def test_pending_join_tickets_freshness_and_order(tmp_path):
    clock = FakeClock()
    hb = str(tmp_path / "hb")
    _ticket(tmp_path, clock, "join-0002-b")
    _ticket(tmp_path, clock, "join-0001-a")
    stale = _ticket(tmp_path, clock, "join-0000-dead", t=clock.t - 60)
    assert stale  # written, but 60s old vs a 20s threshold below
    (tmp_path / "hb" / "join-0003-torn").write_text("{garb")
    got = lv.pending_join_tickets(hb, stale_after=20.0, now=clock.t)
    # fresh tickets only, DETERMINISTIC filename order (the slot-race
    # tie-break), dead/garbled never planned for
    assert got == ["join-0001-a", "join-0002-b"]
    assert lv.pending_join_tickets(str(tmp_path / "nodir"), 20.0) == []


def test_plan_grow_two_joiners_race_one_slot():
    plan = lv.plan_grow(2, members=[0], capacity=2,
                        tickets=["join-0009-late", "join-0001-first"])
    # one free slot (1), first ticket BY NAME wins it; the loser stays
    # unplanned (pending for a future opening)
    assert plan == {"generation": 2, "incumbents": [0],
                    "joiners": {"join-0001-first": 1}}
    # both free: both admitted, filename order maps to slot order
    plan = lv.plan_grow(3, members=[2], capacity=3,
                        tickets=["join-b", "join-a"])
    assert plan["joiners"] == {"join-a": 0, "join-b": 1}
    assert lv.plan_grow(1, [0, 1], 2, ["join-x"]) is None  # no slot
    assert lv.plan_grow(1, [0], 2, []) is None             # no ticket


def test_grow_rendezvous_joiner_appears_mid_settle_window(tmp_path):
    """The happy path, tick by tick: the incumbent announces, the
    joiner's announce + fresh lease appear MID settle window — the
    commit still waits the window out (staleness is the only death
    signal, so an early commit could adopt a just-died joiner), then
    lands WITH the joiner."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0,))
    me.renew()
    plan = {"generation": 2, "incumbents": [0],
            "joiners": {"join-t": 1}}
    me.announce_reform(2)
    # joiner not announced yet: undecidable inside the window
    assert lv.grow_rendezvous_step(me, plan, now_monotonic=0.0,
                                   join_deadline=10.0) is None
    # joiner lands mid-window: announce + a fresh worker-1 lease —
    # still None (the window must fully elapse) ...
    joiner = _lease(tmp_path, clock, index=1, members=(0, 1))
    joiner.renew()
    joiner.announce_reform(2)
    assert lv.grow_rendezvous_step(me, plan, now_monotonic=5.0,
                                   join_deadline=10.0) is None
    # ... and at the deadline the still-fresh joiner is IN.
    assert lv.grow_rendezvous_step(me, plan, now_monotonic=10.0,
                                   join_deadline=10.0) == [0, 1]


def test_grow_rendezvous_joiner_dies_mid_rendezvous(tmp_path):
    """A joiner that announced and then died (lease gone stale) is
    dropped once the settle window expires — the incumbents commit
    WITHOUT it instead of wedging."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0,))
    me.renew()
    me.announce_reform(3)
    joiner = _lease(tmp_path, clock, index=1, members=(0, 1))
    joiner.renew()
    joiner.announce_reform(3)
    plan = {"generation": 3, "incumbents": [0],
            "joiners": {"join-t": 1}}
    clock.t += 30.0  # joiner stops renewing: stale (threshold 20s)
    me.renew()
    # inside the window: keep waiting (it might be a slow renewal)
    assert lv.grow_rendezvous_step(me, plan, now_monotonic=1.0,
                                   join_deadline=10.0) is None
    # window expired: proceed without the dead joiner
    assert lv.grow_rendezvous_step(me, plan, now_monotonic=10.0,
                                   join_deadline=10.0) == [0]
    # a joiner that never even announced resolves the same way
    plan2 = {"generation": 3, "incumbents": [0],
             "joiners": {"join-u": 2}}
    assert lv.grow_rendezvous_step(me, plan2, now_monotonic=11.0,
                                   join_deadline=10.0) == [0]


def test_grow_rendezvous_stale_generation_announce_refused(tmp_path):
    """An announce from a slot the plan never assigned (a joiner
    acting on a stale generation's plan, or a slot collision) is
    excluded from membership and refused LOUDLY — a health event the
    operator can see, not a silent idle process."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0,))
    me.renew()
    me.announce_reform(4)
    stranger = _lease(tmp_path, clock, index=3, members=(0, 3))
    stranger.renew()
    stranger.announce_reform(4)  # never in the plan below
    plan = {"generation": 4, "incumbents": [0], "joiners": {}}
    assert lv.unexpected_announcers(me, plan) == [3]
    # membership never includes the stranger
    assert lv.grow_rendezvous_step(me, plan, now_monotonic=20.0,
                                   join_deadline=10.0) == [0]
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    with activate(tel):
        lv.emit_join_refused(4, 3, "announced a generation it was "
                             "never planned into")
    tel.close()
    ev = [e for e in _events(path) if e.get("status") == "join_refused"]
    assert len(ev) == 1 and ev[0]["slot"] == 3
    assert ev[0]["generation"] == 4


def test_grow_plan_commit_round_trip_and_stale_floor(tmp_path):
    hb = str(tmp_path / "hb")
    (tmp_path / "hb").mkdir()
    plan = {"generation": 2, "incumbents": [0],
            "joiners": {"join-t": 1}}
    lv.write_grow_plan(hb, plan)
    assert lv.read_grow_plan(hb, 2) == plan
    assert lv.read_grow_plan(hb, 9) is None
    assert lv.grow_plan_for(hb, "join-t") == plan
    assert lv.grow_plan_for(hb, "join-other") is None
    # a refused joiner bumps its generation floor: the stale plan is
    # never acted on twice
    assert lv.grow_plan_for(hb, "join-t", min_generation=3) is None
    assert lv.read_commit(hb, 2) is None
    lv.write_commit(hb, 2, [0, 1])
    assert lv.read_commit(hb, 2) == [0, 1]
    (tmp_path / "hb" / "commit-3.json").write_text("{torn")
    assert lv.read_commit(hb, 3) is None


def test_unreadable_lease_dir_monitor_tick(tmp_path):
    """A transiently unreadable rendezvous dir must not turn a monitor
    tick into a mass false 'everyone is lost' diagnosis (our OWN just-
    renewed lease being unreadable is the tell that the DIR is the
    problem), and the admission scan reads it as 'nobody waiting'."""
    import shutil
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    with activate(tel):
        shutil.rmtree(me.directory)  # the whole dir vanishes
        assert me.check_peers() == []  # no spurious worker_lost
        assert lv.pending_join_tickets(me.directory, 20.0) == []
    tel.close()
    assert not [e for e in _events(path)
                if e.get("status") == "worker_lost"]


def test_sweep_lease_dir_keeps_only_current_generation(tmp_path):
    """N reforms leave only current-generation files: superseded
    announce/plan/commit files, departed members' leases, and dead
    join tickets all go; the live membership's leases, the current
    generation's files, and FRESH tickets stay."""
    clock = FakeClock()
    hb = tmp_path / "hb"
    me = _lease(tmp_path, clock, index=0, members=(0, 1))
    me.renew()
    joiner = _lease(tmp_path, clock, index=1, members=(0, 1))
    joiner.renew()
    dead = _lease(tmp_path, clock, index=2, members=(0, 1, 2))
    dead.renew()
    for g in (1, 2, 3):
        me.announce_reform(g)
        lv.write_grow_plan(str(hb), {"generation": g, "incumbents": [0],
                                     "joiners": {}})
        lv.write_commit(str(hb), g, [0])
    _ticket(tmp_path, clock, "join-0009-fresh")
    _ticket(tmp_path, clock, "join-0001-dead", t=clock.t - 999)
    (hb / "worker-0.hb.tmp.77").write_text("torn")
    removed = lv.sweep_lease_dir(str(hb), generation=3, members=[0, 1],
                                 join_stale_after=20.0, now=clock.t)
    assert removed > 0
    left = sorted(p.name for p in hb.iterdir())
    assert left == ["commit-3.json", "grow-3.json", "join-0009-fresh",
                    "reform-3-0", "worker-0.hb", "worker-1.hb"]


def test_lease_stop_sweeps_stale_peer_leases(tmp_path):
    """HeartbeatLease.stop() drops not just our own lease but the
    stale leases of retired/dead members — the long-lived-stream
    litter fix — while a FRESH peer lease survives."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0, 1, 2))
    fresh_peer = _lease(tmp_path, clock, index=1, members=(0, 1, 2))
    dead_peer = _lease(tmp_path, clock, index=2, members=(0, 1, 2))
    me.renew()
    dead_peer.renew()
    clock.t += 60.0  # peer 2's lease goes stale
    me.renew()
    fresh_peer.renew()
    me.stop()
    hb = tmp_path / "hb"
    assert not (hb / "worker-0.hb").exists()   # own lease removed
    assert (hb / "worker-1.hb").exists()       # fresh peer untouched
    assert not (hb / "worker-2.hb").exists()   # stale ghost swept


def test_grow_context_barrier_check(tmp_path):
    """The safe-barrier admission check (single-process arm): a fresh
    ticket against a free slot plans the next generation; at capacity,
    or with no ticket, the barrier is a no-op."""
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.train import _GrowContext
    clock = FakeClock()
    cfg = FmConfig(elastic="grow", heartbeat_seconds=5.0,
                   worker_hosts=("h0:7000", "h1:7001"))
    lease = _lease(tmp_path, clock, index=0, members=(0,))
    lease.renew()
    ctx = _GrowContext(cfg, lease, members=[0], generation=1)
    assert ctx.capacity == 2
    assert ctx.check_barrier() is None  # no ticket waiting
    # Real-clock ticket: check_barrier evaluates freshness against
    # wall time (the production path), unlike the fake-clock lease.
    tk = lv.JoinTicket(str(tmp_path / "hb"), heartbeat_seconds=5.0,
                       name="join-0001-t", pid=7)
    tk.renew()
    plan = ctx.check_barrier()
    assert plan == {"generation": 2, "incumbents": [0],
                    "joiners": {"join-0001-t": 1}}
    # healed to capacity: the same ticket can no longer be planned
    ctx.adopt([0, 1], 2)
    assert ctx.check_barrier() is None


# --- fmstat: RECOVERED verdict --------------------------------------------


def _elastic_event(members, lost=(), joined=(), capacity=None,
                   generation=1, kind="shrink"):
    ev = {"event": "health", "status": "elastic_recovered",
          "kind": kind, "generation": generation,
          "members": list(members), "lost": list(lost),
          "joined": list(joined)}
    if capacity is not None:
        ev["capacity"] = capacity
    return ev


def test_recovered_verdict_when_grow_heals_full_membership():
    hv = health_verdict(_summary(health=[
        _lost_event(1),
        _elastic_event([0], lost=[1], capacity=2, generation=1),
        _elastic_event([0, 1], joined=[1], capacity=2, generation=2,
                       kind="grow")]))
    assert hv["verdict"] == "RECOVERED (gen 2, 2 workers)"
    assert "full membership" in hv["detail"]
    assert "process 1" in hv["detail"]


def test_recovered_requires_last_event_at_capacity():
    """A grow that healed and then ANOTHER kill (kill-grow-kill): the
    last elastic event is back below capacity — DEGRADED, not a stale
    RECOVERED."""
    hv = health_verdict(_summary(health=[
        _lost_event(1),
        _elastic_event([0, 1], joined=[1], capacity=2, generation=2,
                       kind="grow"),
        _lost_event(0),
        _elastic_event([1], lost=[0], capacity=2, generation=3)]))
    assert hv["verdict"].startswith("DEGRADED")


def test_degraded_unchanged_without_capacity_field():
    """Pre-grow streams (no capacity on the event) keep their
    historical DEGRADED rendering."""
    hv = health_verdict(_summary(health=[
        _lost_event(1),
        {"event": "health", "status": "elastic_recovered",
         "generation": 1, "members": [0], "lost": [1]}]))
    assert hv["verdict"] == "DEGRADED (1 worker lost)"


def test_recovered_outranked_by_preempted_and_crash():
    base = [_lost_event(1),
            _elastic_event([0, 1], joined=[1], capacity=2,
                           generation=2, kind="grow")]
    pre = {"event": "health", "status": "preempted", "step": 5,
           "epoch": 0}
    assert health_verdict(
        _summary(health=base + [pre]))["verdict"] == "PREEMPTED"
    assert health_verdict(_summary(
        health=base,
        crashes=[{"event": "crash", "error": "x"}]))["verdict"] == \
        "CRASHED"


def test_worker_table_unflags_rejoined_slot():
    rows = worker_table(_summary(
        health=[_lost_event(1),
                _elastic_event([0, 1], joined=[1], capacity=2,
                               generation=2, kind="grow")],
        gauges={0: {"worker/heartbeat_age_seconds": 0.4,
                    "worker/windows": 12.0, "worker/examples": 100.0},
                1: {"worker/heartbeat_age_seconds": 0.5,
                    "worker/windows": 5.0, "worker/examples": 50.0}}))
    assert len(rows) == 2
    assert "LOST" not in rows[1]  # the replacement owns slot 1 now


# --- config knobs ---------------------------------------------------------


def test_config_rejects_bad_elastic_values():
    from fast_tffm_tpu.config import FmConfig
    with pytest.raises(ValueError, match="elastic"):
        FmConfig(elastic="expand")
    with pytest.raises(ValueError, match="heartbeat_seconds"):
        FmConfig(elastic="shrink", heartbeat_seconds=0.0)
    with pytest.raises(ValueError, match="heartbeat_seconds"):
        FmConfig(elastic="grow", heartbeat_seconds=0.0)
    with pytest.raises(ValueError, match="collective_timeout_seconds"):
        FmConfig(collective_timeout_seconds=-1.0)
    with pytest.raises(ValueError, match="heartbeat_seconds"):
        FmConfig(heartbeat_seconds=-0.5)
    with pytest.raises(ValueError, match="join_settle_seconds"):
        FmConfig(join_settle_seconds=0.0)
    with pytest.raises(ValueError, match="join_timeout_seconds"):
        FmConfig(join_timeout_seconds=-1.0)
    cfg = FmConfig(elastic="shrink", heartbeat_seconds=2.0,
                   collective_timeout_seconds=0.0)
    assert cfg.elastic == "shrink"
    cfg = FmConfig(elastic="grow", heartbeat_seconds=2.0)
    assert cfg.elastic == "grow"
    # Streaming grow needs a publish cadence: the publish settle is
    # the stream's only safe barrier, so a never-publishing stream
    # could never admit a joiner — a config trap, caught here.
    with pytest.raises(ValueError, match="publish_interval_seconds"):
        FmConfig(elastic="grow", run_mode="stream", stream_dir="/tmp/s",
                 publish_interval_seconds=0.0)


def test_cluster_cfg_keys_parse(tmp_path):
    from fast_tffm_tpu.config import load_config
    p = tmp_path / "c.cfg"
    p.write_text("""
[Cluster]
worker_hosts = a:1,b:2
collective_timeout_seconds = 45
heartbeat_seconds = 2.5
elastic = shrink
""")
    cfg = load_config(str(p))
    assert cfg.collective_timeout_seconds == 45.0
    assert cfg.heartbeat_seconds == 2.5
    assert cfg.elastic == "shrink"


def test_generation_bumps_coordinator_port():
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.parallel.distributed import coordinator_address
    cfg = FmConfig(worker_hosts=("h0:7000", "h1:7001", "h2:7002"))
    assert coordinator_address(cfg) == "h0:8000"
    assert coordinator_address(cfg, generation=2) == "h0:8002"
    # reform passes the SURVIVORS: the new chief is the first of them
    assert coordinator_address(cfg, generation=1,
                               hosts=["h1:7001", "h2:7002"]) == "h1:8002"
