"""Compute-plane fault tolerance unit tests (parallel/liveness.py +
the fmstat DEGRADED surface): heartbeat-lease staleness math and the
collective deadline guard under fake clocks — no real multi-process
spawn (the end-to-end legs live in the fmchaos kill-worker-midwindow /
hang-worker scenarios)."""

import json
import threading
import time

import pytest

from fast_tffm_tpu.obs.attribution import (health_verdict, summarize,
                                           worker_table)
from fast_tffm_tpu.obs.telemetry import RunTelemetry, activate
from fast_tffm_tpu.parallel import liveness as lv
from fast_tffm_tpu.parallel.liveness import (HeartbeatLease, PeerInfo,
                                             WorkerLostError,
                                             check_deadline,
                                             guarded_collective,
                                             install_guard,
                                             restore_guard)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture
def guard_teardown():
    """Whatever a test installs, the process-global guard is clean
    after — a leaked guard would silently wrap unrelated tests'
    collectives."""
    yield
    restore_guard(None)


def _lease(tmp_path, clock, index=0, members=(0, 1),
           hb=5.0) -> HeartbeatLease:
    return HeartbeatLease(str(tmp_path / "hb"), process_index=index,
                          members=members, heartbeat_seconds=hb,
                          host=f"host{index}", pid=100 + index,
                          clock=clock)


def _events(path):
    with open(path) as fh:
        return [json.loads(ln) for ln in fh if ln.strip()]


# --- lease staleness math -------------------------------------------------


def test_missing_lease_reads_as_lost(tmp_path):
    clock = FakeClock()
    lease = _lease(tmp_path, clock)
    lease.renew()
    stale = lease.stale_peers()
    assert [p.process_index for p in stale] == [1]
    assert stale[0].age_seconds is None  # never wrote a lease
    assert "no lease on disk" in stale[0].describe()


def test_staleness_threshold_math(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    assert me.stale_peers() == []
    # stale_after defaults to 4 heartbeats = 20s here: 19s fresh,
    # 21s stale.
    clock.t += 19.0
    assert me.stale_peers() == []
    clock.t += 2.0
    stale = me.stale_peers()
    assert [p.process_index for p in stale] == [1]
    assert stale[0].age_seconds == pytest.approx(21.0)
    assert stale[0].host == "host1"
    # our OWN lease is never reported (the monitor runs in-process)
    assert all(p.process_index != 0 for p in stale)


def test_lease_renewal_races_staleness_check(tmp_path):
    """A peer that renews between two checks must drop off the stale
    list — staleness is re-evaluated from the file every time, never
    latched."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    clock.t += 30.0
    assert [p.process_index for p in me.stale_peers()] == [1]
    peer.renew()  # the "race": renewal lands right after a check
    assert me.stale_peers() == []
    assert me.live_members() == [0, 1]


def test_live_members_and_shrunken_membership(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0, 1, 2))
    p2 = _lease(tmp_path, clock, index=2, members=(0, 1, 2))
    me.renew()
    p2.renew()
    clock.t += 30.0
    me.renew()
    p2.renew()
    assert me.live_members() == [0, 2]  # 1 never wrote a lease
    # elastic reform shrinks the expected membership: 1 stops being
    # reported lost forever after
    me.members = (0, 2)
    assert me.stale_peers() == []


def test_check_peers_one_event_per_episode(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    with activate(tel):
        clock.t += 30.0
        assert [p.process_index for p in me.check_peers()] == [1]
        assert me.check_peers() == []  # same episode: no second event
        peer.renew()                   # recovery re-arms
        assert me.check_peers() == []
        clock.t += 30.0
        assert [p.process_index for p in me.check_peers()] == [1]
    tel.close()
    lost = [e for e in _events(path)
            if e.get("event") == "health"
            and e.get("status") == "worker_lost"]
    assert len(lost) == 2
    assert lost[0]["lost"][0]["process_index"] == 1
    assert lost[0]["lost"][0]["host"] == "host1"


def test_torn_lease_file_reads_as_never_heard(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    me.renew()
    (tmp_path / "hb" / "worker-1.hb").write_text("{torn")
    stale = me.stale_peers()
    assert [p.process_index for p in stale] == [1]
    assert stale[0].age_seconds is None


def test_reform_announcements(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0, members=(0, 1, 2))
    p2 = _lease(tmp_path, clock, index=2, members=(0, 1, 2))
    me.announce_reform(1)
    p2.announce_reform(1)
    assert me.reform_members(1) == [0, 2]
    assert me.reform_members(2) == []  # per-generation files


def test_stop_removes_own_lease(tmp_path):
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    me.renew()
    assert me.read(0) is not None
    me.stop()
    assert me.read(0) is None


# --- guarded_collective: inline conversion --------------------------------


def test_no_guard_is_plain_call(guard_teardown):
    assert guarded_collective(lambda a, b: a + b, 1, 2) == 3


def test_exception_converts_when_peer_dead(tmp_path, guard_teardown):
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0, hb=0.01)
    lease.renew()  # peer 1 never does; tiny hb -> tiny staleness grace
    install_guard(lease, 30.0)
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})

    def boom():
        raise RuntimeError("Gloo AllGather failed: connection closed")

    with activate(tel):
        with pytest.raises(WorkerLostError) as ei:
            guarded_collective(boom, label="lockstep/window_fill")
    tel.close()
    assert "process 1" in str(ei.value)
    assert "lockstep/window_fill" in str(ei.value)
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert [p.process_index for p in ei.value.lost] == [1]
    events = [e for e in _events(path)
              if e.get("status") == "worker_lost"]
    assert events and events[0]["lost"][0]["process_index"] == 1


def test_exception_reraised_when_everyone_alive(tmp_path,
                                                guard_teardown):
    # real clocks: the conversion's staleness grace actually polls
    # (~1s bounded by stale_after + one heartbeat at hb=0.2)
    me = HeartbeatLease(str(tmp_path / "hb"), process_index=0,
                        members=(0, 1), heartbeat_seconds=0.2)
    peer = HeartbeatLease(str(tmp_path / "hb"), process_index=1,
                          members=(0, 1), heartbeat_seconds=0.2)
    me.renew()
    peer.start()  # live renew thread: stays fresh through the
    # conversion's grace poll
    install_guard(me, 30.0)

    def boom():
        raise ValueError("not a peer problem")

    try:
        with pytest.raises(ValueError, match="not a peer problem"):
            guarded_collective(boom, label="x")
    finally:
        peer.stop()


def test_worker_lost_error_passes_through_unwrapped(tmp_path,
                                                    guard_teardown):
    lease = _lease(tmp_path, FakeClock(), index=0)
    install_guard(lease, 30.0)
    original = WorkerLostError("already diagnosed",
                               lost=[PeerInfo(3, host="h3")])

    def reraise():
        raise original

    with pytest.raises(WorkerLostError) as ei:
        guarded_collective(reraise, label="x")
    assert ei.value is original


# --- the deadline sentinel ------------------------------------------------


def test_deadline_fires_before_collective_returns(tmp_path,
                                                  guard_teardown):
    """The acceptance shape: a guarded collective is STILL BLOCKED
    when the monitor's deadline check runs — the check must escalate
    with the named diagnosis while the call sits in flight, not wait
    for it to return."""
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0)
    lease.renew()  # peer 1 stale (never wrote)
    hits = []
    install_guard(lease, 0.2, escalate=hits.append)
    release = threading.Event()
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})

    def blocked_collective():
        release.wait(10)

    t = threading.Thread(
        target=lambda: guarded_collective(blocked_collective,
                                          label="train/step_flags"))
    with activate(tel):
        t.start()
        deadline = time.monotonic() + 5
        while lv.current_guard().in_flight is None:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        time.sleep(0.25)  # cross the 0.2s deadline
        assert check_deadline() == "escalated"
    assert len(hits) == 1
    assert "WorkerLostError" in hits[0]
    assert "process 1" in hits[0]
    assert str(lv.EXIT_WORKER_LOST) in hits[0]
    # the collective had NOT returned when the guard fired
    assert lv.current_guard().in_flight is not None
    release.set()
    t.join()
    tel.close()
    events = [e for e in _events(path)
              if e.get("status") == "worker_lost"]
    assert events and events[0]["label"] == "train/step_flags"
    assert events[0]["timeout_seconds"] == 0.2


def test_deadline_quiet_within_budget(tmp_path, guard_teardown):
    lease = _lease(tmp_path, FakeClock(), index=0)
    lease.renew()
    install_guard(lease, 100.0, escalate=lambda m: (_ for _ in ()
                                                    ).throw(
        AssertionError("must not escalate")))
    st = lv.current_guard()
    st.in_flight = ("x", time.monotonic())
    assert check_deadline() is None


def test_deadline_slow_warning_when_everyone_alive(tmp_path,
                                                   guard_teardown):
    """Deadline exceeded but every peer still heartbeating: a one-shot
    collective_slow warning, never an escalation — a slow save or
    compile must not kill a healthy cluster."""
    clock = FakeClock()
    me = _lease(tmp_path, clock, index=0)
    peer = _lease(tmp_path, clock, index=1)
    me.renew()
    peer.renew()
    hits = []
    install_guard(me, 0.1, escalate=hits.append)
    st = lv.current_guard()
    st.in_flight = ("checkpoint/final_save", time.monotonic() - 1.0)
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    with activate(tel):
        assert check_deadline() == "slow"
        assert check_deadline() == "slow"  # warn-once, re-checks fine
    tel.close()
    assert hits == []
    slow = [e for e in _events(path)
            if e.get("status") == "collective_slow"]
    assert len(slow) == 1  # one event despite two ticks
    assert slow[0]["label"] == "checkpoint/final_save"


def test_deadline_covers_unguarded_sync_points(tmp_path,
                                               guard_teardown):
    """No guarded call in flight, but none has COMPLETED within the
    deadline either (async dispatch can park the thread in a
    device_put or result unpack): the sentinel still escalates when a
    peer is stale."""
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0)
    lease.renew()  # peer 1 stale
    hits = []
    install_guard(lease, 0.1, escalate=hits.append)
    st = lv.current_guard()
    st.last_progress = time.monotonic() - 1.0
    assert check_deadline() == "escalated"
    assert "no guarded collective completing" in hits[0]
    # a completing guarded call resets the progress clock
    hits.clear()
    guarded_collective(lambda: None, label="x")
    assert check_deadline() is None


def test_guard_progress_beat_on_completion(tmp_path, guard_teardown):
    lease = _lease(tmp_path, FakeClock(), index=0)
    lease.renew()
    install_guard(lease, 5.0)
    st = lv.current_guard()
    before = st.last_progress
    time.sleep(0.01)
    assert guarded_collective(lambda: 7, label="x") == 7
    assert st.in_flight is None
    assert st.last_progress > before


# --- fmstat: DEGRADED verdict + worker table ------------------------------


def _summary(health=(), crashes=(), starts=1, ends=1, gauges=None):
    return {"health_events": list(health), "crash_events": list(crashes),
            "run_starts": starts, "run_ends": ends,
            "gauges_by_process": gauges or {}}


def _lost_event(*pids):
    return {"event": "health", "status": "worker_lost",
            "label": "lockstep/window_fill",
            "lost": [{"process_index": p, "host": f"h{p}",
                      "age_seconds": 3.2} for p in pids]}


def test_degraded_verdict_names_count():
    hv = health_verdict(_summary(health=[
        _lost_event(1),
        {"event": "health", "status": "elastic_recovered",
         "generation": 1, "members": [0], "lost": [1]}]))
    assert hv["verdict"] == "DEGRADED (1 worker lost)"
    assert "process 1" in hv["detail"]
    assert "elastic shrink recovered" in hv["detail"]


def test_degraded_verdict_plural_and_unrecovered():
    hv = health_verdict(_summary(health=[_lost_event(1, 2)]))
    assert hv["verdict"] == "DEGRADED (2 workers lost)"
    assert "no elastic recovery recorded" in hv["detail"]


def test_degraded_outranked_by_preempted_and_crash():
    pre = {"event": "health", "status": "preempted", "step": 5,
           "epoch": 0}
    hv = health_verdict(_summary(health=[_lost_event(1), pre]))
    assert hv["verdict"] == "PREEMPTED"
    hv = health_verdict(_summary(
        health=[_lost_event(1)],
        crashes=[{"event": "crash", "error": "WorkerLostError: x"}]))
    assert hv["verdict"] == "CRASHED"
    assert "WorkerLostError" in hv["detail"]


def test_degraded_beats_unclosed_stream_heuristic():
    """The dead worker's shard has no run_end; that must read as part
    of the DEGRADED story, not flip the verdict to CRASHED."""
    hv = health_verdict(_summary(health=[_lost_event(1)], starts=2,
                                 ends=1))
    assert hv["verdict"].startswith("DEGRADED")
    assert "no run_end" in hv["detail"]


def test_degraded_ranked_below_stalled_is_above():
    stall = {"event": "health", "status": "stalled",
             "stalled_seconds": 9.0, "stacks_file": "s"}
    hv = health_verdict(_summary(health=[_lost_event(1), stall]))
    assert hv["verdict"].startswith("DEGRADED")


def test_worker_table_rows_and_lost_flag():
    rows = worker_table(_summary(
        health=[_lost_event(1)],
        gauges={0: {"worker/heartbeat_age_seconds": 0.4,
                    "worker/windows": 12.0,
                    "worker/examples": 3072.0},
                1: {"worker/heartbeat_age_seconds": 0.5,
                    "worker/windows": 5.0,
                    "worker/examples": 1280.0},
                2: {"train/examples_per_sec_window": 1.0}}))
    assert len(rows) == 2  # proc 2 published no worker gauges
    assert rows[0].startswith("p0:") and "LOST" not in rows[0]
    assert rows[1].startswith("p1:") and rows[1].endswith("LOST")
    assert "windows 5" in rows[1]


def test_worker_gauges_ride_metrics_flush(tmp_path):
    clock = FakeClock()
    lease = _lease(tmp_path, clock, index=0)
    lease.renew()
    path = str(tmp_path / "m.jsonl")
    tel = RunTelemetry(path, meta={})
    tel.lease = lease
    tel.count("lockstep/windows", 3)
    tel.count("train/examples", 96)
    tel.barrier_flush(7)
    tel.close()
    summary = summarize([path])
    g = summary["gauges_by_process"][0]
    assert g["worker/windows"] == 3
    assert g["worker/examples"] == 96
    assert g["worker/heartbeat_age_seconds"] >= 0
    assert worker_table(summary)


# --- config knobs ---------------------------------------------------------


def test_config_rejects_bad_elastic_values():
    from fast_tffm_tpu.config import FmConfig
    with pytest.raises(ValueError, match="elastic"):
        FmConfig(elastic="grow")
    with pytest.raises(ValueError, match="heartbeat_seconds"):
        FmConfig(elastic="shrink", heartbeat_seconds=0.0)
    with pytest.raises(ValueError, match="collective_timeout_seconds"):
        FmConfig(collective_timeout_seconds=-1.0)
    with pytest.raises(ValueError, match="heartbeat_seconds"):
        FmConfig(heartbeat_seconds=-0.5)
    cfg = FmConfig(elastic="shrink", heartbeat_seconds=2.0,
                   collective_timeout_seconds=0.0)
    assert cfg.elastic == "shrink"


def test_cluster_cfg_keys_parse(tmp_path):
    from fast_tffm_tpu.config import load_config
    p = tmp_path / "c.cfg"
    p.write_text("""
[Cluster]
worker_hosts = a:1,b:2
collective_timeout_seconds = 45
heartbeat_seconds = 2.5
elastic = shrink
""")
    cfg = load_config(str(p))
    assert cfg.collective_timeout_seconds == 45.0
    assert cfg.heartbeat_seconds == 2.5
    assert cfg.elastic == "shrink"


def test_generation_bumps_coordinator_port():
    from fast_tffm_tpu.config import FmConfig
    from fast_tffm_tpu.parallel.distributed import coordinator_address
    cfg = FmConfig(worker_hosts=("h0:7000", "h1:7001", "h2:7002"))
    assert coordinator_address(cfg) == "h0:8000"
    assert coordinator_address(cfg, generation=2) == "h0:8002"
    # reform passes the SURVIVORS: the new chief is the first of them
    assert coordinator_address(cfg, generation=1,
                               hosts=["h1:7001", "h2:7002"]) == "h1:8002"
