"""Parallel host data plane == serial data plane, bit for bit.

The ``host_threads`` knob must be a PURE throughput knob: for the same
config/seed, the multi-worker plane (group scanner -> worker pool ->
bounded ordered ring -> shared emitter) must emit the byte-identical
batch stream the serial pipeline emits — across the C++ fast path, the
tolerant generic path, spill-requeued tails (fixed-U mode), weight
sidecars, keep_empty, raw-ids mode, sharded input, multi-file
multi-epoch shuffle, and error provenance. Plus: the pool must never
leak worker threads (clean end OR abandoned iterator), and the
4-worker build must actually scale (the tier-1 smoke the BENCH row
pins locally)."""

import json
import os
import threading
import time

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data import cparser
from fast_tffm_tpu.data.badlines import BadLineTracker
from fast_tffm_tpu.data.parser import ParseError
from fast_tffm_tpu.data.pipeline import (SpillStats, batch_iterator,
                                         resolve_host_threads)

needs_cpp = pytest.mark.skipif(not cparser.available(),
                               reason="C++ parser extension unavailable")


def _write(tmp_path, n=300, seed=1, name="d.txt", blanks=False,
           nnz_hi=14, vocab=300):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        nnz = rng.integers(1, nnz_hi)
        ids = rng.choice(vocab, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.4 else "0"]
                              + [f"{j}:{rng.random():.4f}" for j in ids]))
        if blanks and i % 11 == 3:
            lines.append("")   # blank line
        if blanks and i % 29 == 7:
            lines.append("   ")  # whitespace-only line
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _cfg(path, host_threads, **kw):
    base = dict(vocabulary_size=300, factor_num=4, batch_size=16,
                train_files=(path,), shuffle=False,
                bucket_ladder=(4, 8, 16), max_features_per_example=16,
                host_threads=host_threads)
    base.update(kw)
    return FmConfig(**base)


def _key(b):
    """Full byte identity of one DeviceBatch."""
    return (b.labels.tobytes(), b.weights.tobytes(),
            None if b.uniq_ids is None else b.uniq_ids.tobytes(),
            b.local_idx.tobytes(), b.vals.tobytes(),
            None if b.fields is None else b.fields.tobytes(),
            b.num_real)


def _stream(cfg, **kw):
    return [_key(b) for b in batch_iterator(cfg, cfg.train_files,
                                            training=True, **kw)]


def _assert_parity(path, cfg_kw=None, it_kw=None):
    cfg_kw, it_kw = cfg_kw or {}, it_kw or {}
    a = _stream(_cfg(path, 1, **cfg_kw), **it_kw)
    b = _stream(_cfg(path, 4, **cfg_kw), **it_kw)
    assert len(a) == len(b) and a == b
    return a


@needs_cpp
def test_fast_path_parity(tmp_path):
    s = _assert_parity(_write(tmp_path))
    assert len(s) == 19  # 300 examples / B=16


@needs_cpp
def test_fast_path_parity_shuffle(tmp_path):
    # Shuffle rng (file order, window draws, per-batch row perms) is
    # shared emitter code fed in ring order — identical draws.
    p1 = _write(tmp_path, n=150, seed=2)
    p2 = _write(tmp_path, n=90, seed=3, name="e.txt")
    _assert_parity(p1, cfg_kw=dict(train_files=(p1, p2), shuffle=True,
                                   seed=5, queue_size=64),
                   it_kw=dict(epochs=2, seed=11))


@needs_cpp
def test_fast_path_parity_keep_empty(tmp_path):
    s = _assert_parity(_write(tmp_path, blanks=True),
                       it_kw=dict(keep_empty=True))
    assert s  # blank lines became zero-feature examples in both


@needs_cpp
def test_fast_path_parity_raw_ids(tmp_path):
    _assert_parity(_write(tmp_path), it_kw=dict(raw_ids=True))


@needs_cpp
def test_fast_path_parity_sharded(tmp_path):
    path = _write(tmp_path, n=400, seed=4)
    for shard in range(3):
        _assert_parity(path, it_kw=dict(shard_index=shard,
                                        num_shards=3))


@needs_cpp
def test_spill_requeued_tail_parity(tmp_path):
    """Fixed-U mode: a unique-budget spill closes a batch early and the
    tail reopens the next one — the parallel plane must replay that
    requeue exactly (invalidate in-flight groups, re-cut from the
    spilled line), with identical spill accounting."""
    path = _write(tmp_path, n=500, seed=6, nnz_hi=16, vocab=3000)
    stats = {}
    streams = {}
    for w in (1, 4):
        cfg = _cfg(path, w, vocabulary_size=3000, batch_size=32)
        st = SpillStats()
        streams[w] = [_key(b) for b in batch_iterator(
            cfg, cfg.train_files, training=True, fixed_shape=True,
            uniq_bucket=128, stats=st)]
        stats[w] = st
    assert streams[1] == streams[4]
    # The config is built to spill hard; if it stops spilling the test
    # stops testing the rewind protocol — fail loudly instead.
    assert stats[1].spilled_batches > 3
    for f in ("batches", "spilled_batches", "real_examples", "max_uniq"):
        assert getattr(stats[1], f) == getattr(stats[4], f), f


@needs_cpp
def test_weight_sidecar_parity(tmp_path):
    # Weighted input pairs weights to lines in Python (GIL-bound): it
    # stays on the serial plane at every host_threads — parity is the
    # pin that the routing actually does that.
    path = _write(tmp_path, n=120, seed=7)
    wpath = tmp_path / "w.txt"
    wpath.write_text("".join(f"{v:.3f}\n" for v in
                             np.random.default_rng(0).uniform(
                                 0.5, 2.0, 120)))
    _assert_parity(path, it_kw=dict(weight_files=(str(wpath),)))


@needs_cpp
def test_quarantine_parity_and_global_dedupe(tmp_path):
    """Tolerant generic plane: identical batch streams, and the
    run-scoped tracker stays GLOBAL across workers — same bad/total
    counts, same per-file attribution, and the quarantine sidecar
    holds the same RECORD SET (order may interleave across workers;
    each (file, lineno) exactly once even over 2 epochs)."""
    path = _write(tmp_path, n=260, seed=8)
    lines = open(path).read().splitlines()
    for i in range(7, 260, 40):
        lines[i] = f"##bad## {lines[i]}"
    dirty = tmp_path / "dirty.txt"
    dirty.write_text("\n".join(lines) + "\n")
    results = {}
    for w in (1, 4):
        qfile = str(tmp_path / f"q{w}.jsonl")
        tracker = BadLineTracker("quarantine", 0.5,
                                 quarantine_file=qfile)
        cfg = _cfg(str(dirty), w, bad_line_policy="quarantine",
                   max_bad_fraction=0.5)
        stream = [_key(b) for b in batch_iterator(
            cfg, cfg.train_files, training=True, epochs=2,
            bad_lines=tracker)]
        tracker.close()
        recs = [json.loads(ln) for ln in open(qfile) if ln.strip()]
        results[w] = (stream, tracker.bad, tracker.total,
                      dict(tracker.by_file),
                      sorted((r["file"], r["lineno"], r["raw"])
                             for r in recs))
    assert results[1] == results[4]
    assert results[1][1] == 2 * 7  # 7 bad lines, counted both epochs
    assert len(results[1][4]) == 7  # quarantined ONCE across epochs


@needs_cpp
def test_parallel_generic_plane_actually_runs(tmp_path):
    """The quarantine config above must really fan out: fm-build
    workers alive while the iterator is draining."""
    path = _write(tmp_path, n=200, seed=9)
    cfg = _cfg(path, 4, bad_line_policy="quarantine")
    it = batch_iterator(cfg, cfg.train_files, training=True)
    next(it)
    alive = [t.name for t in threading.enumerate()
             if t.name.startswith("fm-build") and t.is_alive()]
    it.close()
    assert alive, "generic parallel plane never started its pool"


@needs_cpp
def test_error_provenance_parity(tmp_path):
    """A bad line under policy=error must raise the SAME file/lineno
    diagnosis from the parallel plane as from the serial one (worker
    errors rebase builder-relative linenos onto the stream)."""
    path = _write(tmp_path, n=90, seed=10)
    lines = open(path).read().splitlines()
    lines[61] = "notalabel 3:1"
    bad = tmp_path / "bad.txt"
    bad.write_text("\n".join(lines) + "\n")
    msgs = {}
    for w in (1, 4):
        cfg = _cfg(str(bad), w)
        with pytest.raises(ParseError) as ei:
            list(batch_iterator(cfg, cfg.train_files, training=True))
        msgs[w] = str(ei.value)
    assert msgs[1] == msgs[4]
    assert "line 62" in msgs[1] and "bad.txt" in msgs[1]


@needs_cpp
def test_no_worker_leak_on_completion_and_abandon(tmp_path):
    path = _write(tmp_path, n=200, seed=12)

    def leaked():
        return [t for t in threading.enumerate()
                if t.name.startswith("fm-build") and t.is_alive()]

    cfg = _cfg(path, 4)
    list(batch_iterator(cfg, cfg.train_files, training=True))
    assert not leaked()
    # Abandoned mid-stream: generator close must stop and join the pool.
    it = batch_iterator(cfg, cfg.train_files, training=True)
    next(it)
    it.close()
    assert not leaked()


def test_resolve_host_threads():
    path_free = dict(vocabulary_size=8, batch_size=4)
    assert resolve_host_threads(FmConfig(host_threads=3,
                                         **path_free)) == 3
    assert resolve_host_threads(FmConfig(host_threads=1,
                                         **path_free)) == 1
    auto = resolve_host_threads(FmConfig(host_threads=0, **path_free))
    assert 1 <= auto <= 4
    with pytest.raises(ValueError):
        FmConfig(host_threads=-1, **path_free)


def test_build_ring_orders_and_recovers():
    """_BuildRing unit contract: results re-serialize in submit order
    regardless of completion order; invalidate_after discards
    speculative work; per-task errors surface at their seq; close
    joins the pool."""
    from fast_tffm_tpu.data.pipeline import _BuildRing
    gate = threading.Event()

    def work(_state, payload):
        if payload == "slow":
            gate.wait(5.0)
        if payload == "boom":
            raise ValueError("boom")
        return payload

    ring = _BuildRing(3, depth=8, work=work)
    try:
        s0 = ring.submit("slow")
        s1 = ring.submit("fast1")
        s2 = ring.submit("boom")
        s3 = ring.submit("fast2")
        # Later tasks finish first; wait(s0) must still block until s0.
        deadline = time.monotonic() + 5.0
        while not ring.has(s3) and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ring.has(s1) and ring.has(s3) and not ring.has(s0)
        gate.set()
        assert ring.wait(s0) == ("ok", "slow")
        assert ring.wait(s1) == ("ok", "fast1")
        kind, err = ring.wait(s2)
        assert kind == "error" and isinstance(err, ValueError)
        assert ring.wait(s3) == ("ok", "fast2")
        # Invalidation: queued/unconsumed results past seq are dropped,
        # and new submissions use fresh seqs.
        s4 = ring.submit("a")
        s5 = ring.submit("b")
        ring.wait(s4)
        ring.invalidate_after(s4)
        s6 = ring.submit("c")
        assert s6 > s5
        assert ring.wait(s6) == ("ok", "c")
        assert not ring.has(s5)
    finally:
        ring.close()
    assert all(not t.is_alive() for t in ring._threads)


@needs_cpp
def test_parallel_build_scales(tmp_path):
    """Tier-1 scaling smoke for the BENCH host_only row: the 4-worker
    plane must beat the serial plane by >= 1.3x on a synthetic Criteo-
    like corpus. Same-window INTERLEAVED paired ratios (the repo's A/B
    doctrine — see test_threaded_builder_scales): each trial measures
    W=1 and W=4 back to back and the best paired ratio decides, so
    ambient load on a shared host can't flake the gate; the bar exists
    to catch the plane accidentally SERIALIZING (~1.0x), not to pin
    the ~2-3x a quiet multi-core box shows."""
    if (os.cpu_count() or 1) < 4:
        pytest.skip("needs >= 4 cores to measure scaling")
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(40000):
        ids = rng.choice(100000, size=39, replace=False)
        lines.append("1 " + " ".join(f"{j}:1.5" for j in ids))
    path = tmp_path / "big.txt"
    path.write_text("\n".join(lines) + "\n")

    def rate(w):
        cfg = FmConfig(vocabulary_size=100000, batch_size=8192,
                       train_files=(str(path),), shuffle=False,
                       max_features_per_example=48, bucket_ladder=(48,),
                       host_threads=w)
        n = 0
        t0 = time.perf_counter()
        for b in batch_iterator(cfg, cfg.train_files, training=True):
            n += b.num_real
        return n / (time.perf_counter() - t0)

    ratios = []
    for _ in range(4):
        r1 = rate(1)
        ratios.append(rate(4) / r1)
    assert max(ratios) >= 1.3, (
        f"W=4/W=1 paired ratios {[f'{r:.2f}' for r in ratios]}")
