import textwrap

import pytest

from fast_tffm_tpu.config import FmConfig, load_config


def write_cfg(tmp_path, body):
    p = tmp_path / "test.cfg"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_reference_schema_roundtrip(tmp_path):
    # The reference's sample.cfg shape (SURVEY Appendix A) parses as-is.
    path = write_cfg(tmp_path, """
        [General]
        vocabulary_size = 80000
        vocabulary_block_num = 4
        hash_feature_id = True
        factor_num = 8
        model_file = ./model/fm_model
        log_file = ./log/fm.log

        [Train]
        train_files = data/a.txt, data/b.txt
        epoch_num = 10
        batch_size = 10000
        learning_rate = 0.01
        factor_lambda = 1e-5
        bias_lambda = 1e-5
        init_value_range = 0.01
        loss_type = logistic

        [Predict]
        predict_files = data/test.txt
        score_path = ./score/

        [Cluster]
        ps_hosts = h1:2220,h2:2220
        worker_hosts = h3:2230,h4:2230
    """)
    cfg = load_config(path)
    assert cfg.vocabulary_size == 80000
    assert cfg.hash_feature_id is True
    assert cfg.factor_num == 8
    assert cfg.train_files == ("data/a.txt", "data/b.txt")
    assert cfg.epoch_num == 10
    assert cfg.batch_size == 10000
    assert cfg.factor_lambda == pytest.approx(1e-5)
    assert cfg.worker_hosts == ("h3:2230", "h4:2230")
    assert cfg.row_dim == 9
    assert cfg.pad_id == 80000


def test_appendix_a_cfg_loads_verbatim(tmp_path):
    """SURVEY Appendix A's reconstructed sample.cfg — every key,
    including the [L]-tier ones (weight_files, validation_files,
    save_summaries_steps) — loads without error; no-op reference knobs
    warn instead of raising (VERDICT r3 missing #3).
    save_summaries_steps is a REAL knob now (utils/summaries.py), so it
    loads silently."""
    path = write_cfg(tmp_path, """
        [General]
        vocabulary_size = 80000000
        vocabulary_block_num = 100
        hash_feature_id = True
        factor_num = 8
        model_file = ./model/fm_model
        log_file = ./log/fm.log

        [Train]
        train_files = data/train_*.txt
        weight_files =
        validation_files =
        epoch_num = 10
        batch_size = 10000
        learning_rate = 0.01
        factor_lambda = 1e-5
        bias_lambda = 1e-5
        init_value_range = 0.01
        loss_type = logistic
        queue_size = 10000
        shuffle_threads = 4
        save_summaries_steps = 100

        [Predict]
        predict_files = data/test_*.txt
        score_path = ./score/

        [Cluster]
        ps_hosts = host1:2220,host2:2220
        worker_hosts = host3:2230,host4:2230
    """)
    with pytest.warns(UserWarning) as rec:
        cfg = load_config(path)
    msgs = [str(w.message) for w in rec]
    assert any("vocabulary_block_num" in m for m in msgs)
    assert cfg.vocabulary_size == 80000000
    assert cfg.save_summaries_steps == 100
    assert cfg.weight_files == () and cfg.validation_files == ()
    assert cfg.ps_hosts == ("host1:2220", "host2:2220")


def test_kernel_pallas_fallback_warns():
    """Explicit kernel=pallas on FFM / order>2 warns and resolves to the
    XLA scorer instead of silently betraying the config (VERDICT r3
    weak #2)."""
    from fast_tffm_tpu.models.fm import ModelSpec
    for kwargs in (dict(model_type="ffm", field_num=3),
                   dict(order=3)):
        cfg = FmConfig(kernel="pallas", **kwargs)
        with pytest.warns(UserWarning, match="2nd-order FM"):
            spec = ModelSpec.from_config(cfg)
        assert spec.kernel == "xla"
    # auto never warns — it just resolves.
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        ModelSpec.from_config(FmConfig(order=3))


def test_unknown_key_fails_loudly(tmp_path):
    path = write_cfg(tmp_path, """
        [General]
        vocabulary_sizee = 100
    """)
    with pytest.raises(KeyError) as err:
        load_config(path)
    # A true typo must not get a misleading wrong-section hint.
    assert "belongs in" not in str(err.value)


def test_known_key_in_wrong_section_names_its_home(tmp_path):
    """A key placed in the wrong section (the common miss for the
    [General]-homed extension knobs) errors with a pointer to the right
    section; a true typo gets no misleading hint."""
    path = write_cfg(tmp_path, """
        [General]
        vocabulary_size = 100
        [Train]
        lookup = host
    """)
    with pytest.raises(KeyError, match=r"belongs in \[General\]"):
        load_config(path)


def test_missing_file():
    with pytest.raises(FileNotFoundError):
        load_config("/nonexistent/x.cfg")


def test_validation():
    with pytest.raises(ValueError):
        FmConfig(order=1)
    with pytest.raises(ValueError):
        FmConfig(model_type="ffm")          # needs field_num
    with pytest.raises(ValueError):
        FmConfig(model_type="nope")
    with pytest.raises(ValueError):
        FmConfig(loss_type="hinge")
    ffm = FmConfig(model_type="ffm", field_num=5, factor_num=4)
    assert ffm.row_dim == 21


def test_extension_keys(tmp_path):
    path = write_cfg(tmp_path, """
        [General]
        model_type = ffm
        field_num = 3
        factor_num = 2
        order = 2
    """)
    cfg = load_config(path)
    assert cfg.model_type == "ffm"
    assert cfg.row_dim == 7


def test_every_documented_extension_knob_is_reachable(tmp_path):
    """Every knob sample.cfg's header documents must parse from INI —
    a documented-but-unregistered key (dedup was one) strands the
    feature outside the CLI."""
    path = write_cfg(tmp_path, """
        [General]
        vocabulary_size = 100
        model_type = ffm
        field_num = 4
        order = 2
        lookup = device
        dedup = host

        [Train]
        train_files = data/a.txt
        kernel = xla
        dedup = device
        max_features_per_example = 32
        bucket_ladder = 8,32
        uniq_bucket = 128
        validation_max_batches = 5
        shuffle_threads = 3
    """)
    cfg = load_config(path)
    assert cfg.dedup == "device"        # [Train] wins over [General]
    assert cfg.kernel == "xla"
    assert cfg.model_type == "ffm" and cfg.field_num == 4
    assert cfg.lookup == "device"
    assert cfg.bucket_ladder == (8, 32)
    assert cfg.uniq_bucket == 128
    assert cfg.validation_max_batches == 5
    assert cfg.prefetch_depth == 3
