"""kernel=auto with a multi-rung ladder: one job can (correctly) run
DIFFERENT kernels for different bucket widths — sub-64 buckets take the
XLA scorer, 64+ device-dedup buckets take Pallas (interpret mode on the
CPU test rig). The round-5 per-bucket resolution must hold inside one
training run: same data, mixed dispatch, finite converging loss, and
byte-equal results vs forcing each kernel globally would differ — so
instead we pin that the mixed run equals a run where each batch's
kernel is resolved the same way manually."""

import numpy as np

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import batch_iterator
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                     init_accumulator, init_table,
                                     make_train_step, resolved_kernel)


def _lines(rng, n, nnz_lo, nnz_hi, vocab):
    out = []
    for _ in range(n):
        ids = rng.choice(vocab, size=int(rng.integers(nnz_lo, nnz_hi)),
                         replace=False)
        out.append(" ".join(["1" if rng.random() < 0.5 else "0"]
                            + [f"{i}:1" for i in sorted(ids)]))
    return out


def test_one_job_spans_both_kernel_regimes(tmp_path, rng):
    vocab = 512
    # alternate sparse stretches (bucket 32 -> xla) with dense ones
    # (bucket 64 -> pallas under device dedup)
    lines = []
    for block in range(6):
        lo, hi = ((2, 8) if block % 2 == 0 else (40, 60))
        lines.extend(_lines(rng, 32, lo, hi, vocab))
    data = tmp_path / "mix.txt"
    data.write_text("\n".join(lines) + "\n")
    cfg = FmConfig(vocabulary_size=vocab, factor_num=4, batch_size=32,
                   shuffle=False, kernel="auto", dedup="device",
                   max_features_per_example=64, bucket_ladder=(32, 64),
                   learning_rate=0.1,
                   model_file=str(tmp_path / "m" / "fm"))
    spec = ModelSpec.from_config(cfg)
    # On the CPU rig from_config resolves auto -> xla; force the
    # TPU-side behavior (auto survives) to exercise mixed dispatch.
    import dataclasses
    spec = dataclasses.replace(spec, kernel="auto")
    step = make_train_step(spec)
    table, acc = init_table(cfg), init_accumulator(cfg)
    seen_L = set()
    losses = []
    for batch in batch_iterator(cfg, [str(data)], training=True,
                                epochs=1, raw_ids=True):
        L = batch.vals.shape[-1]
        seen_L.add(L)
        table, acc, loss, _ = step(table, acc, **batch_args(batch))
        losses.append(float(loss))
    assert {32, 64} <= seen_L, seen_L
    assert {resolved_kernel(spec, L) for L in seen_L} == {"xla",
                                                         "pallas"}
    assert np.isfinite(losses).all()
    # parity: the same run with each batch's kernel forced explicitly
    # to what resolution picked must be bit-identical
    table2, acc2 = init_table(cfg), init_accumulator(cfg)
    steps = {k: make_train_step(dataclasses.replace(spec, kernel=k))
             for k in ("xla", "pallas")}
    losses2 = []
    for batch in batch_iterator(cfg, [str(data)], training=True,
                                epochs=1, raw_ids=True):
        k = resolved_kernel(spec, batch.vals.shape[-1])
        table2, acc2, loss, _ = steps[k](table2, acc2,
                                         **batch_args(batch))
        losses2.append(float(loss))
    np.testing.assert_array_equal(np.asarray(losses),
                                  np.asarray(losses2))
    np.testing.assert_array_equal(np.asarray(table),
                                  np.asarray(table2))
