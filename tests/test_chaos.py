"""Tier-1 wiring for the fmchaos fault-injection soaks: each scenario
runs a real (tiny) training job under one injected fault and asserts
the documented recovery behavior — the asserts live in
tools/fmchaos/__init__.py so `make chaos`, CI, and this suite pin the
exact same contracts."""

import pytest

from tools.fmchaos import SCENARIOS


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_chaos_scenario(name, tmp_path):
    detail = SCENARIOS[name](str(tmp_path))
    assert isinstance(detail, str) and detail
