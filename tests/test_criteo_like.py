"""AUC parity on faithfully synthesized Criteo-Kaggle-like CTR data
(BASELINE config #1's metric is examples/sec + test-AUC; no real dataset
ships in this environment, so data/synth.py draws from a KNOWN
generative CTR model — Zipf-skewed categorical fields, log-normal
numerics, FM-style ground-truth logits).

The framework trains through the real CLI (run_tffm train/predict) and
its score-file AUC is compared against an independent pure-NumPy SGD-FM
(hand-derived gradients, no shared model code) trained on the same
parsed data — agreement is evidence the whole train->predict path
optimizes the right objective, not a tautology.
"""

import numpy as np
import pytest

import run_tffm
from fast_tffm_tpu.data import synth
from fast_tffm_tpu.metrics import exact_auc

N_TRAIN, N_TEST = 30000, 10000
VOCAB = 1 << 20
K, LR, EPOCHS = 8, 0.05, 2
LAM = 1e-6


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("criteo_like")
    train, test = str(tmp / "train.txt"), str(tmp / "test.txt")
    meta = synth.write_dataset(train, test, N_TRAIN, N_TEST, seed=3)
    return tmp, train, test, meta


@pytest.mark.slow
def test_criteo_like_auc_parity(dataset):
    tmp, train, test, meta = dataset
    # sane generator: Criteo-like positive rate, a real signal to learn
    assert 0.15 < meta["positive_rate_test"] < 0.35
    assert meta["bayes_auc"] > 0.85

    cfg_path = tmp / "ck.cfg"
    cfg_path.write_text(f"""
[General]
vocabulary_size = {VOCAB}
hash_feature_id = True
factor_num = {K}
model_file = {tmp}/model/ck
log_file = {tmp}/log/ck.log

[Train]
train_files = {train}
epoch_num = {EPOCHS}
batch_size = 512
learning_rate = {LR}
factor_lambda = {LAM}
bias_lambda = {LAM}
init_value_range = 0.01
loss_type = logistic
max_features_per_example = 48
bucket_ladder = 48
shuffle = False

[Predict]
predict_files = {test}
score_path = {tmp}/score
""")
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    assert run_tffm.main(["predict", str(cfg_path)]) == 0
    scores = np.loadtxt(tmp / "score" / "test.txt.score")
    labels = np.loadtxt(test, usecols=0)
    assert len(scores) == N_TEST
    fw_auc = exact_auc(scores, labels)

    # Independent NumPy oracle on the same parsed CSR blocks.
    tr = synth.parse_file_blocks(train, VOCAB, 512)
    te = synth.parse_file_blocks(test, VOCAB, 512)
    oracle_scores = synth.numpy_fm_train_predict(
        tr, te, VOCAB, k=K, lr=LR, epochs=EPOCHS,
        factor_lambda=LAM, bias_lambda=LAM)
    oracle_auc = exact_auc(oracle_scores, labels)

    # Parity: same data, same hyperparameters, independent code paths.
    assert abs(fw_auc - oracle_auc) < 0.015, (fw_auc, oracle_auc)
    # And both genuinely learned (ceiling is meta["bayes_auc"] ~0.90).
    assert fw_auc > 0.72, fw_auc
    assert fw_auc < meta["bayes_auc"]


def test_numpy_oracle_order3_forward_and_grad(rng):
    """Triangulate the trainer-oracle's order-3 math against the
    INDEPENDENT per-example ANOVA-DP oracle (models/oracle.fm_score),
    and its dz gradient against numerical differentiation — so the
    at-scale order-3 parity run rests on a checked oracle."""
    from fast_tffm_tpu.data.synth import _fm_forward
    from fast_tffm_tpu.models.oracle import fm_score
    B, L, k = 5, 7, 3
    z = rng.normal(0.0, 0.7, size=(B, L, k))
    inter, dz = _fm_forward(z, order=3)
    # forward: ANOVA degrees 2..3 summed over latent dims; fm_score
    # computes the same from (v, x) — use x=1 so z == v
    table = np.zeros((L, k + 1))
    for b in range(B):
        table[:, :k] = z[b]
        want = fm_score(table, np.arange(L), np.ones(L), order=3)
        assert inter[b].sum() == pytest.approx(want, rel=1e-9)
    # gradient: central differences on the summed interaction
    eps = 1e-6
    for (b, l, f) in ((0, 0, 0), (2, 3, 1), (4, 6, 2)):
        zp, zm = z.copy(), z.copy()
        zp[b, l, f] += eps
        zm[b, l, f] -= eps
        num = (_fm_forward(zp, 3)[0][b].sum()
               - _fm_forward(zm, 3)[0][b].sum()) / (2 * eps)
        assert dz[b, l, f] == pytest.approx(num, rel=1e-5)


@pytest.mark.slow
def test_avazu_like_ffm_auc_parity(tmp_path):
    """BASELINE config #3's parity leg: field-aware data from a KNOWN
    field-aware generative model, the real CLI FFM train->predict vs
    the independent NumPy FFM-SGD oracle (synth.numpy_ffm_train_predict
    — hand-derived field-aware gradients) at matched settings."""
    F = len(synth.FFM_FIELDS)
    vocab = synth.ffm_vocab_size()
    train, test = str(tmp_path / "tr.txt"), str(tmp_path / "te.txt")
    meta = synth.write_ffm_dataset(train, test, 30000, 8000, seed=5)
    assert meta["bayes_auc"] > 0.8

    cfg_path = tmp_path / "ckffm.cfg"
    cfg_path.write_text(f"""
[General]
vocabulary_size = {vocab}
factor_num = 4
model_type = ffm
field_num = {F}
model_file = {tmp_path}/model/ckffm
log_file = {tmp_path}/log/ckffm.log

[Train]
train_files = {train}
epoch_num = {EPOCHS}
batch_size = 512
learning_rate = {LR}
factor_lambda = {LAM}
bias_lambda = {LAM}
init_value_range = 0.01
loss_type = logistic
max_features_per_example = {F}
bucket_ladder = {F}
shuffle = False

[Predict]
predict_files = {test}
score_path = {tmp_path}/score
""")
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    assert run_tffm.main(["predict", str(cfg_path)]) == 0
    scores = np.loadtxt(tmp_path / "score" / "te.txt.score")
    labels = np.loadtxt(test, usecols=0)
    fw_auc = exact_auc(scores, labels)

    tr_b = synth.parse_ffm_file(train, 512)
    te_b = synth.parse_ffm_file(test, 512)
    oracle_auc = exact_auc(
        synth.numpy_ffm_train_predict(tr_b, te_b, vocab, k=4, lr=LR,
                                      epochs=EPOCHS, factor_lambda=LAM,
                                      bias_lambda=LAM),
        labels)
    assert abs(fw_auc - oracle_auc) < 0.015, (fw_auc, oracle_auc)
    # both learned real signal (0.5 = chance; 30k rows only start to
    # resolve the pairwise truth, so the bar is modest)
    assert fw_auc > 0.58, fw_auc
    assert fw_auc < meta["bayes_auc"]
