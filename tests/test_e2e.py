"""End-to-end: config file -> run_tffm.py train -> checkpoint -> predict
-> score files, on a synthetic separable dataset (the reference's
quick-start smoke run, but asserted; SURVEY §4)."""

import os
import textwrap

import numpy as np
import pytest

import run_tffm
from fast_tffm_tpu.config import load_config
from fast_tffm_tpu.metrics import exact_auc


def make_dataset(path, n, rng, vocab=200, informative=6):
    """label=1 examples prefer ids [0, informative), label=0 prefer
    [informative, 2*informative); both share noise ids."""
    lines = []
    labels = []
    for _ in range(n):
        y = int(rng.integers(0, 2))
        base = 0 if y else informative
        feats = {int(base + rng.integers(0, informative)): 1.0,
                 int(base + rng.integers(0, informative)): 1.0}
        for _ in range(3):
            feats[int(rng.integers(2 * informative, vocab))] = round(
                float(rng.uniform(0.5, 1.5)), 3)
        toks = " ".join(f"{i}:{v}" for i, v in sorted(feats.items()))
        lines.append(f"{y} {toks}\n")
        labels.append(y)
    with open(path, "w") as fh:
        fh.writelines(lines)
    return np.array(labels, dtype=np.float64)


@pytest.fixture
def workdir(tmp_path, rng):
    train = tmp_path / "train.txt"
    test = tmp_path / "test.txt"
    make_dataset(train, 600, rng)
    test_labels = make_dataset(test, 200, rng)
    cfg_path = tmp_path / "fm.cfg"
    cfg_path.write_text(textwrap.dedent(f"""
        [General]
        vocabulary_size = 200
        factor_num = 4
        model_file = {tmp_path}/model/fm_model
        log_file = {tmp_path}/log/fm.log

        [Train]
        train_files = {train}
        validation_files = {test}
        epoch_num = 8
        batch_size = 32
        learning_rate = 0.1
        factor_lambda = 1e-6
        bias_lambda = 1e-6
        init_value_range = 0.01
        loss_type = logistic
        log_steps = 50

        [Predict]
        predict_files = {test}
        score_path = {tmp_path}/score
    """))
    return tmp_path, cfg_path, test_labels


def test_train_then_predict_auc(workdir):
    tmp_path, cfg_path, test_labels = workdir
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    # checkpoint + npz exist at the configured model_file
    assert os.path.isdir(f"{tmp_path}/model/fm_model.ckpt")
    assert os.path.exists(f"{tmp_path}/model/fm_model.npz")
    # log file written with step/loss lines
    log = (tmp_path / "log" / "fm.log").read_text()
    assert "loss" in log

    assert run_tffm.main(["predict", str(cfg_path)]) == 0
    score_file = tmp_path / "score" / "test.txt.score"
    scores = np.loadtxt(score_file)
    # one score per input line, order preserving
    assert len(scores) == 200
    assert np.all((scores >= 0) & (scores <= 1))   # sigmoid for logistic
    auc = exact_auc(scores, test_labels)
    assert auc > 0.85, f"e2e AUC too low: {auc}"


def test_resume_from_checkpoint(workdir):
    tmp_path, cfg_path, _ = workdir
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    npz1 = np.load(f"{tmp_path}/model/fm_model.npz")["table"]
    # second run restores and keeps training (step counter advances)
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    log = (tmp_path / "log" / "fm.log").read_text()
    assert "restored checkpoint at step" in log
    npz2 = np.load(f"{tmp_path}/model/fm_model.npz")["table"]
    assert npz1.shape == npz2.shape
    assert not np.array_equal(npz1, npz2)          # it kept learning


def test_interrupted_epoch_schedule_resumes(workdir):
    """A checkpoint recording an incomplete epoch schedule (epoch <
    epoch_num — what a preemption save writes) must resume at the first
    incomplete epoch, not restart the schedule from zero: under
    recurring preemption a from-zero restart would revisit identical
    data and never terminate. A COMPLETED checkpoint keeps the
    reference's train-more semantics (test_resume_from_checkpoint)."""
    from fast_tffm_tpu.checkpoint import CheckpointState
    from fast_tffm_tpu.train import train
    tmp_path, cfg_path, _ = workdir
    cfg = load_config(str(cfg_path))
    assert cfg.epoch_num == 8

    # Run the full schedule once, then rewrite the final checkpoint's
    # metadata to look like a preemption cut it at 5 completed epochs.
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    from fast_tffm_tpu.train import checkpoint_template
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    steps_full = int(restored["step"])
    steps_per_epoch = steps_full // cfg.epoch_num
    # A save at an existing step is a silent no-op (StepAlreadyExists),
    # so the doctored metadata must land on a NEW step number.
    doctored = steps_full + 1
    ckpt.save(doctored, restored["table"], restored["acc"],
              vocabulary_size=cfg.vocabulary_size, force=True, wait=True,
              epoch=5)
    ckpt.close()

    train(cfg)
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    # Only the 3 incomplete epochs ran (not another full 8)...
    assert int(restored["step"]) == doctored + 3 * steps_per_epoch
    # ...and the finished schedule is recorded as complete.
    assert int(restored["epoch"]) == cfg.epoch_num


def test_predict_without_checkpoint_fails(tmp_path):
    cfg_path = tmp_path / "p.cfg"
    cfg_path.write_text(textwrap.dedent(f"""
        [General]
        vocabulary_size = 10
        model_file = {tmp_path}/model/none
        [Predict]
        predict_files = {tmp_path}/x.txt
        score_path = {tmp_path}/score
    """))
    (tmp_path / "x.txt").write_text("0 1:1\n")
    with pytest.raises(FileNotFoundError):
        run_tffm.main(["predict", str(cfg_path)])


def test_cli_usage_errors():
    assert run_tffm.main([]) == 2
    assert run_tffm.main(["bogus", "x.cfg"]) == 2
