"""Spill observability (VERDICT r2 item 5): undersized uniq_bucket must
be visible (SpillStats), never lossy, on both the C++ fast path and the
generic path; probe_uniq_bucket must not be fooled by a sparse head."""

import os

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import (SPILL_WARN_FRACTION, SpillStats,
                                         batch_iterator, effective_L_cap,
                                         probe_uniq_bucket)


def _dense_file(path, n_lines, ids_per_line, id_stride=1, start=0):
    """Each line holds ``ids_per_line`` distinct ids, lines disjoint when
    id_stride >= ids_per_line — so unique count grows fast."""
    with open(path, "w") as fh:
        for i in range(n_lines):
            base = start + i * id_stride
            toks = " ".join(f"{base + j}:1" for j in range(ids_per_line))
            fh.write(f"{i % 2} {toks}\n")


def _run(cfg, path, **kw):
    stats = SpillStats()
    batches = list(batch_iterator(cfg, [str(path)], training=True,
                                  epochs=1, fixed_shape=True,
                                  uniq_bucket=cfg.uniq_bucket,
                                  stats=stats, **kw))
    return batches, stats


@pytest.mark.parametrize("generic", [False, True])
def test_spill_counted_and_lossless(tmp_path, generic):
    # 64 lines x 8 disjoint ids: a 16-line batch needs 128 uniques + pad,
    # but the bucket holds 64 -> every batch must close early (spill).
    path = tmp_path / "dense.txt"
    _dense_file(path, 64, 8, id_stride=8)
    cfg = FmConfig(vocabulary_size=4096, batch_size=16, uniq_bucket=64,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    # weight_files force the generic (Python make_device_batch) path —
    # keep_empty no longer does (it is a C++ builder mode since ABI 4).
    kw = {}
    if generic:
        wpath = tmp_path / "w.txt"
        wpath.write_text("1.0\n" * 64)
        kw["weight_files"] = (str(wpath),)
    batches, stats = _run(cfg, path, **kw)
    assert stats.spilled_batches > 0
    assert stats.batches == len(batches)
    assert stats.fill_fraction < 1.0
    assert stats.spill_fraction > 0.5
    # Lossless: every line emitted exactly once, in order.
    assert stats.real_examples == 64
    assert sum(b.num_real for b in batches) == 64
    for b in batches:
        assert len(b.uniq_ids) == 64          # shape stays fixed
        assert b.num_real < cfg.batch_size    # every batch spilled here


def test_no_spill_counts_clean(tmp_path):
    path = tmp_path / "sparse.txt"
    _dense_file(path, 64, 4, id_stride=0)     # all lines share 4 ids
    cfg = FmConfig(vocabulary_size=4096, batch_size=16, uniq_bucket=64,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    batches, stats = _run(cfg, path)
    assert stats.spilled_batches == 0
    assert stats.fill_fraction == 1.0
    assert stats.real_examples == 64


def test_probe_sees_dense_tail(tmp_path):
    """Sparse-first data: a head-only probe would pick the minimum
    bucket and every tail batch would spill; the 3-point probe must see
    the dense tail."""
    path = tmp_path / "sorted.txt"
    with open(path, "w") as fh:
        for i in range(512):                  # sparse head: 4 shared ids
            fh.write("1 0:1 1:1 2:1 3:1\n")
        for i in range(512):                  # dense tail: disjoint ids
            base = 100 + i * 12
            toks = " ".join(f"{base + j}:1" for j in range(12))
            fh.write(f"0 {toks}\n")
    cfg = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    b = probe_uniq_bucket(cfg, [str(path)])
    # Dense tail batch: 128 lines x 12 disjoint ids ~ 1536 uniques ->
    # probe must return >= 4096 (2x headroom, pow2); head alone gives 64.
    assert b >= 2048, b


def test_effective_L_cap_shared():
    cfg = FmConfig(bucket_ladder=(8, 16), max_features_per_example=100)
    assert effective_L_cap(cfg) == 128        # pow2 extension past ladder
    cfg2 = FmConfig(bucket_ladder=(8, 64), max_features_per_example=32)
    assert effective_L_cap(cfg2) == 64


def test_probe_sees_dense_later_file(tmp_path):
    """Day-partitioned multi-file data whose LATER files are denser: the
    probe samples first + last + largest files, so a dense final file
    sets the bucket even when file 0 is all-sparse (VERDICT r3 weak #3)."""
    sparse = tmp_path / "day0.txt"
    _dense_file(sparse, 512, 4, id_stride=0)   # 4 shared ids throughout
    dense = tmp_path / "day1.txt"
    with open(dense, "w") as fh:
        for i in range(512):
            base = 100 + i * 12
            toks = " ".join(f"{base + j}:1" for j in range(12))
            fh.write(f"0 {toks}\n")
    cfg = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    assert probe_uniq_bucket(cfg, [str(sparse)]) == 64     # sparse alone
    assert probe_uniq_bucket(cfg, [str(sparse), str(dense)]) >= 2048


def test_adapt_uniq_bucket_raises_on_spill():
    """Epoch-boundary adaptation: job-wide spill above the warn
    threshold doubles the bucket (capped at the worst-case top); an
    explicit config or a clean epoch leaves it alone."""
    import logging
    from fast_tffm_tpu.data.pipeline import uniq_bucket_top
    from fast_tffm_tpu.train import adapt_uniq_bucket
    logger = logging.getLogger("test")
    cfg = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                   max_features_per_example=16, bucket_ladder=(16,))
    top = uniq_bucket_top(cfg)
    assert adapt_uniq_bucket(cfg, 256, spilled=50, batches=100,
                             logger=logger) == 512
    assert adapt_uniq_bucket(cfg, 256, spilled=5, batches=100,
                             logger=logger) == 256          # clean epoch
    assert adapt_uniq_bucket(cfg, top, spilled=50, batches=100,
                             logger=logger) == top          # capped
    assert adapt_uniq_bucket(cfg, top // 2, spilled=50, batches=50,
                             logger=logger) == top
    pinned = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                      max_features_per_example=16, bucket_ladder=(16,),
                      uniq_bucket=256)
    assert adapt_uniq_bucket(pinned, 256, spilled=50, batches=100,
                             logger=logger) == 256          # explicit cfg
    assert adapt_uniq_bucket(cfg, 256, spilled=0, batches=0,
                             logger=logger) == 256          # no batches


def test_adapt_uniq_bucket_shrinks_on_low_fill():
    """Shrink branch (round-4 review: the adaptive bucket only grew, so
    an overshot probe or an early dense file inflated the gather/
    scatter width for the rest of the job): a spill-free epoch whose
    densest batch filled < SHRINK_FILL_FRACTION of the bucket halves
    it — never below 64 or the per-example cap, never when any batch
    spilled, never against an explicit config."""
    import logging
    from fast_tffm_tpu.train import SHRINK_FILL_FRACTION, adapt_uniq_bucket
    logger = logging.getLogger("test")
    cfg = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                   max_features_per_example=16, bucket_ladder=(16,))
    kw = dict(spilled=0, batches=100, logger=logger)
    assert adapt_uniq_bucket(cfg, 512, max_uniq=100, **kw) == 256
    # fill at/above the threshold keeps the width
    at = int(512 * SHRINK_FILL_FRACTION)
    assert adapt_uniq_bucket(cfg, 512, max_uniq=at + 1, **kw) == 512
    # floor: never below 64
    assert adapt_uniq_bucket(cfg, 64, max_uniq=4, **kw) == 64
    assert adapt_uniq_bucket(cfg, 128, max_uniq=4, **kw) == 64
    # floor: the halved bucket must still exceed the per-example cap
    # (128 -> 64 would leave a full 100-feature example unable to fit)
    wide = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                    max_features_per_example=100, bucket_ladder=(128,))
    assert adapt_uniq_bucket(wide, 128, max_uniq=20, **kw) == 128
    # any spill this epoch blocks the shrink (densities are recurring)
    assert adapt_uniq_bucket(cfg, 512, spilled=1, batches=100,
                             max_uniq=100, logger=logger) == 512
    # unknown density (max_uniq=0, e.g. no stats) never shrinks
    assert adapt_uniq_bucket(cfg, 512, max_uniq=0, **kw) == 512
    # explicit config is never overridden
    pinned = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                      max_features_per_example=16, bucket_ladder=(16,),
                      uniq_bucket=512)
    assert adapt_uniq_bucket(pinned, 512, max_uniq=100, **kw) == 512


def test_adaptive_bucket_clears_spill_by_epoch2(tmp_path):
    """Heterogeneous-density multi-file input where the dense file is
    the MIDDLE one (first+last+largest probe misses it when sizes
    match): epoch 1 spills, the epoch-boundary adaptation doubles the
    bucket, epoch 2 runs spill-free (VERDICT r3 next-round #6)."""
    import logging
    from fast_tffm_tpu.train import adapt_uniq_bucket
    files = []
    for name, dense in (("a.txt", False), ("b.txt", True),
                        ("c.txt", False)):
        p = tmp_path / name
        with open(p, "w") as fh:
            for i in range(256):
                if dense:
                    base = 1000 + i * 12
                    toks = " ".join(f"{base + j}:1" for j in range(12))
                else:
                    toks = "0:1 1:1 2:1 3:1"
                fh.write(f"1 {toks}\n")
        files.append(str(p))
    # Pad the sparse files to the dense file's byte size so "largest"
    # cannot accidentally pick the dense middle file.
    target = max(os.path.getsize(f) for f in files)
    for f in (files[0], files[2]):
        with open(f, "a") as fh:
            while os.path.getsize(f) < target:
                fh.write("1 0:1 1:1 2:1 3:1\n")
    cfg = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    bucket = probe_uniq_bucket(cfg, files)
    assert bucket <= 128  # the probe misses the dense middle file

    def run_epoch(b):
        stats = SpillStats()
        for _ in batch_iterator(cfg, files, training=True, epochs=1,
                                fixed_shape=True, uniq_bucket=b,
                                stats=stats):
            pass
        return stats

    s1 = run_epoch(bucket)
    assert s1.spill_fraction > SPILL_WARN_FRACTION
    logger = logging.getLogger("test")
    for _ in range(8):  # train() adapts once per epoch boundary
        new = adapt_uniq_bucket(cfg, bucket, s1.spilled_batches,
                                s1.batches, logger)
        if new == bucket:
            break
        bucket = new
        s1 = run_epoch(bucket)
    # The adaptation's contract: drive spill below the warn threshold
    # (it stops doubling there by design — a stray spilled batch is
    # normal; 67% -> ~7% on this data, fill 36% -> 94%).
    assert s1.spill_fraction <= SPILL_WARN_FRACTION, s1.describe()
    assert s1.fill_fraction > 0.9, s1.describe()
