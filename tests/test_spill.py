"""Spill observability (VERDICT r2 item 5): undersized uniq_bucket must
be visible (SpillStats), never lossy, on both the C++ fast path and the
generic path; probe_uniq_bucket must not be fooled by a sparse head."""

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data.pipeline import (SpillStats, batch_iterator,
                                         effective_L_cap,
                                         probe_uniq_bucket)


def _dense_file(path, n_lines, ids_per_line, id_stride=1, start=0):
    """Each line holds ``ids_per_line`` distinct ids, lines disjoint when
    id_stride >= ids_per_line — so unique count grows fast."""
    with open(path, "w") as fh:
        for i in range(n_lines):
            base = start + i * id_stride
            toks = " ".join(f"{base + j}:1" for j in range(ids_per_line))
            fh.write(f"{i % 2} {toks}\n")


def _run(cfg, path, **kw):
    stats = SpillStats()
    batches = list(batch_iterator(cfg, [str(path)], training=True,
                                  epochs=1, fixed_shape=True,
                                  uniq_bucket=cfg.uniq_bucket,
                                  stats=stats, **kw))
    return batches, stats


@pytest.mark.parametrize("generic", [False, True])
def test_spill_counted_and_lossless(tmp_path, generic):
    # 64 lines x 8 disjoint ids: a 16-line batch needs 128 uniques + pad,
    # but the bucket holds 64 -> every batch must close early (spill).
    path = tmp_path / "dense.txt"
    _dense_file(path, 64, 8, id_stride=8)
    cfg = FmConfig(vocabulary_size=4096, batch_size=16, uniq_bucket=64,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    # weight_files force the generic (Python make_device_batch) path —
    # keep_empty no longer does (it is a C++ builder mode since ABI 4).
    kw = {}
    if generic:
        wpath = tmp_path / "w.txt"
        wpath.write_text("1.0\n" * 64)
        kw["weight_files"] = (str(wpath),)
    batches, stats = _run(cfg, path, **kw)
    assert stats.spilled_batches > 0
    assert stats.batches == len(batches)
    assert stats.fill_fraction < 1.0
    assert stats.spill_fraction > 0.5
    # Lossless: every line emitted exactly once, in order.
    assert stats.real_examples == 64
    assert sum(b.num_real for b in batches) == 64
    for b in batches:
        assert len(b.uniq_ids) == 64          # shape stays fixed
        assert b.num_real < cfg.batch_size    # every batch spilled here


def test_no_spill_counts_clean(tmp_path):
    path = tmp_path / "sparse.txt"
    _dense_file(path, 64, 4, id_stride=0)     # all lines share 4 ids
    cfg = FmConfig(vocabulary_size=4096, batch_size=16, uniq_bucket=64,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    batches, stats = _run(cfg, path)
    assert stats.spilled_batches == 0
    assert stats.fill_fraction == 1.0
    assert stats.real_examples == 64


def test_probe_sees_dense_tail(tmp_path):
    """Sparse-first data: a head-only probe would pick the minimum
    bucket and every tail batch would spill; the 3-point probe must see
    the dense tail."""
    path = tmp_path / "sorted.txt"
    with open(path, "w") as fh:
        for i in range(512):                  # sparse head: 4 shared ids
            fh.write("1 0:1 1:1 2:1 3:1\n")
        for i in range(512):                  # dense tail: disjoint ids
            base = 100 + i * 12
            toks = " ".join(f"{base + j}:1" for j in range(12))
            fh.write(f"0 {toks}\n")
    cfg = FmConfig(vocabulary_size=1 << 16, batch_size=128,
                   max_features_per_example=16, bucket_ladder=(16,),
                   shuffle=False)
    b = probe_uniq_bucket(cfg, [str(path)])
    # Dense tail batch: 128 lines x 12 disjoint ids ~ 1536 uniques ->
    # probe must return >= 4096 (2x headroom, pow2); head alone gives 64.
    assert b >= 2048, b


def test_effective_L_cap_shared():
    cfg = FmConfig(bucket_ladder=(8, 16), max_features_per_example=100)
    assert effective_L_cap(cfg) == 128        # pow2 extension past ladder
    cfg2 = FmConfig(bucket_ladder=(8, 64), max_features_per_example=32)
    assert effective_L_cap(cfg2) == 64
