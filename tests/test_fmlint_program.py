"""tools/fmlint whole-program layer: the project loader (imports, call
graph, summaries), the cross-file rules R007-R012, the committed
baseline, --json — and the seeded-mutant acceptance test proving R007
catches a rank-gated collective planted in the REAL checkpoint.py
restore path."""

import json
import os
import textwrap

import pytest

from tools.fmlint.core import (apply_baseline, main, run_paths,
                               write_baseline)
from tools.fmlint.project import load_project, parse_files

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _project(tmp_path, files):
    """Write {relpath: source} under tmp_path, return (root, paths)."""
    paths = []
    for rel, body in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(body))
        if rel.endswith(".py"):
            paths.append(str(p))
    return str(tmp_path), paths


def _load(tmp_path, files):
    _, paths = _project(tmp_path, files)
    return load_project(parse_files(paths))


def _findings(tmp_path, files, rule=None):
    root, _ = _project(tmp_path, files)
    # Lint the directory (not the file list): directory linting is the
    # shape the repo gate uses.
    found = run_paths([root])
    if rule is not None:
        found = [f for f in found if f.rule == rule]
    return found


# --- project loader -------------------------------------------------------

def test_import_and_call_graph_resolution(tmp_path):
    proj = _load(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/a.py": """\
            from pkg.b import helper
            import pkg.b as bee
            def top():
                helper()
                bee.other()
        """,
        "pkg/b.py": """\
            def helper():
                pass
            def other():
                pass
        """,
    })
    fn = proj.functions["pkg.a.top"]
    assert fn.calls == {"pkg.b.helper", "pkg.b.other"}


def test_collective_summary_is_transitive(tmp_path):
    proj = _load(tmp_path, {
        "m.py": """\
            from jax.experimental import multihost_utils
            def leaf(x):
                return multihost_utils.process_allgather(x)
            def mid(x):
                return leaf(x)
            def top(x):
                return mid(x)
        """,
    })
    assert proj.collectives_of("m.top") == {"process_allgather"}


def test_thread_summary_reaches_nested_target_and_callees(tmp_path):
    """The Watchdog pattern: the Thread target is a closure defined
    under an ``if``, and it calls a method of the same class."""
    proj = _load(tmp_path, {
        "w.py": """\
            import threading
            class W:
                def check(self):
                    self.count = 1
                def start(self):
                    if True:
                        def loop():
                            self.check()
                        threading.Thread(target=loop).start()
        """,
    })
    assert "w.W.start.loop" in proj.thread_funcs
    assert "w.W.check" in proj.thread_funcs


def test_shared_write_lock_detection(tmp_path):
    proj = _load(tmp_path, {
        "s.py": """\
            class S:
                def locked(self):
                    with self._lock:
                        self.x = 1
                def bare(self):
                    self.y = 2
                    self.items.append(3)
        """,
    })
    locked = proj.functions["s.S.locked"].shared_writes
    bare = proj.functions["s.S.bare"].shared_writes
    assert [w.locked for w in locked] == [True]
    assert [(w.target, w.locked) for w in bare] == [
        ("self.y", False), ("self.items", False)]


def test_shared_write_lock_detected_through_nested_with(tmp_path):
    """A lock `with` nested directly inside another `with` body (the
    open-then-lock shape) must still raise the lock depth."""
    proj = _load(tmp_path, {
        "s.py": """\
            class S:
                def work(self, f):
                    with open(f) as fh:
                        with self._lock:
                            self.n = fh.read()
        """,
    })
    writes = proj.functions["s.S.work"].shared_writes
    assert [(w.target, w.locked) for w in writes] == [("self.n", True)]


def test_shared_write_requires_store_context(tmp_path):
    """Reads inside assignment targets are not writes: `buf[self.idx]`
    READS self.idx, and in a chained store only the outermost
    attribute is written."""
    proj = _load(tmp_path, {
        "s.py": """\
            class S:
                def work(self, buf):
                    buf[self.idx] = 1
                def chain(self):
                    self.a.b = 1
        """,
    })
    assert proj.functions["s.S.work"].shared_writes == []
    assert [w.target
            for w in proj.functions["s.S.chain"].shared_writes] == [
        "self.a.b"]


def test_relative_import_resolution_from_package_init(tmp_path):
    """`from .b import helper` inside pkg/__init__.py: the package
    module's modname IS the package, so level=1 must not strip it —
    the call edge (and any collective behind it) would silently
    vanish otherwise."""
    proj = _load(tmp_path, {
        "pkg/__init__.py": """\
            from .b import helper
            def top():
                helper()
        """,
        "pkg/b.py": """\
            from jax.experimental import multihost_utils
            def helper():
                multihost_utils.process_allgather(None)
        """,
    })
    assert proj.functions["pkg.top"].calls == {"pkg.b.helper"}
    assert proj.collectives_of("pkg.top") == {"process_allgather"}


# --- R007: divergent collective -------------------------------------------

_ALLGATHER_DEF = """\
        from jax.experimental import multihost_utils
"""


def test_r007_flags_rank_gated_collective(tmp_path):
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def sync(x):
            if jax.process_index() == 0:
                return multihost_utils.process_allgather(x)
    """}, rule="R007")
    assert len(found) == 1
    assert "process_allgather" in found[0].message


def test_r007_flags_transitive_collective_through_call_graph(tmp_path):
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def deep(x):
            return multihost_utils.broadcast_one_to_all(x)
        def mid(x):
            return deep(x)
        def sync(x):
            if jax.process_index() == 0:
                mid(x)
    """}, rule="R007")
    assert len(found) == 1
    assert "broadcast_one_to_all" in found[0].message


def test_r007_flags_early_return_divergence(tmp_path):
    """`if rank != 0: return` then a collective below: only process 0
    posts it — the same deadlock with no explicit else arm."""
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def sync(x):
            if jax.process_index() != 0:
                return None
            return multihost_utils.process_allgather(x)
    """}, rule="R007")
    assert len(found) == 1


def test_r007_flags_tainted_local_condition(tmp_path):
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def sync(x):
            proc0 = jax.process_index() == 0
            if proc0:
                multihost_utils.sync_global_devices("tag")
    """}, rule="R007")
    assert len(found) == 1


def test_r007_allows_matched_collectives_on_both_arms(tmp_path):
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def sync(x):
            if jax.process_index() == 0:
                v = multihost_utils.process_allgather(x)
            else:
                v = multihost_utils.process_allgather(None)
            return v
    """}, rule="R007")
    assert found == []


def test_r007_allows_process_count_branches(tmp_path):
    """process_count is uniform across processes — branching on it is
    the standard single-process fast path, never divergent."""
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def sync(x):
            if jax.process_count() > 1:
                return multihost_utils.process_allgather(x)
            return x
    """}, rule="R007")
    assert found == []


def test_r007_broadcast_result_is_not_tainted(tmp_path):
    """A value RETURNED by a collective is rank-uniform (that is the
    agreement protocol); branching on it must not be flagged even when
    the pre-broadcast value was rank-dependent."""
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def pick():
            return 3
        def sync(x):
            cand = pick() if jax.process_index() == 0 else -1
            cand = int(multihost_utils.broadcast_one_to_all(cand))
            if cand < 0:
                return None
            return multihost_utils.process_allgather(x)
    """}, rule="R007")
    assert found == []


def test_r007_respects_pragma(tmp_path):
    found = _findings(tmp_path, {"m.py": _ALLGATHER_DEF + """\
        import jax
        def sync(x):
            # fmlint: disable=R007 -- peers post the matching call in f
            if jax.process_index() == 0:
                return multihost_utils.process_allgather(x)
    """}, rule="R007")
    assert found == []


def test_r007_seeded_mutant_of_real_checkpoint_restore(tmp_path):
    """Acceptance pin: plant the exact historical bug — the restore
    epoch-override broadcast gated on process_index instead of
    process_count — into the REAL checkpoint.py via a source overlay,
    and prove R007 catches it cross-file while the unmutated repo is
    clean (tests/test_fmlint.py pins the clean half)."""
    ckpt = os.path.join(REPO, "fast_tffm_tpu", "checkpoint.py")
    with open(ckpt, encoding="utf-8") as fh:
        src = fh.read()
    needle = "if jax.process_count() > 1:"
    assert src.count(needle) == 1, "mutation site drifted"
    mutated = src.replace(needle, "if jax.process_index() == 0:")
    found = run_paths([os.path.join(REPO, "fast_tffm_tpu")],
                      overlay={ckpt: mutated})
    r007 = [f for f in found if f.rule == "R007"]
    assert len(r007) == 1, "\n".join(f.render() for f in found)
    assert r007[0].path.endswith("checkpoint.py")
    assert "guarded_collective" in r007[0].message
    # The mutation introduced nothing else: every other rule stays
    # clean, so the one finding IS the planted deadlock.
    assert [f.rule for f in found] == ["R007"]


# --- R008: unsynchronized shared mutation ---------------------------------

_THREADED = """\
    import threading
    class C:
        def __init__(self):
            self.n = 0
        def work(self):
            {body}
        def start(self):
            threading.Thread(target=self.work).start()
"""


def _threaded(body):
    return {"m.py": _THREADED.format(body=body)}


def test_r008_flags_unlocked_thread_write(tmp_path):
    found = _findings(tmp_path, _threaded("self.n += 1"), rule="R008")
    assert len(found) == 1
    assert "self.n" in found[0].message


def test_r008_flags_transitive_thread_callee(tmp_path):
    found = _findings(tmp_path, {"m.py": """\
        import threading
        class C:
            def helper(self):
                self.state = "x"
            def work(self):
                self.helper()
            def start(self):
                threading.Thread(target=self.work).start()
    """}, rule="R008")
    assert len(found) == 1
    assert "helper" in found[0].message


def test_r008_allows_lock_held_writes(tmp_path):
    found = _findings(
        tmp_path,
        _threaded("with self._lock:\n                self.n += 1"),
        rule="R008")
    assert found == []


def test_r008_allows_main_thread_only_functions(tmp_path):
    found = _findings(tmp_path, {"m.py": """\
        class C:
            def work(self):
                self.n = 1
    """}, rule="R008")
    assert found == []


def test_r008_init_is_exempt(tmp_path):
    """Construction happens before the thread exists; __init__ writes
    are the setup, not the race."""
    found = _findings(tmp_path, {"m.py": """\
        import threading
        class C:
            def __init__(self):
                self.n = 0
                threading.Thread(target=self.__init__).start()
    """}, rule="R008")
    assert found == []


def test_r008_respects_pragma(tmp_path):
    found = _findings(
        tmp_path,
        _threaded("self.n += 1  # fmlint: disable=R008 -- single writer"),
        rule="R008")
    assert found == []


# --- R009: config/knob drift ----------------------------------------------

_CFG_PY = """\
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class FmConfig:
        factor_num: int = 8
        metrics_file: str = ""

        @property
        def row_dim(self):
            return self.factor_num + 1

    _GENERAL_KEYS = {"factor_num": int}
    _TRAIN_KEYS = {"metrics_file": str}
"""

_SAMPLE_OK = """\
    ; factor_num and metrics_file documented here
    [General]
    factor_num = 8
"""

_README_OK = "factor_num and metrics_file\n"


def _r009_files(cfg=_CFG_PY, sample=_SAMPLE_OK, readme=_README_OK,
                extra=None):
    files = {"fast_tffm_tpu/config.py": cfg, "sample.cfg": sample,
             "README.md": readme}
    files.update(extra or {})
    return files


def test_r009_clean_when_docs_cover_schema(tmp_path):
    assert _findings(tmp_path, _r009_files(), rule="R009") == []


def test_r009_flags_knob_missing_from_sample_cfg(tmp_path):
    found = _findings(tmp_path, _r009_files(
        sample="[General]\nfactor_num = 8\n",
        readme=_README_OK), rule="R009")
    assert len(found) == 1
    assert "metrics_file" in found[0].message
    assert "sample.cfg" in found[0].message
    assert found[0].path.endswith("config.py")


def test_r009_flags_knob_missing_from_readme(tmp_path):
    found = _findings(tmp_path, _r009_files(readme="nothing here\n"),
                      rule="R009")
    assert {("metrics_file" in f.message or "factor_num" in f.message)
            for f in found} == {True}
    assert all("README" in f.message for f in found)


def test_r009_flags_unknown_sample_cfg_key(tmp_path):
    found = _findings(tmp_path, _r009_files(
        sample=_SAMPLE_OK + "factr_num = 9\n"), rule="R009")
    assert len(found) == 1
    assert "factr_num" in found[0].message
    assert found[0].path.endswith("sample.cfg")
    assert found[0].line == 4  # the misspelled assignment's line


def test_r009_flags_inconsistent_env_fallback(tmp_path):
    found = _findings(tmp_path, _r009_files(extra={
        "fast_tffm_tpu/cli.py": """\
            import os
            def read():
                ok = os.environ.get("FM_METRICS_FILE")
                bad = os.environ.get("FM_METRIC_FILE")
                return ok, bad
        """,
        "sample.cfg2": ""}), rule="R009")
    assert len(found) == 1
    assert "FM_METRIC_FILE" in found[0].message


def test_r009_flags_stale_cfg_attribute_read(tmp_path):
    found = _findings(tmp_path, _r009_files(extra={
        "fast_tffm_tpu/user.py": """\
            def go(cfg):
                a = cfg.factor_num
                b = cfg.row_dim
                return a, b, cfg.metrics_flle
        """}), rule="R009")
    assert len(found) == 1
    assert "metrics_flle" in found[0].message


# --- R010: unwrapped hot-path IO ------------------------------------------

def _pipe(body):
    return {"fast_tffm_tpu/data/pipeline.py": body}


def test_r010_flags_raw_open_in_pipeline(tmp_path):
    found = _findings(tmp_path, _pipe("""\
        def read(path):
            with open(path) as fh:
                return fh.read()
    """), rule="R010")
    assert len(found) == 1
    assert "utils/retry" in found[0].message


def test_r010_allows_policy_aware_conditional_form(tmp_path):
    found = _findings(tmp_path, _pipe("""\
        from fast_tffm_tpu.utils.retry import open_with_retry
        def read(path, retry=None):
            fh = (open(path) if retry is None else
                  open_with_retry(path, policy=retry))
            return fh
    """), rule="R010")
    assert found == []


def test_r010_allows_explicit_oserror_contract(tmp_path):
    found = _findings(tmp_path, _pipe("""\
        def read_sidecar(path):
            try:
                with open(path) as fh:
                    return fh.read()
            except OSError:
                return None
    """), rule="R010")
    assert found == []


def test_r010_allows_retrying_decorator(tmp_path):
    found = _findings(tmp_path, _pipe("""\
        from fast_tffm_tpu.utils.retry import retrying
        @retrying("sidecar_read")
        def read(path):
            with open(path) as fh:
                return fh.read()
    """), rule="R010")
    assert found == []


def test_r010_scopes_to_hot_modules(tmp_path):
    found = _findings(tmp_path, {"fast_tffm_tpu/metrics.py": """\
        def read(path):
            return open(path).read()
    """}, rule="R010")
    assert found == []


def test_r010_respects_pragma(tmp_path):
    found = _findings(tmp_path, _pipe("""\
        def read(path):
            # fmlint: disable=R010 -- caller owns the OSError contract
            with open(path) as fh:
                return fh.read()
    """), rule="R010")
    assert found == []


# --- baseline + json -------------------------------------------------------

def _one_finding_project(tmp_path):
    # Real package shape (__init__.py present) so the project root —
    # which baseline keys are relative to — lands at tmp_path, the
    # way the repo surface roots at the repo.
    return _project(tmp_path, {
        "fast_tffm_tpu/__init__.py": "",
        "fast_tffm_tpu/data/__init__.py": "",
        "fast_tffm_tpu/data/pipeline.py": """\
            def read(path):
                return open(path).read()
        """})


def test_baseline_suppresses_recorded_findings(tmp_path):
    root, _ = _one_finding_project(tmp_path)
    found = run_paths([root])
    assert [f.rule for f in found] == ["R010"]
    bl = tmp_path / "baseline.txt"
    write_baseline(found, str(bl), root)
    assert run_paths([root], baseline=str(bl)) == []


def test_baseline_does_not_absorb_new_findings(tmp_path):
    """Entries are line-free but counted: one recorded finding absorbs
    one occurrence, a second identical one still fails the gate."""
    root, _ = _one_finding_project(tmp_path)
    found = run_paths([root])
    bl = tmp_path / "baseline.txt"
    write_baseline(found, str(bl), root)
    p = tmp_path / "fast_tffm_tpu" / "data" / "pipeline.py"
    p.write_text(p.read_text()
                 + "\ndef read2(path):\n    return open(path).read()\n")
    remaining = run_paths([root], baseline=str(bl))
    assert [f.rule for f in remaining] == ["R010"]


def test_baseline_survives_line_shifts(tmp_path):
    root, _ = _one_finding_project(tmp_path)
    bl = tmp_path / "baseline.txt"
    write_baseline(run_paths([root]), str(bl), root)
    p = tmp_path / "fast_tffm_tpu" / "data" / "pipeline.py"
    p.write_text("# a comment pushing everything down\n\n\n"
                 + p.read_text())
    assert run_paths([root], baseline=str(bl)) == []


def test_cli_json_output(tmp_path, capsys):
    root, paths = _one_finding_project(tmp_path)
    assert main(["--json", "--no-baseline", root]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["count"] == 1
    assert out["findings"][0]["rule"] == "R010"
    assert out["findings"][0]["path"].endswith("pipeline.py")


def test_cli_update_baseline_round_trip(tmp_path, capsys):
    root, _ = _one_finding_project(tmp_path)
    bl = tmp_path / "baseline.txt"
    assert main(["--baseline", str(bl), "--update-baseline",
                 root]) == 0
    capsys.readouterr()
    # NOTE: the committed repo baseline stores paths relative to the
    # repo root; this round-trip exercises an explicit --baseline file
    # against the same surface it was recorded from.
    assert main(["--baseline", str(bl), root]) == 0


# --- R012: health-catalog drift --------------------------------------------

_ATT_OK = """\
    HEALTH_KINDS = frozenset({"stalled", "gate_held"})
"""

_EMITTERS = """\
    def watchdog(sink):
        sink.emit("health", {"status": "stalled", "step": 3})

    def gate(tel):
        fields = {"status": "gate_held", "auc": 0.2}
        tel.sink.emit("health", fields)
"""


def _r012_files(att=_ATT_OK, emitters=_EMITTERS,
                readme="catalog: stalled and gate_held rows\n"):
    return {"fast_tffm_tpu/obs/attribution.py": att,
            "fast_tffm_tpu/obs/emitters.py": emitters,
            "README.md": readme}


def test_r012_clean_when_catalog_covers_emits(tmp_path):
    assert _findings(tmp_path, _r012_files(), rule="R012") == []


def test_r012_flags_unmapped_emitted_kind(tmp_path):
    found = _findings(tmp_path, _r012_files(
        emitters=_EMITTERS + """\

    def rogue(sink):
        sink.emit("health", {"status": "zombie", "step": 1})
""",
        readme="stalled gate_held zombie\n"), rule="R012")
    assert len(found) == 1
    assert "zombie" in found[0].message
    assert "HEALTH_KINDS" in found[0].message
    assert found[0].path.endswith("emitters.py")


def test_r012_flags_missing_readme_row(tmp_path):
    found = _findings(tmp_path, _r012_files(
        readme="only stalled is documented\n"), rule="R012")
    assert len(found) == 1
    assert "gate_held" in found[0].message
    assert "README" in found[0].message


def test_r012_flags_stale_catalog_entry(tmp_path):
    found = _findings(tmp_path, _r012_files(
        att='HEALTH_KINDS = frozenset({"stalled", "gate_held", '
            '"ghost"})\n',
        readme="stalled gate_held ghost\n"), rule="R012")
    assert len(found) == 1
    assert "ghost" in found[0].message
    assert "stale" in found[0].message
    assert found[0].path.endswith("attribution.py")


def test_r012_ignores_status_dicts_without_health_emit(tmp_path):
    """A {"status": ...} dict that is not a health-emit PAYLOAD is not
    a health kind — whether it lives in a non-emitting scope (an HTTP
    stats payload) or right beside an emit in the same function (the
    scan anchors on the emit call's argument, not the whole scope)."""
    found = _findings(tmp_path, _r012_files(
        emitters=_EMITTERS + """\

    def stats():
        return {"status": "ok", "uptime": 1.0}

    def emit_and_report(sink):
        sink.emit("health", {"status": "stalled"})
        return {"status": "weird_unrelated"}
"""), rule="R012")
    assert found == []


def test_r012_one_readme_finding_per_kind(tmp_path):
    """A kind emitted from several sites with its README row missing
    is ONE finding (the missing artifact is the catalog row), while
    the HEALTH_KINDS mapping check stays per-site."""
    found = _findings(tmp_path, _r012_files(
        emitters=_EMITTERS + """\

    def again(sink):
        sink.emit("health", {"status": "gate_held", "step": 9})
""",
        readme="only stalled is documented\n"), rule="R012")
    assert len(found) == 1
    assert "gate_held" in found[0].message
    assert "README" in found[0].message


def test_r012_pragma_escape(tmp_path):
    found = _findings(tmp_path, _r012_files(
        emitters=_EMITTERS + """\

    def experimental(sink):
        sink.emit("health", {"status": "wip_kind"})  # fmlint: disable=R012 -- staged rollout, catalog lands next PR
""",
        readme="stalled gate_held wip_kind\n"), rule="R012")
    assert found == []


def test_repo_baseline_is_empty():
    """The adoption sweep left ZERO accepted findings: the committed
    baseline must stay empty so any new finding fails the gate."""
    bl = os.path.join(REPO, "tools", "fmlint", "baseline.txt")
    from tools.fmlint.core import load_baseline
    assert load_baseline(bl) == []
