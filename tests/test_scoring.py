"""Cross-file streaming scorer == per-file scoring, bit for bit.

The streaming sweep (fast_tffm_tpu/scoring.py) must be a PURE
throughput change: for every input shape — C++ fast path, tolerant
generic path, unbounded-features generic path, sharded fixed-U (spills
included), multi-file batches that interleave neighbors, empty files —
the per-file score arrays it demuxes out of one continuous batch
stream must be bit-identical to scoring each file in its own sweep
(the pre-refactor per-file protocol), for host_threads = 1 AND > 1.
Plus the demux/writer/merger machinery contracts themselves.
"""

import logging
import os
import threading

import numpy as np
import pytest

from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.data import cparser
from fast_tffm_tpu.data.pipeline import FileMarks, batch_iterator
from fast_tffm_tpu.scoring import (PartMerger, ScoreDemux, ScoreWriter,
                                   score_sweep)

needs_cpp = pytest.mark.skipif(not cparser.available(),
                               reason="C++ parser extension unavailable")

VOCAB = 300


def _write(tmp_path, n, seed, name, blanks=True):
    rng = np.random.default_rng(seed)
    lines = []
    for i in range(n):
        nnz = rng.integers(1, 12)
        ids = rng.choice(VOCAB, size=nnz, replace=False)
        lines.append(" ".join(["1" if rng.random() < 0.4 else "0"]
                              + [f"{j}:{rng.random():.4f}" for j in ids]))
        if blanks and i % 7 == 3:
            lines.append("")  # blank line: scores stay line-aligned
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _files(tmp_path, blanks=True):
    """Three files sized so batches CROSS both boundaries at B=16 (the
    middle file is smaller than one batch: its examples interleave
    with both neighbors' inside single batches), plus one empty file
    and one more regular file after it."""
    a = _write(tmp_path, 40, 1, "a.txt", blanks)
    b = _write(tmp_path, 5, 2, "b.txt", blanks=False)
    empty = tmp_path / "c_empty.txt"
    empty.write_text("")
    d = _write(tmp_path, 23, 3, "d.txt", blanks)
    return [a, b, str(empty), d]


def _cfg(files, host_threads=1, **kw):
    base = dict(vocabulary_size=VOCAB, factor_num=4, batch_size=16,
                train_files=tuple(files), shuffle=False,
                bucket_ladder=(4, 8, 16), max_features_per_example=16,
                host_threads=host_threads)
    base.update(kw)
    return FmConfig(**base)


def _table(cfg):
    from fast_tffm_tpu.models.fm import init_table
    return init_table(cfg, seed=7)


def _line_count(path):
    with open(path) as fh:
        return sum(1 for _ in fh)


def _streamed(cfg, table, files):
    """One continuous sweep -> {path: raw scores} via the demux."""
    out = {}
    n = score_sweep(cfg, table, files,
                    on_file=lambda p, v: out.__setitem__(p, v))
    assert sorted(out) == sorted(files)
    assert sum(len(v) for v in out.values()) == n
    return out


def _per_file(cfg, table, files):
    """The pre-refactor protocol: every file in its own sweep."""
    return {f: _streamed(cfg, table, [f])[f] for f in files}


def _assert_file_parity(tmp_path, cfg_kw=None, blanks=True):
    files = _files(tmp_path, blanks)
    cfg = _cfg(files, 1, **(cfg_kw or {}))
    table = _table(cfg)
    ref = _per_file(cfg, table, files)
    for ht in (1, 4):
        got = _streamed(_cfg(files, ht, **(cfg_kw or {})), table, files)
        for f in files:
            assert got[f].tobytes() == ref[f].tobytes(), (
                f"host_threads={ht}: {os.path.basename(f)} diverged")
            # line alignment: one score per input line, empty incl.
            assert len(got[f]) == _line_count(f)
    return ref


@needs_cpp
def test_streaming_parity_fast_path(tmp_path):
    ref = _assert_file_parity(tmp_path)
    assert len(ref[[k for k in ref if k.endswith("c_empty.txt")][0]]) == 0


def test_streaming_parity_generic_unbounded(tmp_path):
    # max_features_per_example=0 stays on the generic per-line path
    _assert_file_parity(tmp_path,
                        cfg_kw=dict(max_features_per_example=0,
                                    bucket_ladder=(16,)))


@needs_cpp
def test_streaming_parity_tolerant_keep_empty(tmp_path):
    """bad_line_policy=skip under keep_empty (the shape that routed
    SERIAL before the C++ block parser grew keep_empty in ABI 7): a
    corrupt line becomes a zero-feature example — alignment kept —
    and the parallel plane now applies, bit-identical to serial."""
    files = _files(tmp_path)
    # corrupt one mid-file line in a.txt
    lines = open(files[0]).read().splitlines()
    lines[11] = "not libsvm at all :::"
    open(files[0], "w").write("\n".join(lines) + "\n")
    _assert_file_parity(tmp_path, cfg_kw=dict(bad_line_policy="skip"))


@needs_cpp
def test_streaming_parity_sharded_fixed_u(tmp_path):
    """The multi-process shape, emulated per shard: fixed-U sharded
    streams over ALL files, demuxed per (shard, file) through
    FileMarks, then parts concatenated in shard order per file — must
    equal the unsharded per-file reference. uniq_bucket=64 on B=16
    batches with up to 16 features forces SPILLS (batches close
    early), the exact protocol the ledger must survive."""
    from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                         make_batch_scorer,
                                         ships_raw_batches)
    files = _files(tmp_path)
    cfg = _cfg(files)
    table = _table(cfg)
    ref = _per_file(cfg, table, files)
    spec = ModelSpec.from_config(cfg)
    score_fn = make_batch_scorer(spec)
    raw = ships_raw_batches(spec)
    P = 3
    for ht in (1, 4):
        scfg = _cfg(files, ht)
        parts = {f: [None] * P for f in files}
        for p in range(P):
            marks = FileMarks()
            got = {}
            demux = ScoreDemux(marks,
                               lambda f, v, _g=got: _g.__setitem__(f, v))
            it = batch_iterator(scfg, files, training=False, epochs=1,
                                keep_empty=True, shard_index=p,
                                num_shards=P, fixed_shape=True,
                                uniq_bucket=64, raw_ids=raw,
                                file_marks=marks)
            for batch in it:
                args = batch_args(batch)
                args.pop("labels"), args.pop("weights")
                s = np.asarray(score_fn(table, args))
                demux.consume(s[:batch.num_real])
            demux.finalize()
            assert sorted(got) == sorted(files)
            for f, v in got.items():
                parts[f][p] = v
        for f in files:
            merged = np.concatenate(parts[f])
            assert merged.tobytes() == ref[f].tobytes(), (
                f"host_threads={ht}: sharded merge of "
                f"{os.path.basename(f)} diverged")
            assert len(merged) == _line_count(f)


@needs_cpp
def test_predict_e2e_multi_file_score_files(tmp_path):
    """predict() end to end over the multi-file corpus: every file gets
    its .score, line-aligned, including the ZERO-LINE file; file order
    of the returned list matches input order; no writer thread leaks."""
    from fast_tffm_tpu.predict import predict
    files = _files(tmp_path)
    cfg = _cfg(files, host_threads=4,
               predict_files=tuple(files),
               score_path=str(tmp_path / "score"),
               model_file=str(tmp_path / "model" / "fm"))
    written = predict(cfg, table=_table(cfg))
    assert [os.path.basename(w)[:-len(".score")] for w in written] == [
        os.path.basename(f) for f in files]
    table = _table(cfg)
    ref = _per_file(cfg, table, files)
    from fast_tffm_tpu.metrics import sigmoid
    for f, w in zip(files, written):
        with open(w) as fh:  # loadtxt warns on the empty file
            got = np.asarray([float(ln) for ln in fh if ln.strip()])
        assert len(got) == _line_count(f)
        exp = sigmoid(ref[f])
        np.testing.assert_allclose(got, exp, atol=1e-6)
    assert not [t.name for t in threading.enumerate()
                if t.name in ("fm-score-writer", "fetcher")
                and t.is_alive()]


# ------------------------------------------------------------ demux units


def _marks_of(entries):
    m = FileMarks()
    for path, start in entries:
        m.start_file(path, start)
    return m


def test_demux_one_batch_cuts_many_files():
    m = _marks_of([("a", 0), ("b", 3), ("c", 5), ("d", 5)])
    got = []
    d = ScoreDemux(m, lambda p, v: got.append((p, v.tolist())))
    d.consume(np.arange(7, dtype=np.float32))
    # a, b, and the EMPTY c all cut from the single consume; d waits
    assert got == [("a", [0, 1, 2]), ("b", [3, 4]), ("c", [])]
    d.finalize()
    assert got[-1] == ("d", [5, 6])


def test_demux_trailing_empty_files():
    m = _marks_of([("a", 0), ("b", 2), ("c", 2)])
    got = []
    d = ScoreDemux(m, lambda p, v: got.append((p, len(v))))
    d.consume(np.zeros(2, dtype=np.float32))
    d.finalize()
    assert got == [("a", 2), ("b", 0), ("c", 0)]


def test_demux_no_files_no_scores():
    d = ScoreDemux(_marks_of([]), lambda p, v: (_ for _ in ()).throw(
        AssertionError("no files must emit nothing")))
    d.finalize()


def test_demux_late_entry_holds_cut():
    """A file is only cut once its SUCCESSOR's ledger entry exists —
    scores past the boundary wait, then cut retroactively."""
    m = _marks_of([("a", 0)])
    got = []
    d = ScoreDemux(m, lambda p, v: got.append(p))
    d.consume(np.zeros(5, dtype=np.float32))
    assert got == []          # b not announced yet
    m.start_file("b", 3)
    d.consume(np.zeros(0, dtype=np.float32))
    assert got == ["a"]       # announcement alone releases the cut
    d.finalize()
    assert got == ["a", "b"]


# --------------------------------------------------- writer/merger units


def test_score_writer_marker_after_file(tmp_path):
    w = ScoreWriter(logging.getLogger("t"))
    out = str(tmp_path / "x.score")
    w.submit(out, np.asarray([0.25, 0.5], dtype=np.float32),
             marker=out + ".done")
    w.close()
    assert open(out).read() == "0.250000\n0.500000\n"
    assert os.path.exists(out + ".done")


def test_score_writer_surfaces_write_error(tmp_path):
    w = ScoreWriter(logging.getLogger("t"))
    w.submit(str(tmp_path / "nope" / "x.score"),
             np.zeros(1, dtype=np.float32))
    with pytest.raises(OSError):
        w.close()
    w.close(raise_error=False)  # idempotent


def test_part_merger_merges_in_order(tmp_path):
    outs = [str(tmp_path / f"f{i}.score") for i in range(3)]
    m = PartMerger(outs, num_parts=2, logger=logging.getLogger("t"))
    # parts land out of file order — the merger still merges in order
    for fi in (2, 0, 1):
        for p in range(2):
            part = f"{outs[fi]}.part{p}"
            with open(part, "w") as fh:
                fh.write(f"{fi}.{p}\n")
            with open(part + ".done", "w"):
                pass
    assert m.finish() == outs
    for fi, out in enumerate(outs):
        assert open(out).read() == f"{fi}.0\n{fi}.1\n"
    assert not [p for p in os.listdir(tmp_path) if ".part" in p]


def test_part_merger_missing_marker_raises(tmp_path, monkeypatch):
    import fast_tffm_tpu.scoring as scoring
    monkeypatch.setattr(scoring, "_MERGE_GRACE_SECONDS", 0.2)
    out = str(tmp_path / "f.score")
    m = PartMerger([out], num_parts=2, logger=logging.getLogger("t"))
    with open(out + ".part0", "w") as fh:
        fh.write("x\n")
    with open(out + ".part0.done", "w"):
        pass
    # part1 never arrives: finish() must raise naming the marker, not
    # poll forever
    with pytest.raises(FileNotFoundError, match="part1"):
        m.finish()


def test_scrub_stale_parts_removes_only_parts(tmp_path):
    from fast_tffm_tpu.scoring import scrub_stale_parts
    outs = [str(tmp_path / "a.score"), str(tmp_path / "b.score")]
    # A crashed prior sweep's leavings: parts + markers, including a
    # part index beyond this run's process count, and the merged score
    # file itself (which a rerun legitimately overwrites — keep it).
    keep = [outs[0], str(tmp_path / "unrelated.txt")]
    stale = [outs[0] + ".part0", outs[0] + ".part0.done",
             outs[0] + ".part7", outs[1] + ".part1.done"]
    for path in keep + stale:
        with open(path, "w") as fh:
            fh.write("old\n")
    removed = scrub_stale_parts(outs)
    assert sorted(removed) == sorted(stale)
    for path in stale:
        assert not os.path.exists(path)
    for path in keep:
        assert os.path.exists(path)
    assert scrub_stale_parts(outs) == []


def test_part_merger_stop_is_clean(tmp_path):
    m = PartMerger([str(tmp_path / "f.score")], num_parts=1,
                   logger=logging.getLogger("t"))
    m.stop()
    assert not [t.name for t in threading.enumerate()
                if t.name == "fm-part-merger" and t.is_alive()]


# ------------------------------------------------------- fmstat verdict


def test_predict_attribution_rows_and_verdict():
    from fast_tffm_tpu.obs.attribution import attribution
    base = {"counters": {"predict/examples": 1000,
                         "predict/seconds": 2.0,
                         "pipeline/build_seconds": 1.8,
                         "fetch/d2h_seconds": 0.2,
                         "predict/write_seconds": 0.1},
            "gauges": {}, "hists": {}}
    att = attribution(base)
    assert att["predict_parse_share"] == pytest.approx(0.9)
    assert att["predict_d2h_share"] == pytest.approx(0.1)
    assert att["predict_write_share"] == pytest.approx(0.05)
    assert "parse-bound" in att["verdict"]
    # no stage saturating -> dispatch/score named, not guessed
    base["counters"]["pipeline/build_seconds"] = 0.3
    att = attribution(base)
    assert "score/dispatch-bound" in att["verdict"]
    # pre-refactor stream without the stage counters: heuristic kept
    for k in ("pipeline/build_seconds", "fetch/d2h_seconds",
              "predict/write_seconds"):
        del base["counters"][k]
    att = attribution(base)
    assert att["predict_parse_share"] is None
    assert "host/scoring-bound" in att["verdict"]


def test_predict_attribution_gated_to_predict_only_streams():
    # A combined train-then-predict metrics file feeds
    # pipeline/build_seconds and fetch/d2h_seconds from the train loop
    # and its validation sweeps too — the shares must go None (and the
    # verdict stays the train verdict) instead of reporting the train
    # loop's hours as a percentage of the predict sweep.
    from fast_tffm_tpu.obs.attribution import attribution
    base = {"counters": {"predict/examples": 1000,
                         "predict/seconds": 2.0,
                         "pipeline/build_seconds": 3600.0,
                         "fetch/d2h_seconds": 40.0,
                         "predict/write_seconds": 0.1,
                         "train/examples": 500000},
            "gauges": {},
            "hists": {"train/step_seconds":
                      {"sum": 3000.0, "count": 10000}}}
    att = attribution(base)
    assert att["predict_parse_share"] is None
    assert att["predict_d2h_share"] is None
    assert att["predict_write_share"] is None
    assert "predict" not in att.get("verdict", "")
