"""Lookup-backend seam (lookup.py; BASELINE config #5): the host-offload
backend must be interchangeable with the fused device path — same math,
same checkpoints, same CLI surface — with only storage/gather/apply moved
off-device."""

import textwrap

import numpy as np
import pytest

import run_tffm
from fast_tffm_tpu.config import FmConfig, load_config
from fast_tffm_tpu.data.pipeline import batch_iterator
from fast_tffm_tpu.lookup import (HostOffloadLookup, PinnedHostLookup,
                                  make_offload_backend,
                                  make_offload_train_step, memory_report,
                                  probe_placement_mode)
from fast_tffm_tpu.models.fm import (ModelSpec, batch_args,
                                     init_accumulator, init_table,
                                     make_grad_fn, make_train_step)
from tests.orbax_caps import orbax_supports_partial_restore
from tests.test_e2e import make_dataset

# ISSUE 3 triage: these paths need PyTreeRestore(partial_restore=True)
# (CheckpointState.restore_partial — the table-without-accumulator
# restore). On an orbax without it the feature cannot work at all, so
# skipping is honest; a capable install still runs them.
requires_partial_restore = pytest.mark.skipif(
    not orbax_supports_partial_restore(),
    reason="installed orbax PyTreeRestore lacks partial_restore")


def _cfg(tmp_path, **kw):
    base = dict(vocabulary_size=200, factor_num=4, batch_size=32,
                learning_rate=0.1, factor_lambda=1e-6, bias_lambda=1e-6,
                train_files=(str(tmp_path / "train.txt"),),
                model_file=str(tmp_path / "model" / "fm_model"),
                shuffle=False, epoch_num=2)
    base.update(kw)
    return FmConfig(**base)


def test_deferred_allocation():
    cfg = FmConfig(vocabulary_size=100, factor_num=4)
    lk = HostOffloadLookup(cfg, _init=False)
    assert lk.table is None and lk.acc is None
    with pytest.raises(ValueError, match="shape"):
        lk.load(np.zeros((3, 3), np.float32), np.zeros((3, 3), np.float32))


def test_host_backend_matches_device_step_for_step(tmp_path, rng):
    """N steps through the host backend == N steps through the fused
    device jit, batch for batch (same seam math on both sides)."""
    make_dataset(tmp_path / "train.txt", 200, rng)
    cfg = _cfg(tmp_path)
    spec = ModelSpec.from_config(cfg)

    table = init_table(cfg, cfg.seed)
    acc = init_accumulator(cfg)
    step = make_train_step(spec)

    lk = HostOffloadLookup(cfg, cfg.seed)
    grad_fn = make_grad_fn(spec)

    for batch in batch_iterator(cfg, cfg.train_files, training=True,
                                epochs=1):
        args = batch_args(batch)
        table, acc, loss_d, _ = step(table, acc, **args)
        gathered = lk.gather(args["uniq_ids"])
        loss_h, _, grad = grad_fn(gathered, **args)
        lk.apply_grad(args["uniq_ids"], np.asarray(grad),
                      cfg.learning_rate)
        assert float(loss_d) == pytest.approx(float(loss_h), abs=1e-6)

    np.testing.assert_allclose(lk.table[:cfg.num_rows], np.asarray(table),
                               atol=2e-6)
    np.testing.assert_allclose(lk.acc[:cfg.num_rows], np.asarray(acc),
                               atol=2e-6)


@pytest.fixture
def host_cfg_files(tmp_path, rng):
    train = tmp_path / "train.txt"
    test = tmp_path / "test.txt"
    make_dataset(train, 400, rng)
    labels = make_dataset(test, 120, rng)
    cfg_path = tmp_path / "fm.cfg"
    cfg_path.write_text(textwrap.dedent(f"""
        [General]
        vocabulary_size = 200
        factor_num = 4
        model_file = {tmp_path}/model/fm_model
        lookup = host

        [Train]
        train_files = {train}
        validation_files = {test}
        epoch_num = 4
        batch_size = 32
        learning_rate = 0.1
        log_steps = 50

        [Predict]
        predict_files = {test}
        score_path = {tmp_path}/score
    """))
    return tmp_path, cfg_path, labels


@requires_partial_restore
def test_host_lookup_e2e_cli(host_cfg_files):
    """Full CLI train -> checkpoint -> predict with lookup = host, and
    the scores match a device-backend predict from the same checkpoint."""
    tmp_path, cfg_path, labels = host_cfg_files
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    assert (tmp_path / "model" / "fm_model.ckpt").is_dir()
    assert run_tffm.main(["predict", str(cfg_path)]) == 0
    scores_host = np.loadtxt(tmp_path / "score" / "test.txt.score")
    assert len(scores_host) == 120

    from fast_tffm_tpu.metrics import exact_auc
    assert exact_auc(scores_host, labels) > 0.8

    # Same checkpoint scored through the device backend: identical.
    cfg = load_config(str(cfg_path))
    import dataclasses
    dev_cfg = dataclasses.replace(
        cfg, lookup="device", score_path=str(tmp_path / "score_dev"))
    from fast_tffm_tpu.predict import predict
    predict(dev_cfg)
    scores_dev = np.loadtxt(tmp_path / "score_dev" / "test.txt.score")
    np.testing.assert_allclose(scores_host, scores_dev, atol=1e-5)


def test_host_lookup_resume(host_cfg_files):
    """from_checkpoint restores exactly what training saved, and a second
    train run resumes from it (step counter advances, table moves)."""
    tmp_path, cfg_path, _ = host_cfg_files
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    cfg = load_config(str(cfg_path))
    lk = HostOffloadLookup.from_checkpoint(cfg)
    assert lk.table.shape == (cfg.ckpt_rows, cfg.row_dim)
    assert lk.step > 0
    t1 = lk.table.copy()

    assert run_tffm.main(["train", str(cfg_path)]) == 0
    lk2 = HostOffloadLookup.from_checkpoint(cfg)
    assert lk2.step > lk.step
    assert not np.array_equal(t1, lk2.table)


@requires_partial_restore
def test_from_checkpoint_table_only(host_cfg_files):
    """with_acc=False (predict) restores just the table leaf: the
    accumulator — half the state at offload scale — never materializes."""
    tmp_path, cfg_path, _ = host_cfg_files
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    cfg = load_config(str(cfg_path))
    full = HostOffloadLookup.from_checkpoint(cfg)
    lean = HostOffloadLookup.from_checkpoint(cfg, with_acc=False)
    assert lean.acc is None
    np.testing.assert_array_equal(lean.table, full.table)
    assert lean.step == full.step


@requires_partial_restore
def test_predict_with_caller_table_stays_host_side(host_cfg_files):
    """predict(cfg, table=...) under lookup=host must wrap the provided
    host table in the backend (for_table), not ship it to a device."""
    tmp_path, cfg_path, _ = host_cfg_files
    assert run_tffm.main(["train", str(cfg_path)]) == 0
    cfg = load_config(str(cfg_path))
    from fast_tffm_tpu.train import train as _train  # table from train()
    import dataclasses
    from fast_tffm_tpu.predict import predict
    table = HostOffloadLookup.from_checkpoint(cfg).table[:cfg.num_rows]
    cfg2 = dataclasses.replace(cfg,
                               score_path=str(tmp_path / "score_t"))
    predict(cfg2, table=table)
    s1 = np.loadtxt(tmp_path / "score_t" / "test.txt.score")
    predict(cfg)  # checkpoint path
    s2 = np.loadtxt(tmp_path / "score" / "test.txt.score")
    np.testing.assert_allclose(s1, s2, atol=1e-6)
    with pytest.raises(ValueError, match="layout"):
        HostOffloadLookup.for_table(cfg, np.zeros((5, 5), np.float32))


def test_placement_probe_resolves_on_cpu():
    """The hermetic CPU platform supports the un-annotated program
    structure ("plain" — device memory IS host RAM there); the chooser
    must therefore pick the in-jit backend."""
    assert probe_placement_mode() == "plain"
    cfg = FmConfig(vocabulary_size=100, factor_num=4)
    lk = make_offload_backend(cfg, seed=0)
    assert isinstance(lk, PinnedHostLookup)
    assert lk.mode == "plain"


def test_pinned_backend_matches_device_step_for_step(tmp_path, rng):
    """N steps through the FUSED in-jit offload program == N steps
    through the fused device jit, batch for batch — the parity contract
    the numpy backend already meets, now for the pinned one (VERDICT r3
    next-round #1)."""
    make_dataset(tmp_path / "train.txt", 200, rng)
    cfg = _cfg(tmp_path)
    spec = ModelSpec.from_config(cfg)

    table = init_table(cfg, cfg.seed)
    acc = init_accumulator(cfg)
    step = make_train_step(spec)

    lk = PinnedHostLookup(cfg, cfg.seed)
    off_step = make_offload_train_step(spec, lk, cfg.learning_rate)

    for batch in batch_iterator(cfg, cfg.train_files, training=True,
                                epochs=1):
        args = batch_args(batch)
        table, acc, loss_d, _ = step(table, acc, **args)
        loss_p, _ = off_step(**args)
        assert float(loss_d) == pytest.approx(float(loss_p), abs=1e-6)

    t_p, a_p = (np.asarray(x) for x in lk.state())
    np.testing.assert_allclose(t_p[:cfg.num_rows], np.asarray(table),
                               atol=2e-6)
    np.testing.assert_allclose(a_p[:cfg.num_rows], np.asarray(acc),
                               atol=2e-6)


def test_pinned_seam_methods_match_numpy_backend(tmp_path, rng):
    """gather/apply_grad seam parity: PinnedHostLookup and
    HostOffloadLookup are drop-in interchangeable (same init stream,
    same rows, same post-update state)."""
    make_dataset(tmp_path / "train.txt", 100, rng)
    cfg = _cfg(tmp_path)
    lk_np = HostOffloadLookup(cfg, cfg.seed)
    lk_pin = PinnedHostLookup(cfg, cfg.seed)
    batch = next(batch_iterator(cfg, cfg.train_files, training=True,
                                epochs=1))
    ids = batch.uniq_ids
    np.testing.assert_allclose(np.asarray(lk_pin.gather(ids)),
                               lk_np.gather(ids), atol=1e-7)
    grad = rng.normal(0, 0.1, size=(len(ids), cfg.row_dim)).astype(
        np.float32)
    grad[ids >= cfg.vocabulary_size] = 0.0  # pad rows carry zero grads
    lk_np.apply_grad(ids, grad, cfg.learning_rate)
    lk_pin.apply_grad(ids, grad, cfg.learning_rate)
    np.testing.assert_allclose(np.asarray(lk_pin.table), lk_np.table,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(lk_pin.acc), lk_np.acc,
                               atol=1e-6)


def test_pinned_backend_ffm_fused_step(tmp_path, rng):
    """The fused offload program handles the FFM model family (fields
    threaded through grad_body) — config #3 x config #5 composition."""
    import dataclasses
    from tests.test_e2e import make_dataset as _mk
    lines = []
    for _ in range(64):
        toks = [f"{f}:{int(rng.integers(0, 50))}" for f in range(3)]
        lines.append(" ".join([str(int(rng.integers(0, 2)))] + toks))
    (tmp_path / "train.txt").write_text("\n".join(lines) + "\n")
    cfg = _cfg(tmp_path, vocabulary_size=50, model_type="ffm",
               field_num=3, factor_num=2, batch_size=16)
    spec = ModelSpec.from_config(cfg)
    table = init_table(cfg, cfg.seed)
    acc = init_accumulator(cfg)
    step = make_train_step(spec)
    lk = PinnedHostLookup(cfg, cfg.seed)
    off_step = make_offload_train_step(spec, lk, cfg.learning_rate)
    for batch in batch_iterator(cfg, cfg.train_files, training=True,
                                epochs=1):
        args = batch_args(batch)
        table, acc, loss_d, _ = step(table, acc, **args)
        loss_p, _ = off_step(**args)
        assert float(loss_d) == pytest.approx(float(loss_p), abs=1e-6)


def test_pinned_big_init_layout(monkeypatch):
    """The chunked at-scale init writes uniform rows over [0, vocab),
    keeps the pad row and the ckpt-alignment tail zero, and never
    exceeds init_value_range — checked by forcing the big path at a
    small size."""
    monkeypatch.setattr(HostOffloadLookup, "_DEVICE_INIT_MAX_ROWS", 64)
    cfg = FmConfig(vocabulary_size=300, factor_num=4)
    lk = PinnedHostLookup(cfg, seed=3)
    t = np.asarray(lk.table)
    assert t.shape == (cfg.ckpt_rows, cfg.row_dim)
    live = t[:cfg.vocabulary_size]
    assert np.abs(live).max() <= cfg.init_value_range
    assert (live != 0).mean() > 0.99  # uniform rows actually written
    np.testing.assert_array_equal(t[cfg.vocabulary_size:], 0.0)
    a = np.asarray(lk.acc)
    np.testing.assert_array_equal(a, np.float32(cfg.adagrad_init))


def test_host_lookup_rejects_multiprocess(tmp_path, rng, monkeypatch):
    make_dataset(tmp_path / "train.txt", 50, rng)
    cfg = _cfg(tmp_path, lookup="host")
    import jax
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    from fast_tffm_tpu.train import train
    with pytest.raises(ValueError, match="single-process"):
        train(cfg)


def test_memory_report_keys():
    rep = memory_report()
    assert rep["host_rss_mb"] > 0
