"""tools/fmckpt — the offline checkpoint-integrity CLI (ls / verify /
gc) against real CheckpointState-written directories."""

import json
import os

import numpy as np
import pytest

from fast_tffm_tpu.checkpoint import (CheckpointState, QUARANTINE_PREFIX,
                                      list_step_dirs, manifest_path)
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import init_accumulator, init_table
from fast_tffm_tpu.train import ckpt_state
from tools.fmckpt import main, resolve_ckpt_dir, scan


def _mk_ckpt(tmp_path, steps=(1, 2)):
    cfg = FmConfig(vocabulary_size=500, factor_num=4,
                   model_file=str(tmp_path / "m" / "fm"))
    table, acc = ckpt_state(cfg, init_table(cfg), init_accumulator(cfg))
    ckpt = CheckpointState(cfg.model_file)
    for i, s in enumerate(steps):
        ckpt.save(s, table, acc, vocabulary_size=cfg.vocabulary_size,
                  wait=True, epoch=i)
    return cfg, ckpt


def test_resolve_accepts_model_file_and_dir(tmp_path):
    cfg, ckpt = _mk_ckpt(tmp_path)
    ckpt.close()
    d = resolve_ckpt_dir(cfg.model_file)
    assert d.endswith(".ckpt")
    assert resolve_ckpt_dir(d) == d
    with pytest.raises(FileNotFoundError):
        resolve_ckpt_dir(str(tmp_path / "nope"))


def test_missing_path_exits_2(tmp_path, capsys):
    assert main(["ls", str(tmp_path / "nope")]) == 2
    assert "no checkpoint directory" in capsys.readouterr().err


def test_ls_lists_steps_with_manifest_echo(tmp_path, capsys):
    cfg, ckpt = _mk_ckpt(tmp_path)
    ckpt.close()
    assert main(["ls", cfg.model_file]) == 0
    out = capsys.readouterr().out
    assert "step 1" in out and "step 2" in out
    assert "epoch=1 vocab=500" in out
    assert "NO MANIFEST" not in out


def test_ls_json_and_scan_flag_quarantine_and_orphans(tmp_path, capsys):
    cfg, ckpt = _mk_ckpt(tmp_path)
    ckpt.quarantine_step(2, "test")
    # orphan: a sidecar whose step no longer exists
    with open(manifest_path(ckpt.directory, 99), "w") as fh:
        fh.write("{}")
    ckpt.close()
    state = scan(ckpt.directory)
    assert [s["step"] for s in state["steps"]] == [1]
    assert [q["name"] for q in state["quarantined"]] == [
        f"{QUARANTINE_PREFIX}2"]
    assert state["orphans"] == ["manifest-99.json"]
    assert main(["ls", "--json", cfg.model_file]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["steps"][0]["step"] == 1
    assert rec["quarantined"][0]["name"] == f"{QUARANTINE_PREFIX}2"


def test_verify_pass_fail_and_exit_code(tmp_path, capsys):
    from fast_tffm_tpu.testing.faults import truncate_checkpoint
    cfg, ckpt = _mk_ckpt(tmp_path)
    ckpt.close()
    assert main(["verify", cfg.model_file]) == 0
    out = capsys.readouterr().out
    assert "step 1: OK" in out and "step 2: OK" in out
    truncate_checkpoint(cfg.model_file)  # tears step 2
    assert main(["verify", cfg.model_file]) == 1
    out = capsys.readouterr().out
    assert "step 1: OK" in out
    assert "step 2: FAIL" in out and "size mismatch" in out
    # single-step selection still passes for the intact one
    assert main(["verify", cfg.model_file, "--step", "1"]) == 0
    capsys.readouterr()


def test_verify_explicit_missing_step_fails(tmp_path, capsys):
    """A typo'd (or already-quarantined) --step must not read as
    'UNVERIFIABLE, restore accepts it' — restore would fail on it."""
    cfg, ckpt = _mk_ckpt(tmp_path, steps=(1,))
    ckpt.close()
    assert main(["verify", cfg.model_file, "--step", "14"]) == 1
    out = capsys.readouterr().out
    assert "step 14: MISSING" in out


def test_verify_reports_unmanifested_as_unverifiable(tmp_path, capsys):
    cfg, ckpt = _mk_ckpt(tmp_path, steps=(7,))
    os.remove(manifest_path(ckpt.directory, 7))
    ckpt.close()
    assert main(["verify", cfg.model_file]) == 0  # not a failure
    out = capsys.readouterr().out
    assert "UNVERIFIABLE" in out


def test_gc_dry_run_then_delete(tmp_path, capsys):
    cfg, ckpt = _mk_ckpt(tmp_path)
    qdir = ckpt.quarantine_step(2, "test")
    with open(manifest_path(ckpt.directory, 99), "w") as fh:
        fh.write("{}")
    # a killed manifest writer's litter: .tmp for a step that is gone
    tmp_litter = manifest_path(ckpt.directory, 98) + ".tmp"
    with open(tmp_litter, "w") as fh:
        fh.write("{")
    ckpt.close()
    assert main(["gc", cfg.model_file, "--dry-run"]) == 0
    out = capsys.readouterr().out
    assert "would delete" in out
    assert os.path.isdir(qdir)  # dry run touched nothing
    assert main(["gc", cfg.model_file]) == 0
    out = capsys.readouterr().out
    assert "deleted" in out
    assert not os.path.exists(qdir)
    assert not os.path.exists(manifest_path(ckpt.directory, 99))
    assert not os.path.exists(tmp_litter)
    # committed steps and their manifests are never gc'd
    assert list_step_dirs(ckpt.directory) == [1]
    assert os.path.exists(manifest_path(ckpt.directory, 1))


def test_ls_and_verify_cover_vocab_sidecar(tmp_path, capsys):
    """ISSUE 12 satellite: steps carrying a vocab admission sidecar get
    a +VOCAB mark in ls, verify re-checks the sidecar's embedded crc32
    (OK note on the good step), and a garbled sidecar is a verify FAIL
    — an admit-mode restore would silently fall back to fresh
    admission state, so the operator must see it before pointing a
    scorer at the step."""
    import re

    import numpy as np

    from fast_tffm_tpu.checkpoint import vocab_sidecar_path
    from fast_tffm_tpu.vocab.sketch import CountMinSketch
    from fast_tffm_tpu.vocab.table import VocabRuntime

    cfg = FmConfig(vocabulary_size=500, factor_num=4,
                   model_file=str(tmp_path / "m" / "fm"))
    table, acc = ckpt_state(cfg, init_table(cfg), init_accumulator(cfg))
    ckpt = CheckpointState(cfg.model_file)
    from types import SimpleNamespace
    rt = VocabRuntime(cfg.vocabulary_size, cfg.pad_id, 2.0, 0.5,
                      CountMinSketch(width=256))
    for _ in range(4):
        rt.note_trained(SimpleNamespace(
            vocab_obs=np.array([11, 22], np.int64)))
    rt.barrier(None)
    assert rt.live_rows == 2  # the sidecar under test is non-trivial
    ckpt.save(1, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, epoch=0)
    ckpt.save(2, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, epoch=0, vocab_state=rt.state_payload())
    ckpt.close()
    assert main(["ls", cfg.model_file]) == 0
    out = capsys.readouterr().out
    lines = {int(m.group(1)): line for line in out.splitlines()
             if (m := re.search(r"step (\d+)", line))}
    assert "+VOCAB" not in lines[1]
    assert "+VOCAB" in lines[2]
    assert main(["verify", cfg.model_file]) == 0
    out = capsys.readouterr().out
    assert "step 2: OK" in out and "+vocab crc OK" in out
    # Garble the sidecar: verify must FAIL the step and exit 1 — and
    # publish must refuse to point a scorer fleet at it (every
    # admit-mode reload of the step would raise).
    with open(vocab_sidecar_path(ckpt.directory, 2), "wb") as fh:
        fh.write(b"not gzip at all")
    assert main(["verify", cfg.model_file]) == 1
    out = capsys.readouterr().out
    assert "step 2: FAIL" in out and "vocab sidecar" in out
    from fast_tffm_tpu.checkpoint import read_published
    assert main(["publish", cfg.model_file, "2"]) == 1
    out = capsys.readouterr().out
    assert "vocab sidecar" in out and "pointer untouched" in out
    assert read_published(ckpt.directory) is None
