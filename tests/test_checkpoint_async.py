"""Async checkpointing: periodic saves must not stall the train loop for
the full serialization (VERDICT r2 item 7); final saves barrier."""

import time

import numpy as np

from fast_tffm_tpu.checkpoint import CheckpointState
from fast_tffm_tpu.config import FmConfig
from fast_tffm_tpu.models.fm import init_accumulator, init_table
from fast_tffm_tpu.train import checkpoint_template, ckpt_state, train


def test_async_save_returns_before_commit_and_restores(tmp_path):
    cfg = FmConfig(vocabulary_size=200_000, factor_num=8,
                   model_file=str(tmp_path / "m" / "fm"))
    table, acc = ckpt_state(cfg, init_table(cfg), init_accumulator(cfg))
    ckpt = CheckpointState(cfg.model_file)

    t0 = time.perf_counter()
    ckpt.save(1, table, acc, vocabulary_size=cfg.vocabulary_size)
    t_async = time.perf_counter() - t0
    ckpt.wait_until_finished()

    t0 = time.perf_counter()
    ckpt.save(2, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True)
    t_sync = time.perf_counter() - t0
    # The async call skips the serialization wait; it must be visibly
    # cheaper than the full committed write of the same ~13 MB state.
    assert t_async < t_sync, (t_async, t_sync)

    restored = ckpt.restore(template=checkpoint_template(cfg))
    assert int(restored["step"]) == 2
    np.testing.assert_array_equal(np.asarray(restored["table"]),
                                  np.asarray(table))
    ckpt.close()


def test_save_every_step_train_is_resumable(tmp_path, rng):
    """save_steps=1: every step issues an async save; the run must end
    with a committed, restorable checkpoint at the final step."""
    from tests.test_e2e import make_dataset
    make_dataset(tmp_path / "train.txt", 96, rng)
    cfg = FmConfig(vocabulary_size=200, factor_num=4, batch_size=32,
                   epoch_num=1, save_steps=1, shuffle=False,
                   train_files=(str(tmp_path / "train.txt"),),
                   model_file=str(tmp_path / "m" / "fm"))
    train(cfg)
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(restored["step"]) == 3  # 96 examples / batch 32
    assert np.isfinite(np.asarray(restored["table"])).all()


def test_same_step_resave_updates_stale_epoch(tmp_path):
    """The final save landing on the last periodic save's step must not
    silently keep that save's MID-epoch metadata: a completed run would
    restore as 'interrupted' and retrain an epoch (review finding).
    Identical metadata stays a cheap no-op."""
    cfg = FmConfig(vocabulary_size=1000, factor_num=4,
                   model_file=str(tmp_path / "m" / "fm"))
    table, acc = ckpt_state(cfg, init_table(cfg), init_accumulator(cfg))
    ckpt = CheckpointState(cfg.model_file)
    # "Periodic" save mid-final-epoch: 7 completed of 8.
    ckpt.save(40, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, epoch=7)
    # "Final" save, same step, schedule now complete; the caller flags
    # the known-stale collision (train() derives this deterministically
    # from its own last periodic save).
    ckpt.save(40, table, acc, vocabulary_size=cfg.vocabulary_size,
              force=True, wait=True, epoch=8,
              rewrite_stale_metadata=True)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    assert int(restored["epoch"]) == 8
    assert int(restored["step"]) == 40
    ckpt.close()


def test_epoch_sidecar_pruned_and_not_leaked(tmp_path):
    """The stale-epoch correction is a sidecar file, not a delete+resave
    (advisor r4: a hard kill in that window lost the newest step). It
    must (a) survive restore, (b) be dropped by a FRESH save at the same
    step (cleared-and-reused dir), (c) not accumulate once its step is
    GC'd."""
    import os
    cfg = FmConfig(vocabulary_size=1000, factor_num=4,
                   model_file=str(tmp_path / "m" / "fm"))
    table, acc = ckpt_state(cfg, init_table(cfg), init_accumulator(cfg))
    ckpt = CheckpointState(cfg.model_file, max_to_keep=2)
    ckpt.save(10, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, epoch=1)
    ckpt.save(10, table, acc, vocabulary_size=cfg.vocabulary_size,
              force=True, wait=True, epoch=2,
              rewrite_stale_metadata=True)
    sc = ckpt._epoch_sidecar(10)
    assert os.path.exists(sc)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    assert int(restored["epoch"]) == 2
    # steps 20, 30 push step 10 out of max_to_keep=2 -> sidecar pruned
    ckpt.save(20, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, epoch=3)
    ckpt.save(30, table, acc, vocabulary_size=cfg.vocabulary_size,
              wait=True, epoch=4)
    assert not os.path.exists(sc)
    ckpt.close()
    # cleared-and-reused dir: a stray sidecar must not overlay a fresh
    # same-step save's metadata
    ckpt2 = CheckpointState(cfg.model_file)
    with open(ckpt2._epoch_sidecar(40), "w") as fh:
        fh.write("99")
    ckpt2.save(40, table, acc, vocabulary_size=cfg.vocabulary_size,
               wait=True, epoch=5)
    restored = ckpt2.restore(template=checkpoint_template(cfg))
    assert int(restored["epoch"]) == 5
    ckpt2.close()


def test_sigkill_mid_async_save_restores_latest_complete(tmp_path, rng):
    """Crash-inject the async save path: SIGKILL a training process
    while saves are in flight (save_steps=1, ~23 MB state widens the
    write window), then require (a) restore finds a complete step —
    orbax's tmp-dir + atomic-commit protocol must hide any partially
    written step the kill left behind — and (b) a resumed run finishes.
    The resume story assumed this atomicity held under kill -9; this
    pins it (round-4 review item 6)."""
    import os
    import signal
    import subprocess
    import sys
    import time

    from tests.test_e2e import make_dataset
    make_dataset(tmp_path / "train.txt", 2000, rng, vocab=500)
    model = tmp_path / "m" / "fm"
    cfg_path = tmp_path / "kill.cfg"
    cfg_path.write_text(f"""
[General]
vocabulary_size = 300000
factor_num = 8
model_file = {model}

[Train]
train_files = {tmp_path / 'train.txt'}
epoch_num = 50
batch_size = 32
learning_rate = 0.1
shuffle = False
save_steps = 1
log_steps = 0
""")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "run_tffm.py", "train", str(cfg_path)],
        cwd=repo, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    ckpt_dir = str(model) + ".ckpt"
    try:
        # Kill the instant a later step starts appearing: step N's async
        # write is then likely mid-flight. Generous deadline: the child
        # pays interpreter + jax + jit-compile startup (~25 s idle, a
        # multiple of that when the 1-core host is already loaded —
        # observed flaking at 120 s under a concurrent suite).
        deadline = time.time() + 300
        while time.time() < deadline:
            steps = [d for d in (os.listdir(ckpt_dir)
                                 if os.path.isdir(ckpt_dir) else [])
                     if d.isdigit()]
            if len(steps) >= 3:
                break
            time.sleep(0.02)
        else:
            raise AssertionError("child never wrote 3 checkpoint steps")
        proc.send_signal(signal.SIGKILL)
    finally:
        if proc.poll() is None:  # assertion path: don't leak the child
            proc.kill()
        proc.wait(timeout=60)
    assert proc.returncode == -signal.SIGKILL

    from fast_tffm_tpu.config import load_config
    cfg = load_config(str(cfg_path))
    cfg = type(cfg)(**{**cfg.__dict__, "epoch_num": 1})
    ckpt = CheckpointState(cfg.model_file)
    s = ckpt.latest_step()
    assert s is not None, "no complete step visible after SIGKILL"
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(restored["step"]) == s
    table = np.asarray(restored["table"])
    assert np.isfinite(table).all() and np.abs(table).max() > 0
    # the resumed run restores and completes its (already-satisfied or
    # remaining) schedule without tripping on leftover tmp dirs
    from fast_tffm_tpu.train import train
    train(cfg)
    ckpt2 = CheckpointState(cfg.model_file)
    assert ckpt2.latest_step() >= s
    ckpt2.close()


def test_legacy_checkpoint_without_epoch_leaf_restores(tmp_path):
    """Checkpoints written before the 'epoch' leaf existed must still
    restore (default 0 = no interrupted schedule): an upgraded binary
    has to resume a preempted job's old checkpoint."""
    import jax
    import orbax.checkpoint as ocp
    cfg = FmConfig(vocabulary_size=1000, factor_num=4,
                   model_file=str(tmp_path / "m" / "fm"))
    table, acc = ckpt_state(cfg, init_table(cfg), init_accumulator(cfg))
    import os
    path = cfg.model_file + ".ckpt"
    os.makedirs(path, exist_ok=True)
    mngr = ocp.CheckpointManager(path)
    # Plain ints for the scalar leaves (ISSUE 3 triage): the installed
    # orbax's StandardSave rejects numpy scalars outright, and the
    # legacy property under test is the MISSING 'epoch' leaf, not the
    # scalar dtype the old writer happened to use.
    mngr.save(7, args=ocp.args.StandardSave(
        {"table": np.asarray(table), "acc": np.asarray(acc),
         "step": 7, "vocab": int(cfg.vocabulary_size)}))
    mngr.wait_until_finished()
    mngr.close()
    ckpt = CheckpointState(cfg.model_file)
    restored = ckpt.restore(template=checkpoint_template(cfg))
    ckpt.close()
    assert int(restored["step"]) == 7
    assert int(restored["epoch"]) == 0  # defaulted, not an error
